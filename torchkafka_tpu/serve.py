"""Continuous-batching generation server: the streaming-native serving loop.

Extends BASELINE config 5 (prompt topic → generate → commit-after-generation)
from lockstep batches to CONTINUOUS batching: a fixed pool of decode slots,
prompts admitted into free slots as earlier generations finish (EOS or
max_new), offsets marked done per COMPLETION and committed through the same
interval ledger the ingest pipeline uses — so a long generation never blocks
the commit watermark behind it, and at-least-once delivery holds per prompt.
No reference analog (the reference has no models, SURVEY.md §2); this is the
TPU-idiomatic serving pattern (static shapes, slot masks) the way vLLM-style
continuous batching is the GPU one.

XLA shape discipline: everything is static — the slot pool is [B] with
per-slot positions, the admission step always prefills a full [B, P] batch
(rows masked by an admit mask; wasted rows cost one prefill of padding),
and the decode tick advances all B slots with inactive slots masked out.
Slot kv-cache rows are recycled without clearing: a freed slot's stale tail
is overwritten position-by-position before each position becomes readable
(the decode step writes kv at ``pos`` before attending over ``[0, pos]``).
That write-before-attend recycling is an ASSERTED invariant, not a hope:
tests/test_kvcache.py poisons every not-yet-readable cache position after
a recycled admission and requires byte-identical outputs, on BOTH the
dense pool and the paged one (``kv_pages=`` — the block-pool +
radix-prefix-reuse mode, torchkafka_tpu/kvcache, where "stale tail" also
covers freed blocks re-allocated to other slots and idle slots' writes
routed to the sink block).

Citations: commit-exactly-what-completed mirrors the reference's
commit-after-batch contract (/root/reference/src/auto_commit.py:55-58)
generalised to out-of-order completions via the OffsetLedger.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import time
import zlib
from typing import Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from torchkafka_tpu.commit.ledger import OffsetLedger
from torchkafka_tpu.errors import (
    BrokerUnavailableError,
    CommitFailedError,
    ConsumerClosedError,
    OutputDeliveryError,
    ProducerFencedError,
)
from torchkafka_tpu.journal import DecodeJournal, JournalEntry, value_crc
from torchkafka_tpu.kvcache import (
    SINK_BLOCK,
    BlockAllocator,
    HostTier,
    KVBackend,
    PagedKVConfig,
    RadixCache,
    TierConfig,
    resolve_kv_backend,
)
from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.models.generate import (
    _attend_cached,
    _attn_tail,
    _project_qkv,
    check_sampling_params,
    check_serving_mesh,
    kv_kmajor_scale_sharding,
    kv_kmajor_sharding,
    kv_scale_sharding,
    kv_sharding,
    paged_pool_kmajor_sharding,
    paged_pool_sharding,
    paged_scale_kmajor_sharding,
    prefill,
    sample_logits,
    serving_shardings,
    slot_sharding,
)
from torchkafka_tpu.models.quant import embed_rows, load_weight
from torchkafka_tpu.models.transformer import TransformerConfig, _rms_norm, _rope
from torchkafka_tpu.source.records import Record, TopicPartition
from torchkafka_tpu.utils import tracing as xprof
from torchkafka_tpu.utils.metrics import Gauge, LatencyHistogram, RateMeter

_logger = logging.getLogger(__name__)

# v5e HBM peak; decode is bandwidth-bound, so this is the denominator of
# every serving roofline in the repo (serve.decode_roofline, scenario 5).
V5E_PEAK_HBM_GBS = 819.0

# The kv_kernel="auto" engagement threshold and every other which-
# backend decision live in ONE place now: kvcache/backend.py
# ``resolve_kv_backend`` — the capability probe _build/_build_paged
# consume (and ServeMetrics surfaces as kv_backend info).


def decode_tick_bytes(params, cfg: TransformerConfig, batch: int,
                      max_len: int, kv_int8: bool = False) -> tuple[int, int]:
    """(weight_bytes, kv_bytes) streamed from HBM per decode tick.

    Weights: every layer tensor and the lm_head are read in full (the
    logits matmul contracts the whole [D, V] head), but the EMBEDDING
    table is a gather of one row per slot — counting the full [V, D]
    table would overstate bytes/tick ~5-7% at zoo scales. KV: both cache
    halves across all layers at the STATIC pool length (attention reads
    the whole buffer; masking discards, it does not skip); ``kv_int8``
    counts the quantized pool (1 byte/element + one f32 scale per
    (layer, slot, position, head) group)."""
    from torchkafka_tpu.models.quant import quantized_nbytes

    total = quantized_nbytes(params)
    embed = quantized_nbytes(params["embed"])
    embed_rows_read = batch * (embed // max(cfg.vocab_size, 1))
    groups = 2 * cfg.n_layers * batch * max_len * cfg.n_kv_heads
    if kv_int8:
        kv = groups * (cfg.head_dim + 4)  # int8 payload + f32 scale
    else:
        kv = groups * cfg.head_dim * jnp.dtype(cfg.dtype).itemsize
    return total - embed + embed_rows_read, kv


def _pick_slots(logits, key_data, idx, *, temperature, top_k, top_p):
    """Per-slot sampling with per-(record, token-index) keys.

    ``logits``: [B, V]; ``key_data``: [B, W] uint32 — each row the raw
    key data of that slot's RECORD key (derived once at admit from the
    record's identity, ``StreamingGenerator._record_key_data``); ``idx``:
    [B] int32 — the gen-buffer index of the token being sampled. Row b
    draws with ``fold_in(record_key_b, idx_b)``, so a record's token i is
    the same draw no matter which slot, tick, replica, or process decodes
    it — the property warm failover's token-exactness stands on (a
    journal-resumed continuation replays the identical key sequence).
    Greedy (temperature 0) ignores the keys, as everywhere else."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    keys = jax.vmap(jax.random.fold_in)(
        jax.random.wrap_key_data(key_data), idx
    )
    return jax.vmap(
        lambda row, k: sample_logits(
            row, k, temperature=temperature, top_k=top_k, top_p=top_p
        )
    )(logits, keys)


def _quant_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric absmax int8 over the last (head_dim) axis:
    [..., Dh] → (int8 [..., Dh], f32 scale [...]). The shared
    ``models.quant.quant_kv_groups`` scheme — the int8 PAGED pool
    quantizes through the same (position, head) groups, which is what
    keeps int8-paged serving token-exact vs int8-dense serving."""
    from torchkafka_tpu.models.quant import quant_kv_groups

    return quant_kv_groups(x)


def _slot_layer_step_q(
    x, layer, ck_q, ck_s, cv_q, cv_s, pos_b, cfg, use_kernel=False,
    mesh=None,
):
    """int8-KV variant of ``_slot_layer_step``: the pool stores int8
    payloads + per-(position, head) f32 absmax scales over Dh —
    (Dh+4)/(2·Dh) ≈ 52% of bf16 pool bytes at Dh=128 — read through
    ``_attend_cached``'s scale-folded mode (scales land on the small
    score/prob tensors; the big operands carry only a cast). A capacity
    lever that, with scatter writes, also measures neutral-to-BETTER
    equal-slot throughput than bf16 KV (+7% at 8B/96 slots — the
    pre-scatter ~20% deficit was the select-rewrite of the pool's four
    tensors, not the read; PERF.md) for ~2× the slot/context headroom.
    Quantization error is bounded by absmax/127 per group; OPT-IN
    because token-exactness vs the bf16 path is deliberately given
    up."""
    q, k, v = _project_qkv(x, layer, cfg)
    q = _rope(q, pos_b[:, None], cfg.rope_theta)
    k = _rope(k, pos_b[:, None], cfg.rope_theta)
    kq, ks = _quant_kv(k[:, 0])  # [B, K, Dh] int8, [B, K]
    vq, vs = _quant_kv(v[:, 0])
    rows = jnp.arange(ck_q.shape[0])
    if use_kernel:
        # K-MAJOR pool ([B, K, M, Dh] / [B, K, M] per layer): each head's
        # [M, Dh] tile is a contiguous slice, which is what lets the
        # kernel batch its dots over (slot, head) with no relayout — the
        # v1 postmortem's fix (ops/kvattn.py docstring). Writes are
        # scatters like the bf16 path (see _slot_layer_step's note):
        # per-(row, head) at [b, :, pos_b[b]].
        kidx = jnp.arange(ck_q.shape[1])[None, :]

        def upd(c, row):  # payload [B, K, M, Dh] and scale [B, K, M] alike
            return c.at[rows[:, None], kidx, pos_b[:, None]].set(row)

        pool_len = ck_q.shape[2]
    else:
        def upd(c, row):  # payload [B, M, K, Dh] and scale [B, M, K] alike
            return c.at[rows, pos_b].set(row)

        pool_len = ck_q.shape[1]
    ck_q = upd(ck_q, kq)
    ck_s = upd(ck_s, ks)
    cv_q = upd(cv_q, vq)
    cv_s = upd(cv_s, vs)
    if use_kernel:
        # Pallas DYNAMIC-LENGTH int8 decode attention (ops/kvattn.py
        # v3): per-slot watermarks are scalar-prefetched and the kernel
        # manually DMAs M-blocks with cross-program double buffering, so
        # HBM traffic scales with each slot's ACTUAL fill instead of the
        # pool size — inexpressible in XLA, where every read is
        # pool-shaped. Net tick win at long pools (the regime "auto"
        # selects; measured matrix in _build/PERF.md); fills < ~90%
        # (the continuous-batching norm) widen it. Caller gates on
        # tiling shapes (a Pallas call is opaque to GSPMD, the
        # flash_attention_sharded lesson — under a mesh the read runs
        # per (data, tp) shard inside shard_map, each shard over its
        # own slots and kv heads; the capability probe gated the
        # divisibilities).
        from torchkafka_tpu.ops.kvattn import (
            int8_decode_attention_dynlen,
            int8_decode_attention_dynlen_sharded,
        )

        if mesh is not None:
            attn = int8_decode_attention_dynlen_sharded(
                q, ck_q, ck_s, cv_q, cv_s, pos_b, mesh
            )
        else:
            attn = int8_decode_attention_dynlen(
                q, ck_q, ck_s, cv_q, cv_s, pos_b
            )
        x = _attn_tail(x, attn, layer, cfg)
    else:
        valid = jnp.arange(pool_len)[None, :] <= pos_b[:, None]  # [B, M]
        x = _attend_cached(
            x, q, ck_q, cv_q, valid, layer, cfg, k_scale=ck_s, v_scale=cv_s
        )
    return x, ck_q, ck_s, cv_q, cv_s


class ServeMetrics:
    """Observability for the serving loop, mirroring StreamMetrics'
    shape (utils/metrics.py) so dashboards treat both uniformly."""

    def __init__(self) -> None:
        self.completions = RateMeter()
        self.tokens = RateMeter()
        self.truncated = RateMeter()  # stopped by EOS before max_new
        self.readmissions = RateMeter()  # slots refilled MID-STREAM (while
        # other generations were in flight) — continuous batching's defining
        # behavior; 0 in lockstep-equivalent runs
        self.dropped = RateMeter()  # undecodable prompts retired
        self.quarantined = RateMeter()  # poison prompts dead-lettered
        self.commit_failures = RateMeter()
        self.output_flush_failures = RateMeter()  # output topic not durable
        self.output_send_failures = RateMeter()  # sync send refusals (stall)
        self.dlq_delivery_failures = RateMeter()  # quarantine DLQ produces
        # that FAILED (the serve path fail-stops on them, but the count
        # outlives the crash on /metrics — a broken DLQ must page, not
        # only kill)
        # Exactly-once output (exactly_once=True): one transaction per
        # commit window. All zero in at-least-once mode.
        self.txn_commits = RateMeter()  # transactions committed (records
        # + offsets atomic)
        self.txn_aborts = RateMeter()  # transactions aborted (survivable
        # commit failure, send fault, or defensive abort)
        self.txn_held_outputs = Gauge()  # outbox entries a commit could
        # NOT yet publish: finished out of completion order, their
        # offsets above the in-order watermark — published by a later
        # window the moment the watermark passes them
        self.commit_latency = LatencyHistogram()  # full commit path: output
        # flush + durability waits + offset commit (see _commit docstring)
        self.slot_occupancy = Gauge()  # active slots / pool size, last tick
        # Per-tick serving step time (host-observed: chunk pack + device
        # dispatch + sync) and tokens surfaced per tick block — the
        # device-side "where did the tick go" companion to the obs
        # layer's host-side record spans.
        self.tick_time = LatencyHistogram()
        self.tokens_per_tick = Gauge()
        self.output_capped = RateMeter()  # slots force-finished by a
        # per-record output budget (max_new_of) at sync granularity
        # Paged prefix cache (kv_pages=, torchkafka_tpu/kvcache): all zero
        # on the dense path.
        self.prefix_hits = RateMeter()  # admissions that reused cached blocks
        self.prefix_misses = RateMeter()  # admissions that prefilled in full
        self.prefix_tokens_saved = RateMeter()  # prompt tokens NOT re-prefilled
        self.prefill_tokens = RateMeter()  # prompt tokens actually prefilled
        self.cache_evictions = RateMeter()  # cached blocks LRU-evicted
        self.admission_deferrals = RateMeter()  # admissions deferred on pool
        # pressure (records re-offered FIFO once blocks free)
        self.cache_fallbacks = RateMeter()  # paged → dense cache-off fallbacks
        self.cache_pool_occupancy = Gauge()  # allocated / usable blocks
        # Tiered radix cache (kv_tier=, kvcache/tier.py): cold prefix
        # blocks demoted to host RAM instead of freed, promoted back on
        # radix hit. All zero without a tier.
        self.radix_demotions = RateMeter()  # blocks demoted HBM → host tier
        self.radix_promotions = RateMeter()  # blocks promoted tier → HBM
        self.tier_hits = RateMeter()  # prefix walks extended by the tier
        self.tier_occupancy_bytes = Gauge()  # host-RAM tier payload bytes
        # Disaggregated prefill (fleet/prefill.py): records held for a
        # prefill-worker handoff and slots admitted by adopting one
        # (decode never ran the prompt pass). All zero in monolithic
        # serving.
        self.prefill_routed = RateMeter()  # records first held awaiting a
        # handoff (the admission-queue routing decision)
        self.adopted_slots = RateMeter()  # slots filled by handoff adoption
        self.handoffs_published = RateMeter()  # prefill-role only: filled-KV
        # handoffs published onto the transfer plane
        # Online draft distillation (torchkafka_tpu/distill): the serve →
        # distill-topic → trainer → checkpoint-topic → swap loop. All
        # zero without a distill topic / spec serving.
        self.distill_published = RateMeter()  # committed completions
        # framed onto the distill topic (txn: counted at commit)
        self.distill_steps = RateMeter()  # trainer train steps (trainer
        # role only)
        self.distill_records = RateMeter()  # corpus records consumed into
        # train batches (trainer role only)
        self.spec_alpha_window = Gauge()  # windowed live acceptance α the
        # DistillController gates refreshes on (NaN-free: 0 until the
        # first window closes)
        self.draft_version = Gauge()  # draft checkpoint version currently
        # proposing (0 = the built-in / construction-time draft)
        self._draft_refreshes: dict[str, RateMeter] = {}  # draft
        # hot-swaps by reason ("alpha_drop", "forced", ...)
        # Chunked prefill (kv_pages with prefill_chunk != 0): admission
        # enqueues uncached suffixes and every tick carries a bounded
        # chunk of them alongside decode. All zero in legacy/dense modes.
        self.chunk_ticks = RateMeter()  # ticks that carried prefill chunk rows
        self.admission_stall_ticks = RateMeter()  # EXTRA ticks admissions
        # queued beyond the one-tick minimum (0 when every admission's
        # suffix fits the chunk a single tick carries — the prompt-storm
        # regression bound)
        self.admission_queue_tokens = Gauge()  # uncached suffix tokens still
        # queued for chunk prefill, sampled after each tick
        self.chunk_utilization = Gauge()  # cumulative prefill tokens /
        # (chunk ticks x chunk width): how full the static chunk rides
        # Decode journal / warm failover (torchkafka_tpu/journal): all zero
        # without a journal or resume hints.
        self.decoded_tokens = RateMeter()  # tokens produced by decode ticks
        # (prefilled/journal-restored tokens excluded — the cold-vs-warm
        # replay differential reads exactly this)
        self.warm_resumes = RateMeter()  # redelivered prompts resumed from
        # a journal hint (prompt + emitted tokens prefilled in one dispatch)
        self.journal_tokens_restored = RateMeter()  # emitted tokens NOT
        # re-decoded thanks to warm resume
        self.journal_served = RateMeter()  # finished-but-uncommitted
        # completions re-served straight from the journal (zero re-decode)
        self.resume_rejected = RateMeter()  # hints discarded (payload CRC /
        # sampling-contract mismatch, or an unsupported pool mode)
        # Per-tenant prefix-cache counters (lazy label children, tenant =
        # record key): the "cache hit by tenant locality" observable the
        # traffic bench reads. Empty on the dense path.
        self._tenant_prefix_hits: dict[str, RateMeter] = {}
        self._tenant_prefix_misses: dict[str, RateMeter] = {}
        # The resolved KV backend (kvcache.resolve_kv_backend): which
        # pool layout/dtype actually serves, whether the Pallas read
        # engaged, and — when it did not — the machine-readable reason,
        # so the kv_kernel="auto" threshold decision is observable on
        # /metrics instead of silent.
        self.kernel_engaged = Gauge()
        self._kernel_disabled: dict[str, RateMeter] = {}
        self._kv_backend: dict = {}

    def note_backend(self, backend: "KVBackend") -> None:
        """Record the resolved backend (called once per build; a paged
        pool that falls back to dense re-notes the dense resolution)."""
        self._kv_backend = backend.describe()
        self.kernel_engaged.set(1.0 if backend.kernel else 0.0)
        reason = backend.kernel_disabled_reason
        if reason is not None:
            self._kernel_disabled.setdefault(reason, RateMeter()).add(1)

    def kernel_disabled_summary(self) -> dict:
        return {r: m.count for r, m in sorted(self._kernel_disabled.items())}

    def tenant_prefix_hits(self, tenant: str) -> RateMeter:
        return self._tenant_prefix_hits.setdefault(tenant, RateMeter())

    def draft_refreshes(self, reason: str) -> RateMeter:
        return self._draft_refreshes.setdefault(reason, RateMeter())

    def distill_summary(self) -> dict:
        return {
            "published": self.distill_published.count,
            "steps": self.distill_steps.count,
            "records": self.distill_records.count,
            "alpha_window": round(self.spec_alpha_window.value, 4),
            "draft_version": int(self.draft_version.value),
            "refreshes": {
                r: m.count for r, m in sorted(self._draft_refreshes.items())
            },
        }

    def tenant_prefix_misses(self, tenant: str) -> RateMeter:
        return self._tenant_prefix_misses.setdefault(tenant, RateMeter())

    def tenant_cache_summary(self) -> dict:
        out = {}
        for t in sorted(
            set(self._tenant_prefix_hits) | set(self._tenant_prefix_misses)
        ):
            hits = self.tenant_prefix_hits(t).count
            misses = self.tenant_prefix_misses(t).count
            out[t] = {
                "hits": hits,
                "misses": misses,
                "hit_rate": (
                    round(hits / (hits + misses), 4)
                    if hits + misses else None
                ),
            }
        return out

    def reset(self) -> None:
        """Zero the rate clocks — called at run() start so compile/warmup
        time (minutes on remote-compile transports) doesn't dilute rates."""
        for m in (
            self.completions, self.tokens, self.truncated,
            self.readmissions, self.dropped, self.commit_failures,
        ):
            m.reset()

    def summary(self) -> dict:
        return {
            "completions": self.completions.count,
            "completions_per_s": self.completions.rate(),
            "tokens": self.tokens.count,
            "tokens_per_s": self.tokens.rate(),
            "truncated_by_eos": self.truncated.count,
            "readmissions": self.readmissions.count,
            "dropped": self.dropped.count,
            "quarantined": self.quarantined.count,
            "commit_failures": self.commit_failures.count,
            "output_flush_failures": self.output_flush_failures.count,
            "output_send_failures": self.output_send_failures.count,
            "dlq_delivery_failures": self.dlq_delivery_failures.count,
            "txn": {
                "commits": self.txn_commits.count,
                "aborts": self.txn_aborts.count,
                "held_outputs": int(self.txn_held_outputs.value),
            },
            "commit": self.commit_latency.summary(),
            "slot_occupancy": round(self.slot_occupancy.value, 3),
            "ticks": self.tick_time.count,
            "step_time": self.tick_time.summary(),
            "tokens_per_tick": round(self.tokens_per_tick.value, 2),
            "output_capped": self.output_capped.count,
            "prefix_cache": self.cache_summary(),
            "tenant_cache": self.tenant_cache_summary(),
            "disagg": self.disagg_summary(),
            "distill": self.distill_summary(),
            "chunked_prefill": self.chunk_summary(),
            "journal": self.journal_summary(),
            "kv_backend": {
                **self._kv_backend,
                "kernel_engaged": int(self.kernel_engaged.value),
                "kernel_disabled": self.kernel_disabled_summary(),
            },
        }

    def chunk_summary(self) -> dict:
        ticks = self.chunk_ticks.count
        return {
            "chunk_ticks": ticks,
            "prefill_tokens_per_tick": (
                round(self.prefill_tokens.count / ticks, 2) if ticks else None
            ),
            "stall_ticks": self.admission_stall_ticks.count,
            "queue_tokens": int(self.admission_queue_tokens.value),
            "utilization": round(self.chunk_utilization.value, 4),
        }

    def journal_summary(self) -> dict:
        return {
            "decoded_tokens": self.decoded_tokens.count,
            "warm_resumes": self.warm_resumes.count,
            "tokens_restored": self.journal_tokens_restored.count,
            "served_from_journal": self.journal_served.count,
            "resume_rejected": self.resume_rejected.count,
        }

    def cache_summary(self) -> dict:
        hits, misses = self.prefix_hits.count, self.prefix_misses.count
        return {
            "hits": hits,
            "misses": misses,
            "hit_rate": (
                round(hits / (hits + misses), 4) if hits + misses else None
            ),
            "prefix_tokens_saved": self.prefix_tokens_saved.count,
            "prefill_tokens": self.prefill_tokens.count,
            "evictions": self.cache_evictions.count,
            "deferrals": self.admission_deferrals.count,
            "fallbacks": self.cache_fallbacks.count,
            "pool_occupancy": round(self.cache_pool_occupancy.value, 3),
            "tier": {
                "demotions": self.radix_demotions.count,
                "promotions": self.radix_promotions.count,
                "hits": self.tier_hits.count,
                "occupancy_bytes": int(self.tier_occupancy_bytes.value),
            },
        }

    def disagg_summary(self) -> dict:
        return {
            "prefill_routed": self.prefill_routed.count,
            "adopted_slots": self.adopted_slots.count,
            "handoffs_published": self.handoffs_published.count,
        }

    def render_prometheus(self, prefix: str = "torchkafka_serve") -> str:
        """Prometheus text exposition — same conventions (and shared
        renderer) as StreamMetrics.render_prometheus."""
        from torchkafka_tpu.utils.metrics import (
            format_labels,
            render_exposition,
        )

        s = self.summary()
        pc = s["prefix_cache"]
        jn = s["journal"]
        cp = s["chunked_prefill"]
        kb = s["kv_backend"]
        return render_exposition(prefix, [
            # The resolved KV backend as an info-style gauge (value 1,
            # identity in the labels) plus the kernel engagement pair —
            # the "which pool actually serves, and why not the kernel"
            # observables.
            ("kv_backend_info", "gauge", [
                (format_labels(
                    layout=str(kb.get("layout", "dense")),
                    kv_dtype=str(kb.get("kv_dtype", "compute")),
                    sharding=f"data={kb.get('data', 1)},tp={kb.get('tp', 1)}",
                ), 1),
            ]),
            ("kv_kernel_engaged", "gauge", kb["kernel_engaged"]),
            ("kv_kernel_disabled_total", "counter", [
                (format_labels(reason=r), v)
                for r, v in kb["kernel_disabled"].items()
            ] or 0),
            ("chunk_ticks_total", "counter", cp["chunk_ticks"]),
            ("admission_stall_ticks_total", "counter", cp["stall_ticks"]),
            ("admission_queue_tokens", "gauge", cp["queue_tokens"]),
            ("chunk_utilization", "gauge", cp["utilization"]),
            ("prefill_tokens_per_chunk_tick", "gauge",
             cp["prefill_tokens_per_tick"] or 0.0),
            ("decoded_tokens_total", "counter", jn["decoded_tokens"]),
            ("warm_resumes_total", "counter", jn["warm_resumes"]),
            ("journal_tokens_restored_total", "counter", jn["tokens_restored"]),
            ("journal_served_total", "counter", jn["served_from_journal"]),
            ("resume_rejected_total", "counter", jn["resume_rejected"]),
            ("completions_total", "counter", s["completions"]),
            ("tokens_total", "counter", s["tokens"]),
            ("truncated_by_eos_total", "counter", s["truncated_by_eos"]),
            ("slot_readmissions_total", "counter", s["readmissions"]),
            ("dropped_prompts_total", "counter", s["dropped"]),
            ("quarantined_prompts_total", "counter", s["quarantined"]),
            ("commit_failures_total", "counter", s["commit_failures"]),
            ("output_flush_failures_total", "counter", s["output_flush_failures"]),
            ("output_send_failures_total", "counter", s["output_send_failures"]),
            ("dlq_delivery_failures_total", "counter", s["dlq_delivery_failures"]),
            ("txn_commits_total", "counter", s["txn"]["commits"]),
            ("txn_aborts_total", "counter", s["txn"]["aborts"]),
            ("txn_held_outputs", "gauge", s["txn"]["held_outputs"]),
            ("commit_latency_p50_milliseconds", "gauge", s["commit"]["p50_ms"]),
            ("commit_latency_p99_milliseconds", "gauge", s["commit"]["p99_ms"]),
            ("completions_per_second", "gauge", s["completions_per_s"]),
            ("tokens_per_second", "gauge", s["tokens_per_s"]),
            ("slot_occupancy", "gauge", s["slot_occupancy"]),
            ("serve_ticks_total", "counter", s["ticks"]),
            ("step_time_ms", "gauge", [
                ('percentile="p50"', s["step_time"]["p50_ms"]),
                ('percentile="p99"', s["step_time"]["p99_ms"]),
            ]),
            ("tokens_per_tick", "gauge", s["tokens_per_tick"]),
            ("output_capped_total", "counter", s["output_capped"]),
            ("tenant_prefix_cache_hits_total", "counter", [
                (format_labels(tenant=t), v["hits"])
                for t, v in s["tenant_cache"].items()
            ] or 0),
            ("tenant_prefix_cache_misses_total", "counter", [
                (format_labels(tenant=t), v["misses"])
                for t, v in s["tenant_cache"].items()
            ] or 0),
            ("prefix_cache_hits_total", "counter", pc["hits"]),
            ("prefix_cache_misses_total", "counter", pc["misses"]),
            ("prefix_tokens_saved_total", "counter", pc["prefix_tokens_saved"]),
            ("prefill_tokens_total", "counter", pc["prefill_tokens"]),
            ("kvcache_evictions_total", "counter", pc["evictions"]),
            ("admission_deferrals_total", "counter", pc["deferrals"]),
            ("kvcache_fallbacks_total", "counter", pc["fallbacks"]),
            ("prefix_cache_hit_rate", "gauge", pc["hit_rate"] or 0.0),
            ("kvcache_pool_occupancy", "gauge", pc["pool_occupancy"]),
            ("radix_demotions_total", "counter", pc["tier"]["demotions"]),
            ("radix_promotions_total", "counter", pc["tier"]["promotions"]),
            ("tier_hits_total", "counter", pc["tier"]["hits"]),
            ("tier_occupancy_bytes", "gauge", pc["tier"]["occupancy_bytes"]),
            ("prefill_routed_total", "counter", s["disagg"]["prefill_routed"]),
            ("adopted_slots_total", "counter", s["disagg"]["adopted_slots"]),
            ("prefill_handoffs_published_total", "counter",
             s["disagg"]["handoffs_published"]),
            ("distill_published_total", "counter",
             s["distill"]["published"]),
            ("distill_steps_total", "counter", s["distill"]["steps"]),
            ("distill_records_total", "counter", s["distill"]["records"]),
            ("spec_alpha_window", "gauge", s["distill"]["alpha_window"]),
            ("draft_version", "gauge", s["distill"]["draft_version"]),
            ("draft_refreshes_total", "counter", [
                (format_labels(reason=r), v)
                for r, v in s["distill"]["refreshes"].items()
            ] or 0),
        ])


def _slot_layer_step(x, layer, cache_k, cache_v, pos_b, cfg):
    """One decode token through one layer with a DIFFERENT position per
    slot. x: [B, 1, D]; caches [B, M, K, Dh]; pos_b: [B]. Only the rope and
    the cache write differ from the lockstep ``generate._layer_step``; the
    attention/MLP tail is the shared ``_attend_cached``. (Sibling:
    spec_decode._multi_step generalizes this to S queries per row —
    update in step if the write/mask discipline changes.)"""
    q, k, v = _project_qkv(x, layer, cfg)
    q = _rope(q, pos_b[:, None], cfg.rope_theta)
    k = _rope(k, pos_b[:, None], cfg.rope_theta)
    # Per-row cache write as a SCATTER (.at[rows, pos].set). History: r4
    # shipped a vmapped dynamic_update_slice here, with a measurement
    # note claiming the masked-select lowering beat scatter ~10x. r5
    # re-measured both isolated (fori-chained slope: scatter 3.2 µs vs
    # select 41 µs per [16, 192, 8, 256] update) and end-to-end (1B
    # serve tick 6.66 → 4.73 ms, +41% tok/s) — the select rewrites the
    # whole pool every layer while the scatter writes one row per slot;
    # the r4 note did not reproduce and is retracted in PERF.md.
    rows = jnp.arange(cache_k.shape[0])
    cache_k = cache_k.at[rows, pos_b].set(k[:, 0].astype(cache_k.dtype))
    cache_v = cache_v.at[rows, pos_b].set(v[:, 0].astype(cache_v.dtype))
    valid = jnp.arange(cache_k.shape[1])[None, :] <= pos_b[:, None]  # [B, M]
    x = _attend_cached(x, q, cache_k, cache_v, valid, layer, cfg)
    return x, cache_k, cache_v


class _PendingPrefill:
    """One admission's queued chunk-prefill work (paged chunked mode).

    The slot and its blocks are already reserved (table linked, radix
    inserted); ``seq`` is the UNCACHED suffix still to be written —
    ``seq[off:]`` remains — with ``seq[0]`` sitting at logical position
    ``start``. ``resume`` carries a journal warm-resume's emitted
    tokens (activation restores state instead of sampling token 0);
    None for a cold admission."""

    __slots__ = ("slot", "rec", "seq", "off", "start", "key_np", "resume",
                 "enq_tick")

    def __init__(self, slot, rec, seq, start, key_np, resume, enq_tick):
        self.slot = slot
        self.rec = rec
        self.seq = seq
        self.off = 0
        self.start = start
        self.key_np = key_np
        self.resume = resume
        self.enq_tick = enq_tick


@dataclasses.dataclass
class PrefillHandoff:
    """One prompt's filled-KV transfer unit (disaggregated prefill).

    A PREFILL worker (``prefill_role=True``) runs the normal chunked-
    prefill machinery to fill a slot's prompt blocks, samples token 0
    in-dispatch with the standard per-record key discipline, then
    extracts this — record identity + payload CRC (so a handoff can
    never adopt onto a different record), the sampling contract, the
    per-record RNG key, token 0, and the raw per-pool payload bytes of
    the ``prompt_blocks`` blocks covering positions [0, prompt_len) —
    and publishes it on the transfer plane (a broker topic;
    fleet/prefill.py owns the wire encoding). A DECODE replica ADOPTS
    it: payloads scattered into freshly linked pool blocks (radix-
    matched prefix blocks skip the upload — they already hold the
    identical bytes), state merged exactly like a 1-token journal warm
    resume — no prompt pass ever runs on the decode replica, and the
    continuation is bitwise the run a monolithic server would produce
    (the chunk machinery's chunk-width invariance is what makes the
    worker's KV bytes equal the local prefill's).

    ``pools``: one host array per device pool tensor, each sliced to
    the prompt's blocks on axis 1 — 2 arrays on compute-dtype pools,
    4 (payload+scale ×2) on int8 pools. The tier/journal sibling of a
    ``JournalEntry``, generalized from crash recovery to routing."""

    topic: str
    partition: int
    offset: int
    crc: int
    key_data: tuple
    temperature: float
    top_k: int | None
    top_p: float | None
    token0: int
    prompt_blocks: int
    pools: tuple

    @property
    def key(self) -> tuple[str, int, int]:
        return (self.topic, self.partition, self.offset)

    def payload_bytes(self) -> int:
        return sum(a.nbytes for a in self.pools)


class _TxnOutboxProducer:
    """The quarantine's producer in exactly-once mode: dead-letter
    produces are STAGED into the server's transactional outbox (keyed by
    the poison record's identity, parsed from the ``dlq.*`` provenance
    headers the quarantine always writes) instead of sent immediately —
    they are produced inside the commit window's transaction, atomic
    with the offset that retires the record. The returned handle
    resolves immediately: in transactional mode durability IS the
    transaction commit, which the commit discipline already gates before
    any offset becomes durable."""

    def __init__(self, server: "StreamingGenerator") -> None:
        self._server = server

    def send(self, topic, value, *, key=None, partition=None,
             timestamp_ms=None, headers=()):
        from torchkafka_tpu.source.producer import (
            RecordMetadata,
            _ResolvedSend,
        )

        h = {k: v for k, v in headers}
        ident = (
            h["dlq.topic"].decode(),
            int(h["dlq.partition"]),
            int(h["dlq.offset"]),
        )
        self._server._txn_outbox[ident] = dict(
            topic=topic, value=value, key=key, headers=tuple(headers),
        )
        return _ResolvedSend(RecordMetadata(topic, -1, -1))

    def flush(self, timeout_s=None) -> None:
        pass  # staged sends settle at transaction commit

    def close(self) -> None:
        pass


def _record_tenant(record: Record) -> str:
    """Tenant = the record key (the rule fleet/qos.py and obs/trace.py
    admit and label by), for the per-tenant cache-locality counters."""
    if record.key is None:
        return "anon"
    try:
        return record.key.decode("utf-8")
    except UnicodeDecodeError:
        return record.key.hex()


class _ShadowConsumer:
    """The canary shadow generator's consumer-shaped null object: it is
    never a group member, never polls, and owns no partitions — so the
    shadow's commit path is structurally a no-op (an empty assignment
    drops every ledger partition from the snapshot) and nothing a shadow
    decodes can reach a broker. See ``StreamingGenerator.spawn_shadow``."""

    def poll(self, max_records: int = 1, timeout_ms: int = 0) -> list:
        return []

    def assignment(self):
        return frozenset()

    def commit(self, offsets) -> None:
        pass

    def heartbeat(self) -> None:
        pass

    def close(self) -> None:
        pass


def _default_decode_prompt(prompt_len: int) -> Callable[[Record], np.ndarray]:
    def decode(record: Record) -> np.ndarray:
        toks = np.frombuffer(record.value, dtype=np.int32)[:prompt_len]
        if toks.shape[0] < prompt_len:
            toks = np.pad(toks, (0, prompt_len - toks.shape[0]))
        return toks

    return decode


class StreamingGenerator:
    """Continuous-batching server over a Kafka-semantics consumer.

    ``run()`` yields ``(record, tokens)`` in COMPLETION order (not offset
    order); each completion retires its record in the ledger, and offsets
    commit every ``commit_every`` completions plus once at the end — so a
    crash re-delivers exactly the prompts whose generations never finished.
    """

    def __init__(
        self,
        consumer,
        params,
        cfg: TransformerConfig,
        *,
        slots: int = 8,
        prompt_len: int,
        max_new: int,
        eos_id: int | None = None,
        commit_every: int = 32,
        decode_prompt: Callable[[Record], np.ndarray] | None = None,
        max_poll_records: int = 512,
        ticks_per_sync: int = 4,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        rng: jax.Array | None = None,
        output_producer=None,
        output_topic: str | None = None,
        exactly_once: bool = False,
        encode_output: Callable[[Record, np.ndarray], bytes] | None = None,
        max_send_failure_streak: int = 64,
        quarantine=None,
        mesh=None,
        kv_dtype: str | None = None,
        kv_kernel: bool | str = "auto",
        kv_pages: PagedKVConfig | dict | None = None,
        kv_tier: TierConfig | dict | None = None,
        prefill_role: bool = False,
        journal: DecodeJournal | None = None,
        tracer=None,
        trace_replica: int | None = None,
        max_new_of: Callable[[Record], int | None] | None = None,
        model_version: int = 0,
        distill_topic: str | None = None,
        distill_producer=None,
    ) -> None:
        """``ticks_per_sync``: decode ticks chained per device dispatch
        (and per host sync of the done mask). Higher amortises dispatch
        latency; the cost is completed slots idling up to K-1 ticks before
        re-admission. 1 = immediate recycling (lowest latency hardware).

        ``temperature``: 0 = greedy (matches ``generate``'s default);
        > 0 samples categorically per slot from logits/temperature.
        ``rng`` is the BASE of a per-record key schedule: each admitted
        record derives ``fold_in(rng, topic/partition/offset)`` once, and
        token i of that record draws with ``fold_in(record_key, i)`` —
        so a record's sampled continuation is a pure function of (base
        key, record identity), independent of slot placement, tick
        interleaving, admission order, or WHICH replica decodes it. That
        independence is what makes journal-based warm failover
        token-exact (torchkafka_tpu/journal) and same-seed fleet runs
        replayable under chaos. ``top_k``/``top_p`` restrict the sampled
        support (top-k threshold then nucleus mass,
        ``models.generate.sample_logits`` — the SAME definition the
        lockstep path uses, static-shape so the tick stays one compiled
        program; ignored at temperature 0, where the filter cannot
        change the argmax).

        ``output_producer``/``output_topic``: publish each completion to a
        topic (key = the prompt record's key; ``encode_output(record,
        tokens) -> bytes``, default int32 token bytes). Sends are async;
        the producer is FLUSHED before every offset commit, and a failed
        flush SKIPS the commit (fail closed) — outputs are durable before
        the prompts that produced them commit, so a crash regenerates
        instead of losing completions (at-least-once end to end; the
        output topic may see duplicates, keyed by the prompt's key).

        ``exactly_once``: the TRANSACTIONAL output mode — pass a
        ``source.producer.TransactionalProducer`` (or any object with
        its begin/send/send_offsets/commit/abort surface; the kafka
        adapter's ``KafkaTransactionalProducer`` qualifies) as
        ``output_producer`` and every commit window becomes ONE broker
        transaction covering that window's completions AND their source
        offsets, Kafka-KIP-98-style. Consequences, each the upgrade of
        an at-least-once behavior above: completions are invisible to
        ``read_committed`` consumers until the window's offsets commit
        WITH them (no more duplicates-on-replay — a crash before commit
        aborts the transaction and the regenerated outputs are the only
        committed copy); a survivable commit failure (rebalance) aborts
        the whole window and this server re-produces, inside the NEXT
        transaction, exactly the window outputs for partitions it still
        owns (departed partitions' records re-serve on their new owner —
        the only committed copy, again); the quarantine's DLQ produce
        rides the same transaction, so poison retirement (DLQ copy +
        offset) is atomic too; and journal-re-served completions are
        produced inside the new incarnation's transaction while the dead
        incarnation's uncommitted transaction was aborted by the epoch
        fence at ``TransactionalProducer`` construction — never
        double-published. A ``ProducerFencedError`` anywhere on this
        path is terminal fail-stop: another incarnation owns this
        replica's transactional id; serving on would be zombie work.
        ``read_uncommitted`` consumers (the default everywhere) observe
        the output topic exactly as before.

        ``mesh``: model-sharded serving (``jax.sharding.Mesh``) — params
        are committed to the training ``param_specs`` layouts (tp/fsdp,
        quantize-aware), the KV slot pool shards kv heads over ``tp`` and
        slots over ``data``, and XLA inserts the megatron collectives.
        This is what serves anything one chip cannot hold (bf16 8B+, long
        KV budgets). Token-exact vs mesh-less serving
        (differential-tested); the multichip dryrun proves the path.

        ``kv_dtype``: None = the compute dtype (token-exact vs
        ``generate``); ``"int8"`` = quantized slot pool (int8 payload +
        per-(position, head) f32 absmax scale, ≈52% of bf16 pool bytes at
        head_dim 128) — the memory headroom that buys more concurrent
        slots at the 8B-class scales (measured: 192 slots run where bf16
        OOMs; with scatter writes equal-slot throughput is neutral-to-
        BETTER than bf16 KV, +7% at 8B/96 slots — see PERF.md), at the
        cost of bounded quantization error (opt-in precisely because
        token-exactness is given up).

        ``kv_kernel``: the Pallas DYNAMIC-LENGTH int8 decode-attention
        kernel (``ops.kvattn.int8_decode_attention_dynlen``) for the
        pool read: per-slot watermarks are scalar-prefetched and only
        positions [0, pos] are DMA'd per slot, so HBM traffic scales
        with each slot's actual fill instead of the pool size —
        inexpressible in XLA, where every read is pool-shaped. Measured
        at 8B shapes, M=2048 (paired, interleaved): 1.92× the XLA read
        at half fill, 1.57× at mixed fills, 0.94× at exactly-full — and
        continuous batching lives at partial fills. In-tick integration
        still costs ~flat ms at short pools, so ``"auto"`` (default)
        engages the kernel only at int8 pools ≥ 1024 tokens (TPU
        backend, tiling shapes, pool tiling at a ≥ 256 block); else the
        XLA read. Composes with ``mesh``: a Pallas call is opaque to
        GSPMD, so the sharded read runs per (data, tp) shard inside
        ``shard_map`` (``ops.kvattn.int8_decode_attention_dynlen_
        sharded``, the ``flash_attention_sharded`` precedent — slots
        over data, kv heads over tp, no collectives), gated by the
        capability probe on the same divisibilities the XLA layouts
        need. ``True``: REQUIRE the kernel at any pool length; raises
        if shapes/mesh can't honor it (so a benchmark never
        misattributes the XLA read's numbers to the kernel; the reason
        is in the error and on ``metrics``); off-TPU it runs in Pallas
        interpret mode — correct but slow, for tests. ``False``: always
        the XLA read. In kernel mode the pool is stored K-major
        ([L, B, K, M, Dh]) so every head's tile is a contiguous slice —
        the layout lesson from the v1 kernel's negative result
        (ops/kvattn.py docstring); note the tick time is then
        FILL-DEPENDENT (see ``decode_roofline``'s ``fill``).

        ``max_send_failure_streak``: a SYNCHRONOUS send failure leaves its
        record uncommitted (the watermark stalls there, it re-delivers on
        restart) but serving continues — a transient output-broker blip
        should not kill the server. After this many CONSECUTIVE sync
        failures the output path is evidently down and every further
        completion is un-committable replay work, so the server fail-stops
        with ``OutputDeliveryError`` — the same signal the flush/get path
        gives for terminal delivery failures (ADVICE r3).

        ``kv_pages``: a ``kvcache.PagedKVConfig`` (or its dict) — the
        PAGED slot pool with radix-tree prefix reuse. The per-slot dense
        cache is replaced by a shared pool of ``num_blocks`` blocks of
        ``block_size`` tokens plus per-slot block tables; admission
        matches each prompt's longest cached whole-block prefix in a
        host-side radix tree (``kvcache.RadixCache``), links the shared
        physical blocks into the slot's table, and prefills ONLY the
        uncached suffix — prompts sharing a tenant/system prefix stop
        re-prefilling it, and pool bytes follow live tokens instead of
        slots × max_context. Token-comparable with the dense path (same
        ``_attend_cached`` math over a gathered view — the cache-on/off
        differential in tests/test_kvcache.py pins greedy + seeded
        sampling + chaos-replay exactness); eviction is ADVISORY (a miss
        just re-prefills). Pool pressure defers admissions (FIFO
        re-offer once blocks free); a pool too small for even one slot
        falls back to dense cache-off serving with a warning
        (``metrics.cache_fallbacks``). Composes with ``mesh`` in
        chunked mode: the block pools shard kv heads over tp and
        replicate over data (shared storage — any slot's table may
        reference any block), per-slot state shards over data, tables
        ride replicated, and the whole admission/radix/chunk machinery
        is mesh-blind host code — token-exact vs single-device serving
        (differential-tested across {data}, {tp}, {data, tp} meshes).
        Not MoE (the paged prefill routes experts densely — decode's
        rule — which would break exactness vs the training-dispatch
        dense prefill), and the LEGACY per-record admission
        (``prefill_chunk=0``) stays single-device (its [1, S] suffix
        prefill has no data shard; both validated with precise
        errors by ``kvcache.resolve_kv_backend``).

        Admission is CHUNKED by default (``prefill_chunk`` on the
        config): instead of one suffix-prefill dispatch per record (the
        PR-4 path, kept at ``prefill_chunk=0``), admission reserves the
        slot + blocks and enqueues the uncached suffix host-side; every
        decode tick then carries a bounded, statically-shaped chunk of
        queued suffix tokens ALONGSIDE all decode slots in the SAME
        jitted program (Sarathi-style — prefill rides the weight stream
        decode already pays for). Consequences: admission compiles O(1)
        programs regardless of suffix-length mix (the per-(suffix,
        start) jit zoo is gone), decode inter-token latency stays one
        tick per token under prompt storms (the chunk bounds prefill
        work per tick; the queue drains FIFO), and per-record outputs
        stay bitwise identical to the dense and per-record paths (each
        chunk query attends exactly [0, position] of its slot's logical
        view — the same math at every chunk width).

        ``kv_dtype="int8"`` composes with ``kv_pages`` (chunked mode):
        the block pools store int8 payloads + group-wise absmax scales
        (``models.quant.quant_kv_groups`` — the same (position, head)
        groups as the dense int8 pool, so int8-paged is token-exact vs
        int8-DENSE serving), ~52% of the compute-dtype pool bytes.
        ``kv_kernel`` then selects the Pallas BLOCK-TABLE read for the
        decode ticks (``ops.kvattn.int8_paged_decode_attention`` — the
        v3 watermark-DMA structure reading through per-slot block
        tables, so HBM traffic scales with live tokens and no gathered
        view is materialised); "auto" engages it on TPU at pools >=
        1024 tokens with tiling shapes, True requires it (raises when
        it cannot be honored), chunk-carrying ticks read via the XLA
        gather either way (the multi-query chunk needs the gathered
        view).

        ``journal``: a ``journal.DecodeJournal`` — record, per in-flight
        slot, the minimal resumable state (record identity + payload CRC,
        sampling params, the per-record RNG key, tokens emitted so far),
        refreshed every ``journal.cadence`` tokens and always at admit
        and finish, written tmp-fsync-rename so a torn write is
        invisible. Paired with ``add_resume_hints`` (the fleet feeds a
        dead replica's journal to survivors): a redelivered prompt with a
        hint is WARM-RESUMED — ``prompt + emitted_tokens`` prefilled in
        one dispatch (a radix hit under ``kv_pages``, a plain longer
        prefill when dense), RNG key and position restored — so the
        continuation is token-exact vs the never-killed run and the
        re-decoded tokens are bounded by the journal cadence; a
        journaled FINISHED completion re-serves with zero re-decode.
        Warm resume of partial generations needs the compute-dtype pool
        (``kv_dtype=None``) and a resume-capable prefill: one device,
        a data-free mesh, or the paged CHUNKED path under any mesh
        (``_resume_supported``); hints are ignored (cold replay, still
        correct) otherwise.

        ``tracer``: an ``obs.RecordTracer`` — per-record lifecycle span
        events (polled → admitted → first token → per-token ticks →
        finished → committed, plus warm-resume/DLQ/deferral branches)
        emitted at every stage boundary this server crosses, keyed by
        the record's (topic, partition, offset) identity;
        ``trace_replica`` tags the events (the fleet sets it per
        replica). None (the default) costs only the per-site ``is not
        None`` guards — measured in benchmarks/bench_obs.py.

        ``quarantine``: a ``resilience.PoisonQuarantine``. Without it, an
        undecodable prompt is retired immediately as dropped (the
        original policy — no durable copy). With it, each decode failure
        spends the record's retry budget (re-attempted in place — a
        transient external-tokenizer fault heals here), and once the
        budget is gone the prompt is dead-lettered with an ACKNOWLEDGED
        produce before its offset retires (``metrics.quarantined``); a
        failed DLQ produce raises ``OutputDeliveryError`` — fail-stop,
        crash-before-commit, so the committed watermark never covers a
        record that is neither served nor durably quarantined.

        ``distill_topic``: publish each completion as a framed training
        record (``distill.wire.encode_completion`` — prompt ids,
        committed tokens, tenant key, model version) for the online
        draft-distillation loop. The frames follow the SAME durability
        discipline as outputs, commit-gated both ways so the training
        corpus only ever contains COMMITTED tokens: under
        ``exactly_once`` they are staged beside the output outbox and
        produced inside the commit window's transaction (atomic with
        outputs + offsets — an aborted window's frames are invisible,
        a zombie's frames are fenced with its transaction); in
        at-least-once mode they are held host-side and produced only
        AFTER the offset commit that covers them succeeds (a crash
        before commit publishes nothing for the re-delivered records —
        the regenerated completions publish instead). A divergent
        canary or a fenced zombie therefore never trains the draft.
        ``distill_producer`` overrides the producer used for the
        at-least-once publish (default: ``output_producer``); in
        transactional mode the frames always ride the transactional
        producer."""
        if prompt_len + max_new > cfg.max_seq_len:
            raise ValueError("prompt_len + max_new exceeds cfg.max_seq_len")
        if max_new < 2:
            raise ValueError("max_new must be >= 2 (prefill emits token 0)")
        if ticks_per_sync < 1:
            raise ValueError("ticks_per_sync must be >= 1")
        self._consumer = consumer
        self._mesh = mesh
        if mesh is not None:
            check_serving_mesh(cfg, mesh, batch=slots)
            params = jax.device_put(params, serving_shardings(cfg, mesh, params))
        self._params = params
        self._cfg = cfg
        self._slots = slots
        self._prompt_len = prompt_len
        self._max_new = max_new
        self._eos_id = eos_id
        self._commit_every = commit_every
        self._decode_prompt = decode_prompt or _default_decode_prompt(prompt_len)
        self._max_poll = max_poll_records
        self._ticks_per_sync = ticks_per_sync
        self._temperature = float(temperature)
        check_sampling_params(top_k, top_p)
        self._top_k = top_k
        self._top_p = top_p
        rng = jax.random.key(0) if rng is None else rng
        if not jax.dtypes.issubdtype(rng.dtype, jax.dtypes.prng_key):
            # Old-style raw uint32 keys: normalize to a typed key so the
            # per-record fold_in/key_data derivation has one spelling.
            rng = jax.random.wrap_key_data(rng)
        self._rng = rng  # per-record key BASE (never split/mutated)
        self._key_width = int(jax.random.key_data(rng).shape[-1])
        if (output_producer is None) != (output_topic is None):
            raise ValueError(
                "output_producer and output_topic must be given together"
            )
        self._output_producer = output_producer
        self._output_topic = output_topic
        if exactly_once:
            if output_producer is None:
                raise ValueError(
                    "exactly_once requires output_producer/output_topic "
                    "(the transaction is the output path)"
                )
            missing = [
                m for m in ("begin", "send_offsets", "commit", "abort")
                if not callable(getattr(output_producer, m, None))
            ]
            if missing:
                raise ValueError(
                    "exactly_once requires a transactional producer "
                    "(source.producer.TransactionalProducer surface); "
                    f"output_producer lacks {missing}"
                )
            if quarantine is not None and (
                getattr(quarantine, "producer", None) is not output_producer
            ):
                raise ValueError(
                    "exactly_once requires the quarantine to share the "
                    "transactional output producer (its DLQ produce must "
                    "ride the same transaction as the offset that retires "
                    "the poison record); build PoisonQuarantine over the "
                    "same TransactionalProducer instance"
                )
        self._txn_mode = exactly_once
        # The transactional OUTBOX: outputs (and DLQ copies) staged by
        # record identity, PRODUCED ONLY AT COMMIT TIME and only for
        # offsets the in-order ledger snapshot covers. Holding sends to
        # the commit point is what makes "outputs + offsets one atomic
        # unit" literally true: an out-of-completion-order output whose
        # offset the watermark cannot yet cover would otherwise commit
        # in one transaction while its record stays redeliverable —
        # the redelivered re-serve then double-publishes. Keyed staging
        # also dedups the eager-rebalance re-serve for free (the second
        # completion overwrites the identical first). Entries survive
        # aborted transactions untouched (the retry re-sends them) and
        # leave only with the committed transaction that covered them.
        self._txn_outbox: dict[tuple[str, int, int], dict] = {}
        # High-water of offsets ALREADY covered by this server's
        # committed transactions. An eager rebalance can hand the server
        # a second copy of a record it fetched before the generation
        # bump (old copy queued, new copy redelivered); if the first
        # copy's window commits before the second copy finishes, the
        # re-serve re-stages the same identity AFTER its covering commit
        # — without this watermark the next window would publish it
        # again. Entries below it are duplicate serves of committed
        # records and are dropped at the commit point.
        self._txn_committed_wm: dict = {}
        if exactly_once and quarantine is not None:
            # Route the DLQ produce into the outbox: the quarantine copy
            # commits atomically WITH the offset that retires the poison
            # record, instead of racing ahead of it.
            quarantine.rebind_producer(_TxnOutboxProducer(self))
        self._encode_output = encode_output or (
            lambda rec, toks: np.asarray(toks, np.int32).tobytes()
        )
        if distill_topic is not None and not exactly_once:
            if distill_producer is None and output_producer is None:
                raise ValueError(
                    "distill_topic requires a producer (distill_producer "
                    "or output_producer) in at-least-once mode"
                )
        self._distill_topic = distill_topic
        self._distill_producer = distill_producer
        # Distill frames staged by record identity. Txn mode: sent inside
        # the commit window's transaction (the outbox discipline). Non-txn
        # mode: held until the offset commit that covers them SUCCEEDS,
        # then produced — commit-gated either way, so the corpus never
        # contains an uncommitted token.
        self._distill_outbox: dict[tuple[str, int, int], bytes] = {}
        if distill_topic is not None:
            from torchkafka_tpu.distill.wire import encode_completion

            self._encode_distill = encode_completion
        else:
            self._encode_distill = None
        if max_send_failure_streak < 1:
            raise ValueError("max_send_failure_streak must be >= 1")
        if kv_pages is not None and isinstance(kv_pages, dict):
            kv_pages = PagedKVConfig(**kv_pages)
        # ``kv_tier``: demote cold radix blocks to a bounded host-RAM
        # store (kvcache/tier.py) instead of freeing them, promote on
        # radix hit — the effective prefix-cache capacity becomes host
        # memory (plus optional disk spill), not pool blocks. Advisory
        # like eviction itself: token-exactness never depends on it.
        if kv_tier is not None and isinstance(kv_tier, dict):
            kv_tier = TierConfig(**kv_tier)
        if kv_tier is not None and kv_pages is None:
            raise ValueError("kv_tier requires kv_pages (it tiers the "
                             "paged radix cache)")
        self._kv_tier_cfg = kv_tier
        self._kv_tier: HostTier | None = None
        # ``prefill_role``: this server is a disaggregated PREFILL
        # worker — it admits prompts through the normal chunked
        # machinery, but the moment a slot's suffix completes (token 0
        # sampled in-dispatch) the slot is HARVESTED into a
        # ``PrefillHandoff`` instead of decoding: the filled prompt
        # blocks' payloads + resume state, for a decode replica to
        # adopt. The record retires in this server's own ledger only
        # when the caller confirms the handoff published
        # (``note_handoff_published``), so a death mid-transfer
        # re-delivers and re-prefills (at-least-once on the handoff
        # plane; the DECODE group's exactly-once story is untouched —
        # it never depends on handoffs existing).
        if prefill_role:
            if kv_pages is None or kv_pages.prefill_chunk == 0:
                raise ValueError(
                    "prefill_role requires kv_pages in chunked mode "
                    "(the handoff is cut from the chunked-prefill "
                    "machinery)"
                )
        self._prefill_role = prefill_role
        self._prefilled_ready: list[tuple[Record, PrefillHandoff]] = []
        # Decode-side handoff shelf: installed via add_prefill_handoffs
        # (the fleet's handoff-topic poller), consumed at admission.
        self._prefill_handoffs: dict[tuple[str, int, int], PrefillHandoff] = {}
        self._adopt_upload_jits: dict[int, Callable] = {}
        self._tier_seen = [0, 0, 0]  # demotions/promotions/hits mirrored
        # ONE capability probe for the whole (pages × dtype × kernel ×
        # mesh) space: validates the genuine exclusions eagerly (bad
        # dtype/kernel values, MoE + pages, legacy per-record admission
        # under int8 or a mesh, un-honorable kv_kernel=True) and raises
        # precise errors. The composed axes — sharded paged pools,
        # sharded kernels — are SUPPORTED now; _build/_build_paged
        # re-resolve against the final pool length for the engagement
        # decision and surface it on ``metrics`` (kv_backend info +
        # kernel_engaged/kernel_disabled).
        resolve_kv_backend(
            cfg, mesh=mesh, kv_dtype=kv_dtype, kv_kernel=kv_kernel,
            kv_pages=kv_pages, max_len=prompt_len + max_new, slots=slots,
            backend=jax.default_backend(),
        )
        self._kv_pages = kv_pages
        self._paged_deferred: list[Record] = []
        # Chunked-prefill host state (paged mode; see _paged_setup).
        # Defined unconditionally so free_slots/has_active/step are
        # mode-blind: a slot is BUSY while reserved-and-prefilling just
        # as while decoding.
        self._prefilling = np.zeros((slots,), bool)
        self._prefill_queue: list[_PendingPrefill] = []
        self._chunked = False
        self._tick_counter = 0
        self._paged_table_idx = 2  # the table's slot in the state tuple
        self._kv_int8 = kv_dtype == "int8"
        self._kv_kernel_opt = kv_kernel
        self._max_send_failure_streak = max_send_failure_streak
        self._send_failure_streak = 0
        self._quarantine = quarantine
        self._pending_outputs: list = []  # send handles since last commit
        self._ledger = OffsetLedger()
        self._max_len = prompt_len + max_new
        self.metrics = ServeMetrics()
        # Slot bookkeeping lives on the instance (not run() locals) so an
        # EXTERNAL admission loop — the serving fleet's QoS scheduler —
        # can drive the server through note_fetched/admit_records/step
        # without the internal poll loop; run() is built on the same
        # surface.
        self._slot_rec: list[Record | None] = [None] * slots
        self._active = np.zeros((slots,), bool)
        self._uncommitted = 0
        self._closed = False
        # Warm failover (torchkafka_tpu/journal): the journal this server
        # WRITES, the hints it may RESUME from, completions re-servable
        # straight from a journal (finished-but-uncommitted), and the
        # host-side per-slot emitted-token mirrors that drive journal
        # cadence and the decoded-token accounting.
        self._journal = journal
        self._tracer = tracer
        self._trace_replica = trace_replica
        # The model version these weights serve as — stamped on every
        # output ("mv" header), every journal entry, and the journal's
        # own meta, so the exactly-once invariant survives a mid-rollout
        # crash: recovery always knows WHICH weights produced what. 0 is
        # the boot checkpoint; swap_params moves it (only between commit
        # windows — see its preconditions).
        self._model_version = int(model_version)
        if journal is not None:
            journal.set_model_version(self._model_version)
        # Per-record output budget: ``max_new_of(record) -> n`` bounds
        # that record's generation to n tokens (clamped to [1, max_new]).
        # Enforced host-side at sync granularity: when a slot's emitted
        # count reaches its budget it is force-finished exactly like a
        # device ``done`` (output truncated to the budget, slot freed,
        # journal finished) — the static tick program never changes, so
        # heavy-tailed per-record output lengths (workload generation,
        # user-requested max_tokens) cost nothing when None.
        self._max_new_of = max_new_of
        self._resume_hints: dict[tuple[str, int, int], JournalEntry] = {}
        self._journal_ready: list[tuple[Record, np.ndarray]] = []
        self._slot_emitted = np.zeros((slots,), np.int64)
        self._slot_journaled = np.zeros((slots,), np.int64)
        # Per-slot RECORD keys (raw key data), merged at admit and read by
        # every tick's sampling — deliberately outside the donated state
        # tuple so state-poking tests/tools see the same tuple shapes.
        self._slot_keys = jnp.zeros((slots, self._key_width), jnp.uint32)
        # Set by _build/_build_paged (the spec subclass's too): the
        # resolved KVBackend this server actually serves with — a paged
        # pool too small for one slot re-resolves as dense here.
        self._kv_backend: KVBackend | None = None
        self._build()
        if self._kv_backend is not None:
            self.metrics.note_backend(self._kv_backend)

    def _build(self) -> None:
        if self._kv_pages is not None and self._paged_setup():
            self._build_paged()
            return
        if self._prefill_role:
            raise ValueError(
                "prefill_role cannot fall back to dense serving — size "
                "kv_pages to hold at least one slot"
            )
        cfg = self._cfg
        B, P, M = self._slots, self._prompt_len, self._max_len
        nl, kh, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        temp = self._temperature
        mesh = self._mesh

        kv_int8 = self._kv_int8
        # The Pallas decode kernels (ops/kvattn.py). Full-tick pairs on
        # v5e, 8B int8 weights, kernel off vs on: short pools LOSE
        # (M=192/B=16 13.0→13.5 ms with scatter writes) — flat
        # integration cost (K-major layout handling + the fusion break
        # around a Pallas call) — while long pools WIN and the win
        # grows with pool bytes (v2 K-major read: M=2048 33.95→27.24
        # ms, +25% tok/s). The engagement decision (incl. the "auto"
        # >= 1024-pool threshold and the per-mesh divisibilities the
        # shard_map wrapping needs) is the capability probe's —
        # kvcache.resolve_kv_backend — so dense and paged builds, and
        # the metrics that surface the decision, share one rule. Under
        # a mesh the kernel runs per (data, tp) shard inside shard_map
        # (ops.kvattn.int8_decode_attention_dynlen_sharded, the
        # flash_attention_sharded precedent); kv_kernel=True raised at
        # construction if the combination cannot be honored.
        self._kv_backend = resolve_kv_backend(
            cfg, mesh=mesh, kv_dtype="int8" if kv_int8 else None,
            kv_kernel=self._kv_kernel_opt, kv_pages=None, max_len=M,
            slots=B, backend=jax.default_backend(),
        )
        kv_kernel = self._kv_backend.kernel
        self._kv_kernel = kv_kernel

        def pin_state(caches, last_tok, pos, gen):
            """Pin the slot state's layouts inside the jitted programs so
            the donate-and-rebind round trip keeps kv heads on tp and
            slots on data, instead of whatever GSPMD first guesses. int8
            pools carry 4D scale tensors [L, B, M, K] between the 5D
            payloads — same axes minus head_dim; kernel mode stores both
            K-MAJOR ([L, B, K, M, ·]), same axes transposed with the
            layout."""
            if mesh is None:
                return caches, last_tok, pos, gen
            if kv_kernel:
                kv = kv_kmajor_sharding(mesh)
                kvs = kv_kmajor_scale_sharding(mesh)
            else:
                kv = kv_sharding(mesh)
                kvs = kv_scale_sharding(mesh)
            row = slot_sharding(mesh)
            return (
                tuple(
                    lax.with_sharding_constraint(c, kv if c.ndim == 5 else kvs)
                    for c in caches
                ),
                lax.with_sharding_constraint(last_tok, row),
                lax.with_sharding_constraint(pos, row),
                lax.with_sharding_constraint(gen, slot_sharding(mesh, 2)),
            )

        pick_rows = functools.partial(
            _pick_slots, temperature=temp, top_k=self._top_k,
            top_p=self._top_p,
        )

        def admit(params, caches, last_tok, pos, gen, prompts, admit_mask,
                  keys):
            """Prefill the full [B, P] prompt batch; merge admitted rows in.
            prompts: [B, P] int32; admit_mask: [B] bool; keys: [B, W]
            uint32 per-record key data (token 0 draws at index 0)."""
            caches, last_tok, pos, gen = pin_state(caches, last_tok, pos, gen)
            logits, fresh = prefill(params, cfg, prompts, M, mesh)
            sel = admit_mask[None, :, None, None, None]  # over [L, B, M, K, Dh]
            if kv_int8:
                fkq, fks = _quant_kv(fresh.k)
                fvq, fvs = _quant_kv(fresh.v)
                if kv_kernel:
                    # Kernel mode stores the pool K-major: transpose the
                    # freshly-quantized [L, B, M, K, ·] prefill capture
                    # once per admit (bytes ∝ one pool sweep; the per-tick
                    # read path this layout accelerates runs max_new times
                    # per admit).
                    fkq, fvq = (jnp.swapaxes(a, 2, 3) for a in (fkq, fvq))
                    fks, fvs = (jnp.swapaxes(a, 2, 3) for a in (fks, fvs))
                    sel4 = admit_mask[None, :, None, None]  # [L, B, K, M]
                else:
                    sel4 = admit_mask[None, :, None, None]  # [L, B, M, K]
                caches = (
                    jnp.where(sel, fkq, caches[0]),
                    jnp.where(sel4, fks, caches[1]),
                    jnp.where(sel, fvq, caches[2]),
                    jnp.where(sel4, fvs, caches[3]),
                )
            else:
                caches = (
                    jnp.where(sel, fresh.k, caches[0]),
                    jnp.where(sel, fresh.v, caches[1]),
                )
            tok0 = pick_rows(logits, keys, jnp.zeros((B,), jnp.int32))  # [B]
            last_tok = jnp.where(admit_mask, tok0, last_tok)
            pos = jnp.where(admit_mask, P, pos)
            gen = jnp.where(admit_mask[:, None], 0, gen)
            gen = gen.at[:, 0].set(jnp.where(admit_mask, tok0, gen[:, 0]))
            return caches, last_tok, pos, gen

        K = self._ticks_per_sync

        def tick_block(params, caches, last_tok, pos, gen, active_in, skey):
            """K chained decode ticks in ONE dispatch (static K), with a
            LATCHED done mask: a slot that completes at inner tick j is
            masked out of ticks j+1..K, so its output cannot be clobbered.
            One host sync per K tokens — per-token syncing costs a full
            host↔device round trip per generated token, which is the whole
            serving budget on high-latency transports. ``skey``: [B, W]
            uint32 per-slot RECORD keys; tick t of slot b draws at fold
            index ``pos_b - P + 1`` (token 0 was the admit draw), so the
            sampled stream is a pure function of (record, index) — the
            warm-failover exactness contract."""
            caches, last_tok, pos, gen = pin_state(caches, last_tok, pos, gen)

            def one(carry, _):
                caches, last_tok, pos, gen, done_latch, n_out = carry
                act = active_in & ~done_latch
                x = embed_rows(params["embed"], last_tok, cfg.dtype)[:, None, :]

                if kv_int8:
                    def body(x, inputs):
                        layer, ckq, cks, cvq, cvs = inputs
                        x, ckq, cks, cvq, cvs = _slot_layer_step_q(
                            x, layer, ckq, cks, cvq, cvs, pos, cfg,
                            use_kernel=kv_kernel, mesh=mesh,
                        )
                        return x, (ckq, cks, cvq, cvs)
                else:
                    def body(x, inputs):
                        layer, ck, cv = inputs
                        x, ck, cv = _slot_layer_step(x, layer, ck, cv, pos, cfg)
                        return x, (ck, cv)

                x, new_caches = lax.scan(
                    body, x, (params["layers"], *caches)
                )
                caches = new_caches
                x = _rms_norm(x, params["ln_f"])
                logits = jnp.einsum(
                    "bd,dv->bv", x[:, 0], load_weight(params["lm_head"], cfg.dtype),
                    preferred_element_type=jnp.float32,
                )
                tok = pick_rows(logits, skey, pos - P + 1)
                # Inactive slots write stale kv at their frozen position —
                # safe: re-admission overwrites [0, P) via prefill and every
                # later position is rewritten by the tick that reaches it
                # BEFORE the attention that could read it. Freezing the
                # caches with a jnp.where would copy the pool every token.
                t = pos - P  # decode ticks completed before this one
                idx = jnp.minimum(t + 1, self._max_new - 1)
                # One-hot select over the tiny [B, max_new] buffer.
                # (r4 claimed scatter cost ~2 ms here; r5 re-measured
                # both spellings at parity within noise — 5.36 vs 5.34
                # ms 1B tick — so this stays only because it is
                # equivalent, unlike the POOL writes where scatter wins
                # big, see _slot_layer_step.)
                onehot = jnp.arange(self._max_new)[None, :] == idx[:, None]
                gen = jnp.where(onehot & act[:, None], tok[:, None], gen)
                hit_eos = (
                    (tok == self._eos_id) if self._eos_id is not None
                    else jnp.zeros_like(act)
                )
                # Tokens after this tick = t + 2 (prefill's token 0 plus
                # t+1 decode outputs); complete on EOS or a full buffer.
                done_now = act & (hit_eos | (t + 2 >= self._max_new))
                pos = jnp.where(act & ~done_now, pos + 1, pos)
                last_tok = jnp.where(act, tok, last_tok)
                n_out = jnp.where(
                    done_now, jnp.minimum(t + 2, self._max_new), n_out
                )
                done_latch = done_latch | done_now
                return (caches, last_tok, pos, gen, done_latch, n_out), None

            done0 = jnp.zeros((B,), bool)
            n0 = jnp.zeros((B,), jnp.int32)
            (caches, last_tok, pos, gen, done, n_out), _ = lax.scan(
                one, (caches, last_tok, pos, gen, done0, n0), None, length=K
            )
            return caches, last_tok, pos, gen, done, n_out

        def resume_admit(params, caches, last_tok, pos, gen, seq, slot,
                         emitted_row, g):
            """Warm-resume ONE slot from a journal hint: prefill ``seq``
            (= prompt + the g journaled tokens minus the last — position
            P+g-1 is rewritten by the next tick's own write-before-attend
            anyway) into the slot's cache row in one dispatch, and restore
            the position/last-token/gen-buffer state the no-kill run would
            hold. seq: [1, S] with S = P + g - 1; slot/g: scalars;
            emitted_row: [max_new] (journaled tokens, zero-padded — zeros
            beyond g match a fresh admit's cleared buffer)."""
            caches, last_tok, pos, gen = pin_state(caches, last_tok, pos, gen)
            _logits, fresh = prefill(params, cfg, seq, M, mesh)
            caches = (
                lax.dynamic_update_slice(
                    caches[0], fresh.k.astype(caches[0].dtype),
                    (0, slot, 0, 0, 0),
                ),
                lax.dynamic_update_slice(
                    caches[1], fresh.v.astype(caches[1].dtype),
                    (0, slot, 0, 0, 0),
                ),
            )
            last_tok = last_tok.at[slot].set(emitted_row[g - 1])
            pos = pos.at[slot].set(P + g - 1)
            gen = lax.dynamic_update_slice(gen, emitted_row[None, :], (slot, 0))
            return caches, last_tok, pos, gen

        # Donate the cache pool: admit/tick rebuild it every call, and
        # without donation each dispatch copies the full [L, B, M, K, Dh]
        # pair. The run loop rebinds the returned buffers immediately.
        # Params travel as an ARGUMENT, not a closure: a closed-over param
        # tree lowers as jaxpr constants, and at zoo scale (2.5-8 GB) that
        # bloats lowering/compile memory and ships the weights inside the
        # program instead of referencing the resident device buffers.
        _admit = jax.jit(admit, donate_argnums=(1,))
        _tick = jax.jit(tick_block, donate_argnums=(1,))
        # Raw (un-jitted) body for decode_roofline's fori-chained windows.
        self._tick_block_raw = tick_block
        self._admit_fn = lambda *a: _admit(self._params, *a)
        self._tick_fn = lambda *a: _tick(self._params, *a)
        if kv_int8:
            # int8 pools deliberately give up token-exactness, the one
            # contract warm resume exists to keep; hints are filtered out
            # in _take_hint, so no resume program is built.
            self._resume_exec = None
        else:
            _resume = jax.jit(resume_admit, donate_argnums=(1,))
            self._resume_exec = lambda *a: _resume(self._params, *a)
        if kv_int8 and kv_kernel:
            # K-major pool for the Pallas read (see _slot_layer_step_q).
            self._caches = (
                jnp.zeros((nl, B, kh, M, dh), jnp.int8),
                jnp.zeros((nl, B, kh, M), jnp.float32),
                jnp.zeros((nl, B, kh, M, dh), jnp.int8),
                jnp.zeros((nl, B, kh, M), jnp.float32),
            )
        elif kv_int8:
            self._caches = (
                jnp.zeros((nl, B, M, kh, dh), jnp.int8),
                jnp.zeros((nl, B, M, kh), jnp.float32),
                jnp.zeros((nl, B, M, kh, dh), jnp.int8),
                jnp.zeros((nl, B, M, kh), jnp.float32),
            )
        else:
            self._caches = (
                jnp.zeros((nl, B, M, kh, dh), cfg.dtype),
                jnp.zeros((nl, B, M, kh, dh), cfg.dtype),
            )
        self._last_tok = jnp.zeros((B,), jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._gen = jnp.zeros((B, self._max_new), jnp.int32)
        if mesh is not None:
            # Place the initial pool in its serving layout so the first
            # dispatch doesn't start from replicated buffers.
            if kv_kernel:
                kv = kv_kmajor_sharding(mesh)
                kvs = kv_kmajor_scale_sharding(mesh)
            else:
                kv = kv_sharding(mesh)
                kvs = kv_scale_sharding(mesh)
            row = slot_sharding(mesh)
            self._caches = tuple(
                jax.device_put(c, kv if c.ndim == 5 else kvs)
                for c in self._caches
            )
            self._last_tok = jax.device_put(self._last_tok, row)
            self._pos = jax.device_put(self._pos, row)
            self._gen = jax.device_put(self._gen, slot_sharding(mesh, 2))

    # ------------------------------------------------------ paged slot pool
    #
    # kv_pages mode (torchkafka_tpu/kvcache): the dense per-slot cache
    # [L, B, M, K, Dh] becomes a SHARED block pool [L, NB, bs, K, Dh] plus
    # per-slot block tables [B, nblk]. Device shapes stay fully static (the
    # XLA discipline); the dynamic part — which physical block backs which
    # logical position — lives host-side in the allocator/radix pair. The
    # table rides INSIDE the donated state tuple (returned unchanged by the
    # tick) so every dispatch signature matches the dense path and
    # decode_roofline/warmup/step need no special plumbing.

    def _paged_setup(self) -> bool:
        """Host-side paging state; False = pool too small for even ONE
        slot's worst case → graceful cache-off fallback (dense build)."""
        pages = self._kv_pages
        nblk = pages.blocks_per_slot(self._max_len)
        if pages.num_blocks - 1 < nblk:
            _logger.warning(
                "kv_pages pool (%d usable blocks of %d tokens) cannot hold "
                "one slot's %d-token worst case (%d blocks); falling back "
                "to dense cache-off serving",
                pages.num_blocks - 1, pages.block_size, self._max_len, nblk,
            )
            self.metrics.cache_fallbacks.add(1)
            self._kv_pages = None
            return False
        self._blocks_per_slot = nblk
        self._kv_alloc = BlockAllocator(pages.num_blocks)
        if self._kv_tier_cfg is not None:
            self._kv_tier = HostTier(self._kv_tier_cfg)
            self._kv_radix = RadixCache(
                self._kv_alloc, pages.block_size, tier=self._kv_tier,
                read_block=self._tier_read_block,
                write_block=self._tier_write_block,
            )
        else:
            self._kv_radix = RadixCache(self._kv_alloc, pages.block_size)
        self._table_np = np.zeros((self._slots, nblk), np.int32)  # all sink
        self._paged_prefill_jits: dict[tuple[int, int], Callable] = {}
        # Chunked admission (the default; prefill_chunk=0 keeps the
        # legacy per-record dispatch). The auto width covers every
        # admission one serving quantum can offer (<= slots records,
        # <= prompt_len uncached tokens each) so default-config
        # admissions complete their prefill in the single next tick —
        # CAPPED at 256 rows: past that the fused pass's per-chunk-row
        # gather dominates the tick (each chunk row materialises its
        # slot's whole logical view per layer), and a long-prompt storm
        # is exactly where bounded per-tick prefill work is the point.
        self._chunked = pages.prefill_chunk != 0
        self._prefill_chunk = pages.prefill_chunk or min(
            self._slots * self._prompt_len, max(256, 2 * pages.block_size)
        )
        self._prefill_queue = []
        self._prefilling = np.zeros((self._slots,), bool)
        self._tick_counter = 0
        return True

    def _build_paged(self) -> None:
        from torchkafka_tpu.ops.kvattn import (
            block_table_attention,
            block_table_attention_q8,
            int8_paged_decode_attention,
            int8_paged_decode_attention_sharded,
            paged_scatter_kmajor,
        )
        from torchkafka_tpu.models.quant import quant_kv_groups

        cfg = self._cfg
        B, P = self._slots, self._prompt_len
        bs = self._kv_pages.block_size
        NB = self._kv_pages.num_blocks
        nblk = self._blocks_per_slot
        nl, kh, dh = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        temp = self._temperature
        mesh = self._mesh
        kv_int8 = self._kv_int8
        self._paged_table_idx = 4 if kv_int8 else 2

        # Pallas BLOCK-TABLE read (ops/kvattn.py v4): the v3 watermark-
        # DMA kernel reading through per-slot block tables, int8 pools
        # only. Decode-only ticks read through it; chunk-carrying ticks
        # use the XLA gather (the multi-query chunk needs the gathered
        # view, and a storm tick is prefill-dominated anyway). The
        # engagement decision is the shared capability probe's ("auto"
        # only in the measured-win regime — TPU, long pools; True =
        # require-or-raise, validated at construction); under a mesh
        # the read runs per (data, tp) shard inside shard_map
        # (int8_paged_decode_attention_sharded) with the block pools
        # replicated over data and sharded per-block over tp.
        self._kv_backend = resolve_kv_backend(
            cfg, mesh=mesh, kv_dtype="int8" if kv_int8 else None,
            kv_kernel=self._kv_kernel_opt, kv_pages=self._kv_pages,
            max_len=self._max_len, slots=B, backend=jax.default_backend(),
        )
        kv_kernel = self._kv_backend.kernel
        self._kv_kernel = kv_kernel

        pick_rows = functools.partial(
            _pick_slots, temperature=temp, top_k=self._top_k,
            top_p=self._top_p,
        )

        def pull_replicated(x):
            """Constrain a per-slot operand to REPLICATED before the
            chunk tick's concatenation with the (replicated) chunk
            rows — belt to pin_paged's braces (see its docstring for
            why the paged path must keep the data axis out of the
            program on jax 0.4.x)."""
            if mesh is None:
                return x
            from jax.sharding import NamedSharding, PartitionSpec as P

            return lax.with_sharding_constraint(
                x, NamedSharding(mesh, P())
            )

        def pin_paged(pools, last_tok, pos, gen):
            """The paged pin_state: under a mesh, block pools carry kv
            heads over tp and stay REPLICATED over data (shared storage
            — any slot's table may reference any block, so there is no
            slot axis to split), and the per-slot vectors ride
            REPLICATED too. The latter is load-bearing, not a missing
            optimization: on jax 0.4.x, a paged program whose [B]
            state is sharded over data under a multi-axis mesh
            MISCOMPILES at the chunk tick's sharded-with-replicated
            concatenation — wrong VALUES (~O(1) garbage in every chunk
            row's pool write on {data,tp}/{data,fsdp} meshes; exact on
            single-axis meshes; reproduced standalone). Keeping the
            data axis out of the paged program entirely is the
            invariant that is provably exact; tp still shards the kv
            heads and every weight matrix — the actual HBM win — and
            data-parallel serving remains the FLEET's axis (one
            replica per device group). The dense pool keeps its
            slots-over-data layout. Identity on one device."""
            if mesh is None:
                return pools, last_tok, pos, gen
            from jax.sharding import NamedSharding, PartitionSpec as P

            if kv_int8:
                pp = paged_pool_kmajor_sharding(mesh)
                ps = paged_scale_kmajor_sharding(mesh)
            else:
                pp = paged_pool_sharding(mesh)
                ps = None  # compute-dtype pools are all 5D payloads
            rep = NamedSharding(mesh, P())
            return (
                tuple(
                    lax.with_sharding_constraint(c, pp if c.ndim == 5 else ps)
                    for c in pools
                ),
                lax.with_sharding_constraint(last_tok, rep),
                lax.with_sharding_constraint(pos, rep),
                lax.with_sharding_constraint(gen, rep),
            )

        def layer_pass(params, x, positions, tables, pools, *,
                       decode_kernel=False, pos_b=None):
            """All layers' write-then-attend over the paged pool(s) for
            a batch of query rows. x: [Bq, S, D]; positions: [Bq, S];
            tables: [Bq, nblk] PER-ROW block tables — decode rows carry
            the slot table, chunk rows their own freshly-linked rows, so
            one call serves any mix. int8 pools ride as a 4-tuple
            (payload+scale, K-major-per-block); ``decode_kernel`` reads
            through the Pallas block-table kernel at watermarks
            ``pos_b`` (S=1 rows only)."""

            def body(x, inputs):
                layer = inputs[0]
                q, k, v = _project_qkv(x, layer, cfg)
                q = _rope(q, positions, cfg.rope_theta)
                k = _rope(k, positions, cfg.rope_theta)
                if kv_int8:
                    pkq, pks, pvq, pvs = inputs[1:]
                    if decode_kernel:
                        kq, ks = quant_kv_groups(k)
                        vq, vs = quant_kv_groups(v)
                        pkq = paged_scatter_kmajor(pkq, tables, positions, kq)
                        pks = paged_scatter_kmajor(pks, tables, positions, ks)
                        pvq = paged_scatter_kmajor(pvq, tables, positions, vq)
                        pvs = paged_scatter_kmajor(pvs, tables, positions, vs)
                        if mesh is not None:
                            attn = int8_paged_decode_attention_sharded(
                                q, pkq, pks, pvq, pvs, tables, pos_b, mesh
                            )
                        else:
                            attn = int8_paged_decode_attention(
                                q, pkq, pks, pvq, pvs, tables, pos_b
                            )
                        x = _attn_tail(x, attn, layer, cfg)
                    else:
                        x, pkq, pks, pvq, pvs = block_table_attention_q8(
                            x, q, k, v, pkq, pks, pvq, pvs, tables,
                            positions, layer, cfg,
                        )
                    return x, (pkq, pks, pvq, pvs)
                pk, pv = inputs[1:]
                x, pk, pv = block_table_attention(
                    x, q, k, v, pk, pv, tables, positions, layer, cfg
                )
                return x, (pk, pv)

            return lax.scan(body, x, (params["layers"],) + tuple(pools))

        def logits_head(params, x_last):
            return jnp.einsum(
                "bd,dv->bv", x_last, load_weight(params["lm_head"], cfg.dtype),
                preferred_element_type=jnp.float32,
            )

        def suffix_prefill(params, pool_k, pool_v, table_row, toks, *, start):
            """Chunked prefill of ONE slot's uncached prompt suffix.

            toks: [1, S] (S = prompt_len - matched tokens); queries sit at
            positions [start, start + S) and attend over the cached
            prefix (gathered from the shared blocks the radix match
            linked) plus themselves, causally — a miss (start=0) is a
            plain full prefill. Per-S jit specialisations are cached
            (at most prompt_len // block_size + 1 of them). Returns the
            last position's logits (token 0 sampling) + updated pools."""
            s = toks.shape[1]
            x = embed_rows(params["embed"], toks, cfg.dtype)  # [1, S, D]
            positions = (start + jnp.arange(s))[None, :]  # [1, S]

            def body(x, inputs):
                layer, pk, pv = inputs
                q, k, v = _project_qkv(x, layer, cfg)
                q = _rope(q, positions, cfg.rope_theta)
                k = _rope(k, positions, cfg.rope_theta)
                x, pk, pv = block_table_attention(
                    x, q, k, v, pk, pv, table_row, positions, layer, cfg
                )
                return x, (pk, pv)

            x, (pool_k, pool_v) = lax.scan(
                body, x, (params["layers"], pool_k, pool_v)
            )
            x = _rms_norm(x, params["ln_f"])
            logits = jnp.einsum(
                "bd,dv->bv", x[:, -1],
                load_weight(params["lm_head"], cfg.dtype),
                preferred_element_type=jnp.float32,
            )
            return logits, pool_k, pool_v

        self._paged_suffix_fn = suffix_prefill

        def admit_merge(last_tok, pos, gen, logits, admit_mask, keys):
            """The dense admit's sampling/bookkeeping tail over host-
            assembled per-slot logits rows: same [B, V] pick, same
            per-record key discipline (index 0), so cache-on token 0
            matches the dense server's bitwise."""
            tok0 = pick_rows(
                logits, keys, jnp.zeros((logits.shape[0],), jnp.int32)
            )
            last_tok = jnp.where(admit_mask, tok0, last_tok)
            pos = jnp.where(admit_mask, P, pos)
            gen = jnp.where(admit_mask[:, None], 0, gen)
            gen = gen.at[:, 0].set(jnp.where(admit_mask, tok0, gen[:, 0]))
            return last_tok, pos, gen

        self._paged_merge = jax.jit(admit_merge)

        K = self._ticks_per_sync
        ti = self._paged_table_idx

        def decode_bookkeep(logits, skey, act, last_tok, pos, gen,
                            done_latch, n_out):
            """The decode tick's sampling/EOS/position bookkeeping over
            per-slot logits — identical to the dense tick body's tail
            (see the dense ``tick_block`` for the measured rationale on
            the one-hot gen write)."""
            tok = pick_rows(logits, skey, pos - P + 1)
            t = pos - P  # decode ticks completed before this one
            idx = jnp.minimum(t + 1, self._max_new - 1)
            onehot = jnp.arange(self._max_new)[None, :] == idx[:, None]
            gen = jnp.where(onehot & act[:, None], tok[:, None], gen)
            hit_eos = (
                (tok == self._eos_id) if self._eos_id is not None
                else jnp.zeros_like(act)
            )
            done_now = act & (hit_eos | (t + 2 >= self._max_new))
            pos = jnp.where(act & ~done_now, pos + 1, pos)
            last_tok = jnp.where(act, tok, last_tok)
            n_out = jnp.where(
                done_now, jnp.minimum(t + 2, self._max_new), n_out
            )
            done_latch = done_latch | done_now
            return last_tok, pos, gen, done_latch, n_out

        def decode_one(params, pools, table, carry):
            """One decode tick over the paged pool: the dense tick body
            with the block-table scatter/gather (or the Pallas block-
            table read when the kernel is engaged). Inactive slots still
            write their frozen position — their DEVICE table rows point
            at the sink (idle AND still-prefilling slots; see
            _device_table), so the write can never corrupt a block
            another slot holds (pinned by the stale-tail regression in
            tests/test_kvcache.py)."""
            last_tok, pos, gen, done_latch, n_out, active_in, skey = carry
            act = active_in & ~done_latch
            x = embed_rows(params["embed"], last_tok, cfg.dtype)[:, None, :]
            x, pools = layer_pass(
                params, x, pos[:, None], table, pools,
                decode_kernel=kv_kernel, pos_b=pos,
            )
            x = _rms_norm(x, params["ln_f"])
            logits = logits_head(params, x[:, 0])
            last_tok, pos, gen, done_latch, n_out = decode_bookkeep(
                logits, skey, act, last_tok, pos, gen, done_latch, n_out
            )
            return pools, (
                last_tok, pos, gen, done_latch, n_out, active_in, skey,
            )

        def tick_block(params, caches, last_tok, pos, gen, active_in, skey):
            """K decode-only ticks in ONE dispatch — the dense
            tick_block's K-chained latched-done structure over the paged
            pool. The table passes through the donated state
            unchanged."""
            pools, table = caches[:ti], caches[ti]
            pools, last_tok, pos, gen = pin_paged(pools, last_tok, pos, gen)
            done0 = jnp.zeros((B,), bool)
            n0 = jnp.zeros((B,), jnp.int32)

            def one(carry, _):
                pools, rest = carry
                pools, rest = decode_one(params, pools, table, rest)
                return (pools, rest), None

            (pools, rest), _ = lax.scan(
                one,
                (tuple(pools), (last_tok, pos, gen, done0, n0, active_in,
                                skey)),
                None, length=K,
            )
            last_tok, pos, gen, done, n_out = rest[:5]
            return (
                tuple(pools) + (table,) + caches[ti + 1:],
                last_tok, pos, gen, done, n_out,
            )

        C = self._prefill_chunk

        def tick_chunk_block(params, caches, last_tok, pos, gen, active_in,
                             skey, ctok, ctable, cpos, fin_mask, fin_row):
            """THE fused tick: one static program carrying a bounded
            prefill chunk alongside all decode slots. The first inner
            tick concatenates the B decode rows with the C chunk rows
            into ONE [B + C]-row layer sweep — every weight tensor is
            read once for both workloads (the Sarathi property: prefill
            rides the stream decode already pays for); the remaining
            K - 1 ticks are decode-only. Each chunk row is one suffix
            token (ctok) of a reserved-but-prefilling slot, writing at
            its logical position (cpos) through its OWN table row
            (ctable — the device-state table masks prefilling slots to
            the sink, so only the chunk rows can touch their freshly
            linked blocks), attending causally over exactly
            [0, position] of its slot's view — bitwise the same math as
            the dense prefill at any chunk width. Padding rows carry
            all-sink tables (writes land harmlessly; their logits are
            ignored host-side). Returns the chunk rows' logits so the
            host can sample token 0 for admissions whose suffix
            completed this tick.

            ACTIVATION rides the same dispatch: ``fin_mask``/``fin_row``
            [B] mark slots whose LAST suffix token sits at chunk row
            ``fin_row[b]`` — after the decode ticks, token 0 is sampled
            from that row's logits (index-0 per-record-key draw, the
            same merge math as the dense admit, so sampling parity is
            bitwise) and the slot's last-token/position/gen state is
            merged in, ready to decode NEXT dispatch. Cold-admission
            activation therefore costs ZERO extra dispatches; only the
            rare journal warm-resume restores state host-side."""
            pools, table = caches[:ti], caches[ti]
            pools, last_tok, pos, gen = pin_paged(pools, last_tok, pos, gen)
            done0 = jnp.zeros((B,), bool)
            n0 = jnp.zeros((B,), jnp.int32)
            act = active_in
            toks_all = jnp.concatenate([pull_replicated(last_tok), ctok])
            x = embed_rows(params["embed"], toks_all, cfg.dtype)[:, None, :]
            tables_all = jnp.concatenate(
                [pull_replicated(table), ctable], axis=0
            )
            pos_all = jnp.concatenate([pull_replicated(pos), cpos])
            x, pools = layer_pass(
                params, x, pos_all[:, None], tables_all, tuple(pools)
            )
            x = _rms_norm(x, params["ln_f"])
            logits_all = logits_head(params, x[:, 0])  # [B + C, V]
            chunk_logits = logits_all[B:]
            last_tok, pos, gen, done, n_out = decode_bookkeep(
                logits_all[:B], skey, act, last_tok, pos, gen, done0, n0
            )

            def one(carry, _):
                pools, rest = carry
                pools, rest = decode_one(params, pools, table, rest)
                return (pools, rest), None

            (pools, rest), _ = lax.scan(
                one,
                (tuple(pools), (last_tok, pos, gen, done, n_out, active_in,
                                skey)),
                None, length=K - 1,
            )
            last_tok, pos, gen, done, n_out = rest[:5]
            tok0 = pick_rows(
                chunk_logits[fin_row], skey, jnp.zeros((B,), jnp.int32)
            )
            last_tok = jnp.where(fin_mask, tok0, last_tok)
            pos = jnp.where(fin_mask, P, pos)
            gen = jnp.where(fin_mask[:, None], 0, gen)
            gen = gen.at[:, 0].set(jnp.where(fin_mask, tok0, gen[:, 0]))
            return (
                tuple(pools) + (table,) + caches[ti + 1:],
                last_tok, pos, gen, done, n_out,
            )

        _tick = jax.jit(tick_block, donate_argnums=(1,))
        self._tick_jit = _tick
        self._tick_block_raw = tick_block
        self._tick_fn = lambda *a: _tick(self._params, *a)
        if self._chunked:
            _tick_chunk = jax.jit(tick_chunk_block, donate_argnums=(1,))
            self._tick_chunk_jit = _tick_chunk
            self._tick_chunk_fn = lambda *a: _tick_chunk(self._params, *a)
        else:
            self._tick_chunk_fn = None
        self._admit_fn = None  # paged admission is host-orchestrated
        self._resume_exec = None  # paged resume rides the chunk/suffix path
        # _table_np.copy(): jnp.asarray may ZERO-COPY an aligned host
        # buffer on the CPU backend; admissions mutate _table_np in
        # place, which would rewrite this device table from under the
        # tick (prefilling slots lose their sink mask and idle
        # frozen-pos writes corrupt freshly linked blocks).
        if kv_int8:
            self._caches = (
                jnp.zeros((nl, NB, kh, bs, dh), jnp.int8),
                jnp.zeros((nl, NB, kh, bs), jnp.float32),
                jnp.zeros((nl, NB, kh, bs, dh), jnp.int8),
                jnp.zeros((nl, NB, kh, bs), jnp.float32),
                jnp.asarray(self._table_np.copy()),
            )
        else:
            self._caches = (
                jnp.zeros((nl, NB, bs, kh, dh), cfg.dtype),
                jnp.zeros((nl, NB, bs, kh, dh), cfg.dtype),
                jnp.asarray(self._table_np.copy()),
            )
        self._last_tok = jnp.zeros((B,), jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._gen = jnp.zeros((B, self._max_new), jnp.int32)
        if mesh is not None:
            # Place the initial pools/state in their serving layouts so
            # the first dispatch doesn't start from single-device
            # buffers. Per-slot state is REPLICATED — the paged program
            # must keep the data axis out entirely (pin_paged's
            # docstring; sharding it miscompiles on jax 0.4.x) — and
            # the table stays a replicated host snapshot (rebuilt by
            # every admission/retirement).
            from jax.sharding import NamedSharding, PartitionSpec as PSpec

            if kv_int8:
                pp = paged_pool_kmajor_sharding(mesh)
                ps = paged_scale_kmajor_sharding(mesh)
            else:
                pp = paged_pool_sharding(mesh)
                ps = None
            self._caches = tuple(
                jax.device_put(c, pp if c.ndim == 5 else ps)
                for c in self._caches[:self._paged_table_idx]
            ) + self._caches[self._paged_table_idx:]
            rep = NamedSharding(mesh, PSpec())
            self._last_tok = jax.device_put(self._last_tok, rep)
            self._pos = jax.device_put(self._pos, rep)
            self._gen = jax.device_put(self._gen, rep)

    def _paged_prefill_call(self, caches, table_row, toks, *,
                            total_len: int | None = None):
        """Dispatch the per-(suffix, start)-jitted suffix prefill; returns
        (logits [1, V], caches with the pools rebound). ``total_len``: the
        full sequence being prefilled (default prompt_len; a journal warm
        resume prefills prompt + emitted tokens, so its queries start at
        ``total_len - S``). Overridden by the spec server to prefill both
        model pools."""
        s = int(toks.shape[1])
        start = (total_len or self._prompt_len) - s
        fn = self._paged_prefill_jits.get((s, start))
        if fn is None:
            fn = jax.jit(
                functools.partial(self._paged_suffix_fn, start=start),
                donate_argnums=(1, 2),
            )
            self._paged_prefill_jits[(s, start)] = fn
        with xprof.span(xprof.SPAN_ADMIT):
            logits, pool_k, pool_v = fn(
                self._params, caches[0], caches[1], table_row, toks
            )
        return logits, (pool_k, pool_v) + caches[2:]

    def _paged_set_table(self, caches, table_dev):
        """Rebind the device block table inside the state tuple (the
        table's slot in the tuple differs by pool mode — after the 2
        compute-dtype pools, the 4 int8 pools, or the spec server's 4
        two-model pools)."""
        i = self._paged_table_idx
        return caches[:i] + (table_dev,) + caches[i + 1:]

    def _device_table(self) -> jax.Array:
        """The block table the DEVICE state carries. In chunked mode the
        rows of reserved-but-still-prefilling slots are masked to the
        sink: an inactive decode row still writes its frozen position
        unconditionally, and that write must never land in the freshly
        linked blocks the chunk rows are filling (the chunk rows carry
        their REAL rows separately, as the ctable operand)."""
        if self._chunked:
            t = np.where(
                self._active[:, None], self._table_np, SINK_BLOCK
            ).astype(np.int32)
            return jnp.asarray(t)
        # .copy(): jnp.asarray may ZERO-COPY an aligned host buffer on
        # the CPU backend, and _table_np is mutated in place by later
        # admissions/releases — the device table must be a snapshot,
        # never a live view (alignment-dependent corruption otherwise).
        return jnp.asarray(self._table_np.copy())

    def _release_slot_blocks(self, i: int) -> None:
        """Drop a retired slot's references; its table row falls back to
        the sink so the tick's frozen-position write lands harmlessly."""
        row = [int(b) for b in self._table_np[i] if b != SINK_BLOCK]
        if row:
            self._kv_alloc.decref(row)
        self._table_np[i, :] = SINK_BLOCK

    # ------------------------------------------------ tiered radix cache
    #
    # The host-RAM tier's pool I/O (kv_tier=): RadixCache calls these to
    # DEMOTE an evicted block's payload to host memory and to PROMOTE a
    # tier hit back into a fresh block. One payload = the per-pool
    # tensors at one block index (2 on compute-dtype pools, 4 on int8);
    # the bytes round-trip exactly, so a promotion is bitwise the
    # re-prefill it replaces.

    def _tier_read_block(self, block: int) -> tuple:
        ti = self._paged_table_idx
        return tuple(
            np.asarray(jax.device_get(p[:, block]))
            for p in self._caches[:ti]
        )

    def _tier_write_block(self, block: int, payload) -> None:
        fn = getattr(self, "_tier_write_jit", None)
        if fn is None:
            def write(pools, b, pay):
                return tuple(
                    p.at[:, b].set(q.astype(p.dtype))
                    for p, q in zip(pools, pay)
                )

            fn = jax.jit(write, donate_argnums=(0,))
            self._tier_write_jit = fn
        ti = self._paged_table_idx
        pools = fn(
            self._caches[:ti], jnp.int32(block),
            tuple(jnp.asarray(a) for a in payload),
        )
        self._caches = tuple(pools) + self._caches[ti:]

    def _sync_tier_metrics(self) -> None:
        """Mirror the radix/tier counters onto ServeMetrics (the radix
        owns the source of truth; deltas keep re-syncs idempotent)."""
        if self._kv_tier is None:
            return
        r = self._kv_radix
        sd, sp, sh = self._tier_seen
        if r.demotions > sd:
            self.metrics.radix_demotions.add(r.demotions - sd)
        if r.promotions > sp:
            self.metrics.radix_promotions.add(r.promotions - sp)
        if r.tier_hits > sh:
            self.metrics.tier_hits.add(r.tier_hits - sh)
        self._tier_seen = [r.demotions, r.promotions, r.tier_hits]
        self.metrics.tier_occupancy_bytes.set(
            float(self._kv_tier.occupancy_bytes)
        )

    # --------------------------------------------- disaggregated prefill
    #
    # Prefill side (prefill_role=True): completed suffix prefills are
    # harvested into PrefillHandoff units instead of decoding — the
    # slot's prompt-block payloads + resume state, for the fleet's
    # transfer plane (fleet/prefill.py). Decode side: handoffs installed
    # via add_prefill_handoffs are adopted at admission — payload
    # scattered into fresh blocks, token 0 merged like a 1-token warm
    # resume, no prompt pass.

    def _prompt_block_count(self) -> int:
        """Blocks covering positions [0, prompt_len): the straddling
        final block included (its tail past prompt_len is garbage the
        write-before-attend discipline never reads)."""
        return (self._prompt_len - 1) // self._kv_pages.block_size + 1

    def _extract_prompt_blocks(self, slot: int) -> tuple[int, tuple]:
        nb_p = self._prompt_block_count()
        ids = jnp.asarray(self._table_np[slot, :nb_p].astype(np.int32))
        ti = self._paged_table_idx
        return nb_p, tuple(
            np.asarray(jax.device_get(p[:, ids]))
            for p in self._caches[:ti]
        )

    def _harvest_prefilled(self, finishers) -> None:
        """Prefill-role epilogue of a chunk tick: every slot whose
        suffix completed this tick (token 0 already sampled in-dispatch
        by the fin merge — the standard per-record key draw) is cut
        into a handoff and released; nothing ever decodes here."""
        last = np.asarray(jax.device_get(self._last_tok))
        released = False
        for e, _row_idx in finishers:
            i = e.slot
            rec = self._slot_rec[i]
            if rec is None or not self._active[i]:
                continue
            nb_p, pools = self._extract_prompt_blocks(i)
            hand = PrefillHandoff(
                rec.topic, rec.partition, rec.offset, value_crc(rec.value),
                tuple(int(x) for x in np.asarray(e.key_np).ravel()),
                self._temperature, self._top_k, self._top_p,
                int(last[i]), nb_p, pools,
            )
            self._prefilled_ready.append((rec, hand))
            self._active[i] = False
            self._slot_rec[i] = None
            self._slot_emitted[i] = 0
            self._slot_journaled[i] = 0
            self._release_slot_blocks(i)
            released = True
        if released:
            self._caches = self._paged_set_table(
                self._caches, self._device_table()
            )
            self.metrics.cache_pool_occupancy.set(self._kv_alloc.occupancy())

    def take_prefilled(self) -> list[tuple[Record, PrefillHandoff]]:
        """Pop the harvested handoffs (prefill role). The caller
        publishes each onto the transfer plane and then confirms with
        ``note_handoff_published`` — only that retires the record in
        this worker's ledger, so a death between harvest and publish
        re-delivers the prompt to the next prefill incarnation."""
        ready, self._prefilled_ready = self._prefilled_ready, []
        return ready

    def note_handoff_published(self, rec: Record, blocks: int = 0) -> None:
        """The handoff for ``rec`` is durably on the transfer plane:
        retire the record in the prefill group's ledger."""
        self.metrics.handoffs_published.add(1)
        if self._tracer is not None:
            self._tracer.prefill_handoff(
                rec, blocks, replica=self._trace_replica
            )
        self._ledger.emitted(rec)
        self._uncommitted += 1

    def add_prefill_handoffs(self, entries: dict) -> None:
        """Install decoded ``PrefillHandoff`` units keyed by (topic,
        partition, offset). Consumed when the record is next offered for
        admission; CRC/contract-gated at adoption, so a stale or foreign
        handoff can never corrupt a slot (it just falls back to a local
        prefill)."""
        self._prefill_handoffs.update(entries)

    def has_prefill_handoff(self, key: tuple[str, int, int]) -> bool:
        """Routing probe (fleet/prefill.py's PrefillRouter): is a
        handoff ready for this record identity?"""
        return key in self._prefill_handoffs

    def _take_handoff(self, rec: Record) -> "PrefillHandoff | None":
        """Pop and validate ``rec``'s handoff; None = prefill locally
        (the at-least-once fallback every disaggregated path keeps)."""
        if self._kv_pages is None or not self._chunked:
            return None
        hand = self._prefill_handoffs.pop(
            (rec.topic, rec.partition, rec.offset), None
        )
        if hand is None:
            return None
        ti = self._paged_table_idx
        nb_p = self._prompt_block_count()
        ok = (
            hand.crc == value_crc(rec.value)
            and hand.temperature == self._temperature
            and hand.top_k == self._top_k
            and hand.top_p == self._top_p
            and hand.prompt_blocks == nb_p
            and len(hand.pools) == ti
        )
        if ok:
            for a, p in zip(hand.pools, self._caches[:ti]):
                if (
                    tuple(a.shape) != (p.shape[0], nb_p) + tuple(p.shape[2:])
                    or a.dtype != np.dtype(p.dtype)
                ):
                    ok = False
                    break
        if not ok:
            self.metrics.resume_rejected.add(1)
            return None
        return hand

    def _adopt_upload(self, block_ids: list[int], payloads: tuple) -> None:
        """Scatter an adopted handoff's payload blocks into the pool
        (one jit specialisation per upload width, bounded by the prompt
        block count)."""
        n = len(block_ids)
        fn = self._adopt_upload_jits.get(n)
        if fn is None:
            def write(pools, ids, pay):
                return tuple(
                    p.at[:, ids].set(q.astype(p.dtype))
                    for p, q in zip(pools, pay)
                )

            fn = jax.jit(write, donate_argnums=(0,))
            self._adopt_upload_jits[n] = fn
        ti = self._paged_table_idx
        pools = fn(
            self._caches[:ti],
            jnp.asarray(np.asarray(block_ids, np.int32)),
            tuple(jnp.asarray(a) for a in payloads),
        )
        self._caches = tuple(pools) + self._caches[ti:]

    def _pack_chunk(self):
        """Fill the static chunk operands from the FIFO prefill queue:
        up to ``prefill_chunk`` suffix tokens, taken strictly in queue
        order (the ordering the radix-insert-at-admit safety argument
        stands on), each row carrying its token, logical position, and
        its slot's REAL table row. Padding rows point at the sink.
        Returns (ctok, ctable, cpos, fin_mask, fin_row, packed,
        finishers) — finishers are (entry, last_row_index) for
        admissions whose suffix completes in this chunk; cold finishers
        additionally mark ``fin_mask``/``fin_row`` so the fused program
        samples token 0 and merges the activation state IN-DISPATCH
        (journal resumes restore state host-side instead)."""
        C = self._prefill_chunk
        B = self._slots
        nblk = self._blocks_per_slot
        ctok = np.zeros((C,), np.int32)
        cpos = np.zeros((C,), np.int32)
        ctable = np.full((C, nblk), SINK_BLOCK, np.int32)
        fin_mask = np.zeros((B,), bool)
        fin_row = np.zeros((B,), np.int32)
        finishers: list[tuple[_PendingPrefill, int]] = []
        packed = 0
        while packed < C and self._prefill_queue:
            e = self._prefill_queue[0]
            n = min(C - packed, len(e.seq) - e.off)
            if e.off == 0 and self._tracer is not None:
                # First suffix tokens riding a fused tick for this record.
                self._tracer.chunk_scheduled(
                    e.rec, replica=self._trace_replica
                )
            ctok[packed:packed + n] = e.seq[e.off:e.off + n]
            cpos[packed:packed + n] = e.start + e.off + np.arange(n)
            ctable[packed:packed + n] = self._table_np[e.slot]
            e.off += n
            packed += n
            if e.off == len(e.seq):
                finishers.append((e, packed - 1))
                if e.resume is None:
                    fin_mask[e.slot] = True
                    fin_row[e.slot] = packed - 1
                self._prefill_queue.pop(0)
        return ctok, ctable, cpos, fin_mask, fin_row, packed, finishers

    def _activate_chunk_finishers(self, finishers) -> None:
        """Host bookkeeping for slots whose suffix prefill completed
        this tick: flip them active (their first decode tick is the
        NEXT dispatch — the in-program fin merge already sampled token
        0 for cold admissions), restore journal warm-resume state
        (rare; host-side), and push the device table so the newly
        active rows unmask from the sink."""
        B = self._slots
        res_mask = np.zeros((B,), bool)
        res_last = np.zeros((B,), np.int32)
        res_pos = np.zeros((B,), np.int32)
        res_gen = np.zeros((B, self._max_new), np.int32)
        for e, _row_idx in finishers:
            self._prefilling[e.slot] = False
            self._active[e.slot] = True
            if self._tracer is not None:
                # Token 0 was sampled in the activating dispatch (cold) or
                # restored from the journal (warm): TTFT closes here.
                self._tracer.slot_active(
                    e.rec, replica=self._trace_replica,
                    warm=e.resume is not None,
                )
            # Extra ticks spent queued beyond the one-tick minimum — 0
            # when the admission's whole suffix rode the first chunk.
            self.metrics.admission_stall_ticks.add(
                max(0, self._tick_counter - e.enq_tick - 1)
            )
            if e.resume is not None:
                emitted = e.resume
                res_mask[e.slot] = True
                res_last[e.slot] = emitted[-1]
                res_pos[e.slot] = self._prompt_len + len(emitted) - 1
                res_gen[e.slot, : len(emitted)] = emitted
        if res_mask.any():
            m = jnp.asarray(res_mask)
            self._last_tok = jnp.where(
                m, jnp.asarray(res_last), self._last_tok
            )
            self._pos = jnp.where(m, jnp.asarray(res_pos), self._pos)
            self._gen = jnp.where(
                m[:, None], jnp.asarray(res_gen), self._gen
            )
        self._caches = self._paged_set_table(
            self._caches, self._device_table()
        )

    @property
    def pending_admissions(self) -> int:
        """Records accepted by ``admit_records`` but deferred on block-pool
        pressure — they re-offer FIRST (per-partition FIFO) as blocks
        free. Callers subtract this from ``free_slots()`` when sizing new
        offers, and keep calling ``admit_records([])`` while it is
        nonzero so the backlog drains."""
        return len(self._paged_deferred)

    def _admit_records_paged(self, records: list[Record]) -> int:
        """Paged admission: per record — radix longest-prefix match, link
        the shared blocks, allocate private blocks (LRU-evicting
        unreferenced cached prefixes under pressure), then hand the
        uncached suffix to the PREFILL path. Sequential per record so a
        duplicate prompt inside one batch hits its predecessor's freshly
        inserted prefix.

        CHUNKED mode (the default): the slot is reserved and the suffix
        ENQUEUED — the decode tick's fused program processes it a
        bounded chunk at a time (step → _pack_chunk), and the slot
        activates (token 0 sampled with the same per-record-key
        discipline, or journal state restored) the tick its last suffix
        token lands. Admission itself dispatches NOTHING and compiles
        nothing: O(1) programs across any suffix-length mix. The radix
        insert happens here, at reservation time — a later admission
        matching these still-being-filled blocks is safe because the
        chunk queue is strictly FIFO, so the matched positions are
        always written in an earlier (or the same, write-before-attend)
        dispatch than any query that attends over them.

        LEGACY mode (``prefill_chunk=0``, the PR-4 baseline): one
        suffix-prefill dispatch per record (a jit specialisation per
        (suffix, start) pair), ending with the same [B, V] sampling
        merge as the dense admit.

        A record carrying a journal resume hint prefills
        ``prompt + emitted_tokens`` instead (the cached prompt prefix
        still radix-hits) and restores position/RNG state host-side — no
        token 0 to sample; a FINISHED hint consumes no slot at all (the
        completion re-serves from the journal at the next step)."""
        phys_free = [
            i for i in range(self._slots)
            if not self._active[i] and not self._prefilling[i]
        ]
        if len(records) + len(self._paged_deferred) > len(phys_free):
            raise ValueError(
                f"offered {len(records)} records with "
                f"{len(phys_free) - len(self._paged_deferred)} admission "
                "slots (free slots minus deferred admissions)"
            )
        in_flight = self._slots - len(phys_free)
        was_deferred = len(self._paged_deferred)
        queue = self._paged_deferred + list(records)
        self._paged_deferred = []
        bs = self._kv_pages.block_size
        nblk = self._blocks_per_slot
        B, W = self._slots, self._key_width
        admit_mask = np.zeros((B,), bool)
        keys_np = np.zeros((B, W), np.uint32)
        key_mask = np.zeros((B,), bool)
        slot_ids: list[int] = []
        logits_rows: list = []
        resumed: list[tuple[int, np.ndarray]] = []
        adopted: list[tuple[int, np.ndarray]] = []
        reserved = 0  # chunked-mode reservations (prefill enqueued)
        journal_dirty = False
        # NOTE: no local alias of self._caches here — tier demotions/
        # promotions inside radix.match/evict rebind self._caches
        # mid-loop, and an alias taken before the loop would clobber
        # them at the end.
        slot_iter = iter(phys_free)
        while True:
            nxt = self._next_decodable(queue)
            if nxt is None:
                break
            rec, toks = nxt
            toks = np.asarray(toks, np.int32)
            kd = self._record_key_data(rec)
            hint = self._take_hint(rec)
            hand = self._take_handoff(rec) if hint is None else None
            if hint is not None and hint.finished:
                out = np.asarray(hint.tokens, np.int32)
                self._journal_ready.append((rec, out))
                self.metrics.journal_served.add(1)
                if self._tracer is not None:
                    self._tracer.journal_served(
                        rec, len(out), replica=self._trace_replica
                    )
                if self._journal is not None:
                    self._journal_record(rec, hint.key_data or kd, out, True)
                    journal_dirty = True
                continue
            i = next(slot_iter, None)
            if i is None:
                # Unreachable under the caller contract (records <= free
                # slots, finished hints consume none) — fail loudly
                # rather than silently dropping a record.
                raise RuntimeError("paged admission ran out of free slots")
            emitted = (
                np.asarray(hint.tokens, np.int32) if hint is not None
                else None
            )
            seq = (
                toks if emitted is None
                else np.concatenate([toks, emitted[:-1]])
            )
            matched = self._kv_radix.match(seq)
            needed = nblk - len(matched)
            short = needed - self._kv_alloc.available()
            if short > 0:
                evicted = self._kv_radix.evict(short)
                if evicted:
                    self.metrics.cache_evictions.add(evicted)
            priv = self._kv_alloc.alloc(needed)
            if priv is None:
                # Every free block is pinned by in-flight slots: DEFER.
                # Blocks free as generations retire; deferred records
                # re-offer first, keeping per-partition FIFO (the
                # replay-free-drain invariant). The one-slot worst case
                # always fits (constructor fallback guards it), so this
                # is pressure, never deadlock. A resume hint goes back on
                # the shelf with its record.
                if matched:
                    self._kv_alloc.decref(matched)
                if hint is not None:
                    self._resume_hints[
                        (rec.topic, rec.partition, rec.offset)
                    ] = hint
                if hand is not None:
                    # Back on the shelf: the deferred re-offer re-adopts.
                    self._prefill_handoffs[
                        (rec.topic, rec.partition, rec.offset)
                    ] = hand
                if self._tracer is not None:
                    self._tracer.deferred(rec, replica=self._trace_replica)
                self._paged_deferred.append(rec)
                self._paged_deferred.extend(queue)
                queue = []
                break
            row = matched + priv
            self._table_np[i, :] = row
            if hand is not None:
                # ADOPTION: the prefill worker already computed this
                # prompt's KV — scatter the uncached blocks' payloads in
                # (radix-matched blocks already hold the identical
                # bytes) and activate with the handoff's token 0, merged
                # exactly like a 1-token journal warm resume. No prompt
                # pass runs on this replica, at any chunk width.
                nb_p = hand.prompt_blocks
                up = row[len(matched):nb_p]
                if up:
                    self._adopt_upload(up, tuple(
                        a[:, len(matched):nb_p] for a in hand.pools
                    ))
                # Payload uploaded, slot not yet active, record not yet
                # in any ledger snapshot: death here re-delivers and
                # re-adopts (or re-prefills) byte-identically.
                crash_hook("decode_adopt_pre_activate")
                cacheable = RadixCache.matchable_blocks(len(toks), bs)
                self._kv_radix.insert(toks, row[:cacheable])
                self._slot_rec[i] = rec
                key_np = (
                    np.asarray(hand.key_data, np.uint32)
                    if hand.key_data else kd
                )
                keys_np[i] = key_np
                key_mask[i] = True
                self._active[i] = True
                self._slot_emitted[i] = 1
                self._slot_journaled[i] = 1
                adopted.append((i, np.asarray([hand.token0], np.int32)))
                self.metrics.adopted_slots.add(1)
                if self._tracer is not None:
                    self._tracer.adopted(rec, replica=self._trace_replica)
                if self._journal is not None:
                    self._journal_record(rec, key_np, (hand.token0,), False)
                    journal_dirty = True
                continue
            start = len(matched) * bs
            # Register the PROMPT's matchable whole blocks for reuse
            # (existing nodes are the ones we just matched; new nodes
            # adopt this slot's freshly linked private blocks — in
            # chunked mode still being FILLED, safe by chunk-queue FIFO:
            # see the method docstring). Emitted-token blocks are never
            # cached: offsets are unique, so they could only ever match
            # their own redelivery.
            cacheable = RadixCache.matchable_blocks(len(toks), bs)
            self._kv_radix.insert(toks, row[:cacheable])
            tenant = _record_tenant(rec)
            if matched:
                self.metrics.prefix_hits.add(1)
                self.metrics.tenant_prefix_hits(tenant).add(1)
                self.metrics.prefix_tokens_saved.add(start)
            else:
                self.metrics.prefix_misses.add(1)
                self.metrics.tenant_prefix_misses(tenant).add(1)
            self._slot_rec[i] = rec
            key_np = (
                np.asarray(hint.key_data, np.uint32)
                if hint is not None and hint.key_data is not None else kd
            )
            keys_np[i] = key_np
            key_mask[i] = True
            if hint is None:
                self._slot_emitted[i] = 0
                self._slot_journaled[i] = 0
                if self._journal is not None:
                    self._journal_record(rec, kd, (), False)
                    journal_dirty = True
            else:
                self._slot_emitted[i] = len(emitted)
                self._slot_journaled[i] = len(emitted)
                self.metrics.warm_resumes.add(1)
                self.metrics.journal_tokens_restored.add(len(emitted))
                if self._tracer is not None:
                    self._tracer.warm_resumed(
                        rec, len(emitted), replica=self._trace_replica
                    )
                if self._journal is not None:
                    self._journal_record(rec, key_np, emitted, False)
                    journal_dirty = True
            if self._chunked:
                # Reserve, enqueue, dispatch nothing: the tick's fused
                # program prefills this suffix chunk by chunk and the
                # slot activates the tick its last token lands.
                self._prefilling[i] = True
                self._prefill_queue.append(_PendingPrefill(
                    i, rec, np.asarray(seq[start:], np.int32), start,
                    key_np, emitted, self._tick_counter,
                ))
                if self._tracer is not None:
                    self._tracer.prefill_queued(
                        rec, len(seq) - start, replica=self._trace_replica
                    )
                reserved += 1
                continue
            # LEGACY: one suffix-prefill dispatch per record (a jit
            # specialisation per suffix length) + the batched merge.
            self.metrics.prefill_tokens.add(len(seq) - start)
            self._active[i] = True
            table_row = jnp.asarray(self._table_np[i][None, :].copy())
            logits, self._caches = self._paged_prefill_call(
                self._caches, table_row, jnp.asarray(seq[None, start:]),
                total_len=len(seq),
            )
            if hint is None:
                admit_mask[i] = True
                slot_ids.append(i)
                logits_rows.append(logits)
            else:
                resumed.append((i, emitted))
        if queue:  # defensive: slots exhausted with records left
            self._paged_deferred.extend(queue)
        # Count records ENTERING the deferred state, not retry spins: the
        # run/pump loops re-offer the backlog every quantum under
        # pressure, which must not inflate the counter.
        newly_deferred = len(self._paged_deferred) - was_deferred
        if newly_deferred > 0:
            self.metrics.admission_deferrals.add(newly_deferred)
        self.metrics.cache_pool_occupancy.set(self._kv_alloc.occupancy())
        self._sync_tier_metrics()
        admitted = int(admit_mask.sum())
        filled = admitted + len(resumed) + len(adopted) + reserved
        if filled:
            if in_flight > 0:
                self.metrics.readmissions.add(filled)
            if not self._chunked or adopted:
                # Chunked reservations push nothing: the device table
                # keeps prefilling rows masked to the sink until
                # activation (_device_table). Adopted slots activate NOW
                # — their rows must unmask this push.
                self._caches = self._paged_set_table(
                    self._caches, self._device_table()
                )
            self._slot_keys = jnp.where(
                jnp.asarray(key_mask)[:, None], jnp.asarray(keys_np),
                self._slot_keys,
            )
        if admitted:
            logits_b = jnp.zeros(
                (self._slots, self._cfg.vocab_size), jnp.float32
            ).at[jnp.asarray(slot_ids)].set(
                jnp.concatenate(logits_rows, axis=0)
            )
            self._last_tok, self._pos, self._gen = self._paged_merge(
                self._last_tok, self._pos, self._gen, logits_b,
                jnp.asarray(admit_mask), jnp.asarray(keys_np),
            )
            if self._tracer is not None:
                for i in slot_ids:
                    self._tracer.slot_active(
                        self._slot_rec[i], replica=self._trace_replica
                    )
        if resumed or adopted:
            res_mask = np.zeros((B,), bool)
            res_last = np.zeros((B,), np.int32)
            res_pos = np.zeros((B,), np.int32)
            res_gen = np.zeros((B, self._max_new), np.int32)
            for i, emitted in resumed + adopted:
                res_mask[i] = True
                res_last[i] = emitted[-1]
                # An adoption restores exactly one emitted token (the
                # handoff's admit draw) — the g=1 warm-resume state.
                res_pos[i] = self._prompt_len + len(emitted) - 1
                res_gen[i, : len(emitted)] = emitted
            m = jnp.asarray(res_mask)
            self._last_tok = jnp.where(
                m, jnp.asarray(res_last), self._last_tok
            )
            self._pos = jnp.where(m, jnp.asarray(res_pos), self._pos)
            self._gen = jnp.where(
                m[:, None], jnp.asarray(res_gen), self._gen
            )
            if self._tracer is not None:
                for i, _emitted in resumed:
                    self._tracer.slot_active(
                        self._slot_rec[i], replica=self._trace_replica,
                        warm=True,
                    )
                for i, _emitted in adopted:
                    # Adoption's first token genuinely exists now: TTFT
                    # closes here (not warm — nothing predates the poll).
                    self._tracer.slot_active(
                        self._slot_rec[i], replica=self._trace_replica,
                    )
        if journal_dirty:
            self._journal.flush()
        return filled

    def decode_roofline(
        self, *, iters: int = 8, windows: int = 3,
        peak_hbm_gbs: float = V5E_PEAK_HBM_GBS, fill: str = "mid",
    ) -> dict:
        """Pure DEVICE decode speed with HBM-bandwidth roofline accounting.

        Decode is weight/KV-streaming bound: every tick reads the full
        parameter set plus the slot KV pool for one token per slot. This
        measures the decode tick program alone, as the SLOPE between two
        window lengths (``iters`` and 3×``iters`` tick blocks chained
        INSIDE one jitted ``fori_loop``, fenced by one scalar fetch): ONE
        dispatch per window — which the slope then cancels exactly. A
        Python loop of jitted calls here would only amortise the
        per-dispatch host cost (~overhead/K per tick), so in host-bound
        regimes (small models, high per-call RPC latency) it reports the
        host dispatch rate while slope_ok stays True — the exact failure
        mode ``device_step_seconds``' fori-chaining exists to avoid
        (ADVICE r4). Reports achieved bytes/s against the chip's peak
        (v5e: ~819 GB/s), the serving analog of training's MFU. The gap
        between the run loop's end-to-end tokens/s and this number is
        host/tunnel/admission overhead; the gap between this and 100%
        roofline is the program's own inefficiency.

        Slot positions are saved and RESTORED around the probe (the
        'mid' fill pins them, and the probe ticks advance them either
        way); the probe still writes probe kv/tokens through the real
        tick program, so call it while no generations are in flight for
        full state safety. With the dynamic-length kernel engaged, the
        per-tick KV bytes are scaled by the measured fill fraction
        (``kv_read_bytes``) — the kernel only reads live positions, and
        pool-shaped accounting could report >100% of physical peak."""
        cfg = self._cfg
        B, K = self._slots, self._ticks_per_sync
        active = jnp.ones((B,), bool)
        key = self._slot_keys  # per-slot record-key data, [B, W] uint32
        tick_block = self._tick_block_raw
        # ``fill``: the slot positions the measurement starts from. With
        # the dynamic-length kernel the tick reads only [0, pos] per
        # slot, so tick time is FILL-DEPENDENT and measuring from empty
        # pools (pos=0) would overstate throughput. "mid" (default)
        # pins every slot to the steady-state midpoint (prompt +
        # max_new/2); "live" keeps whatever state the server is in
        # (the pre-v3 behavior — fill-independent paths measure the
        # same either way, within noise).
        if fill not in ("mid", "live"):
            raise ValueError(f"fill must be 'mid' or 'live', got {fill!r}")
        # The probe ticks advance (and 'mid' first overwrites) self._pos;
        # without restoring it, a probe taken mid-serving would leave every
        # in-flight slot at a fabricated position and corrupt its remaining
        # generation (ADVICE r5 #2). Restored in the finally below. NOTE
        # the probe still runs real ticks: it writes probe kv/tokens into
        # the pool and gen buffer, so for full safety call it while no
        # generations are in flight (scenario 7 probes after warmup,
        # before serving) — the pos restore makes the IDLE case exact and
        # bounds the damage in the in-flight case.
        pos_saved = self._pos
        if fill == "mid":
            target = min(
                self._prompt_len + self._max_new // 2, self._max_len - 1
            )
            self._pos = jnp.full((B,), target, jnp.int32)
        # The fill the window ACTUALLY measures: positions advance one
        # per tick inside a K-tick block (re-pinned only between blocks)
        # until the done latch freezes them at prompt + max_new - 2, so
        # with a large ticks_per_sync the block's mean fill sits above
        # the pinned start. Report the analytic per-tick mean, not the
        # start value.
        cap = self._prompt_len + self._max_new - 2
        start = np.asarray(self._pos)
        per_tick = np.minimum(start[None, :] + np.arange(K)[:, None], cap)
        measured_fill = float((per_tick + 1).mean()) / self._max_len

        # n is a TRACED loop bound: one compile serves both window lengths.
        # The cache pool is DONATED like the serving tick's dispatch: at
        # the 8B-class scales this path exists for, an un-donated window
        # would hold input + output pools at once (multiple GB) and could
        # OOM mid-benchmark.
        pin_fill = fill == "mid"
        pos0 = self._pos

        @functools.partial(jax.jit, donate_argnums=(2,))
        def run(n, params, caches, last_tok, pos, gen):
            def body(_, carry):
                caches, last_tok, pos, gen = carry
                caches, last_tok, pos, gen, _done, _n_out = tick_block(
                    params, caches, last_tok, pos, gen, active, key
                )
                if pin_fill:
                    # Constant-fill measurement: ticks advance (and then
                    # done-latch-freeze) positions, which would drift the
                    # fill toward pool-full across a long window; re-pin
                    # between tick blocks so a fill-dependent read (the
                    # dynamic-length kernel) is measured AT the stated
                    # fill (drift within one K-tick block only).
                    pos = pos0
                return (caches, last_tok, pos, gen)

            out = lax.fori_loop(0, n, body, (caches, last_tok, pos, gen))
            # Scalar fence transitively dependent on every iteration.
            return out, out[1].ravel()[0]

        # Rebind self state after EVERY window: an exception mid-
        # measurement (a transport blip on the tunneled targets this
        # exists for) must not leave the server holding stale buffers.
        def window(n_dispatches: int) -> float:
            t0 = time.perf_counter()
            out, fence = run(
                n_dispatches, self._params, self._caches, self._last_tok,
                self._pos, self._gen,
            )
            self._caches, self._last_tok, self._pos, self._gen = out
            int(np.asarray(jax.device_get(fence)))  # completion proof
            return time.perf_counter() - t0

        from torchkafka_tpu.utils.timing import two_point_slope

        try:
            window(1)  # warm (compile + route)
            # INTERLEAVED short/long windows: grouping all shorts before all
            # longs lets a drifting transport flip the slope's sign.
            shorts, longs = [], []
            for _ in range(windows):
                shorts.append(window(iters))
                longs.append(window(3 * iters))
        finally:
            # Probe over (or died mid-window): put the real per-slot
            # positions back — pos is never donated, so the saved handle
            # is still alive.
            self._pos = pos_saved
        t_short, t_long = float(np.median(shorts)), float(np.median(longs))
        tick_s, overhead_s, slope_ok = two_point_slope(
            t_short, t_long, iters * K, 3 * iters * K
        )
        overhead_ms = overhead_s * 1e3
        w_bytes, kv_bytes = decode_tick_bytes(
            self._params, cfg, B, self._max_len, kv_int8=self._kv_int8
        )
        # The v3 dynamic-length kernel DMAs only [0, pos] per slot, so the
        # KV bytes a tick actually READS scale with the measured fill —
        # counting the full pool there would let achieved GB/s (and the
        # roofline %) exceed physical peak at partial fills (ADVICE r5
        # #1). The XLA read is pool-shaped either way, so kv_read ==
        # kv_pool without the kernel.
        kv_read = (
            int(round(kv_bytes * measured_fill)) if self._kv_kernel
            else kv_bytes
        )
        bytes_per_tick = w_bytes + kv_read
        roofline_tok_s = B * peak_hbm_gbs * 1e9 / bytes_per_tick
        out = {
            "slope_ok": slope_ok,
            "fill": fill,
            "measured_fill_frac": round(measured_fill, 3),
            "dispatch_overhead_ms": round(overhead_ms, 1),
            "weight_bytes": w_bytes,
            "kv_pool_bytes": kv_bytes,
            "kv_read_bytes": kv_read,
            "weight_bytes_g": round(w_bytes / 1e9, 3),
            "kv_pool_bytes_g": round(kv_bytes / 1e9, 3),
            "peak_hbm_gbs": peak_hbm_gbs,
            "roofline_tok_s": round(roofline_tok_s, 1),
        }
        if not slope_ok:
            # The transport drifted more between windows than the device
            # work separating them — publishing the floored values would
            # fabricate numbers like 1e10 tok/s. Flag and return.
            out.update({
                "device_tick_ms": None, "device_tok_s": None,
                "achieved_hbm_gbs": None, "hbm_roofline_pct": None,
            })
            return out
        achieved_gbs = bytes_per_tick / tick_s / 1e9
        out.update({
            # 6 decimals: a toy model's tick is microseconds.
            "device_tick_ms": round(tick_s * 1e3, 6),
            "device_tok_s": round(B / tick_s, 1),
            "achieved_hbm_gbs": round(achieved_gbs, 1),
            "hbm_roofline_pct": round(100 * achieved_gbs / peak_hbm_gbs, 1),
        })
        return out

    def warmup(self) -> None:
        """Compile the admit and decode programs (no-op inputs) so the
        first real generation doesn't pay XLA compilation; on remote-compile
        transports that is minutes, not milliseconds. The no-op admit
        (all-False mask) leaves the slot state semantically unchanged."""
        B = self._slots
        none = jnp.zeros((B,), bool)
        # The tick/admit "key" operand is per-slot record-key data
        # ([B, W] uint32); the zero-initialized slot keys are exactly the
        # no-op shape.
        key = self._slot_keys
        if self._kv_pages is not None:
            # Compile every program a paged serve can dispatch: the
            # fused chunk tick (chunked; an all-padding chunk — writes
            # land in the sink) OR the legacy miss-path suffix prefill,
            # plus the sampling merge (all-False mask admits nothing)
            # and the decode-only tick. Chunked admission compiles
            # NOTHING later — these are the whole program set, whatever
            # suffix-length mix arrives (the jit-zoo fix).
            if self._chunked:
                C, nblk = self._prefill_chunk, self._blocks_per_slot
                out = self._tick_chunk_fn(
                    self._caches, self._last_tok, self._pos, self._gen,
                    none, key, jnp.zeros((C,), jnp.int32),
                    jnp.full((C, nblk), SINK_BLOCK, jnp.int32),
                    jnp.zeros((C,), jnp.int32), none,
                    jnp.zeros((B,), jnp.int32),
                )
                self._caches, self._last_tok, self._pos, self._gen = out[:4]
                jax.device_get(out[4])
            else:
                table_row = jnp.zeros((1, self._blocks_per_slot), jnp.int32)
                toks = jnp.zeros((1, self._prompt_len), jnp.int32)
                _logits, self._caches = self._paged_prefill_call(
                    self._caches, table_row, toks
                )
            logits_b = jnp.zeros((B, self._cfg.vocab_size), jnp.float32)
            self._last_tok, self._pos, self._gen = self._paged_merge(
                self._last_tok, self._pos, self._gen, logits_b, none, key
            )
            out = self._tick_fn(
                self._caches, self._last_tok, self._pos, self._gen, none, key
            )
            self._caches, self._last_tok, self._pos, self._gen = out[:4]
            jax.device_get(out[4])
            return
        self._caches, self._last_tok, self._pos, self._gen = self._admit_fn(
            self._caches, self._last_tok, self._pos, self._gen,
            jnp.zeros((B, self._prompt_len), jnp.int32), none, key,
        )
        out = self._tick_fn(
            self._caches, self._last_tok, self._pos, self._gen, none, key
        )
        self._caches, self._last_tok, self._pos, self._gen = out[:4]
        jax.device_get(out[4])

    # ---------------------------------------------- live model lifecycle

    @property
    def model_version(self) -> int:
        """The version id of the weights currently serving."""
        return self._model_version

    def swap_params(self, params, version: int) -> None:
        """Hot-swap the serving weights IN PLACE — no recompilation (the
        jitted programs take params as an argument; rebinding the
        closure's source is the whole swap) and no group churn (the
        consumer, lease, and slots are untouched).

        Preconditions make a mixed-version commit window impossible by
        construction: the caller must have QUIESCED (no active or
        prefilling slot — finish in-flight first) and CLOSED the commit
        window (flush_commits) — so every output the old weights
        produced is already committed under the old version tag, and
        everything after this call is produced, journaled, and committed
        under the new one. Durability order is version-journal-first:
        the journal's model_version meta is fsynced BEFORE the in-memory
        rebind, so a SIGKILL between the two restarts on weights that
        match the (empty) journal either way — ``rollout_pre_swap`` dies
        with the OLD version durable, ``swap_mid_apply`` with the NEW;
        the crash matrix kills at both to prove half-old/half-new state
        is unreachable."""
        if self.has_active():
            raise RuntimeError(
                "swap_params requires a quiesced server (drain in-flight "
                "generations first — the warm-drain discipline)"
            )
        if self._uncommitted or (self._txn_mode and self._txn_outbox):
            raise RuntimeError(
                "swap_params requires a closed commit window "
                "(flush_commits first) — a window must never span model "
                "versions"
            )
        version = int(version)
        crash_hook("rollout_pre_swap")
        if self._journal is not None:
            self._journal.set_model_version(version)
            self._journal.sync()
        crash_hook("swap_mid_apply")
        if self._mesh is not None:
            params = jax.device_put(
                params, serving_shardings(self._cfg, self._mesh, params)
            )
        # ONE rebind: the admit/tick lambdas read self._params at call
        # time, so there is no instant where some program sees old and
        # some new weights.
        self._params = params
        self._model_version = version
        if self._tracer is not None:
            self._tracer.swapped(
                version, replica=self._trace_replica
            )

    def spawn_shadow(self, params, version: int) -> "StreamingGenerator":
        """A scratch single-slot generator over CANDIDATE weights for
        canary shadow-serving: same config, prompt decoding, sampling
        contract, and per-record RNG base as this server — so for any
        record its output is byte-for-byte what the candidate version
        WOULD commit — but no consumer group, no producer, no journal:
        nothing a shadow decodes can ever reach the committed view (the
        'divergent canary never publishes' invariant is structural).
        Dense serving path regardless of the incumbent's KV mode (paged/
        dense are differential-tested token-exact)."""
        return StreamingGenerator(
            _ShadowConsumer(), params, self._cfg,
            slots=1,
            prompt_len=self._prompt_len,
            max_new=self._max_new,
            eos_id=self._eos_id,
            commit_every=2**31 - 1,
            decode_prompt=self._decode_prompt,
            ticks_per_sync=1,
            temperature=self._temperature,
            top_k=self._top_k,
            top_p=self._top_p,
            rng=self._rng,
            mesh=self._mesh,
            max_new_of=self._max_new_of,
            model_version=int(version),
        )

    def shadow_decode(self, rec: Record) -> np.ndarray | None:
        """Decode ``rec`` to completion on THIS generator as a shadow
        pass (canary use: call on a ``spawn_shadow`` instance). Returns
        the tokens, or None if the record is undecodable. The record is
        ledger-registered locally but never committed anywhere."""
        self.note_fetched([rec])
        if self.admit_records([rec]) == 0 and not self._journal_ready:
            return None
        out: np.ndarray | None = None
        while self.has_active() or self._journal_ready:
            for done_rec, toks in self.step():
                if done_rec.offset == rec.offset and \
                        done_rec.topic == rec.topic and \
                        done_rec.partition == rec.partition:
                    out = toks
        return out

    # ------------------------------------------- external admission surface
    #
    # run() is a thin loop over four primitives, each usable on its own by
    # an EXTERNAL scheduler (the serving fleet's QoS admission layer,
    # torchkafka_tpu/fleet/): the caller polls its own consumer, decides
    # which records deserve a slot, and drives the device loop tick by
    # tick. The commit/ledger discipline is identical on both paths — the
    # primitives are the same code run() executes.

    @property
    def slots(self) -> int:
        """Size of the decode slot pool."""
        return self._slots

    def free_slots(self) -> int:
        """Slots currently available for admission (a reserved-but-
        still-prefilling chunked admission holds its slot)."""
        return int((~(self._active | self._prefilling)).sum())

    def has_active(self) -> bool:
        """True while any generation is in flight — decoding OR still
        chunk-prefilling (the drain/idle loops must keep ticking until
        queued admissions activate and retire)."""
        return bool(self._active.any() or self._prefilling.any())

    def note_fetched(self, records: list[Record]) -> None:
        """Register polled records with the ledger BEFORE queueing them.

        External admission must call this at poll time, not admit time: a
        record sitting in an admission queue while a LATER record of the
        same partition completes would otherwise be invisible to the
        ledger, and the commit watermark could advance past it — losing it
        on crash. (run() calls this on its own polls.)"""
        # Fetched, not yet registered anywhere durable: death in this
        # window must re-deliver the records verbatim (nothing references
        # them but the broker's uncommitted offsets).
        crash_hook("post_poll")
        self._ledger.fetched_many(records)
        tr = self._tracer
        if tr is not None:
            for r in records:
                tr.polled(r, replica=self._trace_replica)

    def note_partitions_revoked(self, tps) -> None:
        """A rebalance took these partitions away: reset their ledger
        state and drop their internally-deferred admissions. Without
        the reset, records fetched here but served by the NEW owner
        stay 'pending' forever — and if the partition later comes BACK
        (scale-down returning a scale-up's range), the stale entries
        hold the snapshot below the broker's committed watermark and
        the next commit REGRESSES it group-wide (last-write-wins).
        Records already decoding in slots are left alone: their
        completions resolve against the dropped partition as tolerated
        no-ops, and any copy the new owner serves is the ordinary
        at-least-once duplicate."""
        tps = set(tps)
        if not tps:
            return
        self._ledger.drop(tps)
        if self._paged_deferred:
            kept = [r for r in self._paged_deferred if r.tp not in tps]
            dropped = len(self._paged_deferred) - len(kept)
            if dropped:
                self._paged_deferred = kept
                _logger.info(
                    "dropped %d deferred admission(s) for revoked "
                    "partitions", dropped,
                )

    def _next_decodable(self, queue: list[Record]):
        """Pop ``queue`` until a record decodes; returns (record, tokens)
        or None when exhausted. Failures follow the poison policy: with a
        quarantine, each failure spends the record's retry budget (the
        SAME record re-attempts in place — a transient tokenizer fault
        heals here) and an exhausted budget dead-letters it (the record
        is RESOLVED, its offset may retire; a failed DLQ produce raised
        OutputDeliveryError out of note_failure — fail-stop before any
        commit could cover the record). Without one, the record retires
        as dropped (the reference's None-filter analog) — or it would
        re-deliver and crash the server forever on restart."""
        while queue:
            rec = queue.pop(0)
            while True:
                try:
                    return rec, self._decode_prompt(rec)
                except Exception as exc:
                    if self._quarantine is not None:
                        # In exactly_once mode the quarantine's producer
                        # was rebound onto the transactional outbox at
                        # construction: its dead-letter produce stages by
                        # record identity and commits atomically with
                        # the offset that retires the poison record (a
                        # re-quarantine after redelivery overwrites the
                        # identical entry — one committed DLQ copy).
                        try:
                            resolved = self._quarantine.note_failure(rec, exc)
                        except OutputDeliveryError:
                            self.metrics.dlq_delivery_failures.add(1)
                            if self._tracer is not None:
                                self._tracer.dlq_failed(
                                    rec, replica=self._trace_replica
                                )
                            raise
                        if not resolved:
                            continue  # budget left: re-attempt in place
                        self.metrics.quarantined.add(1)
                        if self._tracer is not None:
                            self._tracer.quarantined(
                                rec, replica=self._trace_replica
                            )
                        # DLQ copy acknowledged durable; the offset has
                        # NOT retired yet — the crash window where
                        # redelivery must re-quarantine idempotently.
                        crash_hook("post_dlq_pre_retire")
                    else:
                        _logger.exception(
                            "dropping undecodable prompt %s@%s:%s",
                            rec.topic, rec.partition, rec.offset,
                        )
                        if self._tracer is not None:
                            self._tracer.dropped(
                                rec, replica=self._trace_replica
                            )
                    self._ledger.dropped(rec)
                    self.metrics.dropped.add(1)
                    break  # next record
        return None

    def _record_key_data(self, rec: Record) -> np.ndarray:
        """The record's sampling key: ``rng`` folded with the record's
        identity — a pure function of (base key, topic, partition,
        offset), so every replica/process derives the SAME key for the
        same record (the fleet shares gen_kwargs). Raw key data, journal-
        and device-friendly."""
        k = jax.random.fold_in(
            self._rng, zlib.crc32(rec.topic.encode()) & 0x7FFFFFFF
        )
        k = jax.random.fold_in(k, rec.partition & 0x7FFFFFFF)
        k = jax.random.fold_in(k, rec.offset & 0x7FFFFFFF)
        return np.asarray(jax.random.key_data(k), np.uint32)

    def add_resume_hints(self, entries: dict) -> None:
        """Install journal entries (``journal.DecodeJournal.load`` of a
        dead replica's file, or a previous incarnation's) keyed by
        (topic, partition, offset). A hint is consumed when its record is
        next offered for admission; unmatched hints sit harmlessly (the
        payload CRC check means a hint can never resume a different
        record)."""
        self._resume_hints.update(entries)

    def _take_hint(self, rec: Record) -> JournalEntry | None:
        """Pop and validate ``rec``'s resume hint. None = admit cold."""
        hint = self._resume_hints.pop(
            (rec.topic, rec.partition, rec.offset), None
        )
        if hint is None:
            return None
        g = len(hint.tokens)
        ok = (
            hint.crc == value_crc(rec.value)
            and hint.temperature == self._temperature
            and hint.top_k == self._top_k
            and hint.top_p == self._top_p
            # A prefix decoded under another model version continued
            # under this one would match NEITHER reference — version-
            # mismatched hints fall back to cold replay (still correct).
            and hint.model_version == self._model_version
            and 1 <= g <= self._max_new
            and (hint.finished or g < self._max_new)
            # Partial-generation resume prefills through this server's
            # cache — possible exactly when the pool keeps the
            # exactness contract and the prefill has a spelling here
            # (_resume_supported). Finished hints need no prefill at
            # all.
            and (hint.finished or self._resume_supported())
        )
        if not ok:
            if g >= 1:  # a bare admit-time entry is not a rejection
                self.metrics.resume_rejected.add(1)
            return None
        return hint

    def _resume_supported(self) -> bool:
        """Can a PARTIAL journal hint warm-resume on this backend?

        int8 pools never (exactness was traded away — the one contract
        warm resume exists to keep). Compute-dtype pools: always on one
        device; under a mesh, the paged CHUNKED path resumes fine (the
        prompt + emitted tokens ride the chunk queue and state restores
        host-side), and the dense path resumes when the mesh carries no
        data axis (its [1, S] resume prefill has no batch to shard —
        tp/fsdp-only meshes are unaffected). Everything else falls back
        to cold replay, which is still correct."""
        if self._kv_int8:
            return False
        if self._mesh is None:
            return True
        if self._kv_pages is not None and self._chunked:
            return True
        return self._mesh.shape.get("data", 1) == 1

    def _journal_record(self, rec, key_data, tokens, finished) -> None:
        self._journal.record(
            rec, key_data, tokens=tokens, finished=finished,
            temperature=self._temperature, top_k=self._top_k,
            top_p=self._top_p, model_version=self._model_version,
        )

    def _resume_into_slot(self, i: int, rec: Record, prompt_toks,
                          hint: JournalEntry, key_np: np.ndarray) -> None:
        """Dense warm resume: one prefill dispatch of prompt + journaled
        tokens into slot ``i`` (see the in-jit ``resume_admit``)."""
        emitted = np.asarray(hint.tokens, np.int32)
        g = len(emitted)
        seq = np.concatenate(
            [np.asarray(prompt_toks, np.int32), emitted[:-1]]
        )[None, :]
        row = np.zeros((self._max_new,), np.int32)
        row[:g] = emitted
        out = self._resume_exec(
            self._caches, self._last_tok, self._pos, self._gen,
            jnp.asarray(seq), jnp.int32(i), jnp.asarray(row), jnp.int32(g),
        )
        self._caches, self._last_tok, self._pos, self._gen = out
        self._slot_rec[i] = rec
        self._active[i] = True
        self._slot_emitted[i] = g
        self._slot_journaled[i] = g
        self.metrics.warm_resumes.add(1)
        self.metrics.journal_tokens_restored.add(g)
        if self._tracer is not None:
            self._tracer.warm_resumed(rec, g, replica=self._trace_replica)
            self._tracer.slot_active(
                rec, replica=self._trace_replica, warm=True
            )
        if self._journal is not None:
            self._journal_record(rec, key_np, emitted, False)

    def admit_records(self, records: list[Record]) -> int:
        """Prefill-admit ``records`` into free slots; returns the number
        of slots filled (cold admissions + journal warm resumes; a
        FINISHED journal hint re-serves from the journal without a slot).
        Undecodable records are retired as dropped/quarantined
        (``_next_decodable``) and do not consume a slot. Records must
        already be ``note_fetched``; the caller must not offer more
        records than ``free_slots()`` (minus ``pending_admissions`` in
        paged mode, where pool pressure can also DEFER records — call
        with an empty list to re-offer the deferred backlog)."""
        if self._kv_pages is not None:
            return self._admit_records_paged(records)
        free = [i for i in range(self._slots) if not self._active[i]]
        if len(records) > len(free):
            raise ValueError(
                f"offered {len(records)} records with {len(free)} free slots"
            )
        in_flight = self._slots - len(free)
        B, W = self._slots, self._key_width
        prompts = np.zeros((B, self._prompt_len), np.int32)
        admit_mask = np.zeros((B,), bool)
        keys_np = np.zeros((B, W), np.uint32)
        key_mask = np.zeros((B,), bool)
        queue = list(records)
        slot_iter = iter(free)
        resumed = 0
        journal_dirty = False
        while True:
            nxt = self._next_decodable(queue)
            if nxt is None:
                break
            rec, toks = nxt
            kd = self._record_key_data(rec)
            hint = self._take_hint(rec)
            if hint is not None and hint.finished:
                # The dead replica finished this completion but never
                # committed it: re-serve the journaled tokens verbatim at
                # the next step — zero re-decode, byte-identical output.
                out = np.asarray(hint.tokens, np.int32)
                self._journal_ready.append((rec, out))
                self.metrics.journal_served.add(1)
                if self._tracer is not None:
                    self._tracer.journal_served(
                        rec, len(out), replica=self._trace_replica
                    )
                if self._journal is not None:
                    self._journal_record(rec, hint.key_data or kd, out, True)
                    journal_dirty = True
                continue
            i = next(slot_iter, None)
            if i is None:
                # Unreachable under the caller contract (records <= free
                # slots; finished hints consume none).
                raise RuntimeError("admission ran out of free slots")
            key_np = (
                np.asarray(hint.key_data, np.uint32)
                if hint is not None and hint.key_data is not None else kd
            )
            keys_np[i] = key_np
            key_mask[i] = True
            if hint is not None:
                self._resume_into_slot(i, rec, toks, hint, key_np)
                resumed += 1
                journal_dirty = journal_dirty or self._journal is not None
                continue
            prompts[i] = toks
            self._slot_rec[i] = rec
            admit_mask[i] = True
            self._active[i] = True
            self._slot_emitted[i] = 0
            self._slot_journaled[i] = 0
            if self._journal is not None:
                self._journal_record(rec, kd, (), False)
                journal_dirty = True
        admitted = int(admit_mask.sum())
        filled = admitted + resumed
        if filled:
            if in_flight > 0:
                # Slots refilled while other generations were mid-flight:
                # the observable that distinguishes continuous batching
                # from lockstep waves.
                self.metrics.readmissions.add(filled)
            self._slot_keys = jnp.where(
                jnp.asarray(key_mask)[:, None], jnp.asarray(keys_np),
                self._slot_keys,
            )
        if admitted:
            with xprof.span(xprof.SPAN_ADMIT):
                out = self._admit_fn(
                    self._caches, self._last_tok, self._pos, self._gen,
                    jnp.asarray(prompts), jnp.asarray(admit_mask),
                    jnp.asarray(keys_np),
                )
            # Rebind self state after every dispatch: admit/tick DONATE
            # the pool, so the old self._caches handles are dead buffers —
            # without this, anything reading server state afterwards (a
            # second run, decode_roofline, spec_stats) holds deleted
            # arrays.
            self._caches, self._last_tok, self._pos, self._gen = out
            if self._tracer is not None:
                for i in np.nonzero(admit_mask)[0]:
                    self._tracer.slot_active(
                        self._slot_rec[i], replica=self._trace_replica
                    )
        if journal_dirty:
            self._journal.flush()
        return filled

    def _txn_abort(self) -> None:
        """Defensive abort of an in-flight transaction (best effort — a
        dead broker just leaves it for the next ``begin`` or the next
        incarnation's epoch fence to abort). The outbox is untouched:
        its entries re-send inside the next window's transaction."""
        try:
            if self._output_producer.abort():
                self.metrics.txn_aborts.add(1)
        except Exception:  # noqa: BLE001 - the broker will abort it
            _logger.debug("defensive transaction abort failed", exc_info=True)

    def _retire_completion(
        self, rec: Record, out: np.ndarray,
        completions: list[tuple[Record, np.ndarray]],
    ) -> None:
        """The single completion exit: metrics, output publish (fail
        closed per record), ledger retirement. Shared by tick-produced
        completions and journal-served ones, so both follow the exact
        same durability discipline."""
        self.metrics.completions.add(1)
        self.metrics.tokens.add(len(out))
        if len(out) < self._max_new:
            self.metrics.truncated.add(1)
        if self._tracer is not None:
            self._tracer.finished(
                rec, len(out), replica=self._trace_replica
            )
        if self._distill_topic is not None:
            # Frame the training-corpus record NOW (tokens in hand) but
            # produce it only WITH the commit that covers its offset
            # (txn: inside the transaction; at-least-once: after the
            # commit succeeds) — the corpus holds committed tokens only.
            # Keyed by record identity: a re-serve overwrites the
            # identical frame (one committed copy, ever).
            self._distill_outbox[(rec.topic, rec.partition, rec.offset)] = (
                self._encode_distill(
                    self._decode_prompt(rec), out,
                    tenant=rec.key, model_version=self._model_version,
                )
            )
        sent_ok = True
        if self._output_producer is not None:
            # Async send; durability is settled in _commit (flush
            # + per-handle get) BEFORE offsets commit. A
            # SYNCHRONOUS send failure (buffer full with the
            # output broker down, closed producer, missing topic)
            # must not kill serving OR let the record commit: skip
            # emitted() so the ledger watermark stalls at exactly
            # this record — it re-delivers and regenerates on
            # restart.
            if self._txn_mode:
                # STAGE, don't send: the outbox entry is produced inside
                # the commit window's transaction — and only once the
                # in-order watermark covers this record's offset, so its
                # output and its offset are one atomic broker decision.
                # Keyed by record identity: an eager-rebalance re-serve
                # of the same record overwrites the identical entry (one
                # committed copy, ever). Nothing here can fail, so the
                # send-failure streak machinery doesn't apply — output
                # path health surfaces at transaction commit instead.
                self._txn_outbox[(rec.topic, rec.partition, rec.offset)] = (
                    dict(
                        topic=self._output_topic,
                        value=self._encode_output(rec, out),
                        key=rec.key,
                        # The version tag: every committed output window
                        # records which weights produced it (swap_params
                        # only lands between windows, so a window is
                        # never mixed-version).
                        headers=(
                            ("mv", str(self._model_version).encode()),
                        ),
                    )
                )
            else:
                try:
                    self._pending_outputs.append(
                        self._output_producer.send(
                            self._output_topic,
                            self._encode_output(rec, out),
                            key=rec.key,
                            headers=(
                                ("mv", str(self._model_version).encode()),
                            ),
                        )
                    )
                    self._send_failure_streak = 0
                except Exception:  # noqa: BLE001 - fail closed per record
                    sent_ok = False
                    self.metrics.output_send_failures.add(1)
                    self._send_failure_streak += 1
                    _logger.exception(
                        "output send failed for %s@%d:%d; leaving "
                        "it uncommitted to re-deliver",
                        rec.topic, rec.partition, rec.offset,
                    )
                if (
                    self._send_failure_streak
                    >= self._max_send_failure_streak
                ):
                    # The output path is down, not blinking: every
                    # further completion would be un-committable
                    # replay work behind a permanently stalled
                    # watermark. Fail-stop like the flush/get path
                    # so the operator gets one signal for "output
                    # lost".
                    raise OutputDeliveryError(
                        f"{self._send_failure_streak} "
                        "consecutive output send failures; "
                        "failing stop so uncommitted prompts "
                        "re-deliver instead of serving into a "
                        "stalled commit watermark"
                    )
        if sent_ok:
            self._ledger.emitted(rec)
            self._uncommitted += 1
        completions.append((rec, out))

    def step(self) -> list[tuple[Record, np.ndarray]]:
        """One decode tick block over the active slots; returns the
        completions it retired (ledger-emitted, output-published, commit
        cadence applied) in completion order — journal-served
        completions (finished entries from a dead replica's journal,
        zero re-decode) first, then the tick's. No-op on an idle pool
        with no journal backlog."""
        completions: list[tuple[Record, np.ndarray]] = []
        if self._journal_ready:
            ready, self._journal_ready = self._journal_ready, []
            for rec, out in ready:
                self._retire_completion(rec, out, completions)
        run_chunk = self._chunked and bool(self._prefill_queue)
        if self._active.any() or run_chunk:
            self._tick_counter += 1
            tick_t0 = time.perf_counter()
            finishers = None
            if run_chunk:
                # The fused program: a bounded chunk of queued suffix
                # tokens rides this tick's layer sweep alongside every
                # decode slot — admission work never preempts a decode
                # tick, it shares one.
                with xprof.span(xprof.SPAN_CHUNK_PACK):
                    (ctok, ctable, cpos, fin_mask, fin_row, packed,
                     finishers) = self._pack_chunk()
                with xprof.span(xprof.SPAN_TICK):
                    caches, last_tok, pos, gen, done, n_out = (
                        self._tick_chunk_fn(
                            self._caches, self._last_tok, self._pos,
                            self._gen, jnp.asarray(self._active.copy()),
                            self._slot_keys, jnp.asarray(ctok),
                            jnp.asarray(ctable), jnp.asarray(cpos),
                            jnp.asarray(fin_mask), jnp.asarray(fin_row),
                        )
                    )
                self.metrics.chunk_ticks.add(1)
                self.metrics.prefill_tokens.add(packed)
                self.metrics.chunk_utilization.set(
                    self.metrics.prefill_tokens.count
                    / (self.metrics.chunk_ticks.count * self._prefill_chunk)
                )
            else:
                with xprof.span(xprof.SPAN_TICK):
                    caches, last_tok, pos, gen, done, n_out = self._tick_fn(
                        self._caches, self._last_tok, self._pos, self._gen,
                        jnp.asarray(self._active.copy()), self._slot_keys,
                    )
            self._caches, self._last_tok, self._pos, self._gen = (
                caches, last_tok, pos, gen
            )
            # ONE host sync per tick block: done/n_out/gen/pos fetched
            # together (separate np.asarray calls are separate round trips
            # on high-latency transports).
            with xprof.span(xprof.SPAN_SYNC):
                done_h, n_out_h, gen_h, pos_h = jax.device_get(
                    (done, n_out, gen, pos)
                )
            self.metrics.tick_time.observe(time.perf_counter() - tick_t0)
            crash_hook("mid_tick")
            self.metrics.slot_occupancy.set(float(self._active.mean()))
            if self._max_new_of is not None:
                # device_get may hand back non-writable views; the budget
                # clamp below mutates the done/count mirrors.
                done_h = np.array(done_h)
                n_out_h = np.array(n_out_h)
            # Per-slot emitted-token mirrors: decoded-token accounting
            # (the cold-vs-warm replay differential) and the journal's
            # token cadence both read them. Counted BEFORE retirement so
            # a completing slot's final tokens are journaled while its
            # record is still attached.
            journal_dirty = False
            decoded = 0
            for i in np.nonzero(self._active)[0]:
                cnt = int(
                    n_out_h[i] if done_h[i]
                    else pos_h[i] - self._prompt_len + 1
                )
                if self._max_new_of is not None:
                    budget = self._max_new_of(self._slot_rec[i])
                    if budget is not None:
                        budget = max(1, min(int(budget), self._max_new))
                        if cnt >= budget:
                            # Budget reached (tick blocks may overshoot
                            # by up to ticks_per_sync - 1 tokens; the
                            # overshoot is truncated): force-finish this
                            # slot exactly like a device done.
                            cnt = budget
                            if not done_h[i]:
                                self.metrics.output_capped.add(1)
                            done_h[i] = True
                            n_out_h[i] = budget
                new_toks = cnt - int(self._slot_emitted[i])
                decoded += new_toks
                if self._tracer is not None and new_toks > 0:
                    self._tracer.tokens(
                        self._slot_rec[i], new_toks,
                        replica=self._trace_replica,
                    )
                self._slot_emitted[i] = cnt
                if self._journal is not None:
                    rec = self._slot_rec[i]
                    if done_h[i]:
                        self._journal.finish(rec, gen_h[i, :cnt])
                        journal_dirty = True
                    elif (
                        cnt - int(self._slot_journaled[i])
                        >= self._journal.cadence
                    ):
                        self._journal.progress(rec, gen_h[i, :cnt])
                        self._slot_journaled[i] = cnt
                        journal_dirty = True
            if decoded > 0:
                self.metrics.decoded_tokens.add(decoded)
            self.metrics.tokens_per_tick.set(float(decoded))
            if journal_dirty:
                # Synchronous at the cadence point: the whole point is
                # that a SIGKILL one instruction later finds these tokens
                # on disk.
                self._journal.flush()
            if done_h.any():
                for i in np.nonzero(done_h)[0]:
                    rec = self._slot_rec[i]
                    assert rec is not None
                    self._active[i] = False
                    self._slot_rec[i] = None
                    self._slot_emitted[i] = 0
                    self._slot_journaled[i] = 0
                    if self._kv_pages is not None:
                        # Unpin the slot's blocks: uncached ones return to
                        # the free list; cached prefix blocks stay alive on
                        # the radix tree's own reference. The row falls back
                        # to the sink so this slot's frozen-position tick
                        # writes can never touch a re-allocated block.
                        self._release_slot_blocks(i)
                    out = gen_h[i, : n_out_h[i]].copy()
                    self._retire_completion(rec, out, completions)
                if self._kv_pages is not None:
                    self._caches = self._paged_set_table(
                        self._caches, self._device_table()
                    )
                    self.metrics.cache_pool_occupancy.set(
                        self._kv_alloc.occupancy()
                    )
            if finishers:
                # AFTER the done bookkeeping above (which must see the
                # pre-activation active set and its fetched state):
                # completed prefills activate for the NEXT tick.
                self._activate_chunk_finishers(finishers)
                if self._prefill_role:
                    # Disaggregated prefill: nothing decodes here — cut
                    # the freshly activated slots into handoffs and free
                    # them before any decode tick could run.
                    self._harvest_prefilled(finishers)
            if run_chunk:
                self.metrics.admission_queue_tokens.set(float(sum(
                    len(e.seq) - e.off for e in self._prefill_queue
                )))
        if (
            completions
            and self._uncommitted >= self._commit_every
            and self._commit()
        ):
            self._uncommitted = 0
        return completions

    def flush_commits(self) -> bool:
        """Commit anything emitted since the last commit (cadence-pending
        completions). The external-admission caller's end-of-window flush;
        run() calls it on exit. A SURVIVABLE commit failure (rebalance,
        open circuit, broker fault or outage) leaves the cadence counter
        intact, so the completions stay commit-pending and the next
        cadence point or flush retries them — a transient failure at the
        final flush no longer silently strands the tail uncommitted until
        restart. In exactly_once mode a non-empty outbox also forces the
        flush: held out-of-order outputs (e.g. behind a record that
        resolved as DROPPED, which advances no completion counter) must
        still reach a committed transaction.

        Returns False exactly when a survivable failure left work
        pending — the caller's cue to RETRY at its next safe point even
        if no new completions arrive (a fleet replica that went idle
        with a failed flush would otherwise never commit its tail: the
        broker-outage wedge the durable-broker restart drill exposed).
        True means the flush succeeded or nothing needed flushing."""
        if self._uncommitted or (self._txn_mode and self._txn_outbox):
            if self._commit():
                self._uncommitted = 0
                return True
            return False
        return True

    @property
    def pending_commit(self) -> int:
        """Completions emitted since the last SUCCESSFUL commit — what a
        flush would cover. Nonzero after serving means a survivable
        commit failure is still unhealed (retry flush_commits once the
        broker recovers, or accept the re-delivery on restart)."""
        return self._uncommitted

    def committable_offsets(self) -> dict:
        """The ledger's committable next-read offsets right now — what the
        next commit would durably record. Fleet observability merges these
        per-replica views (commit.ledger.merged_watermarks)."""
        return self._ledger.snapshot()

    def run(
        self, max_records: int | None = None, idle_timeout_ms: int = 2000
    ) -> Iterator[tuple[Record, np.ndarray]]:
        B = self._slots
        pending: list[Record] = []
        served = 0
        exhausted_at: float | None = None
        self.metrics.reset()
        while True:
            free = self.free_slots()
            in_flight = B - free
            # Admission budget: never take more work than max_records allows
            # (completions already served + generations in flight).
            budget = (
                max(0, max_records - served - in_flight)
                if max_records is not None
                else B
            )
            # Paged-mode deferred admissions hold their future slots (and
            # re-offer first, FIFO); always 0 on the dense path.
            deferred = self.pending_admissions
            take_cap = max(0, min(free - deferred, budget))
            if take_cap and len(pending) < take_cap:
                # Never let an empty topic stall in-flight decode ticks:
                # poll without blocking while anything is generating.
                records = self._consumer.poll(
                    max_records=self._max_poll,
                    timeout_ms=0 if in_flight else 50,
                )
                if records:
                    self.note_fetched(records)
                    pending.extend(records)
                    exhausted_at = None
            if (take_cap and pending) or (free and deferred and budget):
                take = pending[:take_cap]
                del pending[: len(take)]
                self.admit_records(take)
            if not self.has_active() and not self._journal_ready:
                if max_records is not None and served >= max_records:
                    break
                if not pending:
                    if exhausted_at is None:
                        exhausted_at = time.monotonic()
                    elif (time.monotonic() - exhausted_at) * 1000 >= idle_timeout_ms:
                        break
                continue
            for rec, out in self.step():
                served += 1
                yield rec, out
            if max_records is not None and served >= max_records and not self.has_active():
                break
        self.flush_commits()

    def _commit(self) -> bool:
        """Commit the ledger watermark; returns True iff offsets were
        durably committed (callers reset the commit cadence only then, so
        failed cadence commits retry instead of silently skipping).
        Commit failure is survivable (the reference's contract,
        /root/reference/src/kafka_dataset.py:131-135): a rebalance raises
        CommitFailedError and the moved partitions' uncommitted prompts
        simply re-deliver to their new owner.

        With an output topic configured, output durability is settled
        FIRST: flush, then ``get()`` every send handle since the last
        commit (kafka-python's ``flush`` resolves futures but does NOT
        re-raise per-record failures — a terminally failed send would
        otherwise slip through a clean flush). A TRANSIENT flush failure
        skips the commit and keeps the handles (retried next commit); a
        TERMINAL per-record failure raises ``OutputDeliveryError`` —
        fail-stop equals crash-before-commit, so everything since the
        last commit re-delivers and regenerates rather than committing
        past lost output.

        ``commit_latency`` observes the WHOLE commit path — output flush +
        per-handle durability waits + the offset commit — so an
        output-broker stall shows up in the p99 an operator watches.

        With ``exactly_once`` the whole discipline above collapses into
        ONE transaction commit — see ``_commit_txn``."""
        t0 = time.perf_counter()
        if self._txn_mode:
            return self._commit_txn(t0)
        if self._output_producer is not None:
            try:
                self._output_producer.flush()
            except Exception:  # noqa: BLE001 - any flush failure fails closed
                self.metrics.output_flush_failures.add(1)
                _logger.exception(
                    "output flush failed; SKIPPING offset commit so the "
                    "affected prompts re-deliver and regenerate"
                )
                return False
            pending, self._pending_outputs = self._pending_outputs, []
            for handle in pending:
                try:
                    handle.get(30.0)
                except Exception as exc:
                    self.metrics.output_flush_failures.add(1)
                    raise OutputDeliveryError(
                        "an output record terminally failed delivery; "
                        "refusing to commit source offsets past lost "
                        "output (restart re-delivers and regenerates)"
                    ) from exc
        snapshot = self._ledger.snapshot()
        # Commit only partitions we still OWN: an eager rebalance (a
        # member joined/left — fleet.scale on the process fleet) can take
        # partitions away with completions still in this ledger. Kafka
        # clients drop those from the commit set — the broker would
        # reject the WHOLE commit as "partitions not owned" otherwise,
        # permanently stalling even the owned partitions' watermark. The
        # new owner re-serves the departed records (at-least-once;
        # duplicates bounded by this replica's uncommitted work), so
        # skipping them here loses nothing. assignment() also syncs the
        # group first, so the commit below carries the POST-rebalance
        # generation instead of burning one doomed attempt.
        try:
            assigned = set(self._consumer.assignment())
        except Exception:  # noqa: BLE001 - transport hiccup: commit as-is
            assigned = None
        if assigned is not None:
            stray = [tp for tp in snapshot if tp not in assigned]
            if stray:
                _logger.info(
                    "dropping %d departed partition(s) from commit after "
                    "rebalance: %s", len(stray), sorted(stray),
                )
                snapshot = {
                    tp: off for tp, off in snapshot.items()
                    if tp in assigned
                }
            if not snapshot:
                return True  # nothing we own has progress to commit
        # Outputs durable, offsets not yet committed: death here must
        # replay (duplicates on the output topic), never lose.
        crash_hook("pre_commit")
        try:
            with xprof.span(xprof.SPAN_COMMIT):
                self._consumer.commit(snapshot)
            self.metrics.commit_latency.observe(time.perf_counter() - t0)
        except CommitFailedError:
            self.metrics.commit_failures.add(1)
            _logger.exception("offset commit failed; prompts will re-deliver")
            return False
        except BrokerUnavailableError:
            # A broker outage that outlived the client's own retry budget
            # (e.g. a broker-process death mid-restart): survivable — the
            # ledger snapshot stays pending, the cadence counter stays
            # intact, and the next flush retries against the recovered
            # broker. Riding the outage here is what lets a WAL-restarted
            # broker pick the fleet back up with zero lost records.
            self.metrics.commit_failures.add(1)
            _logger.warning(
                "broker unavailable at commit; offsets stay pending and "
                "retry at the next flush"
            )
            return False
        if self._tracer is not None:
            # Durably committed: close every covered record's e2e span.
            self._tracer.note_commit(snapshot)
        if self._distill_topic is not None and self._distill_outbox:
            # Commit SUCCEEDED: the frames whose offsets it covers hold
            # committed tokens — publish them now (never before; a crash
            # pre-commit publishes nothing and the re-delivered records'
            # regenerated completions frame the only copy). A send fault
            # keeps the frame for the next commit's retry — losing it to
            # a crash costs one corpus sample, never correctness.
            prod = self._distill_producer or self._output_producer
            if assigned is not None:
                for ident in [
                    i for i in self._distill_outbox
                    if TopicPartition(i[0], i[1]) not in assigned
                ]:
                    del self._distill_outbox[ident]
            covered = [
                i for i in self._distill_outbox
                if i[2] < snapshot.get(TopicPartition(i[0], i[1]), 0)
            ]
            for ident in covered:
                try:
                    prod.send(
                        self._distill_topic, self._distill_outbox[ident]
                    )
                except Exception:  # noqa: BLE001 - retry next commit
                    _logger.warning(
                        "distill frame publish failed; retrying at the "
                        "next commit", exc_info=True,
                    )
                    break
                del self._distill_outbox[ident]
                self.metrics.distill_published.add(1)
        if self._journal is not None:
            # Journal GC at commit flush: entries below the committed
            # watermark are durable history — pruning here is what bounds
            # the journal file by in-flight work.
            self._journal.prune(snapshot)
            self._journal.flush()
        return True

    def _commit_txn(self, t0: float) -> bool:
        """The exactly-once commit: ONE short-lived transaction per
        window — begin, produce every outbox entry the in-order ledger
        snapshot covers (outputs and DLQ copies alike), stage the
        snapshot's offsets with the consumer's CURRENT group metadata,
        commit. Outputs whose offsets the watermark cannot yet cover
        (completions that finished out of order behind a still-pending
        record) are HELD for a later window — publishing them early is
        exactly the committed-output-with-redeliverable-offset hole that
        turns a rebalance into duplicates. Failure classes:

        - ``CommitFailedError`` (rebalance/fencing): SURVIVABLE — the
          broker aborted records + offsets atomically; the outbox is
          untouched, so the next window re-sends whatever this replica
          still owns (the snapshot filter drops departed partitions,
          whose records re-serve on their new owner).
        - ``ProducerFencedError``: TERMINAL — another incarnation owns
          this transactional id; raise (fail-stop, the process fleet
          exits EXIT_FENCED).
        - transport faults: abort defensively and return False — a
          commit whose ack was eaten is answered idempotently by the
          broker on retry.

        ``commit_latency`` observes the whole path — the transaction's
        produces + offset staging + atomic commit — so the measured
        "transaction tax" is honest against the legacy flush-then-commit
        p99."""
        p = self._output_producer
        snapshot = self._ledger.snapshot()
        try:
            assigned = set(self._consumer.assignment())
        except Exception:  # noqa: BLE001 - transport hiccup: commit as-is
            assigned = None
        if assigned is not None:
            stray = [tp for tp in snapshot if tp not in assigned]
            if stray:
                _logger.info(
                    "dropping %d departed partition(s) from txn commit "
                    "after rebalance: %s", len(stray), sorted(stray),
                )
                snapshot = {
                    tp: off for tp, off in snapshot.items()
                    if tp in assigned
                }
            # Outbox entries for departed partitions are STALE: their
            # records either committed under this replica already (never
            # redeliver) or re-serve on the new owner (the only copy).
            # If the partition ever comes back, redelivery re-stages
            # fresh entries; keeping these would double-publish.
            stale = [
                ident for ident in self._txn_outbox
                if TopicPartition(ident[0], ident[1]) not in assigned
            ]
            for ident in stale:
                del self._txn_outbox[ident]
                # The departed record's distill frame is stale with it:
                # its new owner frames the only committed copy.
                self._distill_outbox.pop(ident, None)
        dup_serves = [
            ident for ident in self._txn_outbox
            if ident[2] < self._txn_committed_wm.get(
                TopicPartition(ident[0], ident[1]), 0
            )
        ]
        for ident in dup_serves:
            # A re-serve of a record a previous window already committed
            # (both copies of an eager-rebalance double delivery ran to
            # completion): the committed view has its single copy.
            del self._txn_outbox[ident]
            self._distill_outbox.pop(ident, None)
        if dup_serves:
            _logger.info(
                "dropped %d duplicate re-serve(s) already covered by "
                "committed transactions", len(dup_serves),
            )
        sendable = [
            ident for ident in self._txn_outbox
            if ident[2] < snapshot.get(TopicPartition(ident[0], ident[1]), 0)
        ]
        # Distill frames covered by this window's snapshot ride the SAME
        # transaction as the outputs + offsets: an aborted window's
        # corpus records are invisible, a fenced zombie's are aborted
        # with its transaction — only committed tokens ever train.
        d_sendable = [
            ident for ident in self._distill_outbox
            if ident[2] < snapshot.get(TopicPartition(ident[0], ident[1]), 0)
        ] if self._distill_topic is not None else []
        if not snapshot and not p.in_transaction:
            return True  # nothing resolved, nothing dangling: no-op
        try:
            # begin() also aborts any transaction a lost commit ack left
            # dangling broker-side, so state drift self-heals here.
            p.begin()
            for ident in sendable:
                kw = self._txn_outbox[ident]
                p.send(
                    kw["topic"], kw["value"], key=kw["key"],
                    headers=kw.get("headers", ()),
                )
            for ident in d_sendable:
                # Tenant key rides inside the frame header; no record
                # key needed for the corpus topic.
                p.send(self._distill_topic, self._distill_outbox[ident])
            if snapshot:
                p.send_offsets(
                    getattr(self._consumer, "group_id"), snapshot,
                    member_id=getattr(self._consumer, "member_id", None),
                    generation=getattr(self._consumer, "generation", None),
                )
            p.commit()
        except ProducerFencedError:
            self.metrics.commit_failures.add(1)
            self.metrics.txn_aborts.add(1)
            _logger.exception(
                "transactional producer fenced; failing stop — the "
                "successor incarnation owns this replica's outputs now"
            )
            raise
        except CommitFailedError:
            self.metrics.commit_failures.add(1)
            self.metrics.txn_aborts.add(1)
            self._txn_abort()  # defensive: send_offsets may refuse pre-commit
            _logger.exception(
                "transaction aborted on commit failure; the outbox "
                "re-sends next window, departed records re-serve on "
                "their new owner"
            )
            return False
        except Exception:  # noqa: BLE001 - transport fault: retry later
            self.metrics.commit_failures.add(1)
            self._txn_abort()
            _logger.exception(
                "transaction commit failed in flight; aborted "
                "defensively — the outbox re-sends next flush"
            )
            return False
        for ident in sendable:
            del self._txn_outbox[ident]
        for ident in d_sendable:
            self._distill_outbox.pop(ident, None)
        if d_sendable:
            self.metrics.distill_published.add(len(d_sendable))
        for tp, off in snapshot.items():
            if off > self._txn_committed_wm.get(tp, 0):
                self._txn_committed_wm[tp] = off
        self.metrics.txn_commits.add(1)
        self.metrics.txn_held_outputs.set(float(len(self._txn_outbox)))
        self.metrics.commit_latency.observe(time.perf_counter() - t0)
        if self._tracer is not None:
            self._tracer.note_commit(snapshot)
        if self._journal is not None:
            self._journal.prune(snapshot)
            self._journal.flush()
        return True

    def sync_journal(self) -> None:
        """Flush + fsync the decode journal (no-op without one) — the
        SIGTERM drain path's durability point: whatever is in flight when
        the process exits must be warm-resumable by the next owner."""
        if self._journal is not None:
            self._journal.sync()

    def close(self) -> None:
        """Voluntary shutdown: commit the watermark for everything already
        COMPLETED (abandoning ``run()`` mid-iteration intentionally skips
        this — a crash must re-deliver). In-flight generations stay
        uncommitted and re-deliver on restart, like the stream's close
        contract (/root/reference/src/kafka_dataset.py:89 keeps unfinished
        work uncommitted; finished-and-yielded work is the user's).
        IDEMPOTENT: the drain path can hit this twice (a second SIGTERM
        lands mid-drain) — the second call must not re-commit through a
        consumer the first call's caller already closed."""
        if self._closed:
            return
        self._closed = True
        try:
            self._commit()
        except ConsumerClosedError:
            # A completed drain (Replica.finish_drain) already committed
            # the final watermark and closed the consumer; the close()
            # that a shutdown teardown (or second signal) lands here
            # afterwards must not die re-committing an unchanged
            # watermark through it.
            pass
        finally:
            self.sync_journal()

    def __enter__(self) -> "StreamingGenerator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

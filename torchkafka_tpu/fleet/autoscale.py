"""The autoscaling loop: burn-rate + queue-depth signals driving
per-role fleet scaling, with hysteresis and warm drain.

Every mechanism this controller composes already exists as a measured
part — ``BurnRateMonitor`` emits typed ok→warning→burning→shedding
transitions (obs/burn.py), the admission queue exposes its depth
(fleet/qos.py), replicas join and drain mid-serve with zero loss
(``ServingFleet.scale_to`` in-process, ``ProcessFleet.scale`` across OS
processes), and disaggregated prefill workers are their own scalable
role (fleet/prefill.py). What was missing is the thing production
actually runs: a CONTROLLER that closes the loop from those signals to
replica counts, per role, without flapping under the Poisson burst
noise the workload generator emits.

Three layers, deliberately split:

- ``AutoscaleController`` is the pure decision core: clock-injectable,
  transport-free, deterministic. Per role it walks signals →
  ``ScaleDecision`` through classic control hysteresis: a DEAD-BAND
  between ``queue_low`` and ``queue_high`` per-replica backlog where it
  holds; per-direction COOLDOWNS (scale-down additionally dwells out the
  up-cooldown, so a burst can never trigger up-then-down thrash); STEP
  LIMITS clamping how far one decision moves; and a ``down_confirm``
  streak — the idle condition must hold for K consecutive evaluations
  before capacity is returned, so one quiet gap between bursts never
  drains a replica the next burst needs. Decisions are a pure function
  of (policy, signal sequence, clock readings): under a ManualClock a
  same-seed run replays its decisions byte-identically
  (``decision_digest``), the repo's differential discipline applied to
  the control plane itself.
- ``FleetAutoscaler`` binds the core to the in-process ``ServingFleet``
  (+ an optional ``PrefillPool``): sample admission-queue depth, the
  burn monitor's worst state, slot occupancy, and the prefill backlog;
  evaluate; apply via ``scale_to`` — scale-up joins fresh group members
  mid-serve, scale-down drains WARM (finish in-flight, commit, leave:
  zero lost, zero replay at quiesced transitions).
- ``SupervisorAutoscaler`` binds the same core to a ``ProcessFleet``:
  signals come from the broker (group lag — exactly what a supervisor
  of real processes can know), actuation is ``ProcessFleet.scale(n,
  role=...)`` whose scale-up deliberately reuses a fenced victim's
  replica index so the replacement sorts into the victim's member-id
  range and inherits its journal + radix locality.

Observability rides the existing planes: every decision is a typed
``scale_decision`` event on the tracer's "fleet" topic (ordered against
the joins/fences it causes) and counts on FleetMetrics
(``autoscale_decisions_total{role,direction,reason}``,
``autoscale_target_replicas{role}``, phase + time-in-phase gauges).
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Callable, Mapping, NamedTuple

from torchkafka_tpu.obs.burn import BURNING, OK, SHEDDING, STATE_LEVEL

DECODE = "decode"
PREFILL = "prefill"

UP = "up"
DOWN = "down"

# Controller phases (the time-in-state gauges' domain).
STEADY = "steady"
SCALING_UP = "scaling_up"
SCALING_DOWN = "scaling_down"
PHASES = (STEADY, SCALING_UP, SCALING_DOWN)
PHASE_LEVEL = {p: i for i, p in enumerate(PHASES)}

# Decision reasons (the {reason} label's closed set).
REASON_BURN = "burn"
REASON_QUEUE = "queue"
REASON_IDLE = "idle"


@dataclasses.dataclass(frozen=True)
class RolePolicy:
    """One role's scaling policy.

    ``queue_high``/``queue_low``: per-replica backlog thresholds — above
    high demands capacity, below low (with burn OK and occupancy at most
    ``occupancy_low``) offers it back; between them is the dead-band
    where the controller holds. ``up_step``/``down_step`` clamp how many
    replicas one decision adds/removes. ``up_cooldown_s`` /
    ``down_cooldown_s``: minimum spacing between same-direction
    decisions; a down additionally waits out the up-cooldown since the
    last up (no up→down thrash inside one burst). ``down_confirm``: the
    idle condition must hold for this many CONSECUTIVE evaluations
    before a scale-down fires — the Poisson-burst-noise filter.
    ``burn_up``: burning/shedding burn states force scale-up pressure
    regardless of queue depth (decode's SLO-protection path)."""

    min_replicas: int = 1
    max_replicas: int = 4
    queue_high: float = 8.0
    queue_low: float = 2.0
    up_step: int = 1
    down_step: int = 1
    up_cooldown_s: float = 0.0
    down_cooldown_s: float = 0.0
    down_confirm: int = 3
    burn_up: bool = True
    occupancy_low: float = 0.75

    def __post_init__(self) -> None:
        if not 0 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                "need 0 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}/{self.max_replicas}"
            )
        if not 0 <= self.queue_low <= self.queue_high:
            raise ValueError(
                "need 0 <= queue_low <= queue_high, got "
                f"{self.queue_low}/{self.queue_high}"
            )
        if self.up_step < 1 or self.down_step < 1:
            raise ValueError("up_step / down_step must be >= 1")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if self.down_confirm < 1:
            raise ValueError(
                f"down_confirm must be >= 1, got {self.down_confirm}"
            )
        if not 0 <= self.occupancy_low <= 1:
            raise ValueError(
                f"occupancy_low must sit in [0, 1], got {self.occupancy_low}"
            )


@dataclasses.dataclass(frozen=True)
class RoleSignals:
    """One role's observed inputs for one evaluation sweep.

    ``live``: replicas currently serving (what the controller adopts as
    its initial target). ``queue_depth``: the role's backlog — admission
    queue depth for decode, handoff-plane lag for prefill.
    ``burn_state``: the worst burn-rate state over every monitored scope
    (``BurnRateMonitor.worst_state()``); prefill roles usually leave it
    "ok". ``occupancy``: mean slot occupancy in [0, 1] — a scale-down
    guard (never drain replicas that are still busy)."""

    live: int
    queue_depth: int = 0
    burn_state: str = OK
    occupancy: float = 0.0

    def __post_init__(self) -> None:
        if self.live < 0:
            raise ValueError(f"live must be >= 0, got {self.live}")
        if self.queue_depth < 0:
            raise ValueError(
                f"queue_depth must be >= 0, got {self.queue_depth}"
            )
        if self.burn_state not in STATE_LEVEL:
            raise ValueError(f"unknown burn state {self.burn_state!r}")


class ScaleDecision(NamedTuple):
    """One actuation order: move ``role`` from ``frm`` to ``to`` replicas
    (``direction`` up/down) because ``reason``, decided at ``t_s``."""

    t_s: float
    role: str
    direction: str
    reason: str
    frm: int
    to: int


class _RoleState:
    __slots__ = (
        "target", "last_up_t", "last_down_t", "idle_streak", "phase",
        "phase_since",
    )

    def __init__(self) -> None:
        self.target: int | None = None
        self.last_up_t = -float("inf")
        self.last_down_t = -float("inf")
        self.idle_streak = 0
        self.phase = STEADY
        self.phase_since: float | None = None


class AutoscaleController:
    """The deterministic decision core: signals in, ScaleDecisions out.

    ``policies``: role name → ``RolePolicy``. ``clock``: injectable —
    under a ManualClock every cooldown comparison is exact and the
    decision stream replays byte-identically. ``tracer``/``metrics``:
    optional obs.RecordTracer / FleetMetrics for typed ``scale_decision``
    events and the autoscale metric families. The controller never
    touches a fleet; a binding (``FleetAutoscaler`` /
    ``SupervisorAutoscaler``) applies its decisions."""

    def __init__(
        self,
        policies: Mapping[str, RolePolicy],
        *,
        clock: Callable[[], float] = time.monotonic,
        tracer=None,
        metrics=None,
    ) -> None:
        if not policies:
            raise ValueError("AutoscaleController needs at least one role")
        self.policies = dict(policies)
        self._clock = clock
        self.tracer = tracer
        self.metrics = metrics
        self._state = {role: _RoleState() for role in self.policies}
        self.decisions: list[ScaleDecision] = []
        self.evaluations = 0

    # --------------------------------------------------------- evaluation

    def target(self, role: str) -> int | None:
        """The controller's current target for ``role`` (None before the
        first evaluation adopted the observed live count)."""
        return self._state[role].target

    def _clamp(self, pol: RolePolicy, n: int) -> int:
        return max(pol.min_replicas, min(pol.max_replicas, n))

    def evaluate(
        self, signals: Mapping[str, RoleSignals]
    ) -> list[ScaleDecision]:
        """One control sweep over every role with a signal this round
        (sorted iteration — determinism). Returns the decisions made;
        also appends them to ``self.decisions`` and narrates them on the
        tracer/metrics."""
        t = self._clock()
        self.evaluations += 1
        out: list[ScaleDecision] = []
        for role in sorted(self.policies):
            if role not in signals:
                continue
            pol = self.policies[role]
            sig = signals[role]
            st = self._state[role]
            if st.target is None:
                st.target = self._clamp(pol, sig.live)
                st.phase_since = t
            basis = max(1, st.target)
            burn_hot = pol.burn_up and STATE_LEVEL[sig.burn_state] >= \
                STATE_LEVEL[BURNING]
            hot = burn_hot or sig.queue_depth > pol.queue_high * basis
            cold = (
                not hot
                and sig.queue_depth <= pol.queue_low * basis
                and sig.burn_state == OK
                and sig.occupancy <= pol.occupancy_low
            )
            decision: ScaleDecision | None = None
            if hot:
                st.idle_streak = 0
                if (
                    st.target < pol.max_replicas
                    and t - st.last_up_t >= pol.up_cooldown_s
                ):
                    to = min(pol.max_replicas, st.target + pol.up_step)
                    decision = ScaleDecision(
                        t, role, UP,
                        REASON_BURN if burn_hot else REASON_QUEUE,
                        st.target, to,
                    )
                    st.target = to
                    st.last_up_t = t
                    self._set_phase(st, SCALING_UP, t)
            elif cold:
                st.idle_streak += 1
                if (
                    st.target > pol.min_replicas
                    and st.idle_streak >= pol.down_confirm
                    and t - st.last_down_t >= pol.down_cooldown_s
                    and t - st.last_up_t >= pol.up_cooldown_s
                ):
                    to = max(pol.min_replicas, st.target - pol.down_step)
                    decision = ScaleDecision(
                        t, role, DOWN, REASON_IDLE, st.target, to,
                    )
                    st.target = to
                    st.last_down_t = t
                    st.idle_streak = 0
                    self._set_phase(st, SCALING_DOWN, t)
            else:
                # Dead-band: hold, and reset the idle streak — the
                # confirm counter measures CONSECUTIVE idle sweeps.
                st.idle_streak = 0
                self._set_phase(st, STEADY, t)
            if decision is not None:
                out.append(decision)
                self.decisions.append(decision)
                self._narrate(decision)
            self._gauge(role, st, t)
        return out

    def _set_phase(self, st: _RoleState, phase: str, t: float) -> None:
        if st.phase != phase:
            st.phase = phase
            st.phase_since = t

    def _narrate(self, d: ScaleDecision) -> None:
        if self.metrics is not None:
            self.metrics.autoscale_decision(d.role, d.direction, d.reason) \
                .add(1)
        if self.tracer is not None:
            self.tracer.scale_decision(
                d.role, d.direction, d.reason, d.frm, d.to,
            )

    def _gauge(self, role: str, st: _RoleState, t: float) -> None:
        if self.metrics is None:
            return
        self.metrics.autoscale_target(role).set(st.target or 0)
        self.metrics.autoscale_phase(role).set(PHASE_LEVEL[st.phase])
        since = st.phase_since if st.phase_since is not None else t
        self.metrics.autoscale_time_in_phase(role).set(max(0.0, t - since))

    # ---------------------------------------------------------- reporting

    def decision_digest(self) -> str:
        """SHA-256 over the decision stream's canonical bytes (timestamps
        included — a ManualClock makes them replayable): the byte-
        identity handle for same-seed control-loop replay assertions."""
        h = hashlib.sha256()
        for d in self.decisions:
            h.update(repr(tuple(d)).encode())
        return h.hexdigest()

    def summary(self) -> dict:
        by_reason: dict[str, int] = {}
        for d in self.decisions:
            key = f"{d.role}/{d.direction}/{d.reason}"
            by_reason[key] = by_reason.get(key, 0) + 1
        return {
            "targets": {
                role: st.target for role, st in sorted(self._state.items())
            },
            "phases": {
                role: st.phase for role, st in sorted(self._state.items())
            },
            "decisions": len(self.decisions),
            "by_reason": dict(sorted(by_reason.items())),
            "evaluations": self.evaluations,
            "digest": self.decision_digest(),
        }


# --------------------------------------------------------------- bindings


class FleetAutoscaler:
    """Close the loop for an in-process ``ServingFleet`` (+ optional
    ``PrefillPool``). Call ``step()`` once per scheduling round — e.g.
    from ``WorkloadGenerator.drive(on_round=...)``: it samples signals,
    evaluates the controller, and applies decisions via the fleet's
    warm ``scale_to`` (and the pool's, for the prefill role). Fully
    deterministic under a ManualClock."""

    def __init__(self, fleet, controller: AutoscaleController, *,
                 prefill=None) -> None:
        self.fleet = fleet
        self.controller = controller
        self.prefill = prefill
        if PREFILL in controller.policies and prefill is None:
            raise ValueError(
                "controller has a prefill policy but no PrefillPool was "
                "given"
            )

    def sample(self) -> dict[str, RoleSignals]:
        fleet = self.fleet
        serving = [r for r in fleet.replicas if r.state == "serving"]
        runnable = [r for r in fleet.replicas if r.runnable]
        depth = sum(r.queue.depth() for r in runnable)
        burn = (
            fleet.monitor.worst_state()
            if fleet.monitor is not None else OK
        )
        occ = [
            fleet.metrics.replica_occupancy(r.id).value for r in serving
        ]
        signals = {
            DECODE: RoleSignals(
                live=len(serving),
                queue_depth=depth,
                burn_state=burn,
                occupancy=sum(occ) / len(occ) if occ else 0.0,
            ),
        }
        if self.prefill is not None and PREFILL in self.controller.policies:
            signals[PREFILL] = RoleSignals(
                live=self.prefill.live_count(),
                queue_depth=self.prefill.backlog(),
                occupancy=self.prefill.occupancy(),
            )
        return signals

    def step(self) -> list[ScaleDecision]:
        if getattr(self.fleet, "_draining", False):
            # A fleet-wide drain outranks the controller: never spawn
            # into (or drain under) a shutdown in progress.
            return []
        decisions = self.controller.evaluate(self.sample())
        for d in decisions:
            if d.role == DECODE:
                self.fleet.scale_to(d.to)
            elif d.role == PREFILL and self.prefill is not None:
                self.prefill.scale_to(d.to)
        return decisions


class SupervisorAutoscaler:
    """Close the loop for a real-process ``ProcessFleet``: signals come
    from the broker the supervisor already watches (per-role consumer-
    group lag — offered work not yet committed), actuation is
    ``ProcessFleet.scale(n, role=...)``. Scale-up inherits fenced
    victims' member-id ranges (journal + radix locality); scale-down is
    the SIGTERM warm drain. Real processes live on the wall clock, so
    the controller here narrates rather than replays — the deterministic
    contract lives in the ManualClock bindings above."""

    def __init__(self, fleet, controller: AutoscaleController, *,
                 monitor=None) -> None:
        self.fleet = fleet
        self.controller = controller
        self.monitor = monitor

    def _lag(self, group: str) -> int:
        from torchkafka_tpu.source.records import TopicPartition

        broker = self.fleet.broker
        total = 0
        for p in range(broker.partitions_for(self.fleet.topic)):
            tp = TopicPartition(self.fleet.topic, p)
            total += broker.end_offset(tp) - (
                broker.committed(group, tp) or 0
            )
        return total

    def sample(self) -> dict[str, RoleSignals]:
        fleet = self.fleet
        burn = self.monitor.worst_state() if self.monitor is not None else OK
        signals = {
            DECODE: RoleSignals(
                live=len([
                    i for i in fleet.live() if i.state == "live"
                ]),
                queue_depth=self._lag(fleet.group),
                burn_state=burn,
            ),
        }
        if PREFILL in self.controller.policies:
            if fleet.handoff_topic is None:
                raise ValueError(
                    "prefill policy needs a disaggregated fleet "
                    "(ProcessFleet(prefill_replicas=..., kv_pages=...))"
                )
            signals[PREFILL] = RoleSignals(
                live=len([
                    i for i in fleet.live("prefill") if i.state == "live"
                ]),
                queue_depth=self._lag(f"{fleet.group}-prefill"),
            )
        return signals

    def step(self) -> list[ScaleDecision]:
        """One supervision round with the controller in the loop: sweep
        leases (poll_once), sample, evaluate, apply."""
        self.fleet.poll_once()
        decisions = self.controller.evaluate(self.sample())
        for d in decisions:
            self.fleet.scale(d.to, role=d.role)
        return decisions

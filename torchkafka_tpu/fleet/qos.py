"""QoS admission: per-tenant token buckets, priority lanes, backpressure.

The serving fleet's answer to "which prompt gets a slot when demand
exceeds capacity". Three cooperating mechanisms, all in front of the
generator's admission (the generators themselves stay QoS-blind):

- **Token buckets** rate-limit admission per tenant (tenant read from the
  record KEY by default — Kafka's natural multi-tenant partitioning
  handle). A tenant with no configured rate admits freely; a configured
  tenant admits at most ``rate`` prompts/sec sustained with ``burst``
  headroom. Throttled records stay QUEUED (they were polled and
  ledger-fetched, so the commit watermark stalls below them — re-delivery
  safe), they are never dropped.
- **Priority lanes**: interactive preempts batch for free slots —
  admission always drains the interactive lane before considering batch.
  Within a lane, tenants round-robin so one tenant's flood cannot starve
  another's trickle (head-of-line isolation is per (lane, tenant) queue).
- **Backpressure** is the replica's job (fleet/replica.py): when its slot
  pool is saturated AND its admission queue is at the high-water mark, it
  PAUSES its partitions (Consumer.pause — fetch stops, assignment and
  ledger state keep) and resumes at the low-water mark, so a saturated
  fleet holds a bounded queue instead of buffering the topic into memory.

Time is injectable (``clock``) so token-bucket behavior is exactly
testable with a fake clock; the default is ``time.monotonic``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Mapping

from torchkafka_tpu.source.records import Record, TopicPartition

INTERACTIVE = "interactive"
BATCH = "batch"


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/sec refill up to ``burst``
    capacity; ``try_acquire`` never blocks (a throttled record stays in
    its admission queue). Thread-safe for the threaded-fleet case."""

    def __init__(
        self, rate: float, burst: float | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if rate <= 0:
            raise ValueError(f"rate must be > 0 tokens/sec, got {rate}")
        self._rate = float(rate)
        self._burst = float(burst) if burst is not None else max(1.0, rate)
        if self._burst < 1.0:
            raise ValueError(f"burst must allow at least one token, got {burst}")
        self._clock = clock
        self._tokens = self._burst  # start full: a fresh tenant is not in debt
        self._t = clock()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self._burst, self._tokens + (now - self._t) * self._rate
            )
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


def default_tenant(record: Record) -> str:
    """Tenant = the record key (Kafka's partitioning identity); keyless
    records pool under one anonymous tenant."""
    if record.key is None:
        return "anon"
    try:
        return record.key.decode("utf-8")
    except UnicodeDecodeError:
        return record.key.hex()


def default_lane(record: Record) -> str:
    """Lane from the ``lane`` record header (``b"interactive"`` wins);
    everything else is batch — unclassified traffic must not preempt."""
    for k, v in record.headers:
        if k == "lane":
            return INTERACTIVE if v == b"interactive" else BATCH
    return BATCH


@dataclasses.dataclass(frozen=True)
class QoSConfig:
    """Admission policy for a serving fleet.

    ``tenant_rates``: prompts/sec per tenant; a missing tenant falls back
    to ``default_rate`` (None = unlimited). ``burst``: bucket capacity
    (None = max(1, rate)). ``max_queue_depth``/``resume_queue_depth``:
    per-replica backpressure high/low water marks (records queued beyond
    the slot pool)."""

    tenant_rates: Mapping[str, float] = dataclasses.field(default_factory=dict)
    default_rate: float | None = None
    burst: float | None = None
    tenant_of: Callable[[Record], str] = default_tenant
    lane_of: Callable[[Record], str] = default_lane
    max_queue_depth: int = 256
    resume_queue_depth: int = 64

    def __post_init__(self) -> None:
        if self.max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        if not 0 <= self.resume_queue_depth <= self.max_queue_depth:
            raise ValueError(
                "resume_queue_depth must sit in [0, max_queue_depth]"
            )


class TenantBuckets:
    """Fleet-shared per-tenant buckets (the rate is a TENANT's budget, not
    a per-replica one — replicas draw from the same bucket)."""

    def __init__(
        self, cfg: QoSConfig, clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._cfg = cfg
        self._clock = clock
        self._buckets: dict[str, TokenBucket | None] = {}
        self._lock = threading.Lock()

    def try_acquire(self, tenant: str) -> bool:
        with self._lock:
            if tenant not in self._buckets:
                rate = self._cfg.tenant_rates.get(tenant, self._cfg.default_rate)
                self._buckets[tenant] = (
                    None if rate is None
                    else TokenBucket(rate, self._cfg.burst, self._clock)
                )
            bucket = self._buckets[tenant]
        return True if bucket is None else bucket.try_acquire()


class AdmissionQueue:
    """One replica's lane/tenant-partitioned admission queue.

    ``push`` classifies and enqueues; ``select(n)`` pops up to ``n``
    admissible records — interactive lane fully first, tenants
    round-robin within a lane, each pop gated by the tenant's (shared)
    token bucket. Records denied by their bucket stay queued in order.
    """

    def __init__(
        self,
        cfg: QoSConfig,
        buckets: TenantBuckets,
        metrics,
        clock: Callable[[], float] = time.monotonic,
        *,
        tracer=None,
        replica: int | None = None,
        overload: Callable[[str, str], bool] | None = None,
        on_overload_defer: Callable[[str, int], None] | None = None,
        prefill_router: Callable[[Record], bool] | None = None,
    ) -> None:
        self._cfg = cfg
        self._buckets = buckets
        self._metrics = metrics
        self._clock = clock
        # Lifecycle tracing (obs.RecordTracer): a pop from this queue is
        # the record's qos_admitted stage boundary; ``replica`` tags it.
        self._tracer = tracer
        self._replica = replica
        # Burn-rate overload hook (obs.BurnRateMonitor.should_defer):
        # ``overload(lane, tenant) -> True`` leaves that tenant's records
        # QUEUED this sweep (deferral, never a drop — the watermark
        # stalls below them exactly like a bucket throttle), so a
        # shedding fleet sheds the batch lane instead of collapsing the
        # interactive SLO with it. ``on_overload_defer(tenant, n)``
        # reports each deferral decision for goodput accounting.
        self._overload = overload
        self._on_overload_defer = on_overload_defer
        # Disaggregated-prefill routing (fleet/prefill.py PrefillRouter
        # .should_hold): the shedding hook re-aimed as a ROUTING
        # decision — ``prefill_router(record) -> True`` keeps the
        # tenant's head-of-line record QUEUED this sweep because its
        # filled-KV handoff is still in flight from a prefill worker;
        # the router releases it on handoff arrival (adoption) or when
        # its patience expires (local-prefill fallback). Hold, never
        # drop: the watermark stalls below held records exactly like
        # throttles and burn deferrals.
        self._prefill_router = prefill_router
        # lane -> tenant -> deque[(record, enqueue_time)]
        self._q: dict[str, dict[str, deque]] = {INTERACTIVE: {}, BATCH: {}}
        self._rr: dict[str, int] = {INTERACTIVE: 0, BATCH: 0}
        self._depth = 0

    def push(self, record: Record) -> None:
        lane = self._cfg.lane_of(record)
        lane = lane if lane in self._q else BATCH
        tenant = self._cfg.tenant_of(record)
        self._q[lane].setdefault(tenant, deque()).append(
            (record, self._clock())
        )
        self._depth += 1
        self._metrics.tenant_queue_depth(tenant).set(
            self.tenant_depth(tenant)
        )

    def depth(self) -> int:
        return self._depth

    def tenant_depth(self, tenant: str) -> int:
        return sum(
            len(lane.get(tenant, ())) for lane in self._q.values()
        )

    def prune(self, assigned: set[TopicPartition]) -> int:
        """Drop queued records whose partition this replica no longer owns
        (rebalance took it): their NEW owner re-serves them from the
        committed offset — serving a stale copy here would be pure
        duplicate work behind a commit that can only fail. Returns the
        number dropped from the queue (not from the stream: they remain
        pending in the ledger until the failed-commit partitions age out,
        which is harmless — commits for unowned partitions are rejected
        broker-side)."""
        dropped = 0
        for lanes in self._q.values():
            for tenant, q in lanes.items():
                keep = deque(
                    (r, t) for r, t in q if r.tp in assigned
                )
                dropped += len(q) - len(keep)
                lanes[tenant] = keep
        self._depth -= dropped
        return dropped

    def select(self, n: int) -> list[Record]:
        """Up to ``n`` admissible records, interactive-first, tenant
        round-robin, bucket-gated. Observes lane queue-wait and per-tenant
        admit/throttle counters on the fleet metrics."""
        out: list[Record] = []
        now = self._clock()
        for lane in (INTERACTIVE, BATCH):
            lanes = self._q[lane]
            while len(out) < n:
                tenants = [t for t, q in lanes.items() if q]
                if not tenants:
                    break
                start = self._rr[lane] % len(tenants)
                order = tenants[start:] + tenants[:start]
                self._rr[lane] += 1
                progressed = False
                for tenant in order:
                    if len(out) >= n:
                        break
                    q = lanes[tenant]
                    if not q:
                        continue
                    if self._overload is not None and self._overload(
                        lane, tenant
                    ):
                        # Burn-rate shedding: defer (stay queued, one
                        # decision counted per tenant per sweep, like
                        # throttles) rather than admit into an already-
                        # burning SLO or drop the record.
                        self._metrics.tenant_deferred(tenant).add(1)
                        if self._on_overload_defer is not None:
                            self._on_overload_defer(tenant, 1)
                        continue
                    if self._prefill_router is not None and \
                            self._prefill_router(q[0][0]):
                        # Handoff still in flight: the tenant's FIFO
                        # head waits for its prefill worker (admitting
                        # records BEHIND it would break per-partition
                        # FIFO, so the whole tenant queue holds).
                        continue
                    if not self._buckets.try_acquire(tenant):
                        # Out of tokens: the record stays queued (and the
                        # watermark stalled below it). One throttle event
                        # per denied tenant per sweep, not per record —
                        # the counter measures throttle DECISIONS.
                        self._metrics.tenant_throttled(tenant).add(1)
                        continue
                    rec, t_enq = q.popleft()
                    self._depth -= 1
                    self._metrics.tenant_admitted(tenant).add(1)
                    self._metrics.tenant_queue_depth(tenant).set(
                        self.tenant_depth(tenant)
                    )
                    self._metrics.lane_wait(lane).observe(max(0.0, now - t_enq))
                    if self._tracer is not None:
                        self._tracer.qos_admitted(
                            rec, lane, max(0.0, now - t_enq),
                            replica=self._replica,
                        )
                    out.append(rec)
                    progressed = True
                if not progressed:
                    break
        return out

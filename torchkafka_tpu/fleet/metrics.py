"""Fleet observability: per-replica occupancy, per-tenant QoS counters,
pooled commit-latency percentiles — one summary dict and one Prometheus
exposition for the whole fleet, labeled per replica/tenant/lane, built on
the same primitives (and the shared renderer) as StreamMetrics and
ServeMetrics so dashboards treat all three uniformly."""

from __future__ import annotations

from torchkafka_tpu.utils.metrics import (
    Gauge,
    LatencyHistogram,
    RateMeter,
    format_labels,
    merge_latency_summaries,
    render_exposition,
)


class FleetMetrics:
    """The metric set one ServingFleet maintains.

    Per-tenant and per-lane series are created lazily through the
    accessors (``tenant_admitted`` etc.) so the tenant population never
    needs declaring up front — exactly like Prometheus label children.
    """

    def __init__(self) -> None:
        self.completions = RateMeter()
        self.duplicates = RateMeter()  # completions for an already-served
        # (topic, partition, offset): the fleet-level redelivery observable
        # — nonzero after a replica death, exactly zero in a clean run
        self.backpressure_pauses = RateMeter()
        self.backpressure_resumes = RateMeter()
        self.replica_deaths = RateMeter()
        self.drains = RateMeter()  # replicas that completed a graceful drain
        self.journal_handoffs = RateMeter()  # journal entries handed from a
        # dead replica to survivors as warm-resume hints
        self.drain_timeout_kills = RateMeter()  # replicas killed for
        # overrunning the drain timeout (journal synced first, so the next
        # incarnation resumes warm)
        self.replica_joins = RateMeter()  # members that joined the group
        # (initial spawn, respawn after fencing, scale-up)
        self.replica_fences = RateMeter()  # members evicted involuntarily:
        # lease expiry (real process death or a zombie too slow to renew),
        # kill, or drain-timeout escalation
        self.broker_restarts = RateMeter()  # broker deaths recovered from
        # the write-ahead log (ProcessFleet.restart_broker)
        self.leader_elections = RateMeter()  # broker-cell failovers: a
        # leader death absorbed by an epoch-bumped election + follower
        # promotion (ProcessFleet.kill_leader or a lapsed leader lease)
        self._member_lease_age: dict[str, Gauge] = {}  # seconds since the
        # member's last successful lease renewal (age = session timeout
        # minus observed remaining; 0 right after a heartbeat)
        # Live model lifecycle (fleet/rollout.py): the controller's
        # current phase (0 pending / 1 canary / 2 rolling / 3 complete /
        # 4 rolled_back), each member's serving version, canary shadow
        # token diffs, automatic rollbacks by reason, and checkpoint
        # frames rejected by the wire's CRC/shape gates (graceful
        # degradation — the replica keeps serving the incumbent).
        self.rollout_phase = Gauge()
        self.rollout_target_version = Gauge()
        self.canary_token_diffs = RateMeter()
        self._replica_model_version: dict[str, Gauge] = {}
        self._rollbacks: dict[str, RateMeter] = {}
        self._ckpt_rejects: dict[str, RateMeter] = {}
        # Online draft distillation (torchkafka_tpu/distill): the closed
        # loop's fleet-level view — the controller's windowed live-α,
        # the draft version it applied, each member's proposing draft,
        # refresh verdicts by reason, and the trainer's progress
        # (aggregated from trainer reports). All zero without a loop.
        self.spec_alpha_window = Gauge()
        self.draft_version = Gauge()  # fleet-APPLIED draft version
        self.distill_steps = RateMeter()
        self.distill_records = RateMeter()
        self._draft_refreshes: dict[str, RateMeter] = {}
        self._replica_draft_version: dict[str, Gauge] = {}
        # Autoscale controller families (fleet/autoscale.py): decision
        # counters labeled {role, direction, reason}, the controller's
        # current per-role target, and which phase (steady / scaling_up /
        # scaling_down) it sits in + for how long.
        self._autoscale_decisions: dict[tuple[str, str, str], RateMeter] = {}
        self._autoscale_target: dict[str, Gauge] = {}
        self._autoscale_phase: dict[str, Gauge] = {}
        self._autoscale_phase_s: dict[str, Gauge] = {}
        self._tenant_admitted: dict[str, RateMeter] = {}
        self._tenant_throttled: dict[str, RateMeter] = {}
        self._tenant_deferred: dict[str, RateMeter] = {}  # burn-rate
        # overload deferrals (AdmissionQueue's shedding hook) — distinct
        # from bucket throttles: policy chose to wait, not the tenant's rate
        self._tenant_queue_depth: dict[str, Gauge] = {}
        self._lane_wait: dict[str, LatencyHistogram] = {}
        self._replica_occupancy: dict[int, Gauge] = {}
        self._replica_completions: dict[int, RateMeter] = {}
        self._slo = None  # obs.SLOHistograms, attached by a traced fleet
        self._burn = None  # obs.BurnRateMonitor, attached alongside

    def attach_slo(self, slo) -> None:
        """Bind the fleet tracer's derived SLO histograms
        (``obs.SLOHistograms``) so TTFT / inter-token latency / queue
        wait / e2e percentiles per lane+tenant+replica ride this class's
        ``summary()`` and Prometheus exposition alongside the counters."""
        self._slo = slo

    def attach_burn(self, monitor) -> None:
        """Bind the fleet's ``obs.BurnRateMonitor`` so burn-rate states
        and the per-tenant goodput ledger (completed / within-SLO /
        deferred / quarantined) ride ``summary()`` and the exposition."""
        self._burn = monitor

    # ------------------------------------------------------ lazy accessors

    def tenant_admitted(self, tenant: str) -> RateMeter:
        return self._tenant_admitted.setdefault(tenant, RateMeter())

    def tenant_throttled(self, tenant: str) -> RateMeter:
        return self._tenant_throttled.setdefault(tenant, RateMeter())

    def tenant_deferred(self, tenant: str) -> RateMeter:
        return self._tenant_deferred.setdefault(tenant, RateMeter())

    def tenant_queue_depth(self, tenant: str) -> Gauge:
        return self._tenant_queue_depth.setdefault(tenant, Gauge())

    def lane_wait(self, lane: str) -> LatencyHistogram:
        return self._lane_wait.setdefault(lane, LatencyHistogram())

    def replica_occupancy(self, rid: int) -> Gauge:
        return self._replica_occupancy.setdefault(rid, Gauge())

    def replica_completions(self, rid: int) -> RateMeter:
        return self._replica_completions.setdefault(rid, RateMeter())

    def member_lease_age(self, member: str) -> Gauge:
        return self._member_lease_age.setdefault(member, Gauge())

    def autoscale_decision(self, role: str, direction: str,
                           reason: str) -> RateMeter:
        return self._autoscale_decisions.setdefault(
            (role, direction, reason), RateMeter()
        )

    def autoscale_target(self, role: str) -> Gauge:
        return self._autoscale_target.setdefault(role, Gauge())

    def autoscale_phase(self, role: str) -> Gauge:
        return self._autoscale_phase.setdefault(role, Gauge())

    def autoscale_time_in_phase(self, role: str) -> Gauge:
        return self._autoscale_phase_s.setdefault(role, Gauge())

    def replica_model_version(self, member: str) -> Gauge:
        return self._replica_model_version.setdefault(member, Gauge())

    def rollback(self, reason: str) -> RateMeter:
        return self._rollbacks.setdefault(reason, RateMeter())

    def draft_refreshes(self, reason: str) -> RateMeter:
        return self._draft_refreshes.setdefault(reason, RateMeter())

    def replica_draft_version(self, member: str) -> Gauge:
        return self._replica_draft_version.setdefault(member, Gauge())

    def checkpoint_reject(self, reason: str) -> RateMeter:
        return self._ckpt_rejects.setdefault(reason, RateMeter())

    # ----------------------------------------------------------- reporting

    def summary(self, replicas=None) -> dict:
        """``replicas``: the fleet's replica list, for the pooled
        commit-latency percentiles (each replica's generator keeps its own
        histogram; the fleet view pools the sample windows) and the
        aggregated prefix-cache view (each replica owns a PER-REPLICA
        paged pool + radix tree — kvcache/ — so the fleet hit rate is the
        count-weighted merge of the per-replica counters)."""
        commit = merge_latency_summaries(
            [r.gen.metrics.commit_latency for r in replicas]
            if replicas else []
        )
        gens = [r.gen.metrics for r in replicas] if replicas else []
        hits = sum(m.prefix_hits.count for m in gens)
        misses = sum(m.prefix_misses.count for m in gens)
        occ = [m.cache_pool_occupancy.value for m in gens]
        cache = {
            "hits": hits,
            "misses": misses,
            "hit_rate": (
                round(hits / (hits + misses), 4) if hits + misses else None
            ),
            "prefix_tokens_saved": sum(
                m.prefix_tokens_saved.count for m in gens
            ),
            "prefill_tokens": sum(m.prefill_tokens.count for m in gens),
            "evictions": sum(m.cache_evictions.count for m in gens),
            "deferrals": sum(m.admission_deferrals.count for m in gens),
            "fallbacks": sum(m.cache_fallbacks.count for m in gens),
            "pool_occupancy": round(sum(occ) / len(occ), 3) if occ else 0.0,
            "tier": {
                "demotions": sum(m.radix_demotions.count for m in gens),
                "promotions": sum(m.radix_promotions.count for m in gens),
                "hits": sum(m.tier_hits.count for m in gens),
                "occupancy_bytes": int(sum(
                    m.tier_occupancy_bytes.value for m in gens
                )),
            },
        }
        disagg = {
            "prefill_routed": sum(m.prefill_routed.count for m in gens),
            "adopted_slots": sum(m.adopted_slots.count for m in gens),
            "handoffs_published": sum(
                m.handoffs_published.count for m in gens
            ),
        }
        chunk_ticks = sum(m.chunk_ticks.count for m in gens)
        chunk_prefill_tokens = sum(m.prefill_tokens.count for m in gens)
        chunked = {
            "chunk_ticks": chunk_ticks,
            "prefill_tokens_per_tick": (
                round(chunk_prefill_tokens / chunk_ticks, 2)
                if chunk_ticks else None
            ),
            "stall_ticks": sum(
                m.admission_stall_ticks.count for m in gens
            ),
            "queue_tokens": int(sum(
                m.admission_queue_tokens.value for m in gens
            )),
        }
        journal = {
            "handoffs": self.journal_handoffs.count,
            "drain_timeout_kills": self.drain_timeout_kills.count,
            "warm_resumes": sum(m.warm_resumes.count for m in gens),
            "tokens_restored": sum(
                m.journal_tokens_restored.count for m in gens
            ),
            "served_from_journal": sum(m.journal_served.count for m in gens),
            "resume_rejected": sum(m.resume_rejected.count for m in gens),
        }
        # Device-side "where did the tick go": per-replica step times
        # pooled with the same sample-window merge as the commit
        # percentiles, tokens-per-tick averaged over replicas that ticked.
        tpt = [
            m.tokens_per_tick.value for m in gens if m.tick_time.count
        ]
        serving = {
            "ticks": sum(m.tick_time.count for m in gens),
            "step_time": merge_latency_summaries(
                [m.tick_time for m in gens]
            ),
            "tokens_per_tick": (
                round(sum(tpt) / len(tpt), 2) if tpt else 0.0
            ),
            "output_capped": sum(m.output_capped.count for m in gens),
        }
        autoscale = {
            "targets": {
                role: int(g.value)
                for role, g in sorted(self._autoscale_target.items())
            },
            "phase": {
                role: int(g.value)
                for role, g in sorted(self._autoscale_phase.items())
            },
            "time_in_phase_s": {
                role: round(g.value, 4)
                for role, g in sorted(self._autoscale_phase_s.items())
            },
            "decisions": {
                f"{role}/{direction}/{reason}": m.count
                for (role, direction, reason), m in sorted(
                    self._autoscale_decisions.items()
                )
            },
        }
        rollout = {
            "phase": int(self.rollout_phase.value),
            "target_version": int(self.rollout_target_version.value),
            "canary_token_diffs": self.canary_token_diffs.count,
            "member_versions": {
                m: int(g.value)
                for m, g in sorted(self._replica_model_version.items())
            },
            "rollbacks": {
                reason: m.count
                for reason, m in sorted(self._rollbacks.items())
            },
            "checkpoint_rejects": {
                reason: m.count
                for reason, m in sorted(self._ckpt_rejects.items())
            },
        }
        distill = {
            "alpha_window": round(self.spec_alpha_window.value, 4),
            "applied_version": int(self.draft_version.value),
            "steps": self.distill_steps.count,
            "records": self.distill_records.count,
            "member_draft_versions": {
                m: int(g.value)
                for m, g in sorted(self._replica_draft_version.items())
            },
            "refreshes": {
                reason: m.count
                for reason, m in sorted(self._draft_refreshes.items())
            },
        }
        membership = {
            "joins": self.replica_joins.count,
            "fences": self.replica_fences.count,
            "broker_restarts": self.broker_restarts.count,
            "leader_elections": self.leader_elections.count,
            "lease_age_s": {
                m: round(g.value, 3)
                for m, g in sorted(self._member_lease_age.items())
            },
        }
        return {
            "membership": membership,
            "rollout": rollout,
            "distill": distill,
            "autoscale": autoscale,
            "slo": self._slo.summary() if self._slo is not None else None,
            "burn": (
                self._burn.summary() if self._burn is not None else None
            ),
            "goodput": (
                self._burn.goodput_summary()
                if self._burn is not None else None
            ),
            "serving": serving,
            "prefix_cache": cache,
            "disagg": disagg,
            "chunked_prefill": chunked,
            "journal": journal,
            "completions": self.completions.count,
            "completions_per_s": round(self.completions.rate(), 1),
            "duplicates": self.duplicates.count,
            "backpressure_pauses": self.backpressure_pauses.count,
            "backpressure_resumes": self.backpressure_resumes.count,
            "replica_deaths": self.replica_deaths.count,
            "drains": self.drains.count,
            "tenants": {
                t: {
                    "admitted": self.tenant_admitted(t).count,
                    "admitted_per_s": round(self.tenant_admitted(t).rate(), 2),
                    "throttled": self.tenant_throttled(t).count,
                    "deferred": self.tenant_deferred(t).count,
                    "queue_depth": int(self.tenant_queue_depth(t).value),
                }
                for t in sorted(
                    set(self._tenant_admitted)
                    | set(self._tenant_throttled)
                    | set(self._tenant_deferred)
                )
            },
            "lanes": {
                lane: h.summary() for lane, h in sorted(self._lane_wait.items())
            },
            "replicas": {
                rid: {
                    "slot_occupancy": round(
                        self.replica_occupancy(rid).value, 3
                    ),
                    "completions": self.replica_completions(rid).count,
                }
                for rid in sorted(self._replica_occupancy)
            },
            "commit": commit,
        }

    def render_prometheus(
        self, prefix: str = "torchkafka_fleet", replicas=None,
    ) -> str:
        s = self.summary(replicas)
        pc = s["prefix_cache"]
        cp = s["chunked_prefill"]
        sv = s["serving"]
        series = [
            ("serve_ticks_total", "counter", sv["ticks"]),
            ("step_time_ms", "gauge", [
                ('percentile="p50"', sv["step_time"]["p50_ms"]),
                ('percentile="p99"', sv["step_time"]["p99_ms"]),
            ]),
            ("tokens_per_tick", "gauge", sv["tokens_per_tick"]),
            ("output_capped_total", "counter", sv["output_capped"]),
            ("chunk_ticks_total", "counter", cp["chunk_ticks"]),
            ("admission_stall_ticks_total", "counter", cp["stall_ticks"]),
            ("admission_queue_tokens", "gauge", cp["queue_tokens"]),
            ("prefill_tokens_per_chunk_tick", "gauge",
             cp["prefill_tokens_per_tick"] or 0.0),
            ("completions_total", "counter", s["completions"]),
            ("duplicate_completions_total", "counter", s["duplicates"]),
            ("backpressure_pauses_total", "counter", s["backpressure_pauses"]),
            ("backpressure_resumes_total", "counter", s["backpressure_resumes"]),
            ("replica_deaths_total", "counter", s["replica_deaths"]),
            ("replica_drains_total", "counter", s["drains"]),
            ("replica_joins_total", "counter", s["membership"]["joins"]),
            ("replica_fences_total", "counter", s["membership"]["fences"]),
            ("broker_restarts_total", "counter",
             s["membership"]["broker_restarts"]),
            ("leader_elections_total", "counter",
             s["membership"]["leader_elections"]),
            ("member_lease_age_seconds", "gauge", [
                (format_labels(member=m), age)
                for m, age in s["membership"]["lease_age_s"].items()
            ] or 0),
            ("autoscale_decisions_total", "counter", [
                (
                    format_labels(
                        role=role, direction=direction, reason=reason
                    ),
                    m.count,
                )
                for (role, direction, reason), m in sorted(
                    self._autoscale_decisions.items()
                )
            ] or 0),
            ("autoscale_target_replicas", "gauge", [
                (format_labels(role=role), v)
                for role, v in s["autoscale"]["targets"].items()
            ] or 0),
            ("autoscale_phase", "gauge", [
                (format_labels(role=role), v)
                for role, v in s["autoscale"]["phase"].items()
            ] or 0),
            ("autoscale_time_in_phase_seconds", "gauge", [
                (format_labels(role=role), v)
                for role, v in s["autoscale"]["time_in_phase_s"].items()
            ] or 0),
            ("rollout_phase", "gauge", s["rollout"]["phase"]),
            ("rollout_target_version", "gauge",
             s["rollout"]["target_version"]),
            ("canary_token_diffs_total", "counter",
             s["rollout"]["canary_token_diffs"]),
            ("replica_model_version", "gauge", [
                (format_labels(member=m), v)
                for m, v in s["rollout"]["member_versions"].items()
            ] or 0),
            ("rollbacks_total", "counter", [
                (format_labels(reason=reason), v)
                for reason, v in s["rollout"]["rollbacks"].items()
            ] or 0),
            ("checkpoint_rejects_total", "counter", [
                (format_labels(reason=reason), v)
                for reason, v in s["rollout"]["checkpoint_rejects"].items()
            ] or 0),
            ("spec_alpha_window", "gauge", s["distill"]["alpha_window"]),
            ("draft_applied_version", "gauge",
             s["distill"]["applied_version"]),
            ("draft_version", "gauge", [
                (format_labels(member=m), v)
                for m, v in s["distill"]["member_draft_versions"].items()
            ] or 0),
            ("draft_refreshes_total", "counter", [
                (format_labels(reason=reason), v)
                for reason, v in s["distill"]["refreshes"].items()
            ] or 0),
            ("distill_steps_total", "counter", s["distill"]["steps"]),
            ("distill_records_total", "counter", s["distill"]["records"]),
            ("journal_handoffs_total", "counter", s["journal"]["handoffs"]),
            ("drain_timeout_kills_total", "counter",
             s["journal"]["drain_timeout_kills"]),
            ("warm_resumes_total", "counter", s["journal"]["warm_resumes"]),
            ("journal_tokens_restored_total", "counter",
             s["journal"]["tokens_restored"]),
            ("journal_served_total", "counter",
             s["journal"]["served_from_journal"]),
            ("resume_rejected_total", "counter",
             s["journal"]["resume_rejected"]),
            ("completions_per_second", "gauge", s["completions_per_s"]),
            ("tenant_admitted_total", "counter", [
                (format_labels(tenant=t), v["admitted"])
                for t, v in s["tenants"].items()
            ] or 0),
            ("tenant_throttled_total", "counter", [
                (format_labels(tenant=t), v["throttled"])
                for t, v in s["tenants"].items()
            ] or 0),
            ("tenant_deferred_total", "counter", [
                (format_labels(tenant=t), v["deferred"])
                for t, v in s["tenants"].items()
            ] or 0),
            ("tenant_queue_depth", "gauge", [
                (format_labels(tenant=t), v["queue_depth"])
                for t, v in s["tenants"].items()
            ] or 0),
            ("lane_queue_wait_ms", "gauge", [
                (format_labels(lane=lane, percentile="p50"), v["p50_ms"])
                for lane, v in s["lanes"].items()
            ] + [
                (format_labels(lane=lane, percentile="p99"), v["p99_ms"])
                for lane, v in s["lanes"].items()
            ] or 0),
            ("replica_slot_occupancy", "gauge", [
                (format_labels(replica=rid), v["slot_occupancy"])
                for rid, v in s["replicas"].items()
            ] or 0),
            ("replica_completions_total", "counter", [
                (format_labels(replica=rid), v["completions"])
                for rid, v in s["replicas"].items()
            ] or 0),
            ("commit_latency_ms", "gauge", [
                ('percentile="p50"', s["commit"]["p50_ms"]),
                ('percentile="p99"', s["commit"]["p99_ms"]),
            ]),
            ("prefix_cache_hits_total", "counter", pc["hits"]),
            ("prefix_cache_misses_total", "counter", pc["misses"]),
            ("prefix_tokens_saved_total", "counter", pc["prefix_tokens_saved"]),
            ("prefill_tokens_total", "counter", pc["prefill_tokens"]),
            ("kvcache_evictions_total", "counter", pc["evictions"]),
            ("admission_deferrals_total", "counter", pc["deferrals"]),
            ("prefix_cache_hit_rate", "gauge", pc["hit_rate"] or 0.0),
            ("kvcache_pool_occupancy", "gauge", pc["pool_occupancy"]),
            ("radix_demotions_total", "counter", pc["tier"]["demotions"]),
            ("radix_promotions_total", "counter", pc["tier"]["promotions"]),
            ("tier_hits_total", "counter", pc["tier"]["hits"]),
            ("tier_occupancy_bytes", "gauge", pc["tier"]["occupancy_bytes"]),
            ("prefill_routed_total", "counter",
             s["disagg"]["prefill_routed"]),
            ("adopted_slots_total", "counter", s["disagg"]["adopted_slots"]),
            ("prefill_handoffs_published_total", "counter",
             s["disagg"]["handoffs_published"]),
        ]
        if self._slo is not None:
            series.extend(self._slo.series())
        if self._burn is not None:
            series.extend(
                (f"burn_{name}", *rest)
                for name, *rest in self._burn.series()
            )
        return render_exposition(prefix, series)

"""Rolling weight hot-swap: canary shadow-serving, drain-swap, rollback.

The live model lifecycle plane. A new model version travels as a
versioned checkpoint on a broker topic (source/checkpoint_wire.py — the
same CRC'd, chunked, pickle-free wire discipline as the prefill
handoff), and a ``RolloutController`` walks the fleet through it:

    pending → canary → rolling → complete
                  ↘        ↘
                   rolled_back

- **canary**: ONE replica shadow-serves a deterministic slice of its
  own live traffic under the candidate weights (``spawn_shadow`` /
  ``shadow_decode`` — the shadow has no producer, no journal, and a
  structurally empty consumer assignment, so nothing it does can reach
  a broker) and token-diffs the shadow's outputs against the incumbent's.
  Divergence beyond the gate triggers AUTOMATIC rollback: the candidate
  never reaches a second replica, by construction — no swap directive
  is issued until the canary verdict is in.
- **rolling**: replicas drain-swap ONE AT A TIME behind the existing
  lease protocol. The swap is the PR-15 warm-drain mechanism turned
  inward: ``pause_admission`` (finish in-flight WITHOUT leaving the
  group — a weight swap must not cost a rebalance), close the commit
  window (``maybe_flush(force=True)``), then ``swap_params`` rebinds
  the jitted programs' params argument in place — zero recompiles. The
  journal records the new version BEFORE the rebind, so a SIGKILL at
  either swap crash point restarts on an unambiguous version.
- **rolled_back**: swapped replicas drain-swap BACK to the incumbent,
  newest first; the controller is done when the last swap-back acks.

Every phase transition is typed on the trace stream
(``rollout_phase`` / ``canary_started`` / ``swapped`` /
``rolled_back``) and gauged on FleetMetrics, so an operator — or the
differential test — can replay the lifecycle from either surface.

Two transports share the one state machine:

- ``BrokerRolloutDriver`` + ``RolloutWorker``: directives and acks are
  JSON records on a 1-partition control topic — the real-process fleet
  (fleet/proc.py workers poll the control cursor every pump). After
  completion the driver FENCES any live group member still on a stale
  version, exactly like a stale-generation commit: a zombie that missed
  the rollout cannot write old-version outputs into the committed view.
- ``InProcessRolloutDriver``: drives a ``ServingFleet`` from serve()'s
  ``on_round`` hook on the calling thread — every interleaving stays
  deterministic under the cooperative scheduler, which is what the
  differential tests replay.
"""

from __future__ import annotations

import json
import logging

import numpy as np

from torchkafka_tpu.errors import CheckpointWireError
from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.source.checkpoint_wire import fetch_checkpoint, rebuild_tree
from torchkafka_tpu.source.records import TopicPartition

_logger = logging.getLogger(__name__)

PENDING = "pending"
CANARY = "canary"
ROLLING = "rolling"
COMPLETE = "complete"
ROLLED_BACK = "rolled_back"

# Numeric phase encoding for the ``rollout_phase`` gauge (a Prometheus
# gauge holds one float; the mapping is part of the exposition contract).
PHASE_CODES = {PENDING: 0, CANARY: 1, ROLLING: 2, COMPLETE: 3, ROLLED_BACK: 4}


class RolloutController:
    """The rollout state machine, transport-agnostic.

    Members are opaque ids (replica ints in-process, member-id strings
    for the real-process fleet). Every method that advances the machine
    returns the list of DIRECTIVES the transport must deliver next —
    the controller never touches a broker or a replica itself, which is
    why one machine serves both fleets and why its unit tests need
    neither.

    ``max_canary_diffs`` is the divergence gate: a canary report with
    more mismatched completions than this rolls the fleet back. The
    default 0 encodes the paper's determinism contract — a weights-only
    refresh of the same architecture must be token-identical on the
    greedy path, so ANY diff is a bad checkpoint.
    """

    def __init__(
        self,
        members,
        version: int,
        *,
        canary_member=None,
        canary_slice: int = 8,
        max_canary_diffs: int = 0,
        incumbent_version: int = 0,
        tracer=None,
        metrics=None,
        trace_acks: bool = True,
    ) -> None:
        self.members = list(members)
        if not self.members:
            raise ValueError("a rollout needs at least one member")
        self.version = int(version)
        self.incumbent_version = int(incumbent_version)
        if self.version == self.incumbent_version:
            raise ValueError(
                f"target version {self.version} is already the incumbent"
            )
        self.canary_member = (
            canary_member if canary_member is not None else self.members[0]
        )
        if self.canary_member not in self.members:
            raise ValueError(f"canary {self.canary_member!r} not in members")
        self.canary_slice = int(canary_slice)
        self.max_canary_diffs = int(max_canary_diffs)
        self._tracer = tracer
        self._metrics = metrics
        self._trace_acks = trace_acks
        self.phase = PENDING
        self.rollback_reason: str | None = None
        # Everyone serves the incumbent until their swap acks.
        self.member_versions = {
            m: self.incumbent_version for m in self.members
        }
        self.swapped: list = []  # acked the TARGET version, in swap order
        self._queue: list = []  # members awaiting a swap directive
        self._awaiting = None  # member directed but not yet acked

    # ------------------------------------------------------------- phases

    def _set_phase(self, phase: str) -> None:
        self.phase = phase
        if self._tracer is not None:
            self._tracer.rollout_phase(phase, self.version)
        if self._metrics is not None:
            self._metrics.rollout_phase.set(PHASE_CODES[phase])

    def begin(self) -> list[dict]:
        """pending → canary: direct the canary member to shadow-serve
        ``canary_slice`` completions under the candidate version."""
        if self.phase != PENDING:
            raise RuntimeError(f"begin() in phase {self.phase!r}")
        if self._metrics is not None:
            self._metrics.rollout_target_version.set(self.version)
            for m in self.members:
                self._metrics.replica_model_version(str(m)).set(
                    self.member_versions[m]
                )
        self._set_phase(CANARY)
        if self._tracer is not None:
            self._tracer.canary_started(
                str(self.canary_member), self.version,
                slice_n=self.canary_slice,
            )
        return [{
            "t": "canary", "member": self.canary_member,
            "version": self.version, "n": self.canary_slice,
        }]

    def note_canary_report(self, member, diffs: int, compared: int,
                           version: int | None = None) -> list[dict]:
        """The canary verdict: token-clean → start rolling (canary
        member swaps first — it already validated the weights); any
        divergence past the gate → automatic rollback. Off-phase,
        off-member, or off-VERSION reports are ignored — the control
        topic outlives individual rollouts, so a report from a previous
        rollout's canary must never gate this one."""
        if self.phase != CANARY or member != self.canary_member:
            return []
        if version is not None and int(version) != self.version:
            return []
        if self._metrics is not None:
            self._metrics.canary_token_diffs.add(int(diffs))
        if diffs > self.max_canary_diffs:
            _logger.warning(
                "canary %s diverged: %d/%d completions mismatched under "
                "version %d — rolling back", member, diffs, compared,
                self.version,
            )
            return self.rollback("canary_divergence")
        self._set_phase(ROLLING)
        self._queue = [self.canary_member] + [
            m for m in self.members if m != self.canary_member
        ]
        return self._next()

    def note_ack(self, member, version: int) -> list[dict]:
        """A member finished its drain-swap. One at a time: the NEXT
        directive is only issued once this ack lands, so a wedged swap
        can never leave two replicas quiesced at once."""
        if member != self._awaiting:
            return []
        expect = (
            self.incumbent_version if self.phase == ROLLED_BACK
            else self.version
        )
        if int(version) != expect:
            return []
        self.member_versions[member] = int(version)
        if self._metrics is not None:
            self._metrics.replica_model_version(str(member)).set(int(version))
        if self._trace_acks and self._tracer is not None:
            self._tracer.swapped(int(version), member=str(member))
        if self.phase == ROLLING:
            self.swapped.append(member)
        elif self.phase == ROLLED_BACK and member in self.swapped:
            self.swapped.remove(member)
        self._awaiting = None
        return self._next()

    def note_reject(self, member, version: int, reason: str) -> list[dict]:
        """A member could not apply the checkpoint (torn frames, CRC
        mismatch, tree drift). The member keeps serving the incumbent —
        graceful degradation locally — and the ROLLOUT rolls back: a
        checkpoint one replica rejects must not half-apply across the
        fleet. A reject for any version other than the current target
        is stale control-topic traffic and is ignored."""
        if self.phase in (PENDING, COMPLETE, ROLLED_BACK):
            return []
        if int(version) != self.version:
            return []
        return self.rollback(str(reason))

    def rollback(self, reason: str) -> list[dict]:
        """Halt the rollout and drain-swap every already-swapped member
        back to the incumbent, newest swap first (unwind order)."""
        if self.phase in (COMPLETE, ROLLED_BACK):
            return []
        self.rollback_reason = str(reason)
        if self._tracer is not None:
            self._tracer.rolled_back(self.rollback_reason, self.version)
        if self._metrics is not None:
            self._metrics.rollback(self.rollback_reason).add(1)
        self._set_phase(ROLLED_BACK)
        self._queue = list(reversed(self.swapped))
        self._awaiting = None
        return self._next()

    def _next(self) -> list[dict]:
        if self._awaiting is not None:
            return []
        if self._queue:
            m = self._queue.pop(0)
            self._awaiting = m
            version = (
                self.incumbent_version if self.phase == ROLLED_BACK
                else self.version
            )
            return [{"t": "swap", "member": m, "version": version}]
        if self.phase == ROLLING:
            self._set_phase(COMPLETE)
        return []

    @property
    def done(self) -> bool:
        """Terminal AND settled: complete, or rolled back with every
        swap-back acked (a rollback is only over once no replica is
        left on the candidate version)."""
        if self.phase == COMPLETE:
            return True
        return (
            self.phase == ROLLED_BACK
            and not self.swapped
            and self._awaiting is None
            and not self._queue
        )


class BrokerRolloutDriver:
    """Controller-side transport over the control topic (real-process
    fleets). Directives go out as JSON records; worker acks/reports/
    rejects come back on the SAME topic — the driver's cursor reads
    everything and dispatches by message type, ignoring its own
    directives. After completion, any live group member still on a
    stale version is FENCED (``group`` given): the zombie's lease dies
    and its stale-generation commits are already rejected, so an
    old-version output can never enter the committed view.
    """

    def __init__(self, broker, topic: str, controller: RolloutController,
                 *, group: str | None = None) -> None:
        self._broker = broker
        self._topic = topic
        self._tp = TopicPartition(topic, 0)
        self._ctl = controller
        self._group = group
        # Cursor starts at the CURRENT end of the control topic: the
        # topic outlives individual rollouts, and a fresh driver must
        # never replay a previous rollout's acks/reports into this
        # controller (the version gates below are the second line of
        # defence; this is the first).
        self._cursor = int(broker.end_offset(self._tp))
        self._started = False
        self._fenced_stale = False

    @property
    def controller(self) -> RolloutController:
        return self._ctl

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._publish(self._ctl.begin())

    def pump(self) -> None:
        """One control-plane sweep: deliver worker messages to the
        state machine, publish whatever directives fall out."""
        if not self._started:
            self.start()
        while True:
            recs = self._broker.fetch(self._tp, self._cursor, 256)
            if not recs:
                break
            self._cursor = recs[-1].offset + 1
            for rec in recs:
                msg = _decode_control(rec.value)
                if msg is None:
                    continue
                t = msg.get("t")
                if t == "ack":
                    self._publish(self._ctl.note_ack(
                        msg.get("member"), int(msg.get("version", -1)),
                    ))
                elif t == "canary_report":
                    self._publish(self._ctl.note_canary_report(
                        msg.get("member"), int(msg.get("diffs", 0)),
                        int(msg.get("compared", 0)),
                        version=msg.get("version"),
                    ))
                elif t == "reject":
                    self._publish(self._ctl.note_reject(
                        msg.get("member"), int(msg.get("version", -1)),
                        str(msg.get("reason", "reject")),
                    ))
                # "canary"/"swap" are our own directives echoing back.
        if self._ctl.phase == COMPLETE and not self._fenced_stale:
            self._fence_stale()

    def _fence_stale(self) -> None:
        """Post-completion zombie sweep: a member the broker still lists
        live but that never acked the target version is serving stale
        weights — fence it, exactly like an expired lease."""
        self._fenced_stale = True
        if self._group is None:
            return
        live = self._broker.membership(self._group).get("members", [])
        for m in live:
            if self._ctl.member_versions.get(m) != self._ctl.version:
                _logger.warning(
                    "fencing stale-version member %s (serving %s, fleet "
                    "completed rollout to %d)", m,
                    self._ctl.member_versions.get(m), self._ctl.version,
                )
                self._broker.fence(self._group, m)

    @property
    def done(self) -> bool:
        return self._ctl.done

    def _publish(self, directives: list[dict]) -> None:
        for d in directives:
            self._broker.produce(
                self._topic, json.dumps(d).encode(), partition=0,
            )


class RolloutWorker:
    """Worker-side rollout plane for one real-process replica
    (fleet/proc.py hooks ``pump(completions)`` into its pump loop).

    Keeps a raw fetch cursor on the control topic (partition 0, from
    offset 0 — directives published before this worker booted still
    apply: that is how a crash-restarted worker rejoins a rollout
    mid-flight). Checkpoints are fetched lazily per version and cached
    AS TREES keyed by version — the incumbent's boot weights are seeded
    into the cache, so a rollback swap-back never needs the wire.

    A checkpoint that fails wire validation (``CheckpointWireError``:
    torn manifest, truncated chunk, CRC flip, tree drift) is REJECTED —
    counted on /metrics, reported to the controller — and the worker
    keeps serving the incumbent untouched. Graceful degradation, never
    a crash: the next rollout attempt re-fetches from scratch.
    """

    def __init__(
        self,
        broker,
        topic: str,
        ckpt_topic: str,
        member: str,
        rep,
        *,
        boot_params,
        boot_version: int = 0,
        metrics=None,
    ) -> None:
        self._broker = broker
        self._topic = topic
        self._tp = TopicPartition(topic, 0)
        self._ckpt_topic = ckpt_topic
        self._member = member
        self._rep = rep
        self._metrics = metrics
        self._cursor = 0
        self._params_by_version = {int(boot_version): boot_params}
        # Canary state: (version, n, shadow generator, diffs, compared).
        self._canary = None
        self._pending_swap: int | None = None

    @property
    def model_version(self) -> int:
        return self._rep.gen.model_version

    def cache(self, version: int, params) -> None:
        """Pre-seed the version cache (e.g. a restored incarnation's
        rebuilt tree — a rollback to it must not re-fetch)."""
        self._params_by_version[int(version)] = params

    def pump(self, completions) -> None:
        """One rollout sweep, called every worker pump with that pump's
        completions (the canary's comparison stream)."""
        self._poll_directives()
        if self._canary is not None:
            self._run_canary(completions)
        if self._pending_swap is not None:
            self._try_swap()

    # ----------------------------------------------------------- directives

    def _poll_directives(self) -> None:
        while True:
            recs = self._broker.fetch(self._tp, self._cursor, 256)
            if not recs:
                break
            self._cursor = recs[-1].offset + 1
            for rec in recs:
                msg = _decode_control(rec.value)
                if msg is None or msg.get("member") != self._member:
                    continue
                t = msg.get("t")
                if t == "canary":
                    self._start_canary(
                        int(msg.get("version", -1)), int(msg.get("n", 1)),
                    )
                elif t == "swap":
                    self._pending_swap = int(msg.get("version", -1))
                    self._rep.pause_admission()

    def _start_canary(self, version: int, n: int) -> None:
        params = self._resolve(version)
        if params is None:
            return  # rejected; incumbent keeps serving
        shadow = self._rep.gen.spawn_shadow(params, version)
        self._canary = [version, max(1, n), shadow, 0, 0]

    def _run_canary(self, completions) -> None:
        version, n, shadow, diffs, compared = self._canary
        for rec, toks in completions:
            if compared >= n:
                break
            got = shadow.shadow_decode(rec)
            if got is None or not np.array_equal(
                np.asarray(got), np.asarray(toks)
            ):
                diffs += 1
                if self._metrics is not None:
                    self._metrics.canary_token_diffs.add(1)
            compared += 1
        self._canary[3], self._canary[4] = diffs, compared
        if compared >= n:
            # The verdict is about to become durable on the control
            # topic — a SIGKILL here must leave the incumbent serving
            # and the controller free to retry or roll back.
            crash_hook("canary_pre_verdict")
            self._send({
                "t": "canary_report", "member": self._member,
                "version": version, "diffs": diffs, "compared": compared,
            })
            self._canary = None

    def _try_swap(self) -> None:
        """Complete a pending drain-swap once quiesced: close the commit
        window, rebind params (journal flips first inside swap_params),
        resume admission, ack. Retries every pump until the replica
        actually quiesces and the flush actually lands."""
        version = self._pending_swap
        if not self._rep.quiesced:
            return  # in-flight generations still retiring
        params = self._resolve(version)
        if params is None:
            # Torn checkpoint: abandon the swap, keep the incumbent.
            self._pending_swap = None
            self._rep.resume_admission()
            return
        self._rep.maybe_flush(force=True)
        try:
            self._rep.gen.swap_params(params, version)
        except RuntimeError:
            return  # commit window not closed yet (flush retrying)
        self._pending_swap = None
        self._rep.resume_admission()
        self._send({"t": "ack", "member": self._member, "version": version})

    # ----------------------------------------------------------- checkpoint

    def _resolve(self, version: int):
        """Version → params tree, from cache or the checkpoint topic.
        Wire failure → reject (counted, reported), return None."""
        cached = self._params_by_version.get(version)
        if cached is not None:
            return cached
        try:
            flat, _manifest = fetch_checkpoint(
                self._broker, self._ckpt_topic, version,
            )
            # The incumbent tree is the schema: a checkpoint that does
            # not match it array-for-array is rejected here, before any
            # weight is touched.
            params = rebuild_tree(self._rep.gen._params, flat)
        except CheckpointWireError as e:
            _logger.warning(
                "member %s rejecting checkpoint v%d: %s",
                self._member, version, e,
            )
            if self._metrics is not None:
                self._metrics.checkpoint_reject("wire").add(1)
            self._send({
                "t": "reject", "member": self._member,
                "version": version, "reason": str(e)[:200],
            })
            return None
        self._params_by_version[version] = params
        return params

    def _send(self, msg: dict) -> None:
        self._broker.produce(
            self._topic, json.dumps(msg).encode(), partition=0,
        )


class InProcessRolloutDriver:
    """Drive a rollout against a ``ServingFleet`` on the calling thread.

    Plug ``on_round`` into ``fleet.serve(on_round=...)`` and feed every
    yielded completion to ``observe`` — the same cooperative loop the
    differential tests already replay, so a rollout interleaving is as
    deterministic as any other fleet schedule. ``versions`` maps version
    ints to params trees (the in-process twin of the checkpoint topic;
    the incumbent's entry is what rollback swaps back to).
    """

    def __init__(self, fleet, controller: RolloutController,
                 versions: dict) -> None:
        self._fleet = fleet
        self._ctl = controller
        self._versions = dict(versions)
        self._started = False
        self._canary = None  # [rid, version, n, shadow, diffs, compared]
        self._pending_swap = None  # (rid, version)

    @property
    def controller(self) -> RolloutController:
        return self._ctl

    @property
    def done(self) -> bool:
        return self._ctl.done

    def on_round(self, fleet, served: int) -> None:
        if not self._started:
            self._started = True
            self._dispatch(self._ctl.begin())
        if self._canary is not None and self._canary[5] >= self._canary[2]:
            rid, version, _n, _shadow, diffs, compared = self._canary
            crash_hook("canary_pre_verdict")
            self._canary = None
            self._dispatch(
                self._ctl.note_canary_report(rid, diffs, compared)
            )
        if self._pending_swap is not None:
            self._try_swap()

    def observe(self, rid: int, rec, tokens) -> None:
        """Per-completion hook: during the canary phase, shadow-decode
        the canary replica's completions under the candidate and count
        token diffs."""
        if self._canary is None or rid != self._canary[0]:
            return
        if self._canary[5] >= self._canary[2]:
            return
        shadow = self._canary[3]
        got = shadow.shadow_decode(rec)
        if got is None or not np.array_equal(
            np.asarray(got), np.asarray(tokens)
        ):
            self._canary[4] += 1
        self._canary[5] += 1

    def _dispatch(self, directives: list[dict]) -> None:
        for d in directives:
            rid = d["member"]
            rep = self._fleet.replicas[rid]
            if d["t"] == "canary":
                version = d["version"]
                shadow = rep.gen.spawn_shadow(
                    self._versions[version], version,
                )
                self._canary = [rid, version, d["n"], shadow, 0, 0]
            elif d["t"] == "swap":
                rep.pause_admission()
                self._pending_swap = (rid, d["version"])

    def _try_swap(self) -> None:
        rid, version = self._pending_swap
        rep = self._fleet.replicas[rid]
        if not rep.quiesced:
            return
        rep.maybe_flush(force=True)
        try:
            rep.gen.swap_params(self._versions[version], version)
        except RuntimeError:
            return  # flush still retrying; next round
        self._pending_swap = None
        rep.resume_admission()
        self._dispatch(self._ctl.note_ack(rid, version))


def _decode_control(value: bytes) -> dict | None:
    """Control-topic records are small JSON objects; anything else on
    the topic (a stray produce, a torn frame) is skipped, never fatal —
    the control plane shares the broker's at-least-once floor, so the
    machine must tolerate garbage between directives."""
    try:
        msg = json.loads(value)
    except (ValueError, UnicodeDecodeError):
        return None
    return msg if isinstance(msg, dict) else None

"""The process-fleet supervisor: real OS-process replicas over the socket
broker, with heartbeat leases, zombie fencing, and warm failover.

``ProcessFleet`` is the serving analog of the elastic multi-process
consumer-group tier the ingest path already has (tests/test_pod.py over
``BrokerServer``): it hosts an ``InMemoryBroker`` with a session timeout
behind a ``BrokerServer`` socket, spawns each replica as a REAL process
(``python -m torchkafka_tpu.fleet.proc`` — its own ``BrokerClient``, its
own jit state, its own on-disk ``DecodeJournal``), and supervises
liveness through the broker's heartbeat leases:

- a replica that dies (SIGKILL, OOM, crash) stops renewing its lease;
  the supervisor's sweep — or any survivor's heartbeat — FENCES it:
  eviction + rebalance, so its partitions re-deliver to survivors and
  every commit it might still issue carries a dead generation and is
  rejected (the zombie can stall, never corrupt);
- the victim's journal is read FROM DISK across the process boundary
  (survivors rescan the shared journal dir on every rebalance —
  ``DecodeJournal.scan_dir``), so its in-flight prompts resume warm and
  byte-identical instead of re-decoding from token 0;
- ``respawn=True`` keeps the fleet at its target size: a fenced member
  is replaced by a FRESH incarnation (new member id, new journal file)
  that also scans the shared dir at startup — a replacement is a
  survivor too;
- ``scale(n)`` is elastic membership mid-serve: scale-up spawns joiners
  (the rebalance hands them partitions), scale-down SIGTERMs the newest
  incarnations, which drain cooperatively — finish in-flight work,
  commit, leave — so a scale-down loses nothing and (with per-partition
  FIFO admission) replays nothing.

The supervisor is deliberately OUTSIDE the data path: prompts flow
broker → worker → output topic; the supervisor only watches membership,
fences, respawns, and narrates (``FleetMetrics`` counters + optional
``RecordTracer`` membership events: ``replica_joined`` /
``replica_fenced`` / ``journal_handoff``). Everything it knows, it knows
from the broker and the filesystem — exactly what a survivor of ITS
death would know.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Callable

import numpy as np

from torchkafka_tpu.journal import DecodeJournal
from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.source.records import TopicPartition

_logger = logging.getLogger(__name__)


def sweep_expired(broker, group: str, on_fence=None) -> list[str]:
    """Fence every member of ``group`` whose lease has expired. The
    supervisor's liveness sweep, importable so any process holding a
    broker surface (object or ``BrokerClient``) can run it. Observation
    and action are deliberately split — ``membership`` reaps nothing —
    and the ``lease_expired_pre_fence`` crash point sits exactly in the
    gap: a sweeper that dies there leaves the zombie a member, yet the
    zombie's own next commit still self-fences (commit-time reap), so
    the watermark is safe either way. Returns the fenced member ids."""
    info = broker.membership(group)
    fenced = []
    for member, remaining in info["leases"].items():
        if remaining is not None and remaining <= 0:
            crash_hook("lease_expired_pre_fence")
            broker.fence(group, member)
            fenced.append(member)
            if on_fence is not None:
                on_fence(member, -remaining)
    return fenced


LIVE = "live"
DRAINING = "draining"
ZOMBIE = "zombie"  # fenced by the broker; process may still be running
DEAD = "dead"  # involuntary end (SIGKILL, crash, fenced exit)
DONE = "done"  # voluntary clean exit (drain)


@dataclass
class _Incarnation:
    idx: int
    member: str
    proc: subprocess.Popen | None
    spec_path: str
    journal_path: str
    log_path: str
    metrics_path: str
    role: str = "decode"
    state: str = LIVE
    seen_in_group: bool = False
    exit_code: int | None = None
    fence_reason: str | None = None
    handoff_entries: int = 0

    @property
    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ProcessFleet:
    """Spawn and supervise R real-process serving replicas.

    ``model``: the JSON-serializable model spec ``fleet.proc.build_model``
    consumes (seed + TransformerConfig fields) — every worker rebuilds
    identical params from it. ``broker``: pass an existing
    ``InMemoryBroker`` (it must have been built with
    ``session_timeout_s``) or let the fleet build one. Topics must exist
    before ``start()`` unless created here via ``partitions``.
    """

    def __init__(
        self,
        model: dict,
        *,
        topic: str,
        prompt_len: int,
        max_new: int,
        workdir: str | os.PathLike,
        replicas: int = 2,
        out_topic: str = "fleet-out",
        ready_topic: str | None = "fleet-ready",
        group: str = "pfleet",
        partitions: int | None = 4,
        slots: int = 2,
        commit_every: int = 8,
        journal_cadence: int = 4,
        session_timeout_s: float = 2.0,
        heartbeat_interval_s: float = 0.2,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        sampling_seed: int = 0,
        eos_id: int | None = None,
        idle_exit_ms: int | None = None,
        ticks_per_sync: int = 1,
        respawn: bool = True,
        journal: bool = True,
        exactly_once: bool = False,
        prefill_replicas: int = 0,
        handoff_topic: str = "fleet-handoff",
        kv_pages: dict | None = None,
        kv_tier: dict | None = None,
        route_patience: int = 256,
        rollout: bool = False,
        rollout_topic: str = "fleet-rollout",
        ckpt_topic: str = "fleet-ckpt",
        model_version: int = 0,
        distill_replicas: int = 0,
        distill_topic: str = "fleet-distill",
        publish_every: int = 0,
        draft_layers: int | None = None,
        distill_batch: int = 8,
        distill_lr: float = 1e-3,
        distill_seq_len: int | None = None,
        draft_base_version: int = 0,
        wal_dir: str | os.PathLike | None = None,
        wal_durability: str | None = "batch",
        broker_replicas: int = 1,
        resilient: bool = False,
        reconnect_attempts: int = 6,
        reconnect_deadline_s: float = 15.0,
        broker=None,
        metrics=None,
        tracer=None,
    ) -> None:
        from torchkafka_tpu.fleet.metrics import FleetMetrics
        from torchkafka_tpu.source.memory import InMemoryBroker
        from torchkafka_tpu.source.netbroker import BrokerServer

        self.workdir = os.fspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.journal_dir = os.path.join(self.workdir, "journals")
        os.makedirs(self.journal_dir, exist_ok=True)
        self.group = group
        self.topic = topic
        self.out_topic = out_topic
        self.ready_topic = ready_topic
        self.session_timeout_s = session_timeout_s
        self.respawn = respawn
        self._journal_on = journal
        # Exactly-once output: every worker serves through a
        # TransactionalProducer whose transactional id is keyed by
        # replica INDEX (``_txn_id``), so a respawned replacement's
        # init_producer_id fences its predecessor's epoch — and the
        # supervisor's own fence path aborts a victim's in-flight
        # transaction EAGERLY (``_abort_victim_txn``), so the committed
        # view settles without waiting for a respawn.
        self.exactly_once = exactly_once
        # Broker durability: with ``wal_dir`` set, the hosted broker
        # writes a segmented write-ahead log (source/wal.py) and
        # ``restart_broker`` can crash-and-recover it on the SAME port —
        # workers ride the outage on their reconnect stacks and resume
        # against identical topics/offsets/generations/producer epochs.
        self.wal_dir = None if wal_dir is None else os.fspath(wal_dir)
        self.wal_durability = wal_durability
        # Disaggregated prefill (fleet/prefill.py): ``prefill_replicas``
        # dedicated workers in their own consumer group fill paged KV
        # and publish handoffs on ``handoff_topic``; decode replicas
        # route admission through the handoff shelf (bounded patience →
        # local-prefill fallback). Requires ``kv_pages``.
        self.prefill_replicas = prefill_replicas
        self.handoff_topic = handoff_topic if prefill_replicas else None
        if prefill_replicas and kv_pages is None:
            raise ValueError(
                "prefill_replicas requires kv_pages (the handoff carries "
                "paged KV blocks)"
            )
        # Replicated broker cell: ``broker_replicas >= 2`` hosts the
        # broker as a 1-leader + N-follower quorum cell (source/cluster)
        # instead of a lone InMemoryBroker — every acked mutation is on a
        # majority of WAL replicas, and ``kill_leader()`` fails over to a
        # promoted follower on the SAME advertised port with zero
        # committed-record loss (workers ride it exactly like
        # ``restart_broker``'s outage, reconnect-unfenced).
        self._cell = None
        if broker is None and broker_replicas > 1:
            if self.wal_dir is None:
                raise ValueError(
                    "broker_replicas > 1 requires ProcessFleet(wal_dir=...):"
                    " a quorum cell is made of WAL replicas"
                )
            from torchkafka_tpu.source.cluster import BrokerCell
            from torchkafka_tpu.source.replication import ReplicationConfig
            self._cell = BrokerCell(
                self.wal_dir,
                config=ReplicationConfig(
                    replicas=broker_replicas,
                    durability=(
                        "batch" if wal_durability == "quorum"
                        else wal_durability
                    ),
                    lease_timeout_s=session_timeout_s,
                    heartbeat_interval_s=heartbeat_interval_s,
                ),
                session_timeout_s=session_timeout_s,
            )
            self.broker = self._cell.broker
        else:
            self.broker = broker if broker is not None else InMemoryBroker(
                session_timeout_s=session_timeout_s,
                wal_dir=self.wal_dir, wal_durability=wal_durability,
            )
        # Live model lifecycle (fleet/rollout.py): with ``rollout`` on,
        # workers tail a 1-partition control topic for canary/swap
        # directives and fetch versioned checkpoints from ``ckpt_topic``
        # (CRC'd chunked frames, source/checkpoint_wire.py).
        # ``model_version`` tags the boot weights; every committed output
        # window carries the serving version in its "mv" header.
        self.rollout_topic = rollout_topic if rollout else None
        # Online draft distillation (torchkafka_tpu/distill):
        # ``distill_replicas`` DistillTrainer workers ("d" prefix) in
        # their own consumer group train the layer-truncated draft on the
        # committed-completion corpus decode replicas stage onto
        # ``distill_topic`` inside their commit windows, and publish
        # versioned draft checkpoints onto ``ckpt_topic`` — which is why
        # the checkpoint plane exists for distill fleets even without
        # ``rollout=True``.
        self.distill_replicas = distill_replicas
        self.distill_topic = distill_topic if distill_replicas else None
        self.ckpt_topic = (
            ckpt_topic if (rollout or distill_replicas) else None
        )
        self.model_version = int(model_version)
        self._rollout_driver = None
        for t, p in ((topic, partitions), (out_topic, 1),
                     (ready_topic, 1), (self.handoff_topic, 1),
                     (self.rollout_topic, 1), (self.ckpt_topic, 1),
                     (self.distill_topic, 1)):
            if t is None or p is None:
                continue
            try:
                self.broker.create_topic(t, partitions=p)
            except ValueError:
                pass  # caller already created (and maybe filled) it
        self.server = (
            self._cell.server if self._cell is not None
            else BrokerServer(self.broker)
        )
        self.metrics = metrics if metrics is not None else FleetMetrics()
        self.tracer = tracer
        self._target = replicas
        self._seq = 0
        self._spec_base = {
            "broker": {"host": self.server.host, "port": self.server.port},
            "topic": topic,
            "group": group,
            "out_topic": out_topic,
            "ready_topic": ready_topic,
            "journal_dir": self.journal_dir,
            "journal_cadence": journal_cadence,
            "model": dict(model),
            "prompt_len": prompt_len,
            "max_new": max_new,
            "slots": slots,
            "commit_every": commit_every,
            "ticks_per_sync": ticks_per_sync,
            "temperature": temperature,
            "top_k": top_k,
            "top_p": top_p,
            "sampling_seed": sampling_seed,
            "eos_id": eos_id,
            "heartbeat_interval_s": heartbeat_interval_s,
            "idle_exit_ms": idle_exit_ms,
            "exactly_once": exactly_once,
            "resilient": resilient,
            "reconnect_attempts": reconnect_attempts,
            "reconnect_deadline_s": reconnect_deadline_s,
            "kv_pages": kv_pages,
            "kv_tier": kv_tier,
            "handoff_topic": self.handoff_topic,
            "route_patience": route_patience,
            "rollout_topic": self.rollout_topic,
            "ckpt_topic": self.ckpt_topic,
            "model_version": self.model_version,
            "distill_topic": self.distill_topic,
            "publish_every": publish_every,
            "draft_layers": draft_layers,
            "distill_batch": distill_batch,
            "distill_lr": distill_lr,
            "distill_seq_len": distill_seq_len,
            "draft_base_version": draft_base_version,
        }
        self.incarnations: list[_Incarnation] = []
        self.victims: list[dict] = []  # kill_replica forensics

    # ------------------------------------------------------------ spawning

    def _spawn(self, idx: int, role: str = "decode") -> _Incarnation:
        # Member ids sort by replica INDEX first (r0i* < r1i* < ...), and
        # the broker range-assigns over sorted member ids — so a
        # respawned incarnation slots into its predecessor's position and
        # inherits the same partition range. That bias is what makes the
        # victim's journal (and its radix prefix locality) land where the
        # redelivered prompts do. Prefill ("q") and distill ("d") workers
        # live in their OWN consumer groups, so those prefixes only have
        # to be distinct, not ordered against decode members.
        prefix = {"decode": "r", "prefill": "q", "distill": "d"}[role]
        member = f"{prefix}{idx:03d}i{self._seq:03d}"  # zero-padded
        self._seq += 1                          # order == numeric order
        spec = dict(self._spec_base)
        spec["member_id"] = member
        spec["replica_index"] = idx
        spec["role"] = role
        spec["metrics_path"] = os.path.join(
            self.workdir, f"{member}.metrics.json"
        )
        if not self._journal_on:
            # Journals off (cold-failover baseline for the bench): point
            # each worker at a private throwaway dir so nothing is
            # written where survivors scan.
            spec["journal_dir"] = os.path.join(
                self.workdir, "no-journals", member
            )
        spec_path = os.path.join(self.workdir, f"{member}.spec.json")
        with open(spec_path, "w", encoding="utf-8") as f:
            json.dump(spec, f)
        log_path = os.path.join(self.workdir, f"{member}.log")
        env = dict(os.environ)
        # Children configure jax themselves (CPU); scrub anything that
        # could force a tunneled TPU platform into the worker.
        env.pop("JAX_PLATFORMS", None)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__import__("torchkafka_tpu").__file__)
        ))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        log = open(log_path, "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "torchkafka_tpu.fleet.proc", spec_path],
            env=env, stdout=log, stderr=subprocess.STDOUT,
        )
        log.close()  # the child holds its own fd
        inc = _Incarnation(
            idx=idx, member=member, proc=proc, spec_path=spec_path,
            journal_path=os.path.join(spec["journal_dir"], f"{member}.json"),
            log_path=log_path,
            metrics_path=spec["metrics_path"],
            role=role,
        )
        self.incarnations.append(inc)
        self.metrics.replica_joins.add(1)
        if self.tracer is not None:
            self.tracer.replica_joined(member, replica=idx)
        return inc

    def start(self) -> "ProcessFleet":
        for idx in range(self._target):
            self._spawn(idx)
        for idx in range(self.prefill_replicas):
            self._spawn(idx, role="prefill")
        for idx in range(self.distill_replicas):
            self._spawn(idx, role="distill")
        return self

    def wait_ready(self, timeout_s: float = 120.0) -> None:
        """Block until every live incarnation produced its readiness
        marker (post-warmup) — the paired bench's measured window starts
        here, so per-process jit compile never pollutes a slice."""
        if self.ready_topic is None:
            raise ValueError("fleet was built with ready_topic=None")
        deadline = time.monotonic() + timeout_s
        tp = TopicPartition(self.ready_topic, 0)
        while True:
            ready = {
                r.value.decode()
                for r in self.broker.fetch(tp, 0, 100000)
            }
            waiting = [
                inc for inc in self.incarnations
                if inc.state in (LIVE, DRAINING) and inc.member not in ready
            ]
            if not waiting:
                return
            crashed = [inc for inc in waiting if not inc.running]
            if crashed:
                raise RuntimeError(
                    "replica(s) died before ready: "
                    + ", ".join(
                        f"{i.member} rc={i.proc.returncode} "
                        f"(log: {i.log_path})" for i in crashed
                    )
                )
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replicas not ready after {timeout_s}s: "
                    + ", ".join(i.member for i in waiting)
                )
            time.sleep(0.05)

    # ---------------------------------------------------------- liveness

    def live(self, role: str = "decode") -> list[_Incarnation]:
        return [
            i for i in self.incarnations
            if i.state in (LIVE, DRAINING) and i.role == role
        ]

    def _group_of(self, inc: _Incarnation) -> str:
        return (
            self.group if inc.role == "decode"
            else f"{self.group}-{inc.role}"
        )

    def poll_once(self) -> None:
        """One supervision round: sweep expired leases (fencing) in the
        decode AND prefill groups, update lease-age gauges, reap exited
        children, observe broker-side fencings of still-running
        processes (stalled zombies), trigger journal-handoff accounting,
        and respawn toward the per-role targets."""
        groups = [self.group]
        if self.prefill_replicas:
            groups.append(f"{self.group}-prefill")
        if self.distill_replicas:
            groups.append(f"{self.group}-distill")
        infos: dict[str, dict] = {}
        for group in groups:
            info = self.broker.membership(group)
            timeout = info["session_timeout_s"]
            for member, remaining in info["leases"].items():
                if remaining is not None and timeout is not None:
                    self.metrics.member_lease_age(member).set(
                        max(0.0, timeout - remaining)
                    )
            swept = sweep_expired(
                self.broker, group,
                on_fence=lambda member, age: self._note_fence(
                    member, "lease_expired", age
                ),
            )
            if swept:
                info = self.broker.membership(group)
            infos[group] = info
        for inc in self.incarnations:
            if inc.state not in (LIVE, DRAINING, ZOMBIE):
                continue
            info = infos.get(self._group_of(inc))
            if info is None:
                info = self.broker.membership(self._group_of(inc))
                infos[self._group_of(inc)] = info
            fenced_members = set(info["fenced"])
            if inc.member in info["members"]:
                inc.seen_in_group = True
            if inc.proc is not None and inc.proc.poll() is not None:
                inc.exit_code = inc.proc.returncode
                if inc.exit_code == 0:
                    inc.state = DONE
                    self.metrics.drains.add(1)
                else:
                    # SIGKILL (negative rc), crash, or EXIT_FENCED: an
                    # involuntary end. Make the broker-side fencing
                    # explicit if the sweep has not already done it.
                    was = inc.state
                    inc.state = DEAD
                    if inc.member not in fenced_members:
                        self.broker.fence(self._group_of(inc), inc.member)
                    if was != ZOMBIE and inc.fence_reason is None:
                        self._note_fence(
                            inc.member,
                            "exit_fenced" if inc.exit_code == 3
                            else "process_death",
                            None,
                        )
                    self._abort_victim_txn(inc)
                    self._handoff(inc)
                    self._maybe_respawn(inc)
            elif inc.state != ZOMBIE and inc.member in fenced_members:
                # Fenced broker-side while the process still runs: a
                # stalled (SIGSTOP, GC-of-death, netsplit) zombie. The
                # sweep may not have done it — any survivor's heartbeat
                # reaps expired peers too — so note the fence HERE. Its
                # partitions are already gone; it will learn via
                # heartbeat and exit EXIT_FENCED on its own. Replace it
                # now — the group must not run short while it stalls.
                inc.state = ZOMBIE
                self._note_fence(inc.member, "lease_expired", None)
                self._abort_victim_txn(inc)
                self._handoff(inc)
                self._maybe_respawn(inc)
        if self._rollout_driver is not None and not self._rollout_driver.done:
            # The rollout control plane rides the supervision cadence:
            # worker acks/reports in, next directive out, stale-version
            # zombies fenced after completion.
            self._rollout_driver.pump()
            if self._rollout_driver.controller.phase == "complete":
                # The fleet's incumbent advances ONLY on completion (a
                # rollback leaves it untouched) — the next rollout's
                # controller needs the true incumbent to swap back to.
                self.model_version = self._rollout_driver.controller.version

    def _note_fence(self, member: str, reason: str,
                    lease_age_s: float | None) -> None:
        inc = self._by_member(member)
        if inc is not None and inc.fence_reason is not None:
            return  # already noted (sweep + observation can both fire)
        self.metrics.replica_fences.add(1)
        if inc is not None:
            inc.fence_reason = reason
        if self.tracer is not None:
            self.tracer.replica_fenced(
                member, reason=reason, lease_age_s=lease_age_s,
                replica=inc.idx if inc is not None else None,
            )

    def _txn_id(self, idx: int) -> str:
        """The transactional id for replica index ``idx`` — shared by
        every incarnation of that slot (fleet/proc.py derives the same
        string), which is exactly what makes a respawn's
        init_producer_id fence its predecessor."""
        return f"{self.group}-r{idx:03d}"

    def _abort_victim_txn(self, inc: _Incarnation) -> None:
        """Fence the victim's producer epoch and abort its in-flight
        transaction NOW (exactly_once fleets only). Without this, a
        victim's uncommitted outputs would stay transaction-open —
        blocking read_committed consumers at the LSO — until a
        replacement incarnation happens to re-initialize the id; with
        ``respawn=False`` that is never. Ordered BEFORE any respawn, so
        the replacement's own init lands a newer epoch on top."""
        if not self.exactly_once or inc.role != "decode":
            return
        try:
            self.broker.init_producer_id(self._txn_id(inc.idx))
        except Exception:  # noqa: BLE001 - best effort; the next
            # incarnation's init is the backstop
            _logger.exception(
                "eager transaction fence for %s failed", inc.member
            )

    def _by_member(self, member: str) -> _Incarnation | None:
        for inc in self.incarnations:
            if inc.member == member:
                return inc
        return None

    def _handoff(self, inc: _Incarnation) -> None:
        """Account the victim's on-disk journal as handed off. The ACTUAL
        hint application happens inside the surviving worker processes —
        they rescan the shared journal dir when the rebalance changes
        their assignment; the supervisor only narrates what disk state
        the death left for them."""
        if inc.role != "decode":
            return  # prefill workers hold no decode journal
        entries = len(DecodeJournal.load(inc.journal_path))
        inc.handoff_entries = entries
        if entries:
            self.metrics.journal_handoffs.add(entries)
            if self.tracer is not None:
                self.tracer.journal_handoff(
                    inc.member, entries, replica=inc.idx
                )

    def _maybe_respawn(self, dead: _Incarnation) -> None:
        if not self.respawn:
            return
        alive = len(self.live(dead.role))
        target = {
            "decode": self._target,
            "prefill": self.prefill_replicas,
            "distill": self.distill_replicas,
        }[dead.role]
        if alive < target:
            _logger.info(
                "respawning %s replica %d (member %s %s)",
                dead.role, dead.idx, dead.member, dead.state,
            )
            self._spawn(dead.idx, role=dead.role)

    # ----------------------------------------------------------- control

    def publish_checkpoint(self, version: int, params,
                           kind: str = "serving") -> int:
        """Publish a versioned checkpoint onto the checkpoint topic
        (manifest + CRC'd chunks). Returns the frame count."""
        if self.ckpt_topic is None:
            raise ValueError(
                "fleet was built without rollout=True or distill_replicas"
            )
        from torchkafka_tpu.source.checkpoint_wire import publish_checkpoint

        return publish_checkpoint(
            self.broker, self.ckpt_topic, int(version), params, kind=kind,
        )

    def start_rollout(
        self,
        version: int,
        *,
        canary_member: str | None = None,
        canary_slice: int = 8,
        max_canary_diffs: int = 0,
    ):
        """Begin a rolling hot-swap to ``version`` (already published via
        ``publish_checkpoint``): canary shadow-serve on one member,
        token-diff gate, then drain-swap one member at a time; any
        divergence or checkpoint rejection rolls every swapped member
        back automatically. Driven from ``poll_once`` — ``wait(lambda f:
        f.rollout_done)`` rides the normal supervision loop. Returns the
        ``BrokerRolloutDriver`` (its ``.controller`` is the state
        machine)."""
        if self.rollout_topic is None:
            raise ValueError("fleet was built without rollout=True")
        if self._rollout_driver is not None and not self._rollout_driver.done:
            raise RuntimeError("a rollout is already in flight")
        from torchkafka_tpu.fleet.rollout import (
            BrokerRolloutDriver,
            RolloutController,
        )

        members = sorted(
            self.broker.membership(self.group)["members"]
        ) or sorted(i.member for i in self.live())
        ctl = RolloutController(
            members, int(version),
            canary_member=canary_member,
            canary_slice=canary_slice,
            max_canary_diffs=max_canary_diffs,
            incumbent_version=self.model_version,
            tracer=self.tracer, metrics=self.metrics,
        )
        self._rollout_driver = BrokerRolloutDriver(
            self.broker, self.rollout_topic, ctl, group=self.group,
        )
        self._rollout_driver.start()
        return self._rollout_driver

    @property
    def rollout_done(self) -> bool:
        return self._rollout_driver is not None and self._rollout_driver.done

    @property
    def rollout(self):
        return self._rollout_driver

    def kill_replica(self, idx: int) -> dict:
        """SIGKILL the newest live incarnation of replica ``idx`` — a
        REAL unclean process death (no handlers, no flushes; the decode
        journal is whatever the last cadence fsync left on disk).
        Returns forensics for the zombie-fencing assertions: the victim
        member id and the group generation it held, so a test can forge
        its post-mortem commit and watch it bounce."""
        victims = [
            i for i in self.incarnations
            if i.idx == idx and i.state in (LIVE, DRAINING) and i.running
            and i.role == "decode"
        ]
        if not victims:
            raise ValueError(f"no live process for replica {idx}")
        inc = victims[-1]
        generation = self.broker.membership(self.group)["generation"]
        inc.proc.send_signal(signal.SIGKILL)
        inc.proc.wait()
        forensics = {
            "member": inc.member, "idx": idx, "generation": generation,
            "journal_path": inc.journal_path,
        }
        self.victims.append(forensics)
        return forensics

    def kill_prefill(self, idx: int = 0) -> dict:
        """SIGKILL the newest live prefill-worker incarnation of index
        ``idx`` — the mid-storm disaggregation drill: unpublished
        handoffs vanish with the process, decode replicas' routing
        patience expires and they fall back to local prefills, and (with
        ``respawn=True``) a fresh prefill incarnation re-serves the
        prefill group's uncommitted prompts. Zero decode-path loss by
        construction: the decode group's ledger never depended on a
        handoff existing."""
        victims = [
            i for i in self.incarnations
            if i.idx == idx and i.state in (LIVE, DRAINING) and i.running
            and i.role == "prefill"
        ]
        if not victims:
            raise ValueError(f"no live process for prefill worker {idx}")
        inc = victims[-1]
        inc.proc.send_signal(signal.SIGKILL)
        inc.proc.wait()
        forensics = {
            "member": inc.member, "idx": idx, "role": "prefill",
            "log_path": inc.log_path,
        }
        self.victims.append(forensics)
        return forensics

    def kill_distill(self, idx: int = 0) -> dict:
        """SIGKILL the newest live distill-trainer incarnation of index
        ``idx`` — the trainer-death drill: unpublished draft progress
        (at most ``publish_every`` steps past the last checkpoint)
        vanishes with the process, the serving fleet keeps proposing
        with its incumbent draft (serving never depended on the trainer
        being alive), and (with ``respawn=True``) a fresh incarnation
        resumes from the corpus group's committed offsets — at-least-
        once, so a mid-step death re-delivers that step's records as
        extra gradient samples. Zero committed-token impact by
        construction."""
        victims = [
            i for i in self.incarnations
            if i.idx == idx and i.state in (LIVE, DRAINING) and i.running
            and i.role == "distill"
        ]
        if not victims:
            raise ValueError(f"no live process for distill worker {idx}")
        inc = victims[-1]
        inc.proc.send_signal(signal.SIGKILL)
        inc.proc.wait()
        forensics = {
            "member": inc.member, "idx": idx, "role": "distill",
            "log_path": inc.log_path,
        }
        self.victims.append(forensics)
        return forensics

    def kill_leader(self) -> dict:
        """Leader-death drill for a replicated broker cell
        (``broker_replicas >= 2``): drop the leader the way SIGKILL
        would (its server vanishes mid-conversation, its WAL is
        abandoned un-flushed), run the epoch-bumped election, and
        promote the longest follower onto the SAME advertised port —
        the ``restart_broker`` takeover discipline, minus the outage
        window a lone broker has to ride. Workers reconnect through
        their retry stacks, unfenced; the deposed leader's late ships
        stale-epoch-fence like any zombie's commits. Returns forensics
        (victim/winner indices, epochs, candidate positions, the
        promotion's PR-11 recovery summary, failover wall-clock),
        appended to ``self.victims`` like every other kill drill."""
        if self._cell is None:
            raise ValueError(
                "kill_leader requires ProcessFleet(broker_replicas >= 2): "
                "a lone broker has no follower to promote"
            )
        fx = self._cell.kill_leader()
        self.broker = self._cell.broker
        self.server = self._cell.server
        self.metrics.leader_elections.add(1)
        if self.tracer is not None:
            rec = fx.get("recovery", {})
            self.tracer.broker_restarted(
                replayed_records=rec.get("replayed_records", 0),
                aborted_txns=rec.get("aborted_txns", 0),
                recovery_ms=rec.get("recovery_ms", 0.0),
            )
        forensics = {"kind": "leader", **fx}
        self.victims.append(forensics)
        _logger.info("broker leader failed over: %s", forensics)
        return forensics

    def restart_broker(self, crash: bool = True, down_s: float = 0.0) -> dict:
        """Kill and recover the hosted broker — the broker-death drill.

        ``crash=True`` (default) is an unclean death: the listener and
        every live connection drop mid-RPC (exactly what a SIGKILLed
        broker process looks like from a client socket) and the
        in-memory state object is ABANDONED un-flushed — the only
        surviving truth is whatever the write-ahead log already holds
        per its durability discipline. ``down_s`` holds the port closed
        before recovery so outage-riding (retry storms, circuit
        breakers opening) is actually exercised. Then a fresh
        ``InMemoryBroker(wal_dir=...)`` RECOVERS — records, offsets,
        generations, producer epochs, memberships with fresh leases;
        open transactions aborted — and rebinds a ``BrokerServer`` on
        the SAME port, so every worker's reconnect lands without
        re-configuration. Requires the fleet to have been built with
        ``wal_dir`` (a volatile broker cannot be restarted into
        anything but amnesia). Returns the recovery summary."""
        if self.wal_dir is None:
            raise ValueError(
                "restart_broker requires ProcessFleet(wal_dir=...): "
                "without a WAL there is no state to recover"
            )
        if self._cell is not None:
            raise ValueError(
                "a replicated cell fails over via kill_leader(), not "
                "restart_broker(): promotion, not restart, is its "
                "recovery path"
            )
        from torchkafka_tpu.source.memory import InMemoryBroker
        from torchkafka_tpu.source.netbroker import BrokerServer

        host, port = self.server.host, self.server.port
        self.server.close()  # connections reset: clients see the outage
        if not crash:
            self.broker.close()  # clean shutdown flushes the WAL tail
        # crash=True: the old broker object is simply dropped — no
        # flush, no close; its unfsynced tail is the page cache's
        # problem, exactly as process death leaves it.
        if down_s > 0:
            time.sleep(down_s)
        t0 = time.perf_counter()
        self.broker = InMemoryBroker(
            session_timeout_s=self.session_timeout_s,
            wal_dir=self.wal_dir, wal_durability=self.wal_durability,
        )
        self.server = BrokerServer(self.broker, host=host, port=port)
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        self.metrics.broker_restarts.add(1)
        info = dict(self.broker.recovery_info or {})
        info["restart_ms"] = round(elapsed_ms, 3)
        if self.tracer is not None:
            self.tracer.broker_restarted(
                replayed_records=info.get("replayed_records", 0),
                aborted_txns=info.get("aborted_txns", 0),
                recovery_ms=info.get("recovery_ms", 0.0),
            )
        _logger.info("broker restarted on %s:%s from WAL: %s",
                     host, port, info)
        return info

    def scale(self, n: int, role: str = "decode") -> None:
        """Elastic membership mid-serve, per role. Scale-UP spawns fresh
        members (the rebalance hands them partitions — and their startup
        journal scan makes them failover-capable immediately). Scale-DOWN
        SIGTERMs the newest live incarnations: each drains cooperatively
        (finish in-flight generations, commit, sync journal, leave), so
        nothing is lost and nothing replays.

        Reconciled against BROKER truth first: a scale call can land
        while a lease sweep is fencing a victim (the autoscale
        controller reacts to the very fence events the sweep emits), and
        the supervisor's own incarnation bookkeeping only catches up at
        the next ``poll_once``. Counting such a victim as live would
        make scale-down drain a healthy survivor in its place (the fleet
        then converges BELOW target — an orphaned member-id range slot)
        and scale-up under-provision. So capacity here is incarnations
        that are broker-unfenced AND process-alive; a fenced victim's
        replica index is deliberately free for reuse, so the scale-up
        replacement sorts into the victim's member-id range and inherits
        its journal + radix locality (the PR-9 range trick, made
        deliberate)."""
        floor = 1 if role == "decode" else 0
        if n < floor:
            raise ValueError(
                f"scale target for {role!r} must be >= {floor}, got {n}"
            )
        if role == "prefill" and self.handoff_topic is None:
            raise ValueError(
                "cannot scale the prefill role of a fleet built without "
                "prefill_replicas/kv_pages (no handoff plane exists)"
            )
        if role == "distill" and self.distill_topic is None:
            raise ValueError(
                "cannot scale the distill role of a fleet built without "
                "distill_replicas (no distill corpus topic exists)"
            )
        fenced = set(
            self.broker.membership(
                self.group if role == "decode" else f"{self.group}-{role}"
            )["fenced"]
        )
        cur = [
            i for i in self.live(role)
            if i.member not in fenced and i.running
        ]
        if n > len(cur):
            used = {i.idx for i in cur}
            idx = 0
            for _ in range(n - len(cur)):
                while idx in used:
                    idx += 1
                used.add(idx)
                # Target decided, member-id range slot chosen, the
                # replacement not yet alive: the supervisor-death window
                # the crash matrix SIGKILLs at.
                crash_hook("scale_up_pre_spawn")
                self._spawn(idx, role=role)
        elif n < len(cur):
            # Drain the NEWEST incarnations first (LIFO): the longest-
            # lived members keep their partition/cache locality.
            to_drain = sorted(
                cur, key=lambda i: self.incarnations.index(i)
            )[n:]
            for inc in to_drain:
                if inc.running:
                    inc.proc.send_signal(signal.SIGTERM)
                # Drain initiated (SIGTERM in flight), supervisor
                # bookkeeping not yet updated: the mid-drain
                # supervisor-death window.
                crash_hook("scale_down_mid_drain")
                inc.state = DRAINING
        if role == "decode":
            self._target = n
        elif role == "prefill":
            self.prefill_replicas = n
        else:
            self.distill_replicas = n

    def drain(self) -> None:
        """SIGTERM every live worker (prefill and distill included):
        fleet-wide cooperative drain."""
        for inc in (
            self.live() + self.live("prefill") + self.live("distill")
        ):
            if inc.running:
                inc.proc.send_signal(signal.SIGTERM)
            inc.state = DRAINING
        self._target = 0
        self.prefill_replicas = 0
        self.distill_replicas = 0

    def wait(
        self,
        until: Callable[["ProcessFleet"], bool],
        timeout_s: float = 120.0,
        poll_interval_s: float = 0.05,
    ) -> None:
        """Supervision loop: ``poll_once`` until ``until(self)`` or
        timeout (raises TimeoutError with per-worker log tails)."""
        deadline = time.monotonic() + timeout_s
        while True:
            self.poll_once()
            if until(self):
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fleet condition not reached in {timeout_s}s\n"
                    + self.diagnose()
                )
            time.sleep(poll_interval_s)

    def fully_committed(self) -> bool:
        """True when the group's committed watermark covers every prompt
        partition end-to-end — the zero-lost condition."""
        n = self.broker.partitions_for(self.topic)
        for p in range(n):
            tp = TopicPartition(self.topic, p)
            if (self.broker.committed(self.group, tp) or 0) \
                    < self.broker.end_offset(tp):
                return False
        return True

    # ------------------------------------------------------------ results

    def results(
        self, isolation: str = "read_uncommitted"
    ) -> dict[bytes, list[tuple[str, np.ndarray]]]:
        """Output-topic completions grouped by prompt key:
        ``key -> [(serving member, tokens), ...]`` in produce order —
        duplicates visible, attribution explicit.
        ``isolation="read_committed"``: only records whose transaction
        committed (the downstream consumer's view in an exactly_once
        fleet — the view in which duplicates are asserted ZERO)."""
        out: dict[bytes, list[tuple[str, np.ndarray]]] = {}
        for p in range(self.broker.partitions_for(self.out_topic)):
            tp = TopicPartition(self.out_topic, p)
            if isolation == "read_committed":
                recs, _ = self.broker.fetch_stable(tp, 0, 1000000)
            else:
                recs = self.broker.fetch(tp, 0, 1000000)
            for rec in recs:
                member = dict(rec.headers).get("member", b"?").decode()
                out.setdefault(rec.key, []).append(
                    (member, np.frombuffer(rec.value, dtype=np.int32))
                )
        return out

    def worker_metrics(self) -> list[dict]:
        """Per-incarnation metric dumps (written by workers at clean or
        fenced exit; SIGKILLed victims leave none — honestly)."""
        out = []
        for inc in self.incarnations:
            try:
                with open(inc.metrics_path, encoding="utf-8") as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue
        return out

    def diagnose(self) -> str:
        parts = []
        for inc in self.incarnations:
            rc = inc.proc.poll() if inc.proc is not None else None
            try:
                with open(inc.log_path, "rb") as f:
                    tail = f.read()[-2000:].decode(errors="replace")
            except OSError:
                tail = "<no log>"
            parts.append(
                f"--- {inc.member} state={inc.state} rc={rc} ---\n{tail}"
            )
        return "\n".join(parts)

    # ------------------------------------------------------------ teardown

    def close(self, grace_s: float = 5.0) -> None:
        for inc in self.incarnations:
            if inc.running:
                inc.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + grace_s
        for inc in self.incarnations:
            if inc.proc is None:
                continue
            while inc.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.02)
            if inc.proc.poll() is None:
                inc.proc.kill()
                inc.proc.wait()
        if self._cell is not None:
            self._cell.close()  # leader, followers, servers, WALs
        else:
            self.server.close()
            self.broker.close()  # flush + close the WAL, when one exists

    def __enter__(self) -> "ProcessFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

"""Disaggregated prefill: dedicated workers that fill KV, decode replicas
that adopt it — prompt storms never touch decode's inter-token latency.

DistServe/Splitwise shape over this repo's own primitives: a
``PrefillWorker`` is a ``StreamingGenerator(prefill_role=True)`` in its
OWN consumer group over the prompt topic — it runs the existing
chunked-prefill machinery to fill paged KV blocks and samples token 0
in-dispatch, then publishes each prompt's ``PrefillHandoff`` (record
identity + CRC + sampling contract + RNG key + token 0 + the raw
prompt-block payloads) onto a HANDOFF TOPIC: the broker is the transfer
plane, the PR-9 journal handoff generalized from crash recovery to a
routing primitive. Decode replicas each tail the handoff topic
(broadcast: one private group per replica), install the decoded units on
their generator, and ADOPT at admission — payload scattered into fresh
pool blocks, state merged like a 1-token warm resume, no prompt pass
ever running on the decode path.

Routing is the admission queue's old shedding hook re-aimed: a
``PrefillRouter`` holds a record queued while its handoff is still in
flight (counted once as ``prefill_routed``), releases it the moment the
handoff lands (adoption), and FALLS BACK to a local prefill when
``patience`` pops expire — so a dead prefill worker degrades the
optimization, never correctness. Every path is at-least-once: handoffs
are idempotent by record identity (a duplicate overwrites the identical
unit), the decode group's ledger/exactly-once discipline never depends
on a handoff existing, and the prefill group's own offsets re-deliver
unpublished work to the next prefill incarnation
(``prefill_handoff_pre_publish`` in the crash matrix pins exactly that
window; ``decode_adopt_pre_activate`` pins the adopting side).

Wire format (versioned, self-describing): a 4-byte big-endian length,
a JSON header (identity, contract, token 0, per-array dtype/shape), then
the arrays' raw bytes concatenated — no pickle on the data plane.
"""

from __future__ import annotations

import json
import logging
import time

import numpy as np

from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.serve import PrefillHandoff
from torchkafka_tpu.source.records import Record

_logger = logging.getLogger(__name__)

_WIRE_VERSION = 1


def encode_handoff(hand: PrefillHandoff) -> bytes:
    header = {
        "v": _WIRE_VERSION,
        "t": hand.topic,
        "p": hand.partition,
        "o": hand.offset,
        "crc": hand.crc,
        "rng": list(hand.key_data),
        "temp": hand.temperature,
        "top_k": hand.top_k,
        "top_p": hand.top_p,
        "tok0": hand.token0,
        "nbp": hand.prompt_blocks,
        "arrays": [
            {"dtype": str(a.dtype), "shape": list(a.shape)}
            for a in hand.pools
        ],
    }
    hb = json.dumps(header).encode()
    parts = [len(hb).to_bytes(4, "big"), hb]
    parts.extend(np.ascontiguousarray(a).tobytes() for a in hand.pools)
    return b"".join(parts)


def decode_handoff(data: bytes) -> PrefillHandoff:
    hlen = int.from_bytes(data[:4], "big")
    header = json.loads(data[4:4 + hlen].decode())
    if header.get("v") != _WIRE_VERSION:
        raise ValueError(f"unknown handoff wire version {header.get('v')!r}")
    off = 4 + hlen
    pools = []
    for meta in header["arrays"]:
        dt = np.dtype(meta["dtype"])
        shape = tuple(meta["shape"])
        n = dt.itemsize * int(np.prod(shape)) if shape else dt.itemsize
        pools.append(
            np.frombuffer(data, dtype=dt, count=n // dt.itemsize,
                          offset=off).reshape(shape).copy()
        )
        off += n
    return PrefillHandoff(
        topic=str(header["t"]),
        partition=int(header["p"]),
        offset=int(header["o"]),
        crc=int(header["crc"]),
        key_data=tuple(int(x) for x in header["rng"]),
        temperature=float(header["temp"]),
        top_k=None if header["top_k"] is None else int(header["top_k"]),
        top_p=None if header["top_p"] is None else float(header["top_p"]),
        token0=int(header["tok0"]),
        prompt_blocks=int(header["nbp"]),
        pools=tuple(pools),
    )


class PrefillRouter:
    """The admission-queue prefill-routing decision (the shedding hook's
    sibling): hold a record queued while its handoff may still arrive,
    admit it the moment the handoff lands, fall back to a local prefill
    after ``patience`` hold decisions. Deterministic — the counter is
    hold-decisions, not a clock — so same-seed replays route
    identically."""

    def __init__(self, gen, *, patience: int = 256) -> None:
        if patience < 0:
            raise ValueError(f"patience must be >= 0, got {patience}")
        self._gen = gen
        self._patience = patience
        self._age: dict[tuple[str, int, int], int] = {}
        self._routed: set[tuple[str, int, int]] = set()

    def should_hold(self, rec: Record) -> bool:
        key = (rec.topic, rec.partition, rec.offset)
        if self._gen.has_prefill_handoff(key):
            self._age.pop(key, None)
            return False  # admit: adoption consumes the handoff
        n = self._age.get(key, 0) + 1
        self._age[key] = n
        if key not in self._routed:
            self._routed.add(key)
            self._gen.metrics.prefill_routed.add(1)
        if n > self._patience:
            # The handoff never came (prefill worker dead or drowning):
            # release — local prefill is the always-correct fallback.
            self._age.pop(key, None)
            return False
        return True


def drain_handoffs(consumer, gen, *, max_records: int = 256) -> int:
    """Tail the handoff topic into the generator's shelf; returns units
    installed. Undecodable payloads are logged and skipped (a handoff is
    an optimization, never load-bearing)."""
    records = consumer.poll(max_records=max_records, timeout_ms=0)
    installed = 0
    entries = {}
    for rec in records:
        try:
            hand = decode_handoff(rec.value)
        except Exception:  # noqa: BLE001 - fall back to local prefill
            _logger.exception("dropping undecodable prefill handoff")
            continue
        entries[hand.key] = hand
        installed += 1
    if entries:
        gen.add_prefill_handoffs(entries)
    return installed


class PrefillWorker:
    """One prefill worker: pump the prefill-role generator, publish the
    harvested handoffs, commit the prefill group's offsets at cadence.
    The ledger emit happens only AFTER the publish is issued
    (``note_handoff_published``), with the producer flushed before any
    offset commit — a death mid-transfer re-delivers the prompt to the
    next prefill incarnation (at-least-once on the handoff plane)."""

    def __init__(self, gen, consumer, producer, handoff_topic: str, *,
                 commit_every: int = 8, max_poll_records: int = 64) -> None:
        if not getattr(gen, "_prefill_role", False):
            raise ValueError(
                "PrefillWorker needs a StreamingGenerator built with "
                "prefill_role=True"
            )
        self.gen = gen
        self.consumer = consumer
        self.producer = producer
        self.handoff_topic = handoff_topic
        self._commit_every = commit_every
        self._max_poll = max_poll_records
        self._since_commit = 0
        self._retry_flush = False
        # Warm drain (PrefillPool scale-down): stop polling new prompts,
        # finish + publish + commit the in-flight ones, then leave.
        self.draining = False

    def start_drain(self) -> None:
        self.draining = True

    def pump(self) -> int:
        """One quantum: poll → admit → chunk tick → publish harvested
        handoffs. Returns handoffs published."""
        free = self.gen.free_slots() - self.gen.pending_admissions
        if free > 0 and not self.draining:
            records = self.consumer.poll(
                max_records=min(free, self._max_poll), timeout_ms=0,
            )
            if records:
                self.gen.note_fetched(records)
                self.gen.admit_records(records)
        elif self.gen.pending_admissions:
            self.gen.admit_records([])
        self.gen.step()
        published = 0
        for rec, hand in self.gen.take_prefilled():
            # Filled blocks extracted, nothing published: death here is
            # the mid-transfer window the crash matrix SIGKILLs at.
            crash_hook("prefill_handoff_pre_publish")
            self.producer.send(
                self.handoff_topic, encode_handoff(hand), key=rec.key,
            )
            self.gen.note_handoff_published(rec, blocks=hand.prompt_blocks)
            published += 1
        if published:
            self.producer.flush()
            self._since_commit += published
        if self._retry_flush or self._since_commit >= self._commit_every:
            ok = self.gen.flush_commits()
            self._since_commit = 0
            self._retry_flush = ok is False
        return published

    def idle(self) -> bool:
        return not self.gen.has_active() and self.gen.pending_admissions == 0

    def close(self) -> None:
        try:
            self.producer.flush()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        self.gen.flush_commits()


class PrefillPool:
    """N in-process prefill workers over one broker — the prefill role's
    twin of ``ServingFleet``'s decode replicas, elastic via ``scale_to``
    (the autoscale controller's prefill actuation surface).

    Every member is a ``PrefillWorker`` over its own group-managed
    consumer (one consumer group for the whole pool: partitions of the
    prompt topic range-assign across members) and a producer onto the
    handoff topic. ``pump_once()`` runs one cooperative quantum across
    live members — call it once per fleet scheduling round and the whole
    disaggregated pipeline shares one deterministic timeline (same-seed
    replays place every handoff identically).

    Scale-up builds a fresh member (compile-free after the first
    warmup: shared jit cache). Scale-down drains WARM: the member stops
    polling, finishes + publishes + commits its in-flight prompts, then
    leaves the group — unpublished work never commits, so nothing is
    lost and the survivors (or the decode fallback path) pick up
    whatever a slow drain leaves behind."""

    def __init__(
        self,
        broker,
        topic: str,
        group: str,
        handoff_topic: str,
        params,
        cfg,
        *,
        workers: int = 1,
        slots: int = 2,
        prompt_len: int,
        max_new: int,
        kv_pages: dict,
        commit_every: int = 4,
        max_poll_records: int = 64,
        gen_kwargs: dict | None = None,
    ) -> None:
        if workers < 0:
            raise ValueError(f"workers must be >= 0, got {workers}")
        self.broker = broker
        self.topic = topic
        self.group = group
        self.handoff_topic = handoff_topic
        self._params = params
        self._cfg = cfg
        self._slots = slots
        self._prompt_len = prompt_len
        self._max_new = max_new
        self._kv_pages = dict(kv_pages)
        self._commit_every = commit_every
        self._max_poll = max_poll_records
        self._gen_kwargs = dict(gen_kwargs or {})
        self._warmed = False
        self._seq = 0
        self.workers: list[PrefillWorker] = []
        self.drained = 0  # members that completed a warm drain
        for _ in range(workers):
            self._spawn()

    def _spawn(self) -> PrefillWorker:
        from torchkafka_tpu.serve import StreamingGenerator
        from torchkafka_tpu.source.memory import MemoryConsumer
        from torchkafka_tpu.source.producer import MemoryProducer

        member = f"pf{self._seq:03d}"
        self._seq += 1
        consumer = MemoryConsumer(
            self.broker, self.topic, group_id=self.group, member_id=member,
        )
        gen = StreamingGenerator(
            consumer, self._params, self._cfg,
            slots=self._slots, prompt_len=self._prompt_len,
            max_new=self._max_new, commit_every=2**31 - 1,
            ticks_per_sync=1, max_poll_records=self._max_poll,
            kv_pages=dict(self._kv_pages), prefill_role=True,
            **self._gen_kwargs,
        )
        if self._warmed:
            gen.warmup()
        worker = PrefillWorker(
            gen, consumer, MemoryProducer(self.broker), self.handoff_topic,
            commit_every=self._commit_every,
            max_poll_records=self._max_poll,
        )
        self.workers.append(worker)
        return worker

    def warmup(self) -> None:
        for w in self.workers:
            w.gen.warmup()
        self._warmed = True

    def live_count(self) -> int:
        """Members still polling new work (draining members are winding
        down and no longer count as capacity)."""
        return sum(1 for w in self.workers if not w.draining)

    def backlog(self) -> int:
        """The prefill role's queue-depth signal: prompt-topic offsets
        the pool's group has not committed yet (offered prefill work not
        yet published-and-retired — the handoff-plane lag an autoscale
        controller scales this role on)."""
        from torchkafka_tpu.source.records import TopicPartition

        total = 0
        for p in range(self.broker.partitions_for(self.topic)):
            tp = TopicPartition(self.topic, p)
            total += self.broker.end_offset(tp) - (
                self.broker.committed(self.group, tp) or 0
            )
        return total

    def occupancy(self) -> float:
        """Mean slot occupancy over live members (scale-down guard)."""
        live = [w for w in self.workers if not w.draining]
        if not live:
            return 0.0
        return sum(
            1.0 - w.gen.free_slots() / max(1, w.gen.slots) for w in live
        ) / len(live)

    def scale_to(self, n: int) -> None:
        """Elastic pool membership: up spawns fresh members (the group
        rebalance hands them partitions), down warm-drains the NEWEST
        members (LIFO — the longest-lived keep their partition
        locality); ``pump_once`` completes the drain."""
        if n < 0:
            raise ValueError(f"scale target must be >= 0, got {n}")
        live = [w for w in self.workers if not w.draining]
        if n > len(live):
            for _ in range(n - len(live)):
                self._spawn()
        elif n < len(live):
            for w in live[n:]:
                w.start_drain()

    def pump_once(self) -> int:
        """One cooperative quantum across every open member; completes
        pending drains. Returns handoffs published this quantum."""
        published = 0
        still: list[PrefillWorker] = []
        for w in self.workers:
            published += w.pump()
            if w.draining and w.idle():
                # In-flight work finished, published, committed: leave.
                w.close()
                w.consumer.close()
                self.drained += 1
            else:
                still.append(w)
        self.workers = still
        return published

    def idle(self) -> bool:
        return all(w.idle() for w in self.workers)

    def close(self) -> None:
        for w in self.workers:
            w.close()
            try:
                w.consumer.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        self.workers = []


def run_prefill_worker(spec: dict, broker=None, shutdown=None) -> int:
    """One prefill-worker incarnation as a fleet process (the
    ``role: "prefill"`` twin of ``fleet.proc.run_replica_worker``): own
    BrokerClient, own jit state, its own consumer group
    ``<group>-prefill`` over the prompt topic, heartbeat-leased there,
    publishing handoffs to ``spec["handoff_topic"]``."""
    from torchkafka_tpu.errors import BrokerUnavailableError, FencedMemberError
    from torchkafka_tpu.fleet.proc import _HeartbeatSender, build_model
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.memory import MemoryConsumer
    from torchkafka_tpu.source.producer import MemoryProducer

    EXIT_CLEAN, EXIT_FENCED = 0, 3
    own_client = broker is None
    if own_client:
        from torchkafka_tpu.resilience import RetryPolicy
        from torchkafka_tpu.source.netbroker import BrokerClient

        b = spec["broker"]
        broker = BrokerClient(
            b["host"], int(b["port"]),
            timeout_s=float(spec.get("connect_timeout_s", 30.0)),
            retry=RetryPolicy(
                max_attempts=int(spec.get("reconnect_attempts", 6)),
                base_delay_s=0.05, max_delay_s=1.0,
                deadline_s=float(spec.get("reconnect_deadline_s", 15.0)),
            ),
        )
    member = spec["member_id"]
    consumer = None
    hb = None
    gen = None
    try:
        import jax

        cfg, params = build_model(spec["model"])
        group = f"{spec['group']}-prefill"
        consumer = MemoryConsumer(
            broker, spec["topic"], group_id=group, member_id=member,
        )
        hb_interval = spec.get("heartbeat_interval_s", 0.25)
        if hb_interval is not None and spec.get(
            "heartbeat_mode", "thread"
        ) == "thread":
            hb = _HeartbeatSender(consumer, float(hb_interval))
            hb.start()
        producer = MemoryProducer(broker)
        gen = StreamingGenerator(
            consumer, params, cfg,
            slots=int(spec.get("slots", 2)),
            prompt_len=int(spec["prompt_len"]),
            max_new=int(spec["max_new"]),
            commit_every=2**31 - 1,
            ticks_per_sync=1,
            max_poll_records=int(spec.get("max_poll_records", 64)),
            temperature=float(spec.get("temperature", 0.0)),
            top_k=spec.get("top_k"),
            top_p=spec.get("top_p"),
            rng=jax.random.key(int(spec.get("sampling_seed", 0))),
            kv_pages=spec.get("kv_pages"),
            kv_tier=spec.get("kv_tier"),
            prefill_role=True,
        )
        gen.warmup()
        if spec.get("ready_topic"):
            MemoryProducer(broker).send(
                spec["ready_topic"], member.encode()
            )
        worker = PrefillWorker(
            gen, consumer, producer, spec["handoff_topic"],
            commit_every=int(spec.get("commit_every", 8)),
            max_poll_records=int(spec.get("max_poll_records", 64)),
        )
        idle_exit_ms = spec.get("idle_exit_ms")
        idle_since = None
        while True:
            if shutdown is not None and shutdown.requested:
                worker.close()
                return EXIT_CLEAN
            if hb is not None and hb.fenced:
                raise FencedMemberError(
                    f"prefill member {member!r} fenced"
                )
            if hb is not None and hb.error is not None:
                raise hb.error
            try:
                if hb is None and hb_interval is not None:
                    consumer.heartbeat()
                published = worker.pump()
            except BrokerUnavailableError:
                time.sleep(0.02)
                continue
            if published or not worker.idle():
                idle_since = None
            else:
                now = time.monotonic()
                if idle_since is None:
                    idle_since = now
                elif (
                    idle_exit_ms is not None
                    and (now - idle_since) * 1e3 >= idle_exit_ms
                ):
                    worker.close()
                    return EXIT_CLEAN
                time.sleep(0.002)
    except FencedMemberError:
        return EXIT_FENCED
    finally:
        if hb is not None:
            hb.stop()
        if gen is not None and spec.get("metrics_path"):
            try:
                doc = {
                    "member": member,
                    "role": "prefill",
                    **gen.metrics.disagg_summary(),
                    "prefill_tokens": gen.metrics.prefill_tokens.count,
                    "prefix_hits": gen.metrics.prefix_hits.count,
                }
                tmp = spec["metrics_path"] + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(doc, f)
                import os

                os.replace(tmp, spec["metrics_path"])
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        if consumer is not None:
            try:
                consumer.close()
            except Exception:  # noqa: BLE001
                pass
        if own_client:
            try:
                broker.close()
            except Exception:  # noqa: BLE001
                pass

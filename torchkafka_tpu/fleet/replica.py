"""One serving replica: a generator + a group-managed consumer + QoS queue.

A replica is the fleet's unit of failure and of scale. It owns:

- a **group-managed consumer** over the prompt topic — membership is what
  spreads partitions across the fleet and what makes replica death
  recoverable (leave → rebalance → the committed-offset resume point, the
  exact machinery tests/test_pod.py proves for training ingest);
- a **generator** (``StreamingGenerator`` or ``SpecStreamingGenerator``)
  driven through the external-admission surface
  (note_fetched/admit_records/step/flush_commits), never its internal
  poll loop;
- an **admission queue** (fleet/qos.py) between the two.

``pump()`` is one cooperative scheduling quantum: sync assignment, poll,
enqueue, backpressure, bucket-gated admit, one device tick block. It
returns the completions the tick retired and NEVER commits — the fleet
calls ``maybe_flush()`` after it has registered those completions, so the
commit-follows-completion ordering is externally observable (and
assertable) at every commit point.

Lifecycle: ``serving`` → (``start_drain()``) → ``draining`` →
(``finish_drain()``) → ``done``; or ``kill()`` → ``dead`` at any point —
the crash simulation: leave the group WITHOUT committing, abandoning
in-flight slots and queue, exactly what a SIGKILL'd process looks like to
the broker once its session lapses.
"""

from __future__ import annotations

import logging
import time
from typing import Callable

import numpy as np

from torchkafka_tpu.errors import NotAssignedError
from torchkafka_tpu.fleet.qos import AdmissionQueue, QoSConfig
from torchkafka_tpu.source.records import Record

_logger = logging.getLogger(__name__)

SERVING = "serving"
DRAINING = "draining"
DONE = "done"
DEAD = "dead"


class Replica:
    def __init__(
        self,
        rid: int,
        generator,
        consumer,
        queue: AdmissionQueue,
        qos: QoSConfig,
        metrics,
        *,
        commit_every: int = 8,
        max_poll_records: int = 256,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.id = rid
        self.gen = generator
        self.consumer = consumer
        self.queue = queue
        self._qos = qos
        self._metrics = metrics
        self._commit_every = commit_every
        self._max_poll = max_poll_records
        self._clock = clock
        self.state = SERVING
        self._since_commit = 0
        self._retry_flush = False  # a survivably-failed flush awaits retry
        self._assigned: frozenset = frozenset()
        # Admission pause (the rollout drain-swap's quiesce): while set,
        # pump() neither polls nor admits — in-flight slots finish
        # through further ticks — but the member STAYS in the group
        # (assignment sync and heartbeats continue), unlike a drain,
        # which leaves. That difference is the whole point: a weight
        # swap must not cost a rebalance.
        self._admission_paused = False

    # ----------------------------------------------------------- lifecycle

    @property
    def runnable(self) -> bool:
        return self.state in (SERVING, DRAINING)

    def start_drain(self) -> None:
        """Stop admitting; in-flight slots finish through further pumps.
        Queued-but-unadmitted records are abandoned UNCOMMITTED — they
        re-deliver to the next incarnation, the loss-free half of the
        drain contract (the replay-free half is finish_drain's commit)."""
        if self.state == SERVING:
            self.state = DRAINING

    @property
    def drain_idle(self) -> bool:
        """Draining and every in-flight generation has retired."""
        return self.state == DRAINING and not self.gen.has_active()

    def finish_drain(self) -> None:
        """Commit everything completed, then leave the group. After this,
        a restarted fleet resumes at the committed watermark with ZERO
        replayed completions (drain acceptance contract). The decode
        journal is synced (flush + fsync) before the consumer leaves:
        a clean drain retires everything so the journal is empty-pruned,
        but a SECOND signal racing this path must still find the disk
        state current.

        The final flush RETRIES on survivable failure: in a fleet-wide
        drain a peer's clean leave bumps the group generation, and a
        replica whose last commit races that rebalance gets
        CommitFailedError — one-shot flushing here would exit rc=0 with
        finished completions stranded uncommitted (replayed on restart,
        LOST if the fleet is retiring for good). flush_commits keeps the
        outbox/cadence intact on failure and the next attempt re-syncs
        the group (assignment() adopts the post-rebalance generation),
        so a bounded retry loop converges; past the budget we fall back
        to the loss-free half of the contract (re-delivery)."""
        deadline = time.monotonic() + 15.0
        while not self.gen.flush_commits():
            if time.monotonic() > deadline:
                _logger.warning(
                    "replica %d drain flush still failing at deadline; "
                    "leaving the tail to re-delivery", self.id,
                )
                break
            time.sleep(0.05)
        self.gen.sync_journal()
        self.consumer.close()
        self.state = DONE

    def pause_admission(self) -> None:
        """Quiesce for an in-place operation (weight hot-swap): stop
        POLLING new work without leaving the group. Pumps keep ticking —
        and keep admitting already-fetched (queued) records — so
        everything the ledger holds pending retires; ``quiesced`` turns
        True once it all has. Queued records must DRAIN rather than
        wait: a fetched-but-unadmitted record is ledger-pending, so any
        completion ordered after it is HELD from the committed view
        (exactly-once outbox) — abandoning the queue would leave those
        outputs uncommittable and the swap's closed-commit-window
        precondition unsatisfiable forever."""
        self._admission_paused = True

    def resume_admission(self) -> None:
        self._admission_paused = False

    @property
    def admission_paused(self) -> bool:
        return self._admission_paused

    @property
    def quiesced(self) -> bool:
        """Paused, queue drained, and no generation in flight — the
        state a hot-swap requires (the commit window is the caller's to
        close via ``maybe_flush(force=True)``): nothing fetched is
        unretired, so one forced flush commits an EMPTY pending set and
        the swap sits exactly between commit windows."""
        return (
            self._admission_paused
            and self.queue.depth() == 0
            and not self.gen.has_active()
        )

    def kill(self) -> None:
        """Crash simulation: leave the group with NOTHING committed beyond
        the last cadence commit. In-flight generations and queued records
        vanish; the rebalance hands the partitions to survivors, whose
        polls resume from this replica's last committed offset — its
        uncommitted prompts re-deliver (at-least-once, per prompt, across
        replica failure)."""
        self.state = DEAD
        try:
            # Consumer.close never commits (the reference's close
            # contract) — it only triggers leave/rebalance.
            self.consumer.close()
        except Exception:  # noqa: BLE001 - a dying replica stays dead
            _logger.exception("replica %d consumer close failed", self.id)

    def close(self) -> None:
        """Voluntary shutdown outside a drain: commit completed work and
        leave (mirrors StreamingGenerator.close)."""
        if self.state in (SERVING, DRAINING):
            self.finish_drain()

    # ---------------------------------------------------------------- pump

    def pump(self) -> list[tuple[Record, np.ndarray]]:
        """One scheduling quantum; returns completions (never commits)."""
        if not self.runnable:
            return []
        self._sync_assignment()
        if self.state == SERVING:
            if not self._admission_paused:
                self._poll_into_queue()
                self._backpressure()
            free = self.gen.free_slots()
            # Paged-pool pressure defers admissions inside the generator
            # (StreamingGenerator.pending_admissions); deferred records
            # hold their future slots and re-offer FIRST (per-partition
            # FIFO), so size new QoS picks by the remainder and keep
            # offering while a backlog exists — an empty offer just
            # drains it as blocks free. Always 0 on dense generators.
            deferred = self.gen.pending_admissions
            room = free - deferred
            picks = self.queue.select(room) if room > 0 else []
            if picks or (deferred and free):
                self.gen.admit_records(picks)
        completions = self.gen.step()
        if completions:
            self._since_commit += len(completions)
            self._metrics.replica_completions(self.id).add(len(completions))
        self._metrics.replica_occupancy(self.id).set(
            1.0 - self.gen.free_slots() / max(1, self.gen.slots)
        )
        return completions

    def maybe_flush(self, force: bool = False) -> None:
        """Cadence commit — called by the fleet AFTER it registered the
        completions the last pump returned, so every commit provably
        follows the completions it covers. A flush that FAILS survivably
        (rebalance, broker outage) is retried on every subsequent call
        until it lands: commit-follows-completion counts completions,
        but a replica whose last completions coincided with an outage
        would otherwise idle forever with its tail uncommitted (and, in
        exactly_once mode, its outputs invisible) — found by the
        broker crash-restart drill."""
        if force or self._retry_flush or self._since_commit >= self._commit_every:
            # ``force`` flushes even at zero counted completions: the
            # exactly-once outbox can hold outputs ORDERED AFTER records
            # that completed in an earlier window (flush_commits'
            # outbox-forces-flush contract) — the hot-swap's
            # close-the-window call must reach it, and flush_commits
            # itself is a no-op when truly nothing is pending.
            if force or self._since_commit or self._retry_flush:
                ok = self.gen.flush_commits()
                self._since_commit = 0
                self._retry_flush = ok is False

    # ------------------------------------------------------------ internal

    def _sync_assignment(self) -> None:
        assigned = frozenset(self.consumer.assignment())
        if assigned != self._assigned:
            dropped = self.queue.prune(set(assigned))
            if dropped:
                _logger.info(
                    "replica %d rebalance: pruned %d queued records for "
                    "departed partitions", self.id, dropped,
                )
            departed = self._assigned - assigned
            if departed:
                # Revocation reset: without it, this replica's ledger
                # keeps the pruned records 'pending', and if a departed
                # partition ever RETURNS (a scale-up's range handed
                # back at scale-down) the stale entries would regress
                # the group's committed watermark at the next flush.
                self.gen.note_partitions_revoked(departed)
            self._assigned = assigned

    def _poll_into_queue(self) -> None:
        if self.queue.depth() >= self._qos.max_queue_depth:
            return
        records = self.consumer.poll(
            max_records=min(
                self._max_poll, self._qos.max_queue_depth - self.queue.depth()
            ),
            timeout_ms=0,
        )
        if records:
            # Ledger BEFORE queue: a queued record must already be pending
            # so no later completion can commit past it (see
            # StreamingGenerator.note_fetched).
            self.gen.note_fetched(records)
            for r in records:
                self.queue.push(r)

    def _backpressure(self) -> None:
        """Pause fetches when saturated (slots full + queue at high water),
        resume at low water. Flags live transport-side (consumer.paused),
        so a rebalance — which clears them — self-heals."""
        try:
            if (
                self.gen.free_slots() == 0
                and self.queue.depth() >= self._qos.max_queue_depth
                and not self.consumer.has_paused()
                and self._assigned
            ):
                self.consumer.pause(*self._assigned)
                self._metrics.backpressure_pauses.add(1)
            elif (
                self.consumer.has_paused()
                and self.queue.depth() <= self._qos.resume_queue_depth
            ):
                self.consumer.resume(*self.consumer.paused())
                self._metrics.backpressure_resumes.add(1)
        except NotAssignedError:
            # Raced a rebalance between assignment() and pause(): the new
            # assignment arrives at the next sync; pause flags were
            # cleared transport-side either way.
            pass

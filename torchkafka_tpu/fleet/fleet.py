"""Partitioned multi-replica serving: the fleet orchestrator.

``ServingFleet`` runs N serving replicas as members of ONE consumer group
over the prompt topic, so partitions range-assign across replicas and the
at-least-once contract holds *per prompt across replica failure* — the
consumer-group machinery that is battle-tested for training ingest
(source/memory.py range assignment + generations; tests/test_pod.py
elastic leave/join with exact re-delivery) generalized to the serving
path, the way vLLM-class production stacks put a router with admission
control in front of continuous-batching engines.

The scheduler is COOPERATIVE: ``serve()`` round-robins one ``pump()``
(poll → QoS admit → one tick block) across live replicas on the calling
thread, which keeps every chaos/drain interleaving deterministic under a
seeded schedule — the property the differential tests are built on. Each
replica is still a real, independent group member with its own consumer,
ledger, and commits; point the ``consumer_factory`` at a
``BrokerClient`` (source/netbroker.py) and the same fleet spans OS
processes, one replica each, exactly like the elastic pod tests.

Failure model:

- ``kill_replica`` / ``ReplicaChaos``: the victim leaves the group with
  nothing committed past its last cadence commit. The rebalance hands its
  partitions to survivors, whose polls resume from the committed offset —
  its uncommitted prompts re-deliver and regenerate. Completions the
  victim emitted but never committed are served AGAIN by a survivor
  (duplicates, counted in ``FleetMetrics.duplicates``); completions it
  committed never re-deliver. No prompt is lost, and no commit ever
  covers unfinished work (each replica's interval ledger guarantees it
  locally; the fleet's commit-follows-completion pump ordering makes it
  observable globally).
- ``ShutdownSignal`` / ``drain()``: stop admitting fleet-wide, finish
  every in-flight generation, commit, leave the group — a restart resumes
  with zero replayed completions (drain is the replay-free shutdown; kill
  is the loss-free crash).

Replay-free drain requires per-partition FIFO admission: the ledger
watermark can only cover a completion once every EARLIER offset of its
partition is retired, so a QoS policy that admits offset 10 ahead of a
still-queued offset 3 of the SAME partition (cross-tenant throttling or
cross-lane priority inside one partition) leaves 10 uncommittable at
drain — it re-serves after restart (at-least-once still holds; the
duplicate is the cost). Keep tenants/lanes partition-aligned (keyed
production — Kafka's own multi-tenant idiom, harness scenario 10 shows
the shape) and drain replay-freedom holds alongside QoS.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Iterator

import numpy as np

from torchkafka_tpu.commit.ledger import merged_watermarks
from torchkafka_tpu.fleet.metrics import FleetMetrics
from torchkafka_tpu.fleet.prefill import PrefillRouter, drain_handoffs
from torchkafka_tpu.fleet.qos import AdmissionQueue, QoSConfig, TenantBuckets
from torchkafka_tpu.fleet.replica import (
    DEAD,
    DONE,
    DRAINING,
    SERVING,
    Replica,
)
from torchkafka_tpu.journal import DecodeJournal
from torchkafka_tpu.serve import StreamingGenerator
from torchkafka_tpu.source.records import Record

_logger = logging.getLogger(__name__)


class ReplicaChaos:
    """Seeded replica-death schedule for chaos runs.

    Picks (victim, kill point) from ``seed`` once the fleet is known;
    fires when the fleet has served ``>= kill point`` completions AND the
    victim is mid-generation with at least one completion already emitted
    — the conditions under which a death provably exercises redelivery
    (something uncommitted exists) rather than dying idle. Deterministic:
    the same seed against the same fleet kills the same replica at the
    same completion count."""

    def __init__(
        self, seed: int = 0, *, min_completions: int = 1,
        max_completions: int = 8, kills: int = 1,
    ) -> None:
        if min_completions < 1 or max_completions < min_completions:
            raise ValueError(
                "need 1 <= min_completions <= max_completions for a kill "
                "point that can exercise redelivery"
            )
        self._rng = np.random.default_rng(seed)
        self._lo, self._hi = min_completions, max_completions
        self._kills_left = kills
        self._victim: int | None = None
        self._at: int | None = None
        self.killed: list[int] = []

    def maybe_kill(self, fleet: "ServingFleet", served: int) -> None:
        if self._kills_left <= 0:
            return
        if self._victim is None:
            self._victim = int(self._rng.integers(len(fleet.replicas)))
            self._at = int(self._rng.integers(self._lo, self._hi + 1))
        if served < (self._at or 0):
            return

        def eligible(r) -> bool:
            return (
                r.runnable
                and r.gen.has_active()
                and fleet.metrics.replica_completions(r.id).count >= 1
            )

        victim = fleet.replicas[self._victim]
        if not eligible(victim):
            # The drawn victim cannot exercise redelivery — it drained,
            # died on its own, or simply owns no active work (a keyed
            # topic can concentrate every partition's traffic on one
            # replica). Re-draw among replicas that CAN die
            # mid-generation; if none can right now, wait (still
            # deterministic: re-draws consume the seeded stream only when
            # an eligible replica exists).
            live = [r.id for r in fleet.replicas if eligible(r)]
            if not live:
                return
            self._victim = int(live[self._rng.integers(len(live))])
            victim = fleet.replicas[self._victim]
        fleet.kill_replica(victim.id)
        self.killed.append(victim.id)
        self._kills_left -= 1
        self._victim = None
        self._at = None


class ServingFleet:
    """N replicas, one consumer group, QoS admission in front.

    ``consumer_factory(rid) -> Consumer`` must return a GROUP-MANAGED
    consumer over the prompt topic (same group_id for every replica —
    that sharing is the whole mechanism). ``generator_cls`` defaults to
    ``StreamingGenerator``; pass ``SpecStreamingGenerator`` for a
    speculative fleet. ``gen_kwargs`` forward to the generator
    constructor (kv_dtype, ticks_per_sync, output_producer, ...).

    ``commit_every`` is the per-replica commit cadence in COMPLETIONS,
    owned by the fleet loop (the generators' internal cadence is
    disabled) so commits happen only at points where the fleet has
    already registered every completion they cover.

    ``journal_dir``/``journal_cadence``: WARM failover
    (torchkafka_tpu/journal). Each replica writes a decode journal
    (``<journal_dir>/replica_<rid>.json``) of its in-flight generations.
    When a replica dies — ``kill_replica``, ``ReplicaChaos``, or a
    SIGTERM drain that overruns ``drain_timeout_s`` — the fleet loads
    the victim's journal FROM DISK (exactly what a survivor of a real
    process death would see) and installs its entries as resume hints on
    every surviving replica, so the rebalance-redelivered prompts
    warm-resume instead of re-decoding from token 0. On construction,
    journals left by a PREVIOUS incarnation are consulted the same way —
    a whole-fleet crash restarts warm too.
    """

    def __init__(
        self,
        consumer_factory: Callable[[int], object],
        params,
        cfg,
        *,
        replicas: int = 2,
        prompt_len: int,
        max_new: int,
        slots: int = 4,
        eos_id: int | None = None,
        qos: QoSConfig | None = None,
        commit_every: int = 8,
        generator_cls: type = StreamingGenerator,
        max_poll_records: int = 256,
        clock: Callable[[], float] = time.monotonic,
        gen_kwargs: dict | None = None,
        journal_dir: str | os.PathLike | None = None,
        journal_cadence: int = 8,
        drain_timeout_s: float | None = None,
        obs=None,
        slo_targets=None,
        handoff_consumer_factory: Callable[[int], object] | None = None,
        route_patience: int = 256,
    ) -> None:
        """``obs``: record-lifecycle tracing + SLO histograms for the
        whole fleet (torchkafka_tpu/obs). ``True`` builds a tracer on
        the fleet's own injectable ``clock`` (so ManualClock fleets get
        deterministic timestamps for free); an ``obs.ObsConfig`` sets
        policy (ring capacity, JSONL sink, token events); an existing
        ``obs.RecordTracer`` is shared as-is. The ONE tracer spans every
        replica — events tag the replica id, the SLO histograms label by
        lane/tenant/replica, and ``metrics.summary()`` gains an ``slo``
        section. None (default): zero tracing, guard-only cost.

        ``handoff_consumer_factory``: disaggregated-prefill adoption for
        an in-process fleet — ``(rid) -> Consumer`` tailing the handoff
        topic (one PRIVATE group per replica: handoffs broadcast).
        Each replica then routes admission through a ``PrefillRouter``
        (``route_patience`` hold decisions before the local-prefill
        fallback) and the serve loop drains arrived handoffs onto the
        generator's shelf every round. Requires paged generators
        (``gen_kwargs={"kv_pages": ...}``).

        ``slo_targets``: a list of ``obs.SLOTarget`` — builds a
        ``BurnRateMonitor`` over the tracer's windowed SLO view
        (requires ``obs``; with ``obs=True`` the window width defaults
        to a quarter of the fastest target's fast window), evaluated
        once per scheduling round. Its state transitions ride the trace
        stream as typed ``burn_state`` events, its per-tenant goodput
        ledger rides ``metrics.summary()``, and its shedding state
        becomes the AdmissionQueue overload hook: batch-lane admission
        DEFERS while the SLO burns, instead of the whole fleet
        collapsing together. ``fleet.monitor`` exposes it."""
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self._qos = qos or QoSConfig()
        self._clock = clock
        self.metrics = FleetMetrics()
        self.tracer = None
        self.monitor = None
        if slo_targets and not obs:
            raise ValueError(
                "slo_targets need the tracer: pass obs=True (or an "
                "ObsConfig with window_s set)"
            )
        if obs is not None and obs is not False:
            from torchkafka_tpu.obs import ObsConfig, RecordTracer

            if isinstance(obs, RecordTracer):
                self.tracer = obs
            elif isinstance(obs, ObsConfig):
                self.tracer = RecordTracer(obs)
            elif obs is True:
                kw = {}
                if slo_targets:
                    kw["window_s"] = min(
                        t.fast_window_s for t in slo_targets
                    ) / 4.0
                self.tracer = RecordTracer(ObsConfig(clock=clock, **kw))
            else:
                raise TypeError(
                    "obs must be True, an ObsConfig, or a RecordTracer, "
                    f"got {type(obs).__name__}"
                )
            self.metrics.attach_slo(self.tracer.slo)
        if slo_targets:
            from torchkafka_tpu.obs import BurnRateMonitor

            self.monitor = BurnRateMonitor(
                self.tracer.slo, slo_targets, tracer=self.tracer,
            )
            self.tracer.attach_monitor(self.monitor)
            self.metrics.attach_burn(self.monitor)
        self._buckets = TenantBuckets(self._qos, clock)
        # Everything _build_replica needs, kept so ``scale_to`` can join
        # fresh group members MID-SERVE with the exact construction the
        # initial replicas got (same jit cache, same QoS plumbing).
        self._factory = consumer_factory
        self._params = params
        self._cfg = cfg
        self._slots = slots
        self._prompt_len = prompt_len
        self._max_new = max_new
        self._eos_id = eos_id
        self._commit_every = commit_every
        self._generator_cls = generator_cls
        self._max_poll_records = max_poll_records
        self._gen_kwargs = dict(gen_kwargs or {})
        self._journal_cadence = journal_cadence
        self._handoff_factory = handoff_consumer_factory
        self._route_patience = route_patience
        self._handoff_tails: dict[int, object] = {}
        self._warmed = False
        self._journal_paths: dict[int, str] = {}
        self._journal_dir = (
            None if journal_dir is None else os.fspath(journal_dir)
        )
        carried_hints: dict = {}
        if self._journal_dir is not None:
            os.makedirs(self._journal_dir, exist_ok=True)
            for rid in range(replicas):
                path = os.path.join(
                    self._journal_dir, f"replica_{rid}.json"
                )
                # A journal left by a previous incarnation = that
                # replica's in-flight state at the whole-fleet crash;
                # its prompts redeliver to THIS incarnation's members.
                carried_hints.update(DecodeJournal.load(path))
            if carried_hints:
                _logger.info(
                    "fleet restart: %d journal entries carried over for "
                    "warm resume", len(carried_hints),
                )
        self.replicas: list[Replica] = []
        for _ in range(replicas):
            gen = self._build_replica().gen
            if carried_hints:
                gen.add_resume_hints(carried_hints)
        self._draining = False
        self._drain_timeout_s = drain_timeout_s
        self._drain_started: float | None = None
        # Every (topic, partition, offset) a completion has been emitted
        # for, fleet-wide — updated BEFORE any commit that could cover it
        # (the pump/maybe_flush ordering), so an external observer can
        # assert "committed ⊆ completed" at every commit point.
        self.completed: set[tuple[str, int, int]] = set()

    # ---------------------------------------------------------- elasticity

    def _build_replica(self) -> Replica:
        """Construct and register one replica (the next free id): its
        group-managed consumer, generator, admission queue — and, for a
        disaggregated fleet, its private handoff tail + PrefillRouter.
        Used by the constructor AND by ``scale_to`` mid-serve (the new
        consumer's join triggers the rebalance that hands it
        partitions)."""
        rid = len(self.replicas)
        consumer = self._factory(rid)
        kw = dict(self._gen_kwargs)
        if self._journal_dir is not None:
            path = os.path.join(self._journal_dir, f"replica_{rid}.json")
            self._journal_paths[rid] = path
            kw["journal"] = DecodeJournal(
                path, cadence=self._journal_cadence
            )
        if self.tracer is not None:
            kw.setdefault("tracer", self.tracer)
            kw.setdefault("trace_replica", rid)
        gen = self._generator_cls(
            consumer, self._params, self._cfg,
            slots=self._slots, prompt_len=self._prompt_len,
            max_new=self._max_new, eos_id=self._eos_id,
            # The fleet loop owns the cadence (commit-follows-
            # completion ordering); the generator must never
            # self-commit mid-step.
            commit_every=2**31 - 1,
            **kw,
        )
        prefill_router = None
        if self._handoff_factory is not None:
            self._handoff_tails[rid] = self._handoff_factory(rid)
            prefill_router = PrefillRouter(
                gen, patience=self._route_patience
            ).should_hold
        queue = AdmissionQueue(
            self._qos, self._buckets, self.metrics, self._clock,
            tracer=self.tracer, replica=rid,
            overload=(
                self.monitor.should_defer
                if self.monitor is not None else None
            ),
            on_overload_defer=(
                self.monitor.note_deferred
                if self.monitor is not None else None
            ),
            prefill_router=prefill_router,
        )
        rep = Replica(
            rid, gen, consumer, queue, self._qos, self.metrics,
            commit_every=self._commit_every,
            max_poll_records=self._max_poll_records, clock=self._clock,
        )
        self.replicas.append(rep)
        self.metrics.replica_joins.add(1)
        if self.tracer is not None:
            self.tracer.replica_joined(f"replica-{rid}", replica=rid)
        if self._warmed:
            gen.warmup()  # shared jit cache: scale-up joins compile-free
        return rep

    def live_count(self) -> int:
        """Replicas currently SERVING (draining members are winding down
        and no longer count as capacity — the autoscaler's view)."""
        return sum(1 for r in self.replicas if r.state == SERVING)

    def scale_to(self, n: int) -> None:
        """Elastic membership mid-serve, in-process: the ServingFleet
        twin of ``ProcessFleet.scale``. Scale-UP builds fresh replicas
        (their consumers join the group — the rebalance hands them
        partitions; the shared jit cache makes the join compile-free
        after warmup). Scale-DOWN drains the NEWEST serving replicas
        WARM (stop admitting, finish in-flight generations, commit,
        leave — zero lost, zero replay at quiesced transitions); the
        serve loop completes the drain."""
        if n < 1:
            raise ValueError(f"scale target must be >= 1, got {n}")
        serving = [r for r in self.replicas if r.state == SERVING]
        if n > len(serving):
            for _ in range(n - len(serving)):
                self._build_replica()
        elif n < len(serving):
            # LIFO: the longest-lived replicas keep their partition and
            # radix-cache locality.
            for rep in serving[n:]:
                rep.start_drain()

    # ------------------------------------------------------------- control

    def warmup(self) -> None:
        """Compile every replica's admit/tick programs (shared jit cache:
        replica 0 pays, the rest hit)."""
        for rep in self.replicas:
            rep.gen.warmup()
        self._warmed = True

    def drain(self) -> None:
        """Fleet-wide graceful drain: stop admitting everywhere; serve()
        finishes in-flight generations, commits, and leaves the group.
        With ``drain_timeout_s`` set, a replica whose in-flight work
        outlives the timeout is escalated: its journal is synced (the
        one cooperative act a SIGTERM grace window still allows) and the
        replica is killed — its uncommitted prompts re-deliver to the
        NEXT incarnation, which warm-resumes them from the synced
        journal instead of re-decoding from token 0."""
        self._draining = True
        self._drain_started = self._clock()
        for rep in self.replicas:
            rep.start_drain()

    def _enforce_drain_timeout(self) -> None:
        if (
            self._drain_timeout_s is None
            or self._drain_started is None
            or self._clock() - self._drain_started < self._drain_timeout_s
        ):
            return
        for rep in self.replicas:
            if rep.state == DRAINING:
                # Last cooperative act before the axe: the journal's
                # disk state becomes exactly current, so the overrun
                # in-flight work resumes warm (and token-exact) later.
                rep.gen.sync_journal()
                _logger.warning(
                    "replica %d overran drain timeout (%.1fs); killing "
                    "with journal synced for warm resume", rep.id,
                    self._drain_timeout_s,
                )
                self.kill_replica(rep.id)
                self.metrics.drain_timeout_kills.add(1)

    def start_rollout(
        self,
        version: int,
        params_by_version: dict,
        *,
        canary_replica: int = 0,
        canary_slice: int = 8,
        max_canary_diffs: int = 0,
        incumbent_version: int = 0,
    ):
        """Begin a rolling hot-swap to ``version`` on this in-process
        fleet (fleet/rollout.py). ``params_by_version`` maps version
        ints to params trees — the in-process twin of the checkpoint
        topic; it must hold the target AND the incumbent (rollback swaps
        back to it). Returns an ``InProcessRolloutDriver``: plug its
        ``on_round`` into ``serve(on_round=...)`` and feed every yielded
        completion to ``observe(rid, rec, tokens)`` — the canary's
        token-diff stream. ``trace_acks`` is off because each
        generator's own ``swap_params`` already types the ``swapped``
        event with its replica id."""
        from torchkafka_tpu.fleet.rollout import (
            InProcessRolloutDriver,
            RolloutController,
        )

        ctl = RolloutController(
            [r.id for r in self.replicas if r.state == SERVING],
            int(version),
            canary_member=canary_replica,
            canary_slice=canary_slice,
            max_canary_diffs=max_canary_diffs,
            incumbent_version=incumbent_version,
            tracer=self.tracer, metrics=self.metrics,
            trace_acks=False,
        )
        return InProcessRolloutDriver(self, ctl, params_by_version)

    def start_distill(
        self,
        *,
        policy=None,
        broker=None,
        ckpt_topic: str | None = None,
        versions: dict | None = None,
        applied_version: int = 0,
    ):
        """Close the online-distillation loop on this in-process fleet
        (torchkafka_tpu/distill): a ``DistillController`` tracking the
        windowed live-α from every replica's ``spec_stats`` (requires
        ``generator_cls=SpecStreamingGenerator``) and an
        ``InProcessDistillDriver`` applying refresh directives via
        ``swap_draft_params`` — between ticks, no quiesce, committed
        tokens invariant. Plug the returned driver's ``on_round`` into
        ``serve(on_round=...)`` (compose with a workload driver's hook
        by calling both) and push published draft versions with
        ``driver.note_version``. Delivery is ``broker``+``ckpt_topic``
        (wire fetch, CRC-validated) or a ``versions`` dict (in-process
        twin). The controller shares the fleet clock, so ManualClock
        fleets replay the whole control loop byte-identically."""
        from torchkafka_tpu.distill.controller import (
            DistillController,
            InProcessDistillDriver,
        )

        ctl = DistillController(
            policy,
            applied_version=applied_version,
            clock=self._clock,
            tracer=self.tracer,
            metrics=self.metrics,
        )
        return InProcessDistillDriver(
            self, ctl, broker=broker, ckpt_topic=ckpt_topic,
            versions=versions,
        )

    def kill_replica(self, rid: int) -> None:
        """Simulate a replica crash (see Replica.kill), then consult the
        victim's decode journal for warm failover: its entries — read
        FROM DISK, exactly the state a real process death leaves behind,
        never the dead generator's fresher in-memory view — become resume
        hints on every survivor. The rebalance re-delivers the victim's
        uncommitted prompts to whichever survivor inherits the
        partitions; the hint is consumed there (CRC-checked), and stale
        copies on the other survivors sit harmlessly."""
        self.replicas[rid].kill()
        self._close_handoff_tail(rid)
        self.metrics.replica_deaths.add(1)
        self.metrics.replica_fences.add(1)
        if self.tracer is not None:
            self.tracer.replica_fenced(
                f"replica-{rid}", reason="kill", replica=rid,
            )
        self._install_journal_hints(rid)

    def _install_journal_hints(self, rid: int) -> None:
        path = self._journal_paths.get(rid)
        if path is None:
            return
        hints = DecodeJournal.load(path)
        if not hints:
            return
        survivors = [r for r in self.replicas if r.runnable]
        for rep in survivors:
            rep.gen.add_resume_hints(hints)
        self.metrics.journal_handoffs.add(len(hints))
        if self.tracer is not None:
            self.tracer.journal_handoff(
                f"replica-{rid}", len(hints), replica=rid,
            )
        _logger.info(
            "replica %d death: %d journal entries handed to %d "
            "survivor(s) for warm resume", rid, len(hints), len(survivors),
        )

    def _close_handoff_tail(self, rid: int) -> None:
        tail = self._handoff_tails.pop(rid, None)
        if tail is None:
            return
        try:
            tail.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            _logger.exception("handoff tail close failed for replica %d", rid)

    def close(self) -> None:
        """Graceful stop outside serve(): commit completed work, leave."""
        for rep in self.replicas:
            rep.close()
        for rid in list(self._handoff_tails):
            self._close_handoff_tail(rid)

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------- observability

    def watermarks(self) -> dict:
        """Fleet-level committable view: per-replica ledger snapshots
        merged fail-low (commit.ledger.merged_watermarks)."""
        return merged_watermarks([
            rep.gen.committable_offsets()
            for rep in self.replicas if rep.state != DEAD
        ])

    def pending_by_replica(self) -> dict[int, int]:
        """In-flight (fetched-but-unretired) records per replica."""
        return {
            rep.id: sum(rep.gen._ledger.pending_by_partition().values())
            for rep in self.replicas
        }

    # --------------------------------------------------------------- serve

    def serve(
        self,
        max_records: int | None = None,
        idle_timeout_ms: int = 2000,
        shutdown=None,
        chaos: ReplicaChaos | None = None,
        on_round: Callable[["ServingFleet", int], None] | None = None,
    ) -> Iterator[tuple[int, Record, np.ndarray]]:
        """Yield ``(replica_id, record, tokens)`` in fleet completion
        order until ``max_records`` completions, an idle timeout, or a
        completed drain.

        ``shutdown``: a ``ShutdownSignal`` (or anything with a
        ``requested`` bool) — when it fires, the fleet drains gracefully
        and serve() returns after the last in-flight generation commits.
        ``chaos``: a ``ReplicaChaos`` schedule, evaluated once per
        scheduling round. ``on_round(fleet, served)``: called once at
        the top of every scheduling round — the workload driver's
        injection point (advance a synthetic clock, produce due
        arrivals, fire scheduled chaos) so open-loop load generation
        stays deterministic against the cooperative scheduler."""
        served = 0
        exhausted_at: float | None = None
        while True:
            if on_round is not None:
                on_round(self, served)
            if (
                shutdown is not None
                and getattr(shutdown, "requested", False)
                and not self._draining
            ):
                self.drain()
            if self._draining:
                self._enforce_drain_timeout()
            progressed = False
            for rep in self.replicas:
                if not rep.runnable:
                    continue
                tail = self._handoff_tails.get(rep.id)
                if tail is not None:
                    # Disaggregated adoption: arrived handoffs land on
                    # the generator's shelf BEFORE this round's
                    # admission sweep, so the router releases their
                    # records the same round.
                    drain_handoffs(tail, rep.gen)
                completions = rep.pump()
                # Register BEFORE the flush below: every commit must only
                # ever cover completions already in self.completed.
                for rec, _toks in completions:
                    key = (rec.topic, rec.partition, rec.offset)
                    if key in self.completed:
                        self.metrics.duplicates.add(1)
                    self.completed.add(key)
                self.metrics.completions.add(len(completions))
                rep.maybe_flush()
                if rep.drain_idle:
                    rep.finish_drain()
                    self._close_handoff_tail(rep.id)
                    self.metrics.drains.add(1)
                if completions:
                    progressed = True
                for rec, toks in completions:
                    served += 1
                    yield rep.id, rec, toks
            if self.monitor is not None:
                # One burn-rate sweep per scheduling round: cheap (no
                # new samples → no transitions), deterministic, and the
                # NEXT round's admission sweeps see the fresh state.
                self.monitor.evaluate()
            if chaos is not None:
                chaos.maybe_kill(self, served)
            live = [r for r in self.replicas if r.runnable]
            if not live:
                break  # drained (or every replica died)
            if max_records is not None and served >= max_records and not any(
                r.gen.has_active() for r in live
            ):
                break
            idle = not progressed and not any(
                r.gen.has_active() for r in live
            )
            if idle:
                if not any(r.queue.depth() for r in live):
                    # Truly exhausted (no work anywhere): start the idle
                    # clock. A non-empty queue with nothing admissible is
                    # the THROTTLED case — wait for token refill without
                    # burning a core, but never time out on it.
                    if exhausted_at is None:
                        exhausted_at = time.monotonic()
                    elif (
                        time.monotonic() - exhausted_at
                    ) * 1e3 >= idle_timeout_ms:
                        break
                time.sleep(0.001)
            else:
                exhausted_at = None
        for rep in self.replicas:
            if rep.runnable:
                rep.maybe_flush(force=True)

    # Convenience for scripts/tests that just want everything served.
    def serve_all(
        self, max_records: int | None = None, idle_timeout_ms: int = 2000,
        shutdown=None, chaos: ReplicaChaos | None = None, on_round=None,
    ) -> list[tuple[int, Record, np.ndarray]]:
        return list(self.serve(
            max_records, idle_timeout_ms, shutdown=shutdown, chaos=chaos,
            on_round=on_round,
        ))

"""One serving replica as a real OS process: the process fleet's worker.

``run_replica_worker(spec)`` is a complete replica incarnation — its own
``BrokerClient`` over the supervisor's socket broker, its own jit state
(params rebuilt deterministically from the spec's model seed, so every
process decodes identically), its own on-disk ``DecodeJournal`` — driven
by the same ``Replica`` pump the in-process fleet uses, plus the three
things only a real process needs:

- **heartbeat leases**: every ``heartbeat_interval_s`` the worker renews
  its broker-side lease (``MemoryConsumer.heartbeat``, crash point
  ``heartbeat_pre_send``). A worker that dies — or stalls past the
  session timeout — is FENCED: evicted with a rebalance, its partitions
  re-delivered to survivors, its stale-generation commits rejected. A
  fenced worker learns its fate from ``FencedMemberError`` and exits
  ``EXIT_FENCED`` so a supervisor can respawn a fresh incarnation.
- **cross-process warm failover**: at startup and on every observed
  assignment change (a rebalance means someone died or scaled), the
  worker rescans the shared ``journal_dir`` (``DecodeJournal.scan_dir``,
  crash point ``journal_handoff_pre_load``) and installs every peer
  journal's live entries as warm-resume hints — the victim's in-flight
  generations resume on the survivor byte-identical, bounded re-decode.
- **reconnect-with-backoff**: the ``BrokerClient`` runs behind a
  ``resilience.RetryPolicy``, so a socket drop mid-serve is a retryable
  ``BrokerUnavailableError`` absorbed by jittered reconnects — an outage
  longer than the session timeout still ends in a clean fencing, never
  corruption.

Runnable as ``python -m torchkafka_tpu.fleet.proc <spec.json>`` (the
supervisor writes the spec); importable so the crash matrix and tests can
run the SAME incarnation logic in-process as the recovery run.

Outputs are produced to ``spec["out_topic"]`` keyed by the prompt
record's key, with a ``member`` header naming the serving incarnation —
so a supervisor (or a test) can attribute every completion, count
duplicates, and pick a mid-generation victim without reaching into the
worker's memory.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

EXIT_CLEAN = 0
EXIT_FENCED = 3  # this incarnation was fenced; respawn a fresh member


class _HeartbeatSender(threading.Thread):
    """The lease keeper, on its own thread — Kafka's own split between
    session liveness (the background heartbeat) and processing liveness
    (max.poll.interval): a replica mid-jit-warmup or mid-tick on a
    contended core is SLOW, not DEAD, and must not fence itself. The
    thread renews every ``interval_s``; the serving loop only reads
    ``fenced`` at its own safe points. Transport faults ride the
    client's retry policy; an outage that outlives the session timeout
    ends in FencedMemberError here — observed, flagged, thread exits."""

    def __init__(self, consumer, interval_s: float) -> None:
        super().__init__(name="replica-heartbeat", daemon=True)
        self._consumer = consumer
        self._interval_s = interval_s
        self._stop = threading.Event()
        self.fenced = False
        self.outages = 0  # renewals that found the broker unreachable
        self.error: BaseException | None = None

    def run(self) -> None:
        from torchkafka_tpu.errors import (
            BrokerUnavailableError,
            FencedMemberError,
        )

        while not self._stop.is_set():
            try:
                self._consumer.heartbeat()
            except FencedMemberError:
                self.fenced = True
                return
            except BrokerUnavailableError:
                # Outage outlived the client's retry budget: keep trying
                # — a WAL-recovered broker restores this member with a
                # fresh lease, so the next renewal that lands simply
                # resumes the session. If the broker instead comes back
                # without us (or never), the outcome is FencedMemberError
                # or shutdown, both handled above/outside.
                self.outages += 1
                self._stop.wait(self._interval_s)
                continue
            except Exception as exc:  # noqa: BLE001 - flagged, loop decides
                # A teardown race or a genuine bug: the serving loop
                # surfaces it at its next safe point.
                self.error = exc
                return
            self._stop.wait(self._interval_s)

    def stop(self) -> None:
        self._stop.set()


def build_model(model_spec: dict):
    """Deterministic params from the spec — every process that holds the
    same model spec decodes identically (greedy) or samples identically
    (the per-record key schedule folds from record identity)."""
    import jax
    import jax.numpy as jnp

    from torchkafka_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=int(model_spec["vocab_size"]),
        d_model=int(model_spec["d_model"]),
        n_layers=int(model_spec["n_layers"]),
        n_heads=int(model_spec["n_heads"]),
        n_kv_heads=int(model_spec["n_kv_heads"]),
        d_ff=int(model_spec["d_ff"]),
        max_seq_len=int(model_spec["max_seq_len"]),
        dtype=jnp.float32,
    )
    params = init_params(jax.random.key(int(model_spec.get("seed", 0))), cfg)
    return cfg, params


class _TaggingProducer:
    """Wrap a producer so every output record carries a ``member`` header
    naming this incarnation — the supervisor's attribution handle. Every
    OTHER attribute (the transactional surface — begin/commit/abort/
    send_offsets/in_transaction — when the inner producer is a
    ``TransactionalProducer``) forwards untouched, so serve.py's
    exactly_once mode drives transactions straight through the tag."""

    def __init__(self, inner, member: str) -> None:
        self._inner = inner
        self._member = member.encode()

    def send(self, topic, value, *, key=None, partition=None,
             timestamp_ms=None, headers=()):
        return self._inner.send(
            topic, value, key=key, partition=partition,
            timestamp_ms=timestamp_ms,
            headers=tuple(headers) + (("member", self._member),),
        )

    def flush(self, timeout_s=None):
        return self._inner.flush(timeout_s)

    def close(self):
        return self._inner.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _dump_metrics(
    spec: dict, gen, fleet_metrics, exit_code: int, breaker=None, hb=None,
) -> None:
    path = spec.get("metrics_path")
    if not path:
        return
    m = gen.metrics
    doc = {
        "member": spec["member_id"],
        "exit": exit_code,
        "decoded_tokens": m.decoded_tokens.count,
        "warm_resumes": m.warm_resumes.count,
        "tokens_restored": m.journal_tokens_restored.count,
        "served_from_journal": m.journal_served.count,
        "resume_rejected": m.resume_rejected.count,
        "completions": fleet_metrics.completions.count,
        "commit_failures": m.commit_failures.count,
        # Which weights this incarnation EXITED on — the rollout audit's
        # per-worker version attribution (journal meta is the durable twin).
        "model_version": gen.model_version,
        # Disaggregated decode: slots admitted by handoff adoption (no
        # prompt pass here) vs locally prefilled tokens, plus the tick
        # p50/p99 the "decode ITL never stalls" audit reads.
        "adopted_slots": m.adopted_slots.count,
        "prefill_routed": m.prefill_routed.count,
        "prefill_tokens": m.prefill_tokens.count,
        "step_p50_ms": m.tick_time.summary()["p50_ms"],
        "step_p99_ms": m.tick_time.summary()["p99_ms"],
        "circuit_opens": breaker.opens if breaker is not None else 0,
        "circuit_closes": breaker.closes if breaker is not None else 0,
        "heartbeat_outages": hb.outages if hb is not None else 0,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def run_replica_worker(spec: dict, broker=None, shutdown=None) -> int:
    """One replica incarnation over ``broker`` (a ``BrokerClient`` built
    from the spec when None — the subprocess path; pass an
    ``InMemoryBroker`` directly for in-process recovery runs). Returns
    the process exit code: ``EXIT_CLEAN`` after a drain (idle-exit or
    SIGTERM via ``shutdown``), ``EXIT_FENCED`` when the broker evicted
    this member."""
    from torchkafka_tpu.errors import (
        BrokerUnavailableError,
        FencedMemberError,
        ProducerFencedError,
    )
    from torchkafka_tpu.fleet.metrics import FleetMetrics
    from torchkafka_tpu.fleet.qos import AdmissionQueue, QoSConfig, TenantBuckets
    from torchkafka_tpu.fleet.replica import Replica, SERVING
    from torchkafka_tpu.journal import DecodeJournal
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.memory import MemoryConsumer
    from torchkafka_tpu.source.producer import MemoryProducer

    own_client = broker is None
    if own_client:
        from torchkafka_tpu.resilience import RetryPolicy
        from torchkafka_tpu.source.netbroker import BrokerClient

        b = spec["broker"]
        broker = BrokerClient(
            b["host"], int(b["port"]),
            timeout_s=float(spec.get("connect_timeout_s", 30.0)),
            retry=RetryPolicy(
                max_attempts=int(spec.get("reconnect_attempts", 6)),
                base_delay_s=0.05, max_delay_s=1.0,
                deadline_s=float(spec.get("reconnect_deadline_s", 15.0)),
            ),
        )

    member = spec["member_id"]
    jdir = spec["journal_dir"]
    jpath = os.path.join(jdir, f"{member}.json")
    consumer = None
    gen = None
    journal = None
    hb = None
    breaker = None
    ho_consumer = None
    router = None
    metrics = FleetMetrics()
    exit_code = EXIT_CLEAN
    try:
        # Model first (slow: jax import + init): the lease clock must not
        # run against compile time we have not even joined for yet.
        cfg, params = build_model(spec["model"])
        import jax

        # Version restore: the journal's durable meta — flipped BEFORE the
        # in-memory rebind at every swap — is the restart authority. A
        # worker SIGKILL'd mid-rollout comes back here, reads the version
        # its previous life committed to, and rebuilds THOSE weights from
        # the checkpoint topic byte-identically before serving a single
        # token. A torn/unfetchable checkpoint falls back to the boot
        # weights: the version-tagged resume hints then reject (cold
        # replay — slower, still exactly-once), never a crash.
        boot_version = int(spec.get("model_version", 0))
        boot_params = params
        model_version = boot_version
        ckpt_topic = spec.get("ckpt_topic")
        if ckpt_topic:
            from torchkafka_tpu.journal import DecodeJournal as _DJ

            journaled = _DJ.load_meta(jpath).get("model_version")
            if journaled is not None and int(journaled) != boot_version:
                from torchkafka_tpu.errors import CheckpointWireError
                from torchkafka_tpu.source.checkpoint_wire import (
                    fetch_checkpoint,
                    rebuild_tree,
                )

                try:
                    flat, _mf = fetch_checkpoint(
                        broker, ckpt_topic, int(journaled),
                    )
                    params = rebuild_tree(boot_params, flat)
                    model_version = int(journaled)
                except CheckpointWireError:
                    metrics.checkpoint_reject("restore").add(1)

        consumer = MemoryConsumer(
            broker, spec["topic"], group_id=spec["group"], member_id=member,
        )
        if spec.get("resilient"):
            # Broker-outage riding, made observable: poll/commit run
            # behind a RetryPolicy + CircuitBreaker (resilience/), so a
            # broker-process death mid-storm degrades to empty polls and
            # fast-failed (survivable) commits while the circuit is open,
            # then closes when the WAL-recovered broker answers again —
            # the open-then-close transition counters land in the metrics
            # dump for the supervisor's audit.
            from torchkafka_tpu.resilience import (
                CircuitBreaker,
                ResilientConsumer,
                RetryPolicy,
            )
            from torchkafka_tpu.utils.metrics import ResilienceMetrics

            breaker = CircuitBreaker(
                failure_threshold=int(spec.get("breaker_threshold", 3)),
                reset_timeout_s=float(spec.get("breaker_cooldown_s", 0.25)),
            )
            consumer = ResilientConsumer(
                consumer,
                policy=RetryPolicy(
                    max_attempts=2, base_delay_s=0.02, max_delay_s=0.2,
                    deadline_s=2.0,
                ),
                breaker=breaker,
                metrics=ResilienceMetrics(),
            )
        hb_interval = spec.get("heartbeat_interval_s", 0.25)
        # "thread" (default, Kafka's own split: session liveness on a
        # background sender, so warmup/tick stalls are SLOW, not dead) or
        # "loop" (renew once per pump — deterministic arrival counts, the
        # crash matrix's mode; pair it with a generous session timeout).
        hb_mode = spec.get("heartbeat_mode", "thread")
        if hb_interval is not None and hb_mode == "thread":
            hb = _HeartbeatSender(consumer, float(hb_interval))
            hb.start()
        exactly_once = bool(spec.get("exactly_once", False))
        if exactly_once:
            from torchkafka_tpu.source.producer import TransactionalProducer

            # The transactional id is keyed by replica INDEX, not
            # incarnation: a respawned replacement re-initializes the
            # SAME id, which bumps the epoch — fencing the victim and
            # aborting whatever transaction its death left open. That
            # epoch bump IS the exactly-once handoff (the consumer-side
            # twin is the member-id range slot trick above).
            txn_id = spec.get(
                "transactional_id",
                f"{spec['group']}-r{int(spec.get('replica_index', 0)):03d}",
            )
            inner_producer = TransactionalProducer(broker, txn_id)
        else:
            inner_producer = MemoryProducer(broker)
        producer = _TaggingProducer(inner_producer, member)
        journal = DecodeJournal(
            jpath, cadence=int(spec.get("journal_cadence", 4)),
        )

        gen = StreamingGenerator(
            consumer, params, cfg,
            slots=int(spec.get("slots", 2)),
            prompt_len=int(spec["prompt_len"]),
            max_new=int(spec["max_new"]),
            eos_id=spec.get("eos_id"),
            # The worker loop owns the cadence (commit-follows-completion
            # via Replica.maybe_flush); the generator never self-commits.
            commit_every=2**31 - 1,
            ticks_per_sync=int(spec.get("ticks_per_sync", 1)),
            max_poll_records=int(spec.get("max_poll_records", 64)),
            temperature=float(spec.get("temperature", 0.0)),
            top_k=spec.get("top_k"),
            top_p=spec.get("top_p"),
            rng=jax.random.key(int(spec.get("sampling_seed", 0))),
            output_producer=producer,
            output_topic=spec["out_topic"],
            exactly_once=exactly_once,
            kv_pages=spec.get("kv_pages"),
            kv_tier=spec.get("kv_tier"),
            journal=journal,
            model_version=model_version,
            # Online distillation corpus: committed completions ride the
            # same commit window (exactly-once: same transaction) onto
            # the distill topic, so the trainer only ever sees tokens the
            # committed view holds.
            distill_topic=spec.get("distill_topic"),
        )
        # Disaggregated decode: tail the handoff topic (broadcast — one
        # private group per replica) into the generator's shelf, and
        # route admission through the PrefillRouter so records wait
        # (bounded) for their prefill worker's filled KV instead of
        # prefilling locally.
        handoff_topic = spec.get("handoff_topic")
        if handoff_topic:
            from torchkafka_tpu.fleet.prefill import (
                PrefillRouter,
                drain_handoffs,
            )

            ho_consumer = MemoryConsumer(
                broker, handoff_topic,
                group_id=f"{spec['group']}-ho-{member}",
                member_id=member,
            )
            router = PrefillRouter(
                gen, patience=int(spec.get("route_patience", 256)),
            )
        # Cross-process warm failover, incarnation-start edition: every
        # journal a previous incarnation (own or peer) left in the shared
        # dir becomes a resume hint — CRC-gated at apply, so stale or
        # already-served entries sit harmlessly.
        hints = DecodeJournal.scan_dir(jdir, exclude=(jpath,))
        if hints:
            gen.add_resume_hints(hints)
        gen.warmup()
        if spec.get("ready_topic"):
            # Readiness marker: lets a supervisor (or a paired bench)
            # exclude per-process jit warmup from the measured window.
            MemoryProducer(broker).send(
                spec["ready_topic"], member.encode()
            )
        qos = QoSConfig()
        queue = AdmissionQueue(
            qos, TenantBuckets(qos), metrics,
            prefill_router=(
                router.should_hold if router is not None else None
            ),
        )
        rep = Replica(
            int(spec.get("replica_index", 0)), gen, consumer, queue, qos,
            metrics,
            commit_every=int(spec.get("commit_every", 8)),
            max_poll_records=int(spec.get("max_poll_records", 64)),
        )
        rollout = None
        if spec.get("rollout_topic") and ckpt_topic:
            from torchkafka_tpu.fleet.rollout import RolloutWorker

            rollout = RolloutWorker(
                broker, spec["rollout_topic"], ckpt_topic, member, rep,
                boot_params=boot_params, boot_version=boot_version,
                metrics=metrics,
            )
            if model_version != boot_version:
                # The restored tree is this incarnation's incumbent —
                # a later rollback to it must not need the wire.
                rollout.cache(model_version, params)

        idle_exit_ms = spec.get("idle_exit_ms")
        last_assign: frozenset = frozenset()
        idle_since: float | None = None
        while True:
            now = time.monotonic()
            if shutdown is not None and shutdown.requested:
                if rep.state == SERVING:
                    rep.start_drain()
            if hb is not None and hb.fenced:
                # The broker already gave our partitions away: stop at
                # this safe point — serving on would be zombie work whose
                # commits are all doomed (and whose outputs survivors are
                # already regenerating byte-identically).
                raise FencedMemberError(
                    f"member {member!r} fenced (observed by heartbeat)"
                )
            if hb is not None and hb.error is not None:
                raise hb.error
            if breaker is not None and not breaker.allow():
                # Circuit open: the broker outage is declared. Stop
                # hammering a dead socket; in-flight decode state, the
                # journal, and the outbox keep. The cooldown's half-open
                # probe (the next allowed pump) decides recovery.
                time.sleep(0.02)
                continue
            try:
                if hb is None and hb_interval is not None:
                    consumer.heartbeat()  # loop mode: one renewal per pump
                if ho_consumer is not None:
                    drain_handoffs(ho_consumer, gen)
                assigned = frozenset(consumer.assignment())
                if assigned != last_assign:
                    if assigned - last_assign:
                        # Gained partitions: a peer died or the fleet
                        # rescaled. Its journal, read FROM DISK across the
                        # process boundary, is the warm-failover handoff.
                        fresh = DecodeJournal.scan_dir(jdir, exclude=(jpath,))
                        if fresh:
                            gen.add_resume_hints(fresh)
                    last_assign = assigned
                completions = rep.pump()
                rep.maybe_flush()
                if rollout is not None:
                    # One rollout-plane sweep per pump: control-topic
                    # directives in, canary comparisons over this pump's
                    # completions, a pending drain-swap completed the
                    # moment the replica quiesces.
                    rollout.pump(completions)
            except BrokerUnavailableError:
                # The broker is DOWN past the client's retry budget (a
                # broker-process death; the supervisor is restarting it
                # from the WAL). Ride the outage: commits stay pending
                # and the next pump retries — a recovered broker restores
                # this member's lease and generation, so serving resumes
                # with zero lost records. The breaker counts the outage
                # evidence (open-then-close lands in the metrics dump).
                if breaker is not None:
                    breaker.record_failure()
                time.sleep(0.02)
                continue
            if breaker is not None:
                breaker.record_success()
            if rep.drain_idle:
                rep.finish_drain()
                return EXIT_CLEAN
            if completions or gen.has_active() or queue.depth():
                idle_since = None
            elif rep.state == SERVING and not rep.admission_paused:
                # A quiesced-for-swap replica is WORKING (the swap lands
                # on the next pump), not idle — never idle-exit it.
                if idle_since is None:
                    idle_since = now
                elif (
                    idle_exit_ms is not None
                    and (now - idle_since) * 1e3 >= idle_exit_ms
                ):
                    rep.start_drain()
                    continue
                time.sleep(0.002)
    except (FencedMemberError, ProducerFencedError):
        exit_code = EXIT_FENCED
        # Best-effort journal flush: we are a zombie for the GROUP, but
        # our disk state is still the freshest record of the in-flight
        # work survivors are about to redo — a current journal shrinks
        # their re-decode (CRC/identity gating keeps stale entries inert).
        try:
            if gen is not None:
                gen.sync_journal()
        except Exception:  # noqa: BLE001 - fenced exit must not mask
            pass
        return EXIT_FENCED
    finally:
        if hb is not None:
            hb.stop()
        if gen is not None:
            _dump_metrics(spec, gen, metrics, exit_code, breaker=breaker,
                          hb=hb)
        if journal is not None:
            try:
                journal.close()  # flush + release the single-writer lock
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        if ho_consumer is not None:
            try:
                ho_consumer.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        if consumer is not None:
            try:
                consumer.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        if own_client:
            try:
                broker.close()
            except Exception:  # noqa: BLE001
                pass


def main(argv: list[str]) -> int:
    spec_path = argv[1]
    with open(spec_path, encoding="utf-8") as f:
        spec = json.load(f)
    # SIGUSR1 → all-thread stack dump on stderr (the worker log): the
    # supervisor-side diagnosis tool for a wedged-but-alive replica.
    import faulthandler
    import signal as _signal

    faulthandler.register(_signal.SIGUSR1)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from torchkafka_tpu.resilience.crashpoint import arm_from_env
    from torchkafka_tpu.utils.shutdown import ShutdownSignal

    arm_from_env()
    with ShutdownSignal() as stop:
        if spec.get("role") == "prefill":
            from torchkafka_tpu.fleet.prefill import run_prefill_worker

            return run_prefill_worker(spec, shutdown=stop)
        if spec.get("role") == "distill":
            from torchkafka_tpu.distill.worker import run_distill_worker

            return run_distill_worker(spec, shutdown=stop)
        return run_replica_worker(spec, shutdown=stop)


if __name__ == "__main__":
    sys.exit(main(sys.argv))

"""Serving fleet: partitioned multi-replica serving with QoS admission,
replica failover, and graceful drain (see fleet/fleet.py for the design).
"""

from torchkafka_tpu.fleet.fleet import ReplicaChaos, ServingFleet
from torchkafka_tpu.fleet.metrics import FleetMetrics
from torchkafka_tpu.fleet.qos import (
    BATCH,
    INTERACTIVE,
    AdmissionQueue,
    QoSConfig,
    TenantBuckets,
    TokenBucket,
    default_lane,
    default_tenant,
)
from torchkafka_tpu.fleet.replica import Replica

__all__ = [
    "AdmissionQueue",
    "BATCH",
    "FleetMetrics",
    "INTERACTIVE",
    "QoSConfig",
    "Replica",
    "ReplicaChaos",
    "ServingFleet",
    "TenantBuckets",
    "TokenBucket",
    "default_lane",
    "default_tenant",
]

"""Serving fleet: partitioned multi-replica serving with QoS admission,
replica failover, and graceful drain (see fleet/fleet.py for the design).
``ProcessFleet`` (fleet/supervisor.py) is the REAL-PROCESS deployment of
the same group: one OS process per replica over the socket broker, with
heartbeat leases, zombie fencing, and cross-process warm failover.
"""

from torchkafka_tpu.fleet.autoscale import (
    AutoscaleController,
    FleetAutoscaler,
    RolePolicy,
    RoleSignals,
    ScaleDecision,
    SupervisorAutoscaler,
)
from torchkafka_tpu.fleet.fleet import ReplicaChaos, ServingFleet
from torchkafka_tpu.fleet.metrics import FleetMetrics
from torchkafka_tpu.fleet.prefill import (
    PrefillPool,
    PrefillRouter,
    PrefillWorker,
    decode_handoff,
    encode_handoff,
)
from torchkafka_tpu.fleet.rollout import (
    BrokerRolloutDriver,
    InProcessRolloutDriver,
    RolloutController,
    RolloutWorker,
)
from torchkafka_tpu.fleet.supervisor import ProcessFleet, sweep_expired
from torchkafka_tpu.fleet.qos import (
    BATCH,
    INTERACTIVE,
    AdmissionQueue,
    QoSConfig,
    TenantBuckets,
    TokenBucket,
    default_lane,
    default_tenant,
)
from torchkafka_tpu.fleet.replica import Replica

__all__ = [
    "AdmissionQueue",
    "AutoscaleController",
    "BATCH",
    "BrokerRolloutDriver",
    "InProcessRolloutDriver",
    "RolloutController",
    "RolloutWorker",
    "FleetAutoscaler",
    "FleetMetrics",
    "INTERACTIVE",
    "PrefillPool",
    "PrefillRouter",
    "PrefillWorker",
    "ProcessFleet",
    "QoSConfig",
    "RolePolicy",
    "RoleSignals",
    "ScaleDecision",
    "SupervisorAutoscaler",
    "decode_handoff",
    "encode_handoff",
    "Replica",
    "ReplicaChaos",
    "ServingFleet",
    "sweep_expired",
    "TenantBuckets",
    "TokenBucket",
    "default_lane",
    "default_tenant",
]

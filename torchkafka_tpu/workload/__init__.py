"""Deterministic multi-tenant load generation (torchkafka_tpu/workload).

The "traffic survived" half of the observability story: a seeded,
injectable-clock workload generator — Zipf tenants with keyed partition
pinning, Poisson burst arrivals, heavy-tailed prompt/output lengths,
mixed QoS lanes, scheduled mid-run chaos — that drives the FULL serving
stack (fleet + QoS + paged/chunked KV cache + resilience + journal +
tracer) and replays byte-identically at the same seed. See
``generator.py`` for the draw-stream contract and ``obs/burn.py`` for
the burn-rate engine its traffic is measured against.
"""

from torchkafka_tpu.workload.generator import (
    ArrivalEvent,
    ChaosSchedule,
    WorkloadConfig,
    WorkloadGenerator,
    diurnal_load,
    header_max_new,
    hot_set_shift_at,
    rate_multiplier_at,
    step_load,
    zipf_weights,
)

__all__ = [
    "ArrivalEvent",
    "ChaosSchedule",
    "WorkloadConfig",
    "WorkloadGenerator",
    "diurnal_load",
    "header_max_new",
    "hot_set_shift_at",
    "rate_multiplier_at",
    "step_load",
    "zipf_weights",
]

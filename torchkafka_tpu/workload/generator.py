"""Deterministic production-traffic generation for the serving stack.

What "millions of users" looks like, distilled to the properties that
stress a serving system, each mapped to a seeded, replayable draw:

- **Zipf-distributed tenants** — a few tenants dominate, a long tail
  trickles (popularity exponent ``zipf_s``). The tenant name is the
  record KEY, so the broker's key-hash partitioner pins each tenant to a
  partition — Kafka's own multi-tenant idiom, and what makes per-tenant
  radix-cache locality real (a tenant's traffic lands on the replica
  that owns its partition).
- **Poisson burst arrivals** — bursts arrive as a Poisson process
  (exponential gaps at ``arrival_rate / burst_mean`` bursts/sec), each
  carrying ``1 + Poisson(burst_mean - 1)`` records at the same instant:
  open-loop offered load with the burstiness that defeats average-rate
  provisioning.
- **Heavy-tailed lengths** (lognormal or Pareto) — per record, the
  *uncached prompt suffix* length (the prefill work left after the
  tenant's shared context prefix radix-hits) and the *output budget*
  (enforced by ``StreamingGenerator(max_new_of=...)`` via the
  ``max_new`` record header). Means are configured; tails do the damage.
- **Mixed QoS lanes** — each record draws interactive vs batch
  (``interactive_fraction``), carried on the ``lane`` header the fleet's
  admission queue already classifies by.
- **Scheduled mid-run chaos** — replica kills at synthetic times (fired
  through ``ServingFleet.kill_replica``, i.e. the journal warm-failover
  path) and broker-outage windows (op-counted ``ChaosConsumer`` windows
  behind a ``ResilientConsumer``, the resilience layer's own machinery).

Everything is a pure function of ``WorkloadConfig.seed``: independent
``SeedSequence``-spawned streams per draw (tenants / arrivals / lengths /
lanes / payload) so tuning one knob never reshuffles another's schedule,
and ``schedule_digest()`` hashes the schedule bytes for byte-identity
assertions. Driven through ``drive()`` — which advances a ManualClock,
produces due arrivals, and fires due chaos once per fleet scheduling
round (``ServingFleet.serve(on_round=...)``) — a same-seed run replays
byte-identically: same arrival schedule, same tracer event stream, same
commit ledger. The repo's differential discipline, applied to traffic.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable, NamedTuple

import numpy as np

from torchkafka_tpu.source.records import Record

INTERACTIVE = "interactive"
BATCH = "batch"


def step_load(t_on: float, factor: float,
              t_off: float | None = None) -> tuple:
    """A step load schedule for ``WorkloadConfig.rate_schedule``: 1× base
    rate until ``t_on``, ``factor``× from then on (back to 1× at
    ``t_off`` when given) — the storm-arrives/storm-ends shape an
    autoscale controller must ride without flapping."""
    if t_on < 0 or factor <= 0:
        raise ValueError(f"need t_on >= 0, factor > 0, got {(t_on, factor)}")
    sched = [(0.0, 1.0), (float(t_on), float(factor))]
    if t_off is not None:
        if t_off <= t_on:
            raise ValueError(f"need t_off > t_on, got {(t_on, t_off)}")
        sched.append((float(t_off), 1.0))
    return tuple(sched)


def diurnal_load(period_s: float, peak: float, trough: float = 1.0,
                 phases: int = 8, cycles: int = 2) -> tuple:
    """A piecewise-constant diurnal curve: ``phases`` segments per
    period tracing trough → peak → trough (half-cosine), repeated for
    ``cycles`` periods — the day/night swell that rewards scale-down as
    much as scale-up."""
    if period_s <= 0 or peak < trough or trough <= 0:
        raise ValueError(
            f"need period_s > 0, peak >= trough > 0, got "
            f"{(period_s, peak, trough)}"
        )
    if phases < 2 or cycles < 1:
        raise ValueError(f"need phases >= 2, cycles >= 1, got "
                         f"{(phases, cycles)}")
    sched = []
    for c in range(cycles):
        for i in range(phases):
            frac = i / phases
            mult = trough + (peak - trough) * 0.5 * (
                1.0 - np.cos(2.0 * np.pi * frac)
            )
            sched.append((
                round((c + frac) * period_s, 9), round(float(mult), 6),
            ))
    return tuple(sched)


def rate_multiplier_at(schedule: tuple, t: float) -> float:
    """The offered-load multiplier at synthetic time ``t`` under a
    piecewise-constant ``rate_schedule`` (1.0 before the first entry or
    with no schedule at all)."""
    mult = 1.0
    for t0, m in schedule:
        if t0 > t:
            break
        mult = m
    return mult


def hot_set_shift_at(rotation: tuple, t: float) -> int:
    """The tenant-rank shift in force at synthetic time ``t`` under a
    piecewise-constant ``hot_set_rotation`` (0 before the first entry or
    with no rotation at all)."""
    shift = 0
    for t0, s in rotation:
        if t0 > t:
            break
        shift = int(s)
    return shift


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """Mid-run chaos, on the workload's synthetic timeline.

    ``replica_kills``: (t_s, replica_id) pairs — at synthetic time t the
    replica is killed through the fleet's journal warm-failover path
    (skipped, and recorded as skipped, if it would kill the last
    runnable replica). ``broker_outages``: op-counted (start_op, n_ops)
    windows applied to EVERY consumer built by ``consumer_factory`` —
    polls and commits inside the window raise retryably and the
    resilience layer rides it out."""

    replica_kills: tuple = ()
    broker_outages: tuple = ()

    def __post_init__(self) -> None:
        for t_s, rid in self.replica_kills:
            if t_s < 0 or rid < 0:
                raise ValueError(
                    f"replica_kills need t_s >= 0, rid >= 0, got {(t_s, rid)}"
                )
        for start, n in self.broker_outages:
            if start < 0 or n < 1:
                raise ValueError(
                    "broker_outages need start_op >= 0, n_ops >= 1, got "
                    f"{(start, n)}"
                )


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """Knobs for one synthetic traffic mix (see the module docstring for
    what each distribution models). ``arrival_rate`` is the OFFERED load
    in records/sec of synthetic time — overload sweeps multiply it and
    nothing else, so 1×/2×/4× slices share every other draw stream."""

    tenants: int = 8
    zipf_s: float = 1.1
    total_records: int = 128
    arrival_rate: float = 200.0
    burst_mean: float = 3.0
    interactive_fraction: float = 0.5
    length_dist: str = "lognormal"  # or "pareto"
    mean_suffix: float = 8.0    # mean uncached prompt-suffix tokens
    mean_output: float = 8.0    # mean output-budget tokens
    sigma: float = 0.8          # lognormal shape (log-space std)
    pareto_alpha: float = 1.5   # pareto shape (tail exponent)
    seed: int = 0
    chaos: ChaosSchedule = dataclasses.field(default_factory=ChaosSchedule)
    # Piecewise-constant offered-load multipliers ((t_s, factor), ...),
    # sorted by t_s: the effective rate at synthetic time t is
    # arrival_rate × the last factor whose t_s <= t. Build with
    # ``step_load`` / ``diurnal_load``. Only arrival INSTANTS change —
    # tenants, lanes, lengths, and payloads ride their own independent
    # draw streams, so scheduled and unscheduled runs stay comparable.
    rate_schedule: tuple = ()
    # Scheduled Zipf hot-set rotation ((t_s, shift), ...), strictly
    # increasing t_s: from synthetic time t_s on, popularity rank r maps
    # to tenant (r + shift) % tenants — the head of the Zipf curve
    # MOVES. The rank draw stream is consumed identically whatever the
    # rotation (the remap is pure arithmetic on the drawn rank), so a
    # rotated run shares every arrival instant, lane, and length with
    # its unrotated twin; only WHICH tenant (and hence which shared
    # context prefix — real prompt-content drift, the thing that decays
    # a distilled draft's α) changes at the configured instants.
    hot_set_rotation: tuple = ()

    def __post_init__(self) -> None:
        if self.tenants < 1:
            raise ValueError(f"tenants must be >= 1, got {self.tenants}")
        if self.zipf_s <= 0:
            raise ValueError(f"zipf_s must be > 0, got {self.zipf_s}")
        if self.total_records < 1:
            raise ValueError(
                f"total_records must be >= 1, got {self.total_records}"
            )
        if self.arrival_rate <= 0:
            raise ValueError(
                f"arrival_rate must be > 0 rec/s, got {self.arrival_rate}"
            )
        if self.burst_mean < 1:
            raise ValueError(f"burst_mean must be >= 1, got {self.burst_mean}")
        if not 0 <= self.interactive_fraction <= 1:
            raise ValueError(
                "interactive_fraction must sit in [0, 1], got "
                f"{self.interactive_fraction}"
            )
        if self.length_dist not in ("lognormal", "pareto"):
            raise ValueError(
                "length_dist must be 'lognormal' or 'pareto', got "
                f"{self.length_dist!r}"
            )
        if self.mean_suffix < 1 or self.mean_output < 1:
            raise ValueError("mean_suffix / mean_output must be >= 1")
        if self.length_dist == "pareto" and self.pareto_alpha <= 1:
            raise ValueError(
                f"pareto_alpha must be > 1 (finite mean), got "
                f"{self.pareto_alpha}"
            )
        last_t = -1.0
        for entry in self.rate_schedule:
            if len(entry) != 2:
                raise ValueError(
                    f"rate_schedule entries are (t_s, factor), got {entry!r}"
                )
            t_s, factor = entry
            if t_s < 0 or t_s <= last_t or factor <= 0:
                raise ValueError(
                    "rate_schedule needs strictly increasing t_s >= 0 and "
                    f"factors > 0, got {self.rate_schedule!r}"
                )
            last_t = t_s
        last_t = -1.0
        for entry in self.hot_set_rotation:
            if len(entry) != 2:
                raise ValueError(
                    "hot_set_rotation entries are (t_s, shift), got "
                    f"{entry!r}"
                )
            t_s, shift = entry
            if t_s < 0 or t_s <= last_t or int(shift) != shift:
                raise ValueError(
                    "hot_set_rotation needs strictly increasing t_s >= 0 "
                    f"and integer shifts, got {self.hot_set_rotation!r}"
                )
            last_t = t_s


class ArrivalEvent(NamedTuple):
    """One scheduled record: arrival instant (synthetic seconds), draw
    sequence number, tenant/lane, the heavy-tailed lengths, and the full
    prompt (tenant context prefix + fresh suffix, ``prompt_len`` total)."""

    t_s: float
    seq: int
    tenant: str
    lane: str
    suffix_len: int
    out_len: int
    prompt: np.ndarray

    @property
    def key(self) -> bytes:
        return self.tenant.encode("utf-8")

    @property
    def headers(self) -> tuple:
        return (
            ("lane", self.lane.encode("utf-8")),
            ("max_new", str(self.out_len).encode("utf-8")),
        )


def header_max_new(record: Record) -> int | None:
    """The generator's per-record output budget, read back from the
    ``max_new`` header — pass as ``StreamingGenerator(max_new_of=...)``
    (via ``gen_kwargs`` on a fleet) to enforce heavy-tailed output
    lengths."""
    for k, v in record.headers:
        if k == "max_new":
            try:
                return int(v)
            except ValueError:
                return None
    return None


def zipf_weights(n: int, s: float) -> np.ndarray:
    """Normalized Zipf popularity over ranks 1..n: p(rank) ∝ rank^-s."""
    w = np.arange(1, n + 1, dtype=np.float64) ** -float(s)
    return w / w.sum()


class WorkloadGenerator:
    """Synthesizes and drives one ``WorkloadConfig`` against a serving
    fleet. Construction binds the serving frame (``prompt_len`` /
    ``max_new`` / ``vocab_size``) the draws are clamped to; everything
    else is derived from the config's seed."""

    def __init__(
        self, config: WorkloadConfig, *, prompt_len: int, max_new: int,
        vocab_size: int,
    ) -> None:
        if prompt_len < 2:
            raise ValueError(f"prompt_len must be >= 2, got {prompt_len}")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        self.config = config
        self.prompt_len = prompt_len
        self.max_new = max_new
        self.vocab_size = vocab_size
        # One independent stream per draw: new knobs / different rates
        # never reshuffle another stream's schedule (the resilience
        # layer's per-fault-type spawn-key discipline).
        ss = np.random.SeedSequence(config.seed).spawn(5)
        self._rng_tenant = np.random.default_rng(ss[0])
        self._rng_arrival = np.random.default_rng(ss[1])
        self._rng_length = np.random.default_rng(ss[2])
        self._rng_lane = np.random.default_rng(ss[3])
        self._rng_payload = np.random.default_rng(ss[4])
        self.tenant_names = tuple(
            f"tenant-{i:02d}" for i in range(config.tenants)
        )
        self._weights = zipf_weights(config.tenants, config.zipf_s)
        # Per-tenant shared context stream: records reuse its prefix to
        # depth (prompt_len - suffix_len), so radix-cache hits follow
        # tenant locality. Drawn from the payload stream FIRST so record
        # suffix draws line up identically across configs.
        self._context = {
            t: self._rng_payload.integers(
                0, vocab_size, prompt_len, dtype=np.int32
            )
            for t in self.tenant_names
        }
        self._schedule: list[ArrivalEvent] | None = None

    # ---------------------------------------------------------- synthesis

    def _draw_len(self, mean: float, hi: int) -> int:
        cfg = self.config
        if cfg.length_dist == "lognormal":
            mu = np.log(mean) - cfg.sigma**2 / 2.0  # E[X] = mean
            x = self._rng_length.lognormal(mu, cfg.sigma)
        else:
            a = cfg.pareto_alpha
            xm = mean * (a - 1.0) / a  # Pareto(xm, a) mean = xm*a/(a-1)
            x = xm * (1.0 + self._rng_length.pareto(a))
        return int(np.clip(round(x), 1, hi))

    def schedule(self) -> list[ArrivalEvent]:
        """The full arrival schedule, synthesized once and cached — a
        pure function of (config, prompt_len, max_new, vocab_size)."""
        if self._schedule is not None:
            return self._schedule
        cfg = self.config
        events: list[ArrivalEvent] = []
        burst_rate = cfg.arrival_rate / cfg.burst_mean
        t = 0.0
        while len(events) < cfg.total_records:
            # Inhomogeneous arrivals by gap scaling: the unit draw stream
            # is consumed identically whatever the schedule, so a step or
            # diurnal curve changes arrival INSTANTS only (the same
            # stream-independence contract as scaling arrival_rate).
            gap = float(self._rng_arrival.exponential(1.0 / burst_rate))
            t += gap / rate_multiplier_at(cfg.rate_schedule, t)
            size = 1 + int(self._rng_arrival.poisson(cfg.burst_mean - 1.0))
            for _ in range(min(size, cfg.total_records - len(events))):
                seq = len(events)
                # The Zipf draw picks a popularity RANK; the rotation in
                # force at this instant maps rank → tenant. Pure
                # arithmetic after the draw: zero extra RNG consumption,
                # so rotated and unrotated runs share every stream.
                rank = int(
                    self._rng_tenant.choice(cfg.tenants, p=self._weights)
                )
                shift = hot_set_shift_at(cfg.hot_set_rotation, t)
                tenant = self.tenant_names[(rank + shift) % cfg.tenants]
                lane = (
                    INTERACTIVE
                    if self._rng_lane.random() < cfg.interactive_fraction
                    else BATCH
                )
                suffix = self._draw_len(
                    cfg.mean_suffix, self.prompt_len - 1
                )
                out_len = self._draw_len(cfg.mean_output, self.max_new)
                prompt = np.concatenate([
                    self._context[tenant][: self.prompt_len - suffix],
                    self._rng_payload.integers(
                        0, self.vocab_size, suffix, dtype=np.int32
                    ),
                ])
                events.append(ArrivalEvent(
                    round(t, 9), seq, tenant, lane, suffix, out_len, prompt,
                ))
        self._schedule = events
        return events

    def schedule_digest(self) -> str:
        """SHA-256 over the schedule's canonical bytes — the byte-
        identity handle for same-seed replay assertions."""
        h = hashlib.sha256()
        for ev in self.schedule():
            h.update(repr((
                ev.t_s, ev.seq, ev.tenant, ev.lane, ev.suffix_len,
                ev.out_len,
            )).encode())
            h.update(ev.prompt.tobytes())
        return h.hexdigest()

    def tenant_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {t: 0 for t in self.tenant_names}
        for ev in self.schedule():
            counts[ev.tenant] += 1
        return counts

    # ------------------------------------------------------------ serving

    def consumer_factory(
        self, broker, topic: str, group_id: str, *, resilient=None,
        clock=None,
    ) -> Callable[[int], object]:
        """A ``ServingFleet`` consumer factory over ``broker`` with the
        chaos schedule's broker-outage windows applied: MemoryConsumer →
        ChaosConsumer(outage windows) → ResilientConsumer (skip the last
        wrap with ``resilient=False``; without outage windows the chaos
        wrap is elided entirely). Deterministic per replica id.

        ``clock``: the drive's ManualClock — when given, the retry
        policy and circuit breaker run on the SAME synthetic timeline as
        everything else (backoff sleeps advance it, breaker cooldowns
        count rounds), which is what makes outage recovery — and with
        it the whole trace — byte-identical across same-seed replays.
        Leaving the resilience stack on the real clock makes breaker
        half-open probe timing wall-clock-dependent."""
        from torchkafka_tpu.source.chaos import ChaosConsumer
        from torchkafka_tpu.source.memory import MemoryConsumer

        outages = tuple(self.config.chaos.broker_outages)
        if resilient is None:
            resilient = bool(outages)

        def factory(rid: int):
            # Explicit zero-padded member ids: the broker range-assigns
            # over SORTED member ids, and the auto-generated fallback
            # ("member-<global counter>") sorts by digit count — two
            # same-seed runs in one process would draw different counter
            # values and could land different partition splits. A pure
            # function of (group, rid) keeps placement replayable, scale
            # events included.
            consumer = MemoryConsumer(
                broker, topic, group_id=group_id,
                member_id=f"{group_id}-r{rid:03d}",
            )
            if outages:
                consumer = ChaosConsumer(
                    consumer, seed=self.config.seed * 1009 + rid,
                    outages=list(outages),
                )
            if resilient:
                from torchkafka_tpu.resilience import (
                    CircuitBreaker, ResilientConsumer, RetryPolicy,
                )

                kw = {}
                bkw = {}
                if clock is not None:
                    kw = {"clock": clock.now, "sleep": clock.sleep}
                    bkw = {"clock": clock.now}
                consumer = ResilientConsumer(
                    consumer,
                    policy=RetryPolicy(
                        max_attempts=2, base_delay_s=0.001,
                        max_delay_s=0.002, deadline_s=5.0,
                        seed=self.config.seed * 1013 + rid, **kw,
                    ),
                    breaker=CircuitBreaker(
                        failure_threshold=2, reset_timeout_s=0.02, **bkw,
                    ),
                )
            return consumer

        return factory

    def produce_due(self, broker, topic: str, now_s: float,
                    cursor: int) -> int:
        """Produce every scheduled arrival with ``t_s <= now_s`` starting
        at ``cursor``; returns the new cursor. Records carry the tenant
        key (partition pinning), lane + max_new headers, and the
        synthetic arrival time as their timestamp."""
        sched = self.schedule()
        while cursor < len(sched) and sched[cursor].t_s <= now_s:
            ev = sched[cursor]
            broker.produce(
                topic, ev.prompt.tobytes(), key=ev.key,
                headers=ev.headers,
                timestamp_ms=int(round(ev.t_s * 1e3)),
            )
            cursor += 1
        return cursor

    def drive(
        self,
        fleet,
        broker,
        topic: str,
        *,
        clock,
        tick_dt: float = 0.002,
        idle_timeout_ms: int = 4000,
        settle_s: float = 10.0,
        on_round: "Callable | None" = None,
        settle_rounds: int | None = None,
    ) -> dict:
        """Run the schedule through ``fleet`` on the synthetic timeline.

        Once per fleet scheduling round (``serve(on_round=...)``): the
        ManualClock advances ``tick_dt`` (the synthetic cost of one
        round — what turns offered-load excess into real queueing in the
        SLO numbers), due arrivals are produced, and due replica kills
        fire through the journal warm-failover path. After serving,
        survivable commit failures left by outage windows are retried
        until the ledger settles (bounded by ``settle_s`` wall seconds).

        ``on_round(fleet, served)``: an extra per-round callback run
        AFTER the driver's own work (arrivals produced, chaos fired) —
        the autoscale controller's injection point: it samples the round
        the load change already hit and actuates before the next round
        serves. Anything it does rides the same synthetic timeline, so
        the whole control loop replays with the rest.

        ``settle_rounds``: DETERMINISTIC termination for control-loop
        replays. Without it, serve() ends on a wall-clock idle timeout —
        the number of trailing idle rounds (each advancing the synthetic
        clock) varies run to run, which is invisible when nothing
        happens in them but breaks byte-identity the moment a controller
        acts there. With it, once the schedule has fully arrived and the
        fleet has quiesced (nothing active, queued, or commit-pending),
        exactly ``settle_rounds`` more rounds run — room for the
        controller's post-storm scale-downs to fire on the synthetic
        clock — and then the fleet DRAINS (warm: finish, commit, leave),
        so the run ends at the same round on every replay.

        Returns completions (fleet order, duplicates included), the
        kills that fired/skipped, rounds driven, and whether the
        schedule fully arrived and served."""
        import time as _time

        sched = self.schedule()
        cursor = 0
        rounds = 0
        settled = 0
        kills = sorted(self.config.chaos.replica_kills)
        fired: list[tuple[float, int]] = []
        skipped: list[tuple[float, int]] = []

        class _Stop:
            requested = False

        stop = _Stop()

        def _on_round(f, _served):
            nonlocal cursor, rounds, settled
            rounds += 1
            clock.advance(tick_dt)
            now = clock.now()
            cursor = self.produce_due(broker, topic, now, cursor)
            while kills and kills[0][0] <= now:
                t_s, rid = kills.pop(0)
                runnable = [r for r in f.replicas if r.runnable]
                victim = f.replicas[rid] if rid < len(f.replicas) else None
                if (
                    victim is not None and victim.runnable
                    and len(runnable) > 1
                ):
                    f.kill_replica(rid)
                    fired.append((t_s, rid))
                else:
                    skipped.append((t_s, rid))
            if cursor == len(sched):
                # Schedule exhausted and the fleet idle: flush the
                # cadence stragglers NOW, at the synthetic instant the
                # work actually finished — otherwise their commit (and
                # the trace's e2e) is stamped thousands of empty rounds
                # later, when the real-time idle timeout finally trips.
                live = [r for r in f.replicas if r.runnable]
                if live and not any(
                    r.gen.has_active() or r.queue.depth() for r in live
                ):
                    for r in live:
                        r.maybe_flush(force=True)
            if on_round is not None:
                on_round(f, _served)
            if settle_rounds is not None and not stop.requested:
                live = [r for r in f.replicas if r.runnable]
                quiesced = cursor == len(sched) and live and not any(
                    r.gen.has_active() or r.queue.depth()
                    or r.gen.pending_commit
                    for r in live
                )
                settled = settled + 1 if quiesced else 0
                if settled >= settle_rounds:
                    stop.requested = True

        completions = fleet.serve_all(
            idle_timeout_ms=idle_timeout_ms, on_round=_on_round,
            shutdown=stop,
        )
        # Outage-window commit failures are survivable: completions stay
        # commit-pending; retry against the healed broker.
        deadline = _time.monotonic() + settle_s
        while any(rep.gen.pending_commit for rep in fleet.replicas):
            for rep in fleet.replicas:
                if rep.runnable and rep.gen.pending_commit:
                    rep.gen.flush_commits()
            if _time.monotonic() > deadline:
                break
            _time.sleep(0.002)
        served_keys = [
            (rec.partition, rec.offset) for _rid, rec, _t in completions
        ]
        return {
            "completions": completions,
            "served_keys": served_keys,
            "unique_served": len(set(served_keys)),
            "duplicates": len(served_keys) - len(set(served_keys)),
            "arrived": cursor,
            "all_arrived": cursor == len(sched),
            "kills_fired": fired,
            "kills_skipped": skipped,
            "rounds": rounds,
            "end_time_s": clock.now(),
        }

// _tk_native: C++ hot-path record decoding for torchkafka_tpu.
//
// Net-new capability (the reference is pure Python with no native code —
// SURVEY.md §2 "zero C++/Rust/CUDA components"); this is the host-side
// throughput lever the TPU design calls for: the ingest pipeline's per-chunk
// decode work (byte gathering, JSON field scan + tokenize) done as one C
// call per poll chunk, writing straight into the batcher's NumPy buffers
// with no intermediate joins or per-record Python objects.
//
// Interface contract (kept tiny on purpose):
//   gather_rows(values: list[bytes], out: writable buffer [n, width_bytes],
//               pad: int) -> None
//       Row i = values[i] truncated/zero-padded to width_bytes.
//   json_tokens(values: list[bytes], field: bytes, out: writable int32
//               buffer [n, seq_len], keep: writable uint8 buffer [n],
//               pad_id: int) -> None
//       Minimal flat-JSON scan for "field": "...", tokenised as utf-8 byte
//       values (the same stand-in tokenizer as transform.json_field's
//       default); keep[i]=0 marks a drop (missing/invalid field).
//
// Python-side fallbacks with identical semantics live in
// torchkafka_tpu/native/__init__.py; differential tests enforce equality.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>

namespace {

// ---------------------------------------------------------------- gather

// Row i = values[i], truncated to whole items of `itemsize` bytes, padded
// to the row width with the `pad_pattern` (one item's byte image) — item-
// level semantics, so e.g. an int32 pad of -1 is a true -1, and a partial
// trailing item in the input is replaced by pad, never half-copied.
PyObject* gather_rows(PyObject*, PyObject* args) {
  PyObject* values;
  Py_buffer out;
  Py_buffer pad;
  if (!PyArg_ParseTuple(args, "O!w*y*", &PyList_Type, &values, &out, &pad)) {
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(values);
  Py_ssize_t itemsize = pad.len;
  auto release = [&]() {
    PyBuffer_Release(&out);
    PyBuffer_Release(&pad);
  };
  if (n == 0) {
    release();
    Py_RETURN_NONE;
  }
  if (itemsize <= 0 || out.len % n != 0 || (out.len / n) % itemsize != 0) {
    release();
    PyErr_SetString(PyExc_ValueError, "out buffer / pad pattern shape mismatch");
    return nullptr;
  }
  Py_ssize_t width = out.len / n;
  auto* dst = static_cast<uint8_t*>(out.buf);
  const auto* pad_bytes = static_cast<const uint8_t*>(pad.buf);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GET_ITEM(values, i);
    char* src;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(item, &src, &len) != 0) {
      release();
      return nullptr;
    }
    Py_ssize_t take = len < width ? len : width;
    take -= take % itemsize;  // whole items only
    std::memcpy(dst, src, static_cast<size_t>(take));
    for (Py_ssize_t off = take; off < width; off += itemsize) {
      std::memcpy(dst + off, pad_bytes, static_cast<size_t>(itemsize));
    }
    dst += width;
  }
  release();
  Py_RETURN_NONE;
}

// ------------------------------------------------------------ json scan

// Find `"field"` (quoted) followed by optional spaces, ':', optional
// spaces, '"', and return [start, end) of the raw string body (first
// unescaped '"'). Returns false when absent or not a string value.
bool find_string_field(const char* buf, Py_ssize_t len, const char* field,
                       Py_ssize_t field_len, const char** out_start,
                       Py_ssize_t* out_len) {
  for (Py_ssize_t i = 0; i + field_len + 2 <= len; ++i) {
    if (buf[i] != '"') continue;
    if (std::memcmp(buf + i + 1, field, static_cast<size_t>(field_len)) != 0)
      continue;
    Py_ssize_t j = i + 1 + field_len;
    if (j >= len || buf[j] != '"') continue;
    ++j;
    while (j < len && (buf[j] == ' ' || buf[j] == '\t' || buf[j] == '\n')) ++j;
    if (j >= len || buf[j] != ':') continue;
    ++j;
    while (j < len && (buf[j] == ' ' || buf[j] == '\t' || buf[j] == '\n')) ++j;
    if (j >= len || buf[j] != '"') return false;  // field exists, not a string
    Py_ssize_t start = ++j;
    while (j < len) {
      if (buf[j] == '\\') {
        j += 2;
        continue;
      }
      if (buf[j] == '"') {
        *out_start = buf + start;
        *out_len = j - start;
        return true;
      }
      ++j;
    }
    return false;  // unterminated
  }
  return false;
}

PyObject* json_tokens(PyObject*, PyObject* args) {
  PyObject* values;
  Py_buffer field;
  Py_buffer out;
  Py_buffer keep;
  int pad_id;
  if (!PyArg_ParseTuple(args, "O!y*w*w*i", &PyList_Type, &values, &field, &out,
                        &keep, &pad_id)) {
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(values);
  auto release = [&]() {
    PyBuffer_Release(&field);
    PyBuffer_Release(&out);
    PyBuffer_Release(&keep);
  };
  if (n == 0) {
    release();
    Py_RETURN_NONE;
  }
  if (static_cast<Py_ssize_t>(keep.len) != n ||
      out.len % (n * static_cast<Py_ssize_t>(sizeof(int32_t))) != 0) {
    release();
    PyErr_SetString(PyExc_ValueError, "out/keep buffer shape mismatch");
    return nullptr;
  }
  Py_ssize_t seq_len = out.len / n / static_cast<Py_ssize_t>(sizeof(int32_t));
  auto* tokens = static_cast<int32_t*>(out.buf);
  auto* keep_flags = static_cast<uint8_t*>(keep.buf);
  const char* fname = static_cast<const char*>(field.buf);
  Py_ssize_t flen = field.len;

  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GET_ITEM(values, i);
    char* src;
    Py_ssize_t len;
    int32_t* row = tokens + i * seq_len;
    if (PyBytes_AsStringAndSize(item, &src, &len) != 0) {
      release();
      return nullptr;
    }
    const char* text;
    Py_ssize_t text_len;
    if (!find_string_field(src, len, fname, flen, &text, &text_len)) {
      keep_flags[i] = 0;
      for (Py_ssize_t t = 0; t < seq_len; ++t) row[t] = pad_id;
      continue;
    }
    keep_flags[i] = 1;
    Py_ssize_t take = text_len < seq_len ? text_len : seq_len;
    for (Py_ssize_t t = 0; t < take; ++t) {
      row[t] = static_cast<int32_t>(static_cast<uint8_t>(text[t]));
    }
    for (Py_ssize_t t = take; t < seq_len; ++t) row[t] = pad_id;
  }
  release();
  Py_RETURN_NONE;
}

PyMethodDef methods[] = {
    {"gather_rows", gather_rows, METH_VARARGS,
     "gather_rows(values, out_buffer, pad): pack bytes rows fixed-width"},
    {"json_tokens", json_tokens, METH_VARARGS,
     "json_tokens(values, field, out_i32, keep_u8, pad_id): scan+tokenize"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_tk_native",
    "C++ hot-path decoders for torchkafka_tpu", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__tk_native() { return PyModule_Create(&module); }

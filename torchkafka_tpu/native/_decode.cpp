// _tk_native: C++ hot-path record decoding for torchkafka_tpu.
//
// Net-new capability (the reference is pure Python with no native code —
// SURVEY.md §2 "zero C++/Rust/CUDA components"); this is the host-side
// throughput lever the TPU design calls for: the ingest pipeline's per-chunk
// decode work (byte gathering, JSON field scan + tokenize) done as one C
// call per poll chunk, writing straight into the batcher's NumPy buffers
// with no intermediate joins or per-record Python objects.
//
// Interface contract (kept tiny on purpose):
//   gather_rows(values: list[bytes], out: writable buffer [n, width_bytes],
//               pad: int) -> None
//       Row i = values[i] truncated/zero-padded to width_bytes.
//   json_tokens(values: list[bytes], field: bytes, out: writable int32
//               buffer [n, seq_len], keep: writable uint8 buffer [n],
//               pad_id: int) -> None
//       Minimal flat-JSON scan for "field": "...", tokenised as utf-8 byte
//       values (the same stand-in tokenizer as transform.json_field's
//       default); keep[i]=0 marks a drop (missing/invalid field).
//
// Python-side fallbacks with identical semantics live in
// torchkafka_tpu/native/__init__.py; differential tests enforce equality.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <zlib.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

// ---------------------------------------------------------------- gather

// Row i = values[i], truncated to whole items of `itemsize` bytes, padded
// to the row width with the `pad_pattern` (one item's byte image) — item-
// level semantics, so e.g. an int32 pad of -1 is a true -1, and a partial
// trailing item in the input is replaced by pad, never half-copied.
PyObject* gather_rows(PyObject*, PyObject* args) {
  PyObject* values;
  Py_buffer out;
  Py_buffer pad;
  if (!PyArg_ParseTuple(args, "O!w*y*", &PyList_Type, &values, &out, &pad)) {
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(values);
  Py_ssize_t itemsize = pad.len;
  auto release = [&]() {
    PyBuffer_Release(&out);
    PyBuffer_Release(&pad);
  };
  if (n == 0) {
    release();
    Py_RETURN_NONE;
  }
  if (itemsize <= 0 || out.len % n != 0 || (out.len / n) % itemsize != 0) {
    release();
    PyErr_SetString(PyExc_ValueError, "out buffer / pad pattern shape mismatch");
    return nullptr;
  }
  Py_ssize_t width = out.len / n;
  auto* dst = static_cast<uint8_t*>(out.buf);
  const auto* pad_bytes = static_cast<const uint8_t*>(pad.buf);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GET_ITEM(values, i);
    char* src;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(item, &src, &len) != 0) {
      release();
      return nullptr;
    }
    Py_ssize_t take = len < width ? len : width;
    take -= take % itemsize;  // whole items only
    std::memcpy(dst, src, static_cast<size_t>(take));
    for (Py_ssize_t off = take; off < width; off += itemsize) {
      std::memcpy(dst + off, pad_bytes, static_cast<size_t>(itemsize));
    }
    dst += width;
  }
  release();
  Py_RETURN_NONE;
}

// ------------------------------------------------------------ json scan

// Find `"field"` (quoted) followed by optional spaces, ':', optional
// spaces, '"', and return [start, end) of the raw string body (first
// unescaped '"'). Returns false when absent or not a string value.
bool find_string_field(const char* buf, Py_ssize_t len, const char* field,
                       Py_ssize_t field_len, const char** out_start,
                       Py_ssize_t* out_len) {
  for (Py_ssize_t i = 0; i + field_len + 2 <= len; ++i) {
    if (buf[i] != '"') continue;
    if (std::memcmp(buf + i + 1, field, static_cast<size_t>(field_len)) != 0)
      continue;
    Py_ssize_t j = i + 1 + field_len;
    if (j >= len || buf[j] != '"') continue;
    ++j;
    while (j < len && (buf[j] == ' ' || buf[j] == '\t' || buf[j] == '\n')) ++j;
    if (j >= len || buf[j] != ':') continue;
    ++j;
    while (j < len && (buf[j] == ' ' || buf[j] == '\t' || buf[j] == '\n')) ++j;
    if (j >= len || buf[j] != '"') return false;  // field exists, not a string
    Py_ssize_t start = ++j;
    while (j < len) {
      if (buf[j] == '\\') {
        j += 2;
        continue;
      }
      if (buf[j] == '"') {
        *out_start = buf + start;
        *out_len = j - start;
        return true;
      }
      ++j;
    }
    return false;  // unterminated
  }
  return false;
}

PyObject* json_tokens(PyObject*, PyObject* args) {
  PyObject* values;
  Py_buffer field;
  Py_buffer out;
  Py_buffer keep;
  int pad_id;
  if (!PyArg_ParseTuple(args, "O!y*w*w*i", &PyList_Type, &values, &field, &out,
                        &keep, &pad_id)) {
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(values);
  auto release = [&]() {
    PyBuffer_Release(&field);
    PyBuffer_Release(&out);
    PyBuffer_Release(&keep);
  };
  if (n == 0) {
    release();
    Py_RETURN_NONE;
  }
  if (static_cast<Py_ssize_t>(keep.len) != n ||
      out.len % (n * static_cast<Py_ssize_t>(sizeof(int32_t))) != 0) {
    release();
    PyErr_SetString(PyExc_ValueError, "out/keep buffer shape mismatch");
    return nullptr;
  }
  Py_ssize_t seq_len = out.len / n / static_cast<Py_ssize_t>(sizeof(int32_t));
  auto* tokens = static_cast<int32_t*>(out.buf);
  auto* keep_flags = static_cast<uint8_t*>(keep.buf);
  const char* fname = static_cast<const char*>(field.buf);
  Py_ssize_t flen = field.len;

  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GET_ITEM(values, i);
    char* src;
    Py_ssize_t len;
    int32_t* row = tokens + i * seq_len;
    if (PyBytes_AsStringAndSize(item, &src, &len) != 0) {
      release();
      return nullptr;
    }
    const char* text;
    Py_ssize_t text_len;
    if (!find_string_field(src, len, fname, flen, &text, &text_len)) {
      keep_flags[i] = 0;
      for (Py_ssize_t t = 0; t < seq_len; ++t) row[t] = pad_id;
      continue;
    }
    keep_flags[i] = 1;
    Py_ssize_t take = text_len < seq_len ? text_len : seq_len;
    for (Py_ssize_t t = 0; t < take; ++t) {
      row[t] = static_cast<int32_t>(static_cast<uint8_t>(text[t]));
    }
    for (Py_ssize_t t = take; t < seq_len; ++t) row[t] = pad_id;
  }
  release();
  Py_RETURN_NONE;
}

// ------------------------------------------------------------- png decode
//
// Minimal-but-real PNG decoder for the image-ingest hot path: 8-bit RGB
// (color type 2), non-interlaced — the shape an image topic's producer
// controls. Full chunk walk, zlib inflate of the concatenated IDAT stream,
// and all five scanline filters reversed (None/Sub/Up/Average/Paeth).
// Chunk CRCs are NOT verified (Kafka already checksums the record payload;
// a corrupt stream fails structurally or in inflate and drops the record
// via keep=0).

inline uint8_t paeth(int a, int b, int c) {
  int p = a + b - c;
  int pa = std::abs(p - a), pb = std::abs(p - b), pc = std::abs(p - c);
  if (pa <= pb && pa <= pc) return static_cast<uint8_t>(a);
  if (pb <= pc) return static_cast<uint8_t>(b);
  return static_cast<uint8_t>(c);
}

inline uint32_t be32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) | (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

// Decode one PNG into dst[h*w*3]. Scratch vectors are reused across
// records by the caller (no per-record allocations in the chunk loop).
bool decode_one_png(const uint8_t* buf, Py_ssize_t len, Py_ssize_t h,
                    Py_ssize_t w, uint8_t* dst, std::vector<uint8_t>& idat,
                    std::vector<uint8_t>& raw) {
  static const uint8_t kSig[8] = {137, 'P', 'N', 'G', 13, 10, 26, 10};
  if (len < 8 + 25 || std::memcmp(buf, kSig, 8) != 0) return false;
  idat.clear();
  bool saw_ihdr = false;
  Py_ssize_t pos = 8;
  while (pos + 8 <= len) {
    uint32_t clen = be32(buf + pos);
    const uint8_t* ctype = buf + pos + 4;
    const uint8_t* cdata = buf + pos + 8;
    if (pos + 8 + static_cast<Py_ssize_t>(clen) + 4 > len) return false;
    if (std::memcmp(ctype, "IHDR", 4) == 0) {
      if (clen != 13) return false;
      uint32_t pw = be32(cdata), ph = be32(cdata + 4);
      // bitdepth 8, colortype 2 (RGB), compression 0, filter 0, interlace 0
      if (pw != static_cast<uint32_t>(w) || ph != static_cast<uint32_t>(h) ||
          cdata[8] != 8 || cdata[9] != 2 || cdata[10] != 0 ||
          cdata[11] != 0 || cdata[12] != 0) {
        return false;
      }
      saw_ihdr = true;
    } else if (std::memcmp(ctype, "IDAT", 4) == 0) {
      idat.insert(idat.end(), cdata, cdata + clen);
    } else if (std::memcmp(ctype, "IEND", 4) == 0) {
      break;
    }
    pos += 8 + static_cast<Py_ssize_t>(clen) + 4;  // + CRC (unverified)
  }
  if (!saw_ihdr || idat.empty()) return false;

  const size_t stride = static_cast<size_t>(w) * 3;
  const size_t raw_len = static_cast<size_t>(h) * (1 + stride);
  raw.resize(raw_len);
  uLongf out_len = static_cast<uLongf>(raw_len);
  if (uncompress(raw.data(), &out_len, idat.data(),
                 static_cast<uLong>(idat.size())) != Z_OK ||
      out_len != raw_len) {
    return false;
  }

  const uint8_t* prior = nullptr;  // previous DEFILTERED row
  for (Py_ssize_t y = 0; y < h; ++y) {
    const uint8_t* src = raw.data() + static_cast<size_t>(y) * (1 + stride);
    uint8_t filter = src[0];
    const uint8_t* cur = src + 1;
    uint8_t* out = dst + static_cast<size_t>(y) * stride;
    switch (filter) {
      case 0:
        std::memcpy(out, cur, stride);
        break;
      case 1:  // Sub: + left
        for (size_t i = 0; i < 3 && i < stride; ++i) out[i] = cur[i];
        for (size_t i = 3; i < stride; ++i)
          out[i] = static_cast<uint8_t>(cur[i] + out[i - 3]);
        break;
      case 2:  // Up: + above
        if (prior == nullptr) {
          std::memcpy(out, cur, stride);
        } else {
          for (size_t i = 0; i < stride; ++i)
            out[i] = static_cast<uint8_t>(cur[i] + prior[i]);
        }
        break;
      case 3:  // Average: + floor((left + above) / 2)
        for (size_t i = 0; i < stride; ++i) {
          int left = i >= 3 ? out[i - 3] : 0;
          int up = prior ? prior[i] : 0;
          out[i] = static_cast<uint8_t>(cur[i] + ((left + up) >> 1));
        }
        break;
      case 4:  // Paeth predictor
        for (size_t i = 0; i < stride; ++i) {
          int left = i >= 3 ? out[i - 3] : 0;
          int up = prior ? prior[i] : 0;
          int ul = (prior && i >= 3) ? prior[i - 3] : 0;
          out[i] = static_cast<uint8_t>(cur[i] + paeth(left, up, ul));
        }
        break;
      default:
        return false;
    }
    prior = out;
  }
  return true;
}

PyObject* decode_png_rgb(PyObject*, PyObject* args) {
  PyObject* values;
  Py_buffer out;
  Py_buffer keep;
  Py_ssize_t h, w;
  if (!PyArg_ParseTuple(args, "O!w*w*nn", &PyList_Type, &values, &out, &keep,
                        &h, &w)) {
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(values);
  auto release = [&]() {
    PyBuffer_Release(&out);
    PyBuffer_Release(&keep);
  };
  if (n == 0) {
    release();
    Py_RETURN_NONE;
  }
  if (static_cast<Py_ssize_t>(keep.len) != n || h <= 0 || w <= 0 ||
      out.len != n * h * w * 3) {
    release();
    PyErr_SetString(PyExc_ValueError, "out/keep buffer shape mismatch");
    return nullptr;
  }
  auto* dst = static_cast<uint8_t*>(out.buf);
  auto* keep_flags = static_cast<uint8_t*>(keep.buf);
  const size_t img = static_cast<size_t>(h) * static_cast<size_t>(w) * 3;
  // Snapshot (ptr, len) under the GIL, then release it for the decode
  // loop — inflate+defilter is milliseconds of pure C work per chunk, and
  // holding the GIL through it would serialize transform threads and stall
  // the poll loop. The values list keeps the bytes objects alive.
  std::vector<std::pair<const uint8_t*, Py_ssize_t>> srcs(
      static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GET_ITEM(values, i);
    char* src;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(item, &src, &len) != 0) {
      release();
      return nullptr;
    }
    srcs[static_cast<size_t>(i)] = {reinterpret_cast<const uint8_t*>(src), len};
  }
  Py_BEGIN_ALLOW_THREADS;
  std::vector<uint8_t> idat, raw;
  for (Py_ssize_t i = 0; i < n; ++i) {
    uint8_t* row = dst + static_cast<size_t>(i) * img;
    const auto& sv = srcs[static_cast<size_t>(i)];
    if (decode_one_png(sv.first, sv.second, h, w, row, idat, raw)) {
      keep_flags[i] = 1;
    } else {
      keep_flags[i] = 0;
      std::memset(row, 0, img);
    }
  }
  Py_END_ALLOW_THREADS;
  release();
  Py_RETURN_NONE;
}

// ------------------------------------------------------------- bit packing
//
// Sub-byte wire codec for bounded-vocab token rows: values < 2^bits pack
// into a little-endian bit stream per row (uint16 in, uint8 out). The
// host packs (here, one C call per chunk); the accelerator unpacks with
// vectorized shifts (ops/bitpack.py) — wire bytes are the ingest
// pipeline's scarce resource, so a 15-bit vocab rides the wire at 15/16
// of uint16.

PyObject* pack_bits(PyObject*, PyObject* args) {
  Py_buffer in;   // uint16, C-contiguous [n, s]
  Py_buffer out;  // uint8, C-contiguous [n, w]
  int bits;
  Py_ssize_t n, s, w;
  if (!PyArg_ParseTuple(args, "y*w*innn", &in, &out, &bits, &n, &s, &w)) {
    return nullptr;
  }
  auto release = [&]() {
    PyBuffer_Release(&in);
    PyBuffer_Release(&out);
  };
  if (bits < 1 || bits > 16 ||
      in.len != n * s * static_cast<Py_ssize_t>(sizeof(uint16_t)) ||
      out.len != n * w || w * 8 < s * bits) {
    release();
    PyErr_SetString(PyExc_ValueError, "pack_bits buffer shape mismatch");
    return nullptr;
  }
  const auto* src = static_cast<const uint16_t*>(in.buf);
  auto* dst = static_cast<uint8_t*>(out.buf);
  const uint32_t mask = (1u << bits) - 1u;
  Py_BEGIN_ALLOW_THREADS;
  for (Py_ssize_t r = 0; r < n; ++r) {
    const uint16_t* row = src + r * s;
    uint8_t* o = dst + r * w;
    std::memset(o, 0, static_cast<size_t>(w));
    uint32_t acc = 0;
    int nbits = 0;
    Py_ssize_t pos = 0;
    for (Py_ssize_t i = 0; i < s; ++i) {
      acc |= (static_cast<uint32_t>(row[i]) & mask) << nbits;
      nbits += bits;
      while (nbits >= 8) {
        o[pos++] = static_cast<uint8_t>(acc & 0xFFu);
        acc >>= 8;
        nbits -= 8;
      }
    }
    if (nbits > 0) o[pos] = static_cast<uint8_t>(acc & 0xFFu);
  }
  Py_END_ALLOW_THREADS;
  release();
  Py_RETURN_NONE;
}

PyMethodDef methods[] = {
    {"gather_rows", gather_rows, METH_VARARGS,
     "gather_rows(values, out_buffer, pad): pack bytes rows fixed-width"},
    {"pack_bits", pack_bits, METH_VARARGS,
     "pack_bits(in_u16, out_u8, bits, n, s, w): little-endian bit packing"},
    {"json_tokens", json_tokens, METH_VARARGS,
     "json_tokens(values, field, out_i32, keep_u8, pad_id): scan+tokenize"},
    {"decode_png_rgb", decode_png_rgb, METH_VARARGS,
     "decode_png_rgb(values, out_u8[n,h,w,3], keep_u8, h, w): PNG decode"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_tk_native",
    "C++ hot-path decoders for torchkafka_tpu", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__tk_native() { return PyModule_Create(&module); }

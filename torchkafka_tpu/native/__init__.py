"""Native (C++) hot-path decoders, with pure-NumPy fallbacks.

Build model: ``_decode.cpp`` compiles on demand (first import) with g++ into
the package directory and loads as a CPython extension; no pip/pybind11
involved. If no toolchain is available the pure-Python fallbacks below serve
identical semantics (differential-tested), so the framework never *requires*
the native path — it's a throughput lever, not a dependency.

Public surface:
- ``available()`` — True when the extension loaded.
- ``gather_rows(values, width, dtype, pad)`` — list[bytes] → [n, width] array.
- ``json_tokens_scan(values, field, seq_len, pad_id)`` — list[bytes] →
  (int32 [n, seq_len], keep uint8 [n]); minimal flat-JSON string-field scan,
  utf-8-byte tokenization (raw bytes — escape sequences are not decoded).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "_decode.cpp")
_SO = os.path.join(_HERE, "_tk_native" + (sysconfig.get_config_var("EXT_SUFFIX") or ".so"))

_native = None


def _build() -> bool:
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", _SRC, "-o", _SO + ".tmp",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)  # atomic: concurrent imports see whole file
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError) as e:
        detail = getattr(e, "stderr", b"")
        logger.warning(
            "native decoder build failed (falling back to NumPy): %s %s",
            e, detail.decode() if isinstance(detail, bytes) else detail,
        )
        return False


def _load() -> None:
    global _native
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        if not _build():
            return
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location("_tk_native", _SO)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _native = mod
    except Exception as e:  # pragma: no cover - loader failure is environmental
        logger.warning("native decoder load failed (falling back to NumPy): %s", e)


_load()


def available() -> bool:
    return _native is not None


# ------------------------------------------------------------------- gather


def gather_rows(
    values: list[bytes], width: int, dtype=np.uint8, pad: int = 0
) -> np.ndarray:
    """Pack list[bytes] into a [n, width]-items array of ``dtype``
    (truncate/pad each row). One C call for the whole chunk when native."""
    dtype = np.dtype(dtype)
    itemsize = dtype.itemsize
    width_bytes = width * itemsize
    n = len(values)
    out = np.empty((n, width), dtype=dtype)
    if n == 0:
        return out
    pad_pattern = np.asarray([pad]).astype(dtype).tobytes()
    if _native is not None:
        _native.gather_rows(
            values, out.view(np.uint8).reshape(n, width_bytes), pad_pattern
        )
        return out
    # Fallback: join-based bulk decode (still C-speed via bytes.join).
    exact = all(len(v) == width_bytes for v in values)
    if exact:
        return np.frombuffer(b"".join(values), dtype=dtype).reshape(n, width)
    out[:] = np.frombuffer(pad_pattern, dtype=dtype)[0]
    for i, v in enumerate(values):
        take = len(v) - len(v) % itemsize
        row = np.frombuffer(v[: min(take, width_bytes)], dtype=dtype)
        out[i, : row.shape[0]] = row
    return out


# ---------------------------------------------------------------- json scan


def _py_find_string_field(buf: bytes, field: bytes) -> bytes | None:
    """Python mirror of the C++ scanner (same raw-bytes semantics)."""
    needle = b'"' + field + b'"'
    i = buf.find(needle)
    while i != -1:
        j = i + len(needle)
        while j < len(buf) and buf[j : j + 1] in b" \t\n":
            j += 1
        if j < len(buf) and buf[j : j + 1] == b":":
            j += 1
            while j < len(buf) and buf[j : j + 1] in b" \t\n":
                j += 1
            if j >= len(buf) or buf[j : j + 1] != b'"':
                return None  # field exists but is not a string
            j += 1
            start = j
            while j < len(buf):
                if buf[j : j + 1] == b"\\":
                    j += 2
                    continue
                if buf[j : j + 1] == b'"':
                    return buf[start:j]
                j += 1
            return None
        i = buf.find(needle, i + 1)
    return None


def json_tokens_scan(
    values: list[bytes], field: str, seq_len: int, pad_id: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """→ (tokens int32 [n, seq_len], keep uint8 [n]). keep=0 rows are
    pad_id-filled (missing / non-string / unterminated field)."""
    n = len(values)
    tokens = np.empty((n, seq_len), dtype=np.int32)
    keep = np.empty((n,), dtype=np.uint8)
    if n == 0:
        return tokens, keep
    fname = field.encode()
    if _native is not None:
        _native.json_tokens(values, fname, tokens, keep, pad_id)
        return tokens, keep
    for i, v in enumerate(values):
        text = _py_find_string_field(v, fname)
        if text is None:
            keep[i] = 0
            tokens[i] = pad_id
            continue
        keep[i] = 1
        row = np.frombuffer(text[:seq_len], dtype=np.uint8)
        tokens[i, : row.shape[0]] = row
        tokens[i, row.shape[0] :] = pad_id
    return tokens, keep

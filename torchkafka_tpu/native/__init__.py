"""Native (C++) hot-path decoders, with pure-NumPy fallbacks.

Build model: ``_decode.cpp`` compiles on demand (first import) with g++ into
the package directory and loads as a CPython extension; no pip/pybind11
involved. If no toolchain is available the pure-Python fallbacks below serve
identical semantics (differential-tested), so the framework never *requires*
the native path — it's a throughput lever, not a dependency.

Public surface:
- ``available()`` — True when the extension loaded.
- ``gather_rows(values, width, dtype, pad)`` — list[bytes] → [n, width] array.
- ``json_tokens_scan(values, field, seq_len, pad_id)`` — list[bytes] →
  (int32 [n, seq_len], keep uint8 [n]); minimal flat-JSON string-field scan,
  utf-8-byte tokenization (raw bytes — escape sequences are not decoded).
- ``decode_png_rgb(values, height, width)`` — list[bytes] of 8-bit RGB PNGs
  → (uint8 [n, h, w, 3], keep uint8 [n]); real zlib inflate + all five
  scanline filters; keep=0 (zeroed row) for anything structurally invalid
  or with mismatched dimensions. Chunk CRCs are not verified (Kafka already
  checksums the payload; corruption fails structurally → drop).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "_decode.cpp")
_SO = os.path.join(_HERE, "_tk_native" + (sysconfig.get_config_var("EXT_SUFFIX") or ".so"))

_native = None


def _build() -> bool:
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", _SRC, "-o", _SO + ".tmp", "-lz",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)  # atomic: concurrent imports see whole file
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError) as e:
        detail = getattr(e, "stderr", b"")
        logger.warning(
            "native decoder build failed (falling back to NumPy): %s %s",
            e, detail.decode() if isinstance(detail, bytes) else detail,
        )
        return False


def _load() -> None:
    global _native
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        if not _build():
            return
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location("_tk_native", _SO)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _native = mod
    except Exception as e:  # pragma: no cover - loader failure is environmental
        logger.warning("native decoder load failed (falling back to NumPy): %s", e)


_load()


def available() -> bool:
    return _native is not None


# ------------------------------------------------------------------- gather


def gather_rows(
    values: list[bytes], width: int, dtype=np.uint8, pad: int = 0
) -> np.ndarray:
    """Pack list[bytes] into a [n, width]-items array of ``dtype``
    (truncate/pad each row). One C call for the whole chunk when native."""
    dtype = np.dtype(dtype)
    itemsize = dtype.itemsize
    width_bytes = width * itemsize
    n = len(values)
    out = np.empty((n, width), dtype=dtype)
    if n == 0:
        return out
    pad_pattern = np.asarray([pad]).astype(dtype).tobytes()
    if _native is not None:
        _native.gather_rows(
            values, out.view(np.uint8).reshape(n, width_bytes), pad_pattern
        )
        return out
    # Fallback: join-based bulk decode (still C-speed via bytes.join).
    exact = all(len(v) == width_bytes for v in values)
    if exact:
        return np.frombuffer(b"".join(values), dtype=dtype).reshape(n, width)
    out[:] = np.frombuffer(pad_pattern, dtype=dtype)[0]
    for i, v in enumerate(values):
        take = len(v) - len(v) % itemsize
        row = np.frombuffer(v[: min(take, width_bytes)], dtype=dtype)
        out[i, : row.shape[0]] = row
    return out


# -------------------------------------------------------------- bit packing


def packed_width(seq: int, bits: int) -> int:
    """Bytes per packed row of ``seq`` values at ``bits`` bits each. The
    device-side unpack (ops/bitpack.py) reads a 3-byte window per value
    with tail indices clipped; no extra padding is needed — whenever a
    value's bits spill past the second byte, that third byte necessarily
    exists (the value's own bits occupy it), and a clipped duplicate byte
    only ever contributes bit positions the mask discards."""
    if not 1 <= bits <= 16:
        raise ValueError("bits must be in [1, 16]")
    return (seq * bits + 7) // 8


def pack_bits(rows: np.ndarray, bits: int) -> np.ndarray:
    """[n, s] non-negative ints < 2^bits → [n, packed_width] uint8, packed
    as one little-endian bit stream per row. One C call per chunk when
    native; NumPy packbits fallback with identical layout."""
    n, s = rows.shape
    w = packed_width(s, bits)
    rows16 = np.ascontiguousarray(rows, dtype=np.uint16)
    out = np.empty((n, w), dtype=np.uint8)
    if n == 0:
        return out
    if _native is not None:
        _native.pack_bits(rows16, out, bits, n, s, w)
        return out
    # Fallback: expand each value to its little-endian bits, pad the row's
    # bit stream to w*8, and let packbits do the byte assembly.
    bit_mat = (
        (rows16[:, :, None] >> np.arange(bits, dtype=np.uint16)) & 1
    ).astype(np.uint8).reshape(n, s * bits)
    padded = np.zeros((n, w * 8), dtype=np.uint8)
    padded[:, : s * bits] = bit_mat
    return np.packbits(padded, axis=1, bitorder="little")


# ---------------------------------------------------------------- json scan


def _py_find_string_field(buf: bytes, field: bytes) -> bytes | None:
    """Python mirror of the C++ scanner (same raw-bytes semantics)."""
    needle = b'"' + field + b'"'
    i = buf.find(needle)
    while i != -1:
        j = i + len(needle)
        while j < len(buf) and buf[j : j + 1] in b" \t\n":
            j += 1
        if j < len(buf) and buf[j : j + 1] == b":":
            j += 1
            while j < len(buf) and buf[j : j + 1] in b" \t\n":
                j += 1
            if j >= len(buf) or buf[j : j + 1] != b'"':
                return None  # field exists but is not a string
            j += 1
            start = j
            while j < len(buf):
                if buf[j : j + 1] == b"\\":
                    j += 2
                    continue
                if buf[j : j + 1] == b'"':
                    return buf[start:j]
                j += 1
            return None
        i = buf.find(needle, i + 1)
    return None


def json_tokens_scan(
    values: list[bytes], field: str, seq_len: int, pad_id: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """→ (tokens int32 [n, seq_len], keep uint8 [n]). keep=0 rows are
    pad_id-filled (missing / non-string / unterminated field)."""
    n = len(values)
    tokens = np.empty((n, seq_len), dtype=np.int32)
    keep = np.empty((n,), dtype=np.uint8)
    if n == 0:
        return tokens, keep
    fname = field.encode()
    if _native is not None:
        _native.json_tokens(values, fname, tokens, keep, pad_id)
        return tokens, keep
    for i, v in enumerate(values):
        text = _py_find_string_field(v, fname)
        if text is None:
            keep[i] = 0
            tokens[i] = pad_id
            continue
        keep[i] = 1
        row = np.frombuffer(text[:seq_len], dtype=np.uint8)
        tokens[i, : row.shape[0]] = row
        tokens[i, row.shape[0] :] = pad_id
    return tokens, keep


# ---------------------------------------------------------------- png decode


def _py_defilter_row(filt: int, cur, out, prior, stride: int):
    """Reverse one PNG scanline filter (bpp=3). ``cur`` is the filtered
    bytes (int32 work dtype), ``out`` the row being produced (uint8),
    ``prior`` the previous defiltered row or None."""
    if filt == 0:
        out[:] = cur
    elif filt == 1:  # Sub — per-channel cumulative sum is exactly +left mod 256
        px = cur.reshape(-1, 3)
        out[:] = (np.cumsum(px, axis=0, dtype=np.int64) % 256).astype(
            np.uint8
        ).reshape(-1)
    elif filt == 2:  # Up
        out[:] = cur if prior is None else (cur + prior) % 256
    elif filt == 3:  # Average — sequential in x (left depends on output)
        up = np.zeros(stride, np.int32) if prior is None else prior.astype(np.int32)
        px, upx = cur.reshape(-1, 3), up.reshape(-1, 3)
        o = out.reshape(-1, 3)
        left = np.zeros(3, np.int32)
        for x in range(px.shape[0]):
            left = (px[x] + ((left + upx[x]) >> 1)) % 256
            o[x] = left
    elif filt == 4:  # Paeth — sequential in x
        up = np.zeros(stride, np.int32) if prior is None else prior.astype(np.int32)
        px, upx = cur.reshape(-1, 3), up.reshape(-1, 3)
        o = out.reshape(-1, 3)
        left = np.zeros(3, np.int32)
        ul = np.zeros(3, np.int32)
        for x in range(px.shape[0]):
            p = left + upx[x] - ul
            pa, pb, pc = np.abs(p - left), np.abs(p - upx[x]), np.abs(p - ul)
            pred = np.where(
                (pa <= pb) & (pa <= pc), left, np.where(pb <= pc, upx[x], ul)
            )
            left = (px[x] + pred) % 256
            o[x] = left
            ul = upx[x]
    else:
        raise ValueError(f"unknown PNG filter {filt}")


def _py_decode_one_png(buf: bytes, h: int, w: int) -> np.ndarray | None:
    """Python mirror of the C++ decoder (same accept/reject semantics)."""
    import struct
    import zlib

    if len(buf) < 33 or buf[:8] != b"\x89PNG\r\n\x1a\n":
        return None
    pos = 8
    idat = bytearray()
    saw_ihdr = False
    while pos + 8 <= len(buf):
        (clen,) = struct.unpack_from(">I", buf, pos)
        ctype = buf[pos + 4 : pos + 8]
        data = buf[pos + 8 : pos + 8 + clen]
        if pos + 8 + clen + 4 > len(buf):
            return None
        if ctype == b"IHDR":
            if clen != 13:
                return None
            pw, ph = struct.unpack_from(">II", data, 0)
            if (pw, ph) != (w, h) or data[8:13] != b"\x08\x02\x00\x00\x00":
                return None
            saw_ihdr = True
        elif ctype == b"IDAT":
            idat += data
        elif ctype == b"IEND":
            break
        pos += 8 + clen + 4
    if not saw_ihdr or not idat:
        return None
    stride = w * 3
    try:
        raw = zlib.decompress(bytes(idat))
    except zlib.error:
        return None
    if len(raw) != h * (1 + stride):
        return None
    rows = np.frombuffer(raw, np.uint8).reshape(h, 1 + stride)
    out = np.empty((h, stride), np.uint8)
    prior = None
    for y in range(h):
        if rows[y, 0] > 4:
            return None  # unknown filter byte — drop, same as the C++ path
        _py_defilter_row(
            int(rows[y, 0]), rows[y, 1:].astype(np.int32), out[y], prior, stride
        )
        prior = out[y]
    return out.reshape(h, w, 3)


def decode_png_rgb(
    values: list[bytes], height: int, width: int
) -> tuple[np.ndarray, np.ndarray]:
    """list of 8-bit RGB PNG payloads → (uint8 [n, h, w, 3], keep uint8 [n]).
    Invalid/mismatched records decode to zeros with keep=0 (the vectorized
    None-drop contract). One C call for the whole chunk when native."""
    n = len(values)
    out = np.empty((n, height, width, 3), dtype=np.uint8)
    keep = np.empty((n,), dtype=np.uint8)
    if n == 0:
        return out, keep
    if _native is not None:
        _native.decode_png_rgb(values, out, keep, height, width)
        return out, keep
    for i, v in enumerate(values):
        img = _py_decode_one_png(v, height, width)
        if img is None:
            keep[i] = 0
            out[i] = 0
        else:
            keep[i] = 1
            out[i] = img
    return out, keep

"""WAL-frame replication: the leader's ship path and the follower's
apply path of a broker cell.

PR 11 made the broker crash-SAFE — every acked mutation is a CRC-framed
WAL event replayed at construction — but a single process still rode
every outage (scenario 19's 2.5 s) and a lost disk lost everything. The
observation this module builds on is that the WAL frames are ALREADY a
replication stream: the broker funnels every acknowledged state change
through one chokepoint (``InMemoryBroker._wal_append``), each event is a
self-contained ``(kind, dict)`` pair, and replaying a prefix of them
reconstructs a broker. So replication is: after the leader's local
append, ship the same frame over the existing netbroker wire to N
followers, each of which appends it to its OWN write-ahead log (same
fsync discipline, same torn-tail repair), and ack the mutation only once
a MAJORITY of replicas hold it — ``wal_durability="quorum"``. Promotion
is then exactly PR 11 recovery pointed at a follower's directory.

Fencing. Every shipped frame carries the cell EPOCH. An election (see
source/cluster.py) bumps the epoch and stamps it on every reachable
follower before the winner promotes, so a deposed leader's late ships
meet ``StaleEpochError`` from the survivors, fail their quorum, and are
NEVER applied — the cell-level twin of the producer-epoch fence that
already rejects a zombie replica's transaction commits.

Ordering. Frames are shipped under the broker's own lock in append
order, and a follower only appends the frame whose sequence number
matches its applied count — a follower's WAL is always a strict PREFIX
of the leader's frame log. A follower that missed frames (transport
fault mid-ship) reports its applied count back and the leader re-ships
the gap from its in-memory frame log on the next append; election picks
the longest prefix, so majority-acked frames can never be lost (they are
on ≥ quorum replicas, and the winner holds at least every frame any
quorum holds... the SUPERVISED-cell argument: one BrokerCell orchestrates
membership, so two concurrent elections cannot split the brain).

Crash points: ``repl_frame_pre_ship`` (leader WAL has the frame, no
follower does — unacked, must never surface as a committed duplicate),
``repl_frame_post_majority_pre_ack`` (majority holds it, client never saw
the ack — durable cell-wide, the retry is answered idempotently).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

from torchkafka_tpu.errors import (
    BrokerUnavailableError,
    QuorumLostError,
    StaleEpochError,
)
from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.source import wal as _wal


@dataclass
class ReplicationConfig:
    """Cell-wide replication knobs.

    ``replicas`` counts EVERY member, leader included: a cell of 3 is one
    leader plus two followers and commits on 2 acks. ``durability`` is
    the PER-REPLICA local fsync discipline (the ``wal.DURABILITIES``
    values) — quorum mode changes what an ACK means, not how each
    replica syncs its own disk. ``lease_timeout_s`` is the leader lease
    the followers' heartbeats renew; letting it lapse is what triggers an
    epoch-bumped election. ``rpc_timeout_s`` bounds every replication
    RPC so a hung follower reads as unreachable, not as a stalled cell."""

    replicas: int = 3
    durability: str | None = "batch"
    segment_bytes: int = 4 * 1024 * 1024
    lease_timeout_s: float = 2.0
    heartbeat_interval_s: float = 0.2
    rpc_timeout_s: float = 5.0

    def __post_init__(self) -> None:
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.durability not in _wal.DURABILITIES:
            raise ValueError(
                f"durability must be one of {_wal.DURABILITIES}, got "
                f"{self.durability!r}"
            )
        if self.lease_timeout_s <= 0:
            raise ValueError(
                f"lease_timeout_s must be > 0, got {self.lease_timeout_s}"
            )

    @property
    def quorum(self) -> int:
        return self.replicas // 2 + 1


class FollowerReplica:
    """The apply side: owns one WAL directory and appends the frames the
    leader ships. Served over the netbroker wire (``BrokerServer`` wraps
    this object directly; ``repl_append``/``repl_status`` are on the
    server's method allowlist), so replication rides the same
    length-prefixed frames, the same marshalled-exception discipline, and
    the same ``WireFaults`` chaos coverage as every client RPC.

    Construction REPAIRS: the directory's torn tail (a death mid-append,
    or a leader that died mid-ship leaving a half-written frame) is
    truncated away exactly as broker recovery would, so ``applied`` and
    the on-disk log agree before any new frame lands."""

    def __init__(
        self,
        wal_dir: str | os.PathLike,
        *,
        durability: str | None = "batch",
        segment_bytes: int = 4 * 1024 * 1024,
        metrics=None,
    ) -> None:
        self.wal_dir = os.fspath(wal_dir)
        events, truncated = _wal.replay(self.wal_dir, repair=True)
        self.applied = len(events)
        self.truncated_bytes = truncated
        self.epoch = 0
        self._metrics = metrics
        self._lock = threading.Lock()
        self._closed = False
        self._wal = _wal.WriteAheadLog(
            self.wal_dir, durability=durability, segment_bytes=segment_bytes
        )

    # -------------------------------------------------- wire-facing RPCs

    def repl_append(self, epoch: int, base: int, frames) -> int:
        """Append ``frames`` (leader frame-log slice starting at sequence
        ``base``) and return this replica's applied count — the leader's
        ack AND its catch-up cursor. Stale epochs are REJECTED before any
        frame is touched; already-held frames are skipped idempotently; a
        gap (``base`` beyond ``applied``) appends nothing, and the
        returned count tells the leader where to re-ship from."""
        with self._lock:
            if self._closed:
                raise BrokerUnavailableError("follower replica is closed")
            if epoch < self.epoch:
                if self._metrics is not None:
                    self._metrics.repl_stale_rejections.add(1)
                raise StaleEpochError(
                    f"replicated frame carries epoch {epoch} but this "
                    f"replica already accepted epoch {self.epoch}: the "
                    f"sender is a deposed leader"
                )
            self.epoch = max(self.epoch, epoch)
            for i, (kind, event) in enumerate(frames):
                seq = base + i
                if seq < self.applied:
                    continue  # duplicate re-ship: already durable here
                if seq > self.applied:
                    break  # gap: report position, leader re-ships
                self._wal.append(kind, event)
                self.applied += 1
                if self._metrics is not None:
                    self._metrics.repl_frames_applied.add(1)
            return self.applied

    def repl_status(self, epoch: int | None = None) -> dict:
        """Position probe; with ``epoch`` set, also ADOPTS it (the
        election stamps the bumped epoch on every reachable follower
        here, which is the instant the old leader becomes fenceable)."""
        with self._lock:
            if self._closed:
                raise BrokerUnavailableError("follower replica is closed")
            if epoch is not None and epoch > self.epoch:
                self.epoch = epoch
            return {
                "applied": self.applied,
                "epoch": self.epoch,
                "wal_bytes": self._wal.total_bytes(),
            }

    # ----------------------------------------------------------- local

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._wal.close()


class _FollowerLink:
    """Leader-side view of one follower: its RPC client plus the acked
    cursor (how much of the frame log the leader knows it holds)."""

    __slots__ = ("idx", "client", "acked")

    def __init__(self, idx: int, client, acked: int = 0):
        self.idx = idx
        self.client = client
        self.acked = acked


class Replicator:
    """The ship side, attached to the leader broker as
    ``broker.replicator``: ``_wal_append`` calls :meth:`ship` after the
    leader-local append, and the mutation is acknowledged only if
    :meth:`ship` returns — i.e. only once ``quorum`` replicas (leader
    included) hold the frame. Raising here aborts the in-memory apply,
    so a quorum-less leader can never diverge its served state from what
    the cell can durably prove."""

    def __init__(
        self,
        *,
        epoch: int,
        quorum: int,
        log: list | None = None,
        metrics=None,
    ) -> None:
        self.epoch = epoch
        self.quorum = quorum
        self.log: list[tuple[str, dict]] = list(log) if log else []
        self.deposed = False
        self._metrics = metrics
        self._followers: list[_FollowerLink] = []

    def add_follower(self, idx: int, client, *, acked: int = 0) -> None:
        self._followers.append(_FollowerLink(idx, client, acked))

    @property
    def follower_count(self) -> int:
        return len(self._followers)

    def _ship_to(self, link: _FollowerLink, target: int) -> bool:
        """Push frames ``[link.acked, target)`` to one follower; True iff
        it holds the full prefix afterwards. Transport faults read as
        no-ack (the quorum decides); a stale-epoch rejection marks this
        leader deposed — its ack can never count again."""
        try:
            ret = link.client.repl_append(
                self.epoch, link.acked, self.log[link.acked : target]
            )
        except StaleEpochError:
            self.deposed = True
            if self._metrics is not None:
                self._metrics.repl_stale_rejections.add(1)
            return False
        except (BrokerUnavailableError, ConnectionError, OSError):
            return False
        link.acked = ret
        return ret >= target

    def ship(self, kind: str, event: dict) -> None:
        """Replicate one frame; returns only on majority. Called under
        the broker lock right after the leader-local WAL append, so the
        frame log and every follower WAL share one total order."""
        crash_hook("repl_frame_pre_ship")
        if self.deposed:
            raise QuorumLostError(
                f"leader at epoch {self.epoch} was deposed: a newer epoch "
                f"fenced its replication stream"
            )
        self.log.append((kind, event))
        target = len(self.log)
        if self._metrics is not None:
            self._metrics.repl_frames_shipped.add(1)
        acks = 1  # the leader's own WAL append already happened
        for link in self._followers:
            if self._ship_to(link, target):
                acks += 1
        if acks < self.quorum:
            raise QuorumLostError(
                f"frame {target - 1} reached {acks}/{self.quorum} replicas "
                f"(epoch {self.epoch}): mutation not acknowledged"
            )
        if self._metrics is not None:
            self._metrics.repl_quorum_commits.add(1)
        crash_hook("repl_frame_post_majority_pre_ack")

    def sync(self) -> dict[int, int]:
        """Best-effort catch-up: push the full frame-log tail to every
        follower (promotion uses this so the survivors converge on the
        new leader's prefix before fresh traffic lands). Returns
        idx -> applied for the followers that answered."""
        out: dict[int, int] = {}
        target = len(self.log)
        for link in self._followers:
            if self._ship_to(link, target):
                out[link.idx] = link.acked
        return out

    def close(self) -> None:
        for link in self._followers:
            try:
                link.client.close()
            except OSError:
                pass

"""Checkpoint wire: versioned model weights as CRC'd broker frames.

The rollout plane's data format — the PrefillHandoff wire discipline
(length-prefixed JSON header + raw array bytes, no pickle) applied to
whole checkpoints. A checkpoint version is TWO frame kinds on one topic:

- a MANIFEST frame (magic ``CKMF``): the version id, the kind
  (``serving`` or ``draft`` — ROADMAP item 1's distilled-draft refresh
  rides the same plane), every array's name/dtype/shape in the
  deterministic flatten order, the chunking geometry, a CRC per chunk
  and a CRC over the whole payload;
- N CHUNK frames (magic ``CKCH``): the raw payload split at
  ``chunk_bytes`` boundaries, each self-describing (version, index,
  size) and self-checking (CRC over its own bytes).

Chunking is what makes the torn-frame story testable byte-by-byte: a
truncated or bit-flipped frame — at ANY byte — decodes to
``CheckpointWireError``, never to a crash and never to silently wrong
weights. The fetch path verifies chunk CRCs, assembly completeness, the
payload CRC, and finally dtype/shape against the incumbent tree
(``rebuild_tree``); a replica that rejects keeps serving the incumbent
and a re-published checkpoint converges. Frames are idempotent by
(version, index): a duplicate or re-publish overwrites with identical
bytes, so last-wins assembly is deterministic.

Arrays travel in the tree's flatten order (dict keys sorted, sequence
elements by index) so every process — publisher on one machine, replica
on another — maps name ↔ bytes identically without negotiation.
"""

from __future__ import annotations

import json
import zlib

import numpy as np

from torchkafka_tpu.errors import CheckpointWireError
from torchkafka_tpu.source.records import TopicPartition

_WIRE_VERSION = 1
_MANIFEST_MAGIC = b"CKMF"
_CHUNK_MAGIC = b"CKCH"
DEFAULT_CHUNK_BYTES = 1 << 18


def flatten_params(tree) -> list[tuple[str, np.ndarray]]:
    """The deterministic tree walk: nested dicts by sorted key, lists and
    tuples by index, leaves as numpy arrays — the single flatten order
    both ends of the wire share. Paths join with ``/`` (key names in the
    model trees never contain it)."""
    flat: list[tuple[str, np.ndarray]] = []

    def walk(node, path: str) -> None:
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], f"{path}/{k}" if path else str(k))
        elif isinstance(node, (list, tuple)):
            for i, item in enumerate(node):
                walk(item, f"{path}/{i}" if path else str(i))
        else:
            flat.append((path, np.asarray(node)))

    walk(tree, "")
    return flat


def rebuild_tree(template, flat: dict[str, np.ndarray]):
    """Rebuild a params tree with the SAME structure as ``template`` but
    the wire's arrays as leaves — the incumbent tree is the schema, so a
    checkpoint that drops, adds, or reshapes an array is rejected
    (``CheckpointWireError``) before any weight is touched. Returns a new
    tree; the caller owns device placement."""
    used: set[str] = set()

    def walk(node, path: str):
        if isinstance(node, dict):
            return {
                k: walk(node[k], f"{path}/{k}" if path else str(k))
                for k in node
            }
        if isinstance(node, (list, tuple)):
            rebuilt = [
                walk(item, f"{path}/{i}" if path else str(i))
                for i, item in enumerate(node)
            ]
            return type(node)(rebuilt) if isinstance(node, tuple) else rebuilt
        leaf = np.asarray(node)
        arr = flat.get(path)
        if arr is None:
            raise CheckpointWireError(
                f"checkpoint is missing array {path!r}"
            )
        if tuple(arr.shape) != tuple(leaf.shape) or arr.dtype != leaf.dtype:
            raise CheckpointWireError(
                f"checkpoint array {path!r} is {arr.dtype}{arr.shape}, "
                f"incumbent is {leaf.dtype}{tuple(leaf.shape)}"
            )
        used.add(path)
        return arr

    tree = walk(template, "")
    extra = set(flat) - used
    if extra:
        raise CheckpointWireError(
            f"checkpoint carries arrays the incumbent tree has no slot "
            f"for: {sorted(extra)[:4]}"
        )
    return tree


# ------------------------------------------------------------------ framing


def _frame(magic: bytes, header: dict, payload: bytes = b"") -> bytes:
    hb = json.dumps(header).encode()
    return b"".join((magic, len(hb).to_bytes(4, "big"), hb, payload))


def _open_frame(data: bytes, magic: bytes, what: str) -> tuple[dict, bytes]:
    """Shared validation for both frame kinds: magic, length prefix,
    JSON header, wire version — every malformation (including truncation
    at ANY byte) is ``CheckpointWireError``."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise CheckpointWireError(f"{what} frame is not bytes")
    data = bytes(data)
    if len(data) < 8:
        raise CheckpointWireError(
            f"{what} frame truncated at {len(data)} bytes"
        )
    if data[:4] != magic:
        raise CheckpointWireError(
            f"{what} frame has magic {data[:4]!r}, want {magic!r}"
        )
    hlen = int.from_bytes(data[4:8], "big")
    if len(data) < 8 + hlen:
        raise CheckpointWireError(
            f"{what} frame truncated inside header "
            f"({len(data)} of {8 + hlen} bytes)"
        )
    try:
        header = json.loads(data[8:8 + hlen].decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise CheckpointWireError(
            f"{what} frame header is not JSON: {exc}"
        ) from exc
    if not isinstance(header, dict):
        raise CheckpointWireError(f"{what} frame header is not an object")
    if header.get("v") != _WIRE_VERSION:
        raise CheckpointWireError(
            f"unknown {what} wire version {header.get('v')!r}"
        )
    return header, data[8 + hlen:]


def encode_manifest(
    version: int, kind: str, arrays, chunk_bytes: int,
    chunk_crcs: list[int], payload_crc: int, total_bytes: int,
) -> bytes:
    header = {
        "v": _WIRE_VERSION,
        "version": int(version),
        "kind": kind,
        "arrays": [
            {"name": name, "dtype": str(a.dtype), "shape": list(a.shape)}
            for name, a in arrays
        ],
        "chunk_bytes": int(chunk_bytes),
        "n_chunks": len(chunk_crcs),
        "chunk_crcs": [int(c) for c in chunk_crcs],
        "payload_crc": int(payload_crc),
        "total_bytes": int(total_bytes),
    }
    return _frame(_MANIFEST_MAGIC, header)


def decode_manifest(data: bytes) -> dict:
    header, rest = _open_frame(data, _MANIFEST_MAGIC, "manifest")
    if rest:
        raise CheckpointWireError(
            f"manifest frame has {len(rest)} trailing bytes"
        )
    try:
        version = int(header["version"])
        kind = str(header["kind"])
        arrays = [
            (str(m["name"]), np.dtype(m["dtype"]), tuple(
                int(x) for x in m["shape"]))
            for m in header["arrays"]
        ]
        chunk_crcs = [int(c) for c in header["chunk_crcs"]]
        n_chunks = int(header["n_chunks"])
        out = {
            "version": version,
            "kind": kind,
            "arrays": arrays,
            "chunk_bytes": int(header["chunk_bytes"]),
            "n_chunks": n_chunks,
            "chunk_crcs": chunk_crcs,
            "payload_crc": int(header["payload_crc"]),
            "total_bytes": int(header["total_bytes"]),
        }
    except (KeyError, TypeError, ValueError) as exc:
        raise CheckpointWireError(
            f"manifest header malformed: {exc!r}"
        ) from exc
    if len(chunk_crcs) != n_chunks:
        raise CheckpointWireError(
            f"manifest claims {n_chunks} chunks but lists "
            f"{len(chunk_crcs)} CRCs"
        )
    declared = sum(
        dt.itemsize * int(np.prod(shape, dtype=np.int64)) if shape
        else dt.itemsize
        for _, dt, shape in out["arrays"]
    )
    if declared != out["total_bytes"]:
        raise CheckpointWireError(
            f"manifest arrays sum to {declared} bytes, claims "
            f"{out['total_bytes']}"
        )
    return out


def encode_chunk(version: int, idx: int, payload: bytes) -> bytes:
    header = {
        "v": _WIRE_VERSION,
        "version": int(version),
        "idx": int(idx),
        "size": len(payload),
        "crc": zlib.crc32(payload) & 0xFFFFFFFF,
    }
    return _frame(_CHUNK_MAGIC, header, payload)


def decode_chunk(data: bytes) -> tuple[int, int, bytes]:
    """Returns ``(version, idx, payload)`` — size- and CRC-verified."""
    header, payload = _open_frame(data, _CHUNK_MAGIC, "chunk")
    try:
        version = int(header["version"])
        idx = int(header["idx"])
        size = int(header["size"])
        crc = int(header["crc"])
    except (KeyError, TypeError, ValueError) as exc:
        raise CheckpointWireError(f"chunk header malformed: {exc!r}") from exc
    if len(payload) != size:
        raise CheckpointWireError(
            f"chunk {idx} of version {version} truncated "
            f"({len(payload)} of {size} payload bytes)"
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CheckpointWireError(
            f"chunk {idx} of version {version} fails CRC"
        )
    return version, idx, payload


# --------------------------------------------------------- publish / fetch


def checkpoint_frames(
    version: int, params, *, kind: str = "serving",
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> list[bytes]:
    """Encode ``params`` as its ordered frame list (manifest first) —
    the unit the publisher produces and the fuzz tests mutilate."""
    if chunk_bytes < 1:
        raise ValueError(f"chunk_bytes must be >= 1, got {chunk_bytes}")
    flat = flatten_params(params)
    payload = b"".join(
        np.ascontiguousarray(a).tobytes() for _, a in flat
    )
    chunks = [
        payload[i:i + chunk_bytes]
        for i in range(0, len(payload), chunk_bytes)
    ] or [b""]
    frames = [encode_manifest(
        version, kind, flat, chunk_bytes,
        [zlib.crc32(c) & 0xFFFFFFFF for c in chunks],
        zlib.crc32(payload) & 0xFFFFFFFF, len(payload),
    )]
    frames.extend(
        encode_chunk(version, i, c) for i, c in enumerate(chunks)
    )
    return frames


def publish_checkpoint(
    broker, topic: str, version: int, params, *, kind: str = "serving",
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> int:
    """Produce a checkpoint version onto ``topic`` (manifest, then every
    chunk, keyed by version so a tail can group frames). Idempotent by
    construction: re-publishing a version appends identical-content
    frames and last-wins assembly converges. Returns frames produced."""
    frames = checkpoint_frames(
        version, params, kind=kind, chunk_bytes=chunk_bytes,
    )
    key = str(int(version)).encode()
    for frame in frames:
        broker.produce(topic, frame, key=key)
    return len(frames)


def fetch_checkpoint(
    broker, topic: str, version: int,
) -> tuple[dict[str, np.ndarray], dict]:
    """Assemble and verify checkpoint ``version`` from ``topic``.

    Last-published manifest for the version wins (a corrupt publish
    followed by a clean re-publish converges); chunks are last-wins by
    index, individually CRC'd, then the assembled payload is CRC'd whole
    before slicing into named arrays. ANY defect — missing manifest,
    missing chunk, torn frame, CRC mismatch, size drift — raises
    ``CheckpointWireError``; the caller keeps the incumbent weights.
    Returns ``(name → array, manifest)``."""
    version = int(version)
    tp = TopicPartition(topic, 0)
    try:
        end = broker.end_offset(tp)
        records = broker.fetch(tp, 0, end) if end else []
    except Exception as exc:  # noqa: BLE001 - unknown topic, transport
        raise CheckpointWireError(
            f"cannot read checkpoint topic {topic!r}: {exc}"
        ) from exc
    manifest: dict | None = None
    chunks: dict[int, bytes] = {}
    for rec in records:
        value = rec.value or b""
        if value[:4] == _MANIFEST_MAGIC:
            try:
                m = decode_manifest(value)
            except CheckpointWireError:
                continue  # torn manifest: a later re-publish may supersede
            if m["version"] == version:
                manifest = m
                chunks.clear()  # chunks published before this manifest
        elif value[:4] == _CHUNK_MAGIC and manifest is not None:
            try:
                v, idx, payload = decode_chunk(value)
            except CheckpointWireError:
                continue  # torn chunk: assembly fails as missing below
            if v == version:
                chunks[idx] = payload
    if manifest is None:
        raise CheckpointWireError(
            f"no valid manifest for version {version} on {topic!r}"
        )
    missing = [i for i in range(manifest["n_chunks"]) if i not in chunks]
    if missing:
        raise CheckpointWireError(
            f"version {version} is missing chunks {missing[:4]} "
            f"(of {manifest['n_chunks']})"
        )
    for i in range(manifest["n_chunks"]):
        if zlib.crc32(chunks[i]) & 0xFFFFFFFF != manifest["chunk_crcs"][i]:
            raise CheckpointWireError(
                f"version {version} chunk {i} does not match its "
                "manifest CRC"
            )
    payload = b"".join(chunks[i] for i in range(manifest["n_chunks"]))
    if len(payload) != manifest["total_bytes"]:
        raise CheckpointWireError(
            f"version {version} assembled to {len(payload)} bytes, "
            f"manifest claims {manifest['total_bytes']}"
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != manifest["payload_crc"]:
        raise CheckpointWireError(
            f"version {version} assembled payload fails CRC"
        )
    flat: dict[str, np.ndarray] = {}
    off = 0
    for name, dt, shape in manifest["arrays"]:
        n = (
            dt.itemsize * int(np.prod(shape, dtype=np.int64))
            if shape else dt.itemsize
        )
        try:
            flat[name] = np.frombuffer(
                payload, dtype=dt, count=n // dt.itemsize, offset=off,
            ).reshape(shape).copy()
        except ValueError as exc:
            raise CheckpointWireError(
                f"version {version} array {name!r} unreadable: {exc}"
            ) from exc
        off += n
    return flat, manifest

"""Producer protocol + transports: write records back to Kafka.

The reference is consume-only (SURVEY.md §2 — no producer anywhere in its
tree), but its users' pipelines don't end at the training loop: dead
letters go to a quarantine topic, serving results go to an output topic,
and metrics/audit events go somewhere durable. This module closes the
loop with the same transport split as the consumer side: an in-memory
producer over ``InMemoryBroker`` (hermetic tests) and a kafka-python
adapter (gated import, in source/kafka.py).

Delivery contract: ``send`` is asynchronous-capable — it returns a
``SendHandle`` whose ``get(timeout_s)`` blocks until the record is durable
on the broker and returns its ``RecordMetadata``; ``flush()`` drains
everything in flight. The memory transport resolves synchronously (the
broker append IS durability); the kafka adapter wraps the client's future.
Partitioning matches Kafka's default partitioner: explicit partition wins,
else key-hash, else round-robin.
"""

from __future__ import annotations

import dataclasses
from typing import Protocol, runtime_checkable

from torchkafka_tpu.errors import ProducerClosedError
from torchkafka_tpu.source.memory import InMemoryBroker
from torchkafka_tpu.source.records import Record


@dataclasses.dataclass(frozen=True, slots=True)
class RecordMetadata:
    """Where a produced record landed (Kafka's RecordMetadata analog)."""

    topic: str
    partition: int
    offset: int


class SendHandle(Protocol):
    def get(self, timeout_s: float | None = None) -> RecordMetadata: ...


@dataclasses.dataclass(frozen=True, slots=True)
class _ResolvedSend:
    """A send that was durable the moment it returned (memory transport)."""

    metadata: RecordMetadata

    def get(self, timeout_s: float | None = None) -> RecordMetadata:
        return self.metadata


@runtime_checkable
class Producer(Protocol):
    def send(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        partition: int | None = None,
        timestamp_ms: int | None = None,
        headers: tuple[tuple[str, bytes], ...] = (),
    ) -> SendHandle: ...

    def flush(self, timeout_s: float | None = None) -> None: ...

    def close(self) -> None: ...


class MemoryProducer:
    """Producer over ``InMemoryBroker`` — the hermetic twin of
    ``MemoryConsumer``. Appends are durable synchronously; partitioning
    (explicit / key-hash / round-robin) is the broker's, which mirrors
    Kafka's default partitioner."""

    def __init__(self, broker: InMemoryBroker) -> None:
        self._broker = broker
        self._closed = False

    def send(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        partition: int | None = None,
        timestamp_ms: int | None = None,
        headers: tuple[tuple[str, bytes], ...] = (),
    ) -> SendHandle:
        if self._closed:
            raise ProducerClosedError("producer is closed")
        rec = self._broker.produce(
            topic, value, key=key, partition=partition,
            timestamp_ms=timestamp_ms, headers=headers,
        )
        return _ResolvedSend(RecordMetadata(rec.topic, rec.partition, rec.offset))

    def flush(self, timeout_s: float | None = None) -> None:
        if self._closed:
            raise ProducerClosedError("producer is closed")
        # Synchronous appends: nothing is ever in flight.

    def close(self) -> None:
        self._closed = True


def dead_letter_to_topic(
    producer: Producer, topic: str, *, timeout_s: float | None = 30.0
):
    """Adapt a Producer into a ``KafkaStream(dead_letter=...)`` callback:
    poison records land on a quarantine topic with their provenance and
    the error in headers, key preserved (so compacted/keyed DLQ topics
    keep working).

    The callback BLOCKS on the send handle (``get(timeout_s)``): the
    poison record's offset retires into the source watermark the moment
    this returns, so the quarantine copy must be durable FIRST — an async
    fire-and-forget would let a broker-side send failure (or a crash
    before flush) lose the record permanently with the source already
    committed past it. Failures raise here and land in the stream's DLQ
    guard, which logs and swallows them — a broken DLQ must not take down
    ingest (pipeline/stream.py's dead_letter contract) — but the failure
    is at least visible in the logs and metrics. Poison is rare by
    definition; the per-record ack round-trip is not a hot path."""

    def on_dead_letter(record: Record, exc: BaseException) -> None:
        producer.send(
            topic,
            record.value,
            key=record.key,
            headers=(
                ("dlq.error", str(exc).encode()),
                ("dlq.topic", record.topic.encode()),
                ("dlq.partition", str(record.partition).encode()),
                ("dlq.offset", str(record.offset).encode()),
            ),
        ).get(timeout_s)

    return on_dead_letter

"""Producer protocol + transports: write records back to Kafka.

The reference is consume-only (SURVEY.md §2 — no producer anywhere in its
tree), but its users' pipelines don't end at the training loop: dead
letters go to a quarantine topic, serving results go to an output topic,
and metrics/audit events go somewhere durable. This module closes the
loop with the same transport split as the consumer side: an in-memory
producer over ``InMemoryBroker`` (hermetic tests) and a kafka-python
adapter (gated import, in source/kafka.py).

Delivery contract: ``send`` is asynchronous-capable — it returns a
``SendHandle`` whose ``get(timeout_s)`` blocks until the record is durable
on the broker and returns its ``RecordMetadata``; ``flush()`` drains
everything in flight. The memory transport resolves synchronously (the
broker append IS durability); the kafka adapter wraps the client's future.
Partitioning matches Kafka's default partitioner: explicit partition wins,
else key-hash, else round-robin.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Protocol, runtime_checkable

from torchkafka_tpu.errors import ProducerClosedError, TransactionStateError
from torchkafka_tpu.source.memory import InMemoryBroker
from torchkafka_tpu.source.records import Record, TopicPartition

_logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True, slots=True)
class RecordMetadata:
    """Where a produced record landed (Kafka's RecordMetadata analog)."""

    topic: str
    partition: int
    offset: int


class SendHandle(Protocol):
    def get(self, timeout_s: float | None = None) -> RecordMetadata: ...


@dataclasses.dataclass(frozen=True, slots=True)
class _ResolvedSend:
    """A send that was durable the moment it returned (memory transport)."""

    metadata: RecordMetadata

    def get(self, timeout_s: float | None = None) -> RecordMetadata:
        return self.metadata


@runtime_checkable
class Producer(Protocol):
    def send(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        partition: int | None = None,
        timestamp_ms: int | None = None,
        headers: tuple[tuple[str, bytes], ...] = (),
    ) -> SendHandle: ...

    def flush(self, timeout_s: float | None = None) -> None: ...

    def close(self) -> None: ...


class MemoryProducer:
    """Producer over ``InMemoryBroker`` — the hermetic twin of
    ``MemoryConsumer``. Appends are durable synchronously; partitioning
    (explicit / key-hash / round-robin) is the broker's, which mirrors
    Kafka's default partitioner."""

    def __init__(self, broker: InMemoryBroker) -> None:
        self._broker = broker
        self._closed = False

    def send(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        partition: int | None = None,
        timestamp_ms: int | None = None,
        headers: tuple[tuple[str, bytes], ...] = (),
    ) -> SendHandle:
        if self._closed:
            raise ProducerClosedError("producer is closed")
        rec = self._broker.produce(
            topic, value, key=key, partition=partition,
            timestamp_ms=timestamp_ms, headers=headers,
        )
        return _ResolvedSend(RecordMetadata(rec.topic, rec.partition, rec.offset))

    def flush(self, timeout_s: float | None = None) -> None:
        if self._closed:
            raise ProducerClosedError("producer is closed")
        # Synchronous appends: nothing is ever in flight.

    def close(self) -> None:
        self._closed = True


class TransactionalProducer:
    """Kafka-KIP-98-style transactional producer over an
    ``InMemoryBroker`` surface (the object itself or a ``BrokerClient``
    socket proxy — duck-typed alike).

    Construction calls ``init_producer_id(transactional_id)``: it
    acquires a producer id and an EPOCH, and — the fencing half — bumps
    the epoch past any previous holder of the same transactional id,
    aborting whatever transaction that incarnation left open. Two live
    handles can hold the same transactional id only transiently: the
    older one's next transactional call raises the terminal
    ``ProducerFencedError``.

    Cycle: ``begin()`` → ``send(...)``* / ``send_offsets(...)``* →
    ``commit()`` or ``abort()``. Records appended inside a transaction
    are invisible to ``read_committed`` consumers until ``commit()`` and
    are erased from their view forever by ``abort()``;
    ``send_offsets`` buffers consumer offsets that land atomically WITH
    the records — the consume-transform-produce loop's exactly-once
    primitive. ``send`` outside a transaction raises
    ``TransactionStateError`` (this producer has no non-transactional
    mode; use ``MemoryProducer`` for that).

    Error classes: ``ProducerFencedError`` is terminal for this handle
    (another incarnation owns the id — exit or re-init);
    ``CommitFailedError`` out of ``send_offsets``/``commit`` is terminal
    for the TRANSACTION but survivable for the caller (the broker
    aborted it atomically; re-serve and retry in a fresh transaction);
    transport faults surface as the retryable ``BrokerUnavailableError``
    exactly as on every other ``BrokerClient`` path. The named crash
    points (``txn_begin_post`` / ``txn_produce_mid`` / ``txn_pre_commit``
    / ``txn_post_commit_pre_ack``) fire HERE so every user of the class
    — the serving loop, the process fleet, the fuzz suite — pins the
    same death windows."""

    def __init__(self, broker, transactional_id: str) -> None:
        self._broker = broker
        self._txn_id = transactional_id
        self._closed = False
        self._in_txn = False
        self._pid, self._epoch = broker.init_producer_id(transactional_id)

    @property
    def transactional_id(self) -> str:
        return self._txn_id

    @property
    def producer_id(self) -> int:
        return self._pid

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def in_transaction(self) -> bool:
        return self._in_txn

    def _check_open(self) -> None:
        if self._closed:
            raise ProducerClosedError("producer is closed")

    def begin(self) -> None:
        self._check_open()
        from torchkafka_tpu.resilience.crashpoint import crash_hook

        self._broker.begin_txn(self._pid, self._epoch)
        self._in_txn = True
        # Transaction open on the broker, nothing produced: death here
        # must leave no trace once the next incarnation's init aborts it.
        crash_hook("txn_begin_post")

    def send(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        partition: int | None = None,
        timestamp_ms: int | None = None,
        headers: tuple[tuple[str, bytes], ...] = (),
    ) -> SendHandle:
        self._check_open()
        if not self._in_txn:
            raise TransactionStateError(
                "send outside a transaction; call begin() first "
                "(TransactionalProducer has no non-transactional mode)"
            )
        from torchkafka_tpu.resilience.crashpoint import crash_hook

        rec = self._broker.txn_produce(
            self._pid, self._epoch, topic, value, key=key,
            partition=partition, timestamp_ms=timestamp_ms, headers=headers,
        )
        # Some of the window's records are in the transaction, the rest
        # never will be: death here must surface NONE of them committed.
        crash_hook("txn_produce_mid")
        return _ResolvedSend(RecordMetadata(rec.topic, rec.partition, rec.offset))

    def send_offsets(
        self,
        group_id: str,
        offsets: Mapping[TopicPartition, int],
        *,
        member_id: str | None = None,
        generation: int | None = None,
    ) -> None:
        """Buffer consumer offsets into the open transaction (they
        commit atomically with its records). ``member_id``/``generation``
        are the consumer's group metadata — present them so the offset
        half is generation-fenced exactly like a plain commit; omit for
        standalone (manual-assignment) consumers."""
        self._check_open()
        if not self._in_txn:
            raise TransactionStateError(
                "send_offsets outside a transaction; call begin() first"
            )
        self._broker.txn_commit_offsets(
            self._pid, self._epoch, group_id, dict(offsets),
            member_id=member_id, generation=generation,
        )

    def commit(self) -> None:
        """Atomically commit records + offsets. On ``CommitFailedError``
        the broker has ALREADY aborted the transaction (atomicity is
        total); this handle's state reflects that — a fresh ``begin()``
        starts clean."""
        self._check_open()
        if not self._in_txn:
            raise TransactionStateError("no transaction to commit")
        from torchkafka_tpu.errors import CommitFailedError
        from torchkafka_tpu.resilience.crashpoint import crash_hook

        # Records + offsets all staged, the atomic flip not yet asked
        # for: death here aborts at recovery — outputs must re-serve.
        crash_hook("txn_pre_commit")
        try:
            self._broker.commit_txn(self._pid, self._epoch)
        except CommitFailedError:
            self._in_txn = False  # broker aborted it atomically
            raise
        except TransactionStateError:
            # The broker has no open transaction for this epoch and no
            # committed ``last`` outcome to answer idempotently: a broker
            # that died and RECOVERED mid-cycle aborted it (begin with no
            # commit marker). Terminal for the transaction, survivable
            # for the caller — same contract as CommitFailedError: this
            # handle's state heals so a fresh begin() re-sends the work.
            self._in_txn = False
            raise
        self._in_txn = False
        # Committed ON the broker, the ack not yet observed by the
        # caller: death here must NOT re-publish at recovery — the
        # committed view already has exactly one copy, and the offsets
        # already moved, so nothing re-delivers.
        crash_hook("txn_post_commit_pre_ack")

    def abort(self) -> bool:
        """Abort the open transaction (no-op returning False when none
        is open — recovery paths abort defensively)."""
        self._check_open()
        if not self._in_txn:
            return False
        self._in_txn = False
        return self._broker.abort_txn(self._pid, self._epoch)

    def flush(self, timeout_s: float | None = None) -> None:
        self._check_open()
        # Broker RPCs are synchronous: nothing is ever in flight. The
        # durability point is commit(), not flush — flushing mid-
        # transaction proves nothing about visibility.

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._in_txn:
            # Kafka's close() aborts an in-flight transaction; so here —
            # best-effort (a dead broker just leaves it for the next
            # incarnation's init fence to abort).
            try:
                self._broker.abort_txn(self._pid, self._epoch)
            except Exception:  # noqa: BLE001 - teardown best-effort
                _logger.debug("abort on close failed", exc_info=True)
            self._in_txn = False


def dead_letter_to_topic(
    producer: Producer, topic: str, *, timeout_s: float | None = 30.0,
    metrics=None, tracer=None,
):
    """Adapt a Producer into a ``KafkaStream(dead_letter=...)`` callback:
    poison records land on a quarantine topic with their provenance and
    the error in headers, key preserved (so compacted/keyed DLQ topics
    keep working).

    The callback BLOCKS on the send handle (``get(timeout_s)``): the
    poison record's offset retires into the source watermark the moment
    this returns, so the quarantine copy must be durable FIRST — an async
    fire-and-forget would let a broker-side send failure (or a crash
    before flush) lose the record permanently with the source already
    committed past it. Failures raise here and land in the stream's DLQ
    guard, which logs and swallows them — a broken DLQ must not take down
    ingest (pipeline/stream.py's dead_letter contract). To make a broken
    DLQ *observable* rather than stderr-only, pass ``metrics`` (an object
    with a ``dlq_delivery_failures`` RateMeter — ``StreamMetrics`` /
    ``ServeMetrics`` both carry one, exported on ``/metrics``) and/or
    ``tracer`` (an ``obs.RecordTracer``; a ``dlq_failed`` span event is
    emitted per failed produce): each failure is counted and traced HERE,
    at the only point that knows it happened, before it re-raises into
    the guard. Poison is rare by definition; the per-record ack
    round-trip is not a hot path."""

    def on_dead_letter(record: Record, exc: BaseException) -> None:
        try:
            producer.send(
                topic,
                record.value,
                key=record.key,
                headers=(
                    ("dlq.error", str(exc).encode()),
                    ("dlq.topic", record.topic.encode()),
                    ("dlq.partition", str(record.partition).encode()),
                    ("dlq.offset", str(record.offset).encode()),
                ),
            ).get(timeout_s)
        except Exception:
            if metrics is not None:
                metrics.dlq_delivery_failures.add(1)
            if tracer is not None:
                tracer.dlq_failed(record)
            raise

    return on_dead_letter

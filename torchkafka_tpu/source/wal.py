"""Segmented, CRC-framed write-ahead log: broker durability.

Every other process in the tree is already a crash-recoverable
participant — workers SIGKILL mid-decode and resume warm from journals,
transactions abort on epoch fences — but the broker those guarantees
route through was fully volatile: kill the supervisor's
``InMemoryBroker`` and every topic, offset watermark, membership
generation, and open transaction vanished, voiding the exactly-once
contract FAILOVER_BENCH just asserted. This module is the durability
substrate that closes that hole: an append-only event log the broker
writes BEFORE acknowledging state changes and replays at construction
(Kafka's own story — the log IS the broker; KIP-98 commit/abort markers
live in the same log as the records they settle).

Format. A log is a directory of segments ``wal-<n>.log``; each segment
is a sequence of frames::

    [u32 length][u32 crc32(payload)][payload]

with the payload a pickled ``(kind, dict)`` event (trusted local file —
the same payload discipline as the netbroker's trusted socket). A torn
tail — a frame whose length header, body, or CRC is incomplete because
the writer died mid-append — is DETECTED (short read or CRC mismatch)
and TRUNCATED at recovery: the log's authoritative content is the
longest clean frame prefix, and a torn frame is never replayed (its
write was never acknowledged, so dropping it loses nothing that was
promised). Segments roll at ``segment_bytes`` so recovery tooling and
retention can reason about bounded files.

Durability discipline (``durability=``):

- ``"commit"`` — fsync after EVERY append: survives machine power loss
  at per-append cost (Kafka's ``flush.messages=1``).
- ``"batch"`` — fsync only on COMMIT-class appends (offset commits,
  transaction commit/abort markers, producer inits): the produces of a
  window ride their window's commit fsync — the classic group-commit
  amortization.
- ``None`` — never fsync. Appends still hit the kernel page cache via
  unbuffered ``write()``, so a SIGKILLed *process* loses nothing — only
  a machine crash can eat the tail. This is the honest floor the WAL-tax
  bench measures against.

Crash points ``wal_append_mid`` (death between the two halves of a
frame's body — the torn-tail generator) and ``wal_pre_fsync`` (frame
written, fsync pending) pin the windows the recovery contract is sworn
against; the broker-side markers (``txn_marker_*``) live in
source/memory.py where the commit decision is made.
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib
from dataclasses import dataclass, field

from torchkafka_tpu.resilience.crashpoint import crash_hook

_HEADER = struct.Struct(">II")  # (payload length, crc32(payload))

#: Appends of these kinds are the durability points ``durability="batch"``
#: fsyncs on — everything appended since the last one rides the same sync.
COMMIT_KINDS = frozenset({"commit", "txn_commit", "txn_abort", "init_pid"})

DURABILITIES = (None, "batch", "commit")


@dataclass
class WalStats:
    appends: int = 0
    bytes_written: int = 0
    fsyncs: int = 0
    truncated_bytes: int = 0  # torn tail repaired away at recovery
    segments: int = 0
    replayed_events: int = 0


@dataclass
class _Segment:
    path: str
    index: int
    size: int = field(default=0)


def _segment_name(index: int) -> str:
    return f"wal-{index:08d}.log"


def _list_segments(wal_dir: str) -> list[_Segment]:
    try:
        names = sorted(os.listdir(wal_dir))
    except FileNotFoundError:
        return []
    out = []
    for name in names:
        if not (name.startswith("wal-") and name.endswith(".log")):
            continue
        try:
            idx = int(name[4:-4])
        except ValueError:
            continue
        path = os.path.join(wal_dir, name)
        out.append(_Segment(path, idx, os.path.getsize(path)))
    out.sort(key=lambda s: s.index)
    return out


def _scan_segment(path: str) -> tuple[list[tuple[str, dict]], int]:
    """Parse one segment's clean frame prefix. Returns ``(events,
    clean_bytes)`` where ``clean_bytes`` is the offset of the first torn
    or corrupt frame (== file size when the segment is wholly clean).
    Never raises on damage — the clean prefix is the answer."""
    events: list[tuple[str, dict]] = []
    clean = 0
    with open(path, "rb") as f:
        data = f.read()
    n = len(data)
    pos = 0
    while pos + _HEADER.size <= n:
        length, crc = _HEADER.unpack_from(data, pos)
        body_end = pos + _HEADER.size + length
        if body_end > n:
            break  # torn tail: body incomplete
        payload = data[pos + _HEADER.size : body_end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break  # torn or corrupt frame: never replay past it
        try:
            kind, event = pickle.loads(payload)
        except Exception:  # noqa: BLE001 - CRC passed but payload bad:
            break  # treat as damage, stop at the clean prefix
        events.append((kind, event))
        clean = body_end
        pos = body_end
    return events, clean


def replay(wal_dir: str | os.PathLike, *, repair: bool = True):
    """Read a WAL directory's clean event prefix.

    Returns ``(events, truncated_bytes)``. Damage (a torn tail from a
    death inside ``append``, or external corruption) ends the replay at
    the last clean frame; with ``repair=True`` the damaged segment is
    truncated to its clean prefix and any LATER segments are removed, so
    the on-disk log and the replayed state agree and a subsequent
    recovery is idempotent. A missing directory is an empty log."""
    wal_dir = os.fspath(wal_dir)
    segments = _list_segments(wal_dir)
    events: list[tuple[str, dict]] = []
    truncated = 0
    for i, seg in enumerate(segments):
        seg_events, clean = _scan_segment(seg.path)
        events.extend(seg_events)
        if clean < seg.size:
            truncated = (seg.size - clean) + sum(
                s.size for s in segments[i + 1 :]
            )
            if repair:
                with open(seg.path, "ab") as f:
                    f.truncate(clean)
                for later in segments[i + 1 :]:
                    os.unlink(later.path)
            break
    return events, truncated


class WriteAheadLog:
    """Append side of the log. One writer per directory (the broker holds
    it under its own lock); recovery uses :func:`replay` first, then
    constructs this to continue appending after the clean tail."""

    def __init__(
        self,
        wal_dir: str | os.PathLike,
        *,
        durability: str | None = None,
        segment_bytes: int = 4 * 1024 * 1024,
        metrics=None,
    ) -> None:
        if durability not in DURABILITIES:
            raise ValueError(
                f"durability must be one of {DURABILITIES}, got "
                f"{durability!r}"
            )
        if segment_bytes < 1024:
            raise ValueError(
                f"segment_bytes must be >= 1024, got {segment_bytes}"
            )
        self.wal_dir = os.fspath(wal_dir)
        os.makedirs(self.wal_dir, exist_ok=True)
        self.durability = durability
        self.segment_bytes = segment_bytes
        self.stats = WalStats()
        self._metrics = metrics
        self._closed = False
        segments = _list_segments(self.wal_dir)
        if segments:
            tail = segments[-1]
            self._seg_index = tail.index
            self._seg_size = tail.size
        else:
            self._seg_index = 0
            self._seg_size = 0
        self.stats.segments = max(1, len(segments))
        # Unbuffered: every frame write is a kernel write() — a SIGKILL
        # after append() returns can never lose an acknowledged event,
        # fsync or not (only machine crash reaches the durability knob).
        self._fd = os.open(
            self._seg_path(self._seg_index),
            os.O_CREAT | os.O_WRONLY | os.O_APPEND,
            0o644,
        )

    def _seg_path(self, index: int) -> str:
        return os.path.join(self.wal_dir, _segment_name(index))

    @property
    def closed(self) -> bool:
        return self._closed

    def _roll(self) -> None:
        os.close(self._fd)
        self._seg_index += 1
        self._seg_size = 0
        self.stats.segments += 1
        self._fd = os.open(
            self._seg_path(self._seg_index),
            os.O_CREAT | os.O_WRONLY | os.O_APPEND,
            0o644,
        )

    def append(self, kind: str, event: dict) -> None:
        """Durably append one ``(kind, event)`` frame per the configured
        discipline. The two-part body write around ``wal_append_mid``
        pins the torn-frame window (a death there leaves a frame the
        CRC rejects — recovery truncates, never replays); the
        ``wal_pre_fsync`` window pins an appended-but-unsynced frame."""
        if self._closed:
            raise ValueError("write-ahead log is closed")
        payload = pickle.dumps((kind, event), protocol=pickle.HIGHEST_PROTOCOL)
        frame = _HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF)
        if self._seg_size and self._seg_size + len(frame) + len(payload) \
                > self.segment_bytes:
            self._roll()
        half = len(payload) // 2
        os.write(self._fd, frame + payload[:half])
        crash_hook("wal_append_mid")
        os.write(self._fd, payload[half:])
        crash_hook("wal_pre_fsync")
        if self.durability == "commit" or (
            self.durability == "batch" and kind in COMMIT_KINDS
        ):
            os.fsync(self._fd)
            self.stats.fsyncs += 1
            if self._metrics is not None:
                self._metrics.wal_fsyncs.add(1)
        nbytes = len(frame) + len(payload)
        self._seg_size += nbytes
        self.stats.appends += 1
        self.stats.bytes_written += nbytes
        if self._metrics is not None:
            self._metrics.wal_appends.add(1)
            self._metrics.wal_bytes_written.add(nbytes)

    def sync(self) -> None:
        """Unconditional fsync (clean-shutdown path)."""
        if not self._closed:
            os.fsync(self._fd)
            self.stats.fsyncs += 1
            if self._metrics is not None:
                self._metrics.wal_fsyncs.add(1)

    def total_bytes(self) -> int:
        """On-disk size of every segment (the recovery-curve x-axis)."""
        return sum(s.size for s in _list_segments(self.wal_dir))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            os.fsync(self._fd)
        except OSError:
            pass
        os.close(self._fd)

"""Source layer: transports implementing the Consumer/Producer protocols."""

from torchkafka_tpu.source.assignment import local_batch_size, partitions_for_process
from torchkafka_tpu.source.chaos import ChaosConsumer, ChaosProducer
from torchkafka_tpu.source.consumer import Consumer, seek_to_timestamp
from torchkafka_tpu.source.kafka import (
    HAVE_KAFKA_PYTHON,
    KafkaConsumer,
    KafkaProducer,
    KafkaTransactionalProducer,
)
from torchkafka_tpu.source.cluster import BrokerCell
from torchkafka_tpu.source.memory import InMemoryBroker, MemoryConsumer
from torchkafka_tpu.source.netbroker import (
    BrokerClient,
    BrokerServer,
    ChaosTransport,
    WireFaults,
)
from torchkafka_tpu.source.replication import (
    FollowerReplica,
    ReplicationConfig,
    Replicator,
)
from torchkafka_tpu.source.wal import WriteAheadLog
from torchkafka_tpu.source.producer import (
    MemoryProducer,
    Producer,
    RecordMetadata,
    TransactionalProducer,
    dead_letter_to_topic,
)
from torchkafka_tpu.source.records import Record, TopicPartition

__all__ = [
    "BrokerCell",
    "BrokerClient",
    "BrokerServer",
    "ChaosConsumer",
    "ChaosProducer",
    "ChaosTransport",
    "Consumer",
    "HAVE_KAFKA_PYTHON",
    "InMemoryBroker",
    "KafkaConsumer",
    "KafkaProducer",
    "KafkaTransactionalProducer",
    "MemoryConsumer",
    "MemoryProducer",
    "Producer",
    "TransactionalProducer",
    "RecordMetadata",
    "FollowerReplica",
    "ReplicationConfig",
    "Replicator",
    "dead_letter_to_topic",
    "seek_to_timestamp",
    "Record",
    "TopicPartition",
    "WireFaults",
    "WriteAheadLog",
    "local_batch_size",
    "partitions_for_process",
]

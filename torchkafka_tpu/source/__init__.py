"""Source layer: transports implementing the Consumer protocol."""

from torchkafka_tpu.source.assignment import local_batch_size, partitions_for_process
from torchkafka_tpu.source.chaos import ChaosConsumer
from torchkafka_tpu.source.consumer import Consumer, seek_to_timestamp
from torchkafka_tpu.source.kafka import HAVE_KAFKA_PYTHON, KafkaConsumer
from torchkafka_tpu.source.memory import InMemoryBroker, MemoryConsumer
from torchkafka_tpu.source.records import Record, TopicPartition

__all__ = [
    "ChaosConsumer",
    "Consumer",
    "HAVE_KAFKA_PYTHON",
    "InMemoryBroker",
    "KafkaConsumer",
    "MemoryConsumer",
    "seek_to_timestamp",
    "Record",
    "TopicPartition",
    "local_batch_size",
    "partitions_for_process",
]

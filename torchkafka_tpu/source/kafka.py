"""kafka-python transport adapter (optional dependency, gated import).

Wraps ``kafka.KafkaConsumer`` behind the framework's Consumer protocol. The
reference constructs KafkaConsumer directly and force-disables auto-commit
(/root/reference/src/kafka_dataset.py:188-206); we do the same here, and keep
the reference's kwargs-passthrough config philosophy (SURVEY.md §5): every
keyword argument flows verbatim to kafka-python except the forced override.

This module imports cleanly without kafka-python installed; constructing
``KafkaConsumer`` without it raises a clear error.
"""

from __future__ import annotations

from typing import Iterator, Mapping, Sequence

from torchkafka_tpu import errors
from torchkafka_tpu.source.consumer import ConsumerIterMixin
from torchkafka_tpu.source.records import Record, TopicPartition

try:  # pragma: no cover - exercised only where kafka-python is installed
    import kafka as _kafka
    import kafka.errors as _kafka_errors

    HAVE_KAFKA_PYTHON = True
except ImportError:  # pragma: no cover
    _kafka = None
    _kafka_errors = None
    HAVE_KAFKA_PYTHON = False


def _ktp(tp: TopicPartition):
    """Framework TopicPartition → kafka-python's (the one conversion)."""
    return _kafka.TopicPartition(tp.topic, tp.partition)


def _wrap_listener(listener):
    """User listeners receive FRAMEWORK TopicPartitions on both transports;
    kafka-python would hand its own type through, so translate here (and
    subclass its listener base, which subscribe() type-checks)."""
    base = getattr(_kafka, "ConsumerRebalanceListener", object)

    class _Adapter(base):  # type: ignore[misc, valid-type]
        def on_partitions_revoked(self, revoked):
            fn = getattr(listener, "on_partitions_revoked", None)
            if fn is not None:
                fn([TopicPartition(tp.topic, tp.partition) for tp in revoked])

        def on_partitions_assigned(self, assigned):
            fn = getattr(listener, "on_partitions_assigned", None)
            if fn is not None:
                fn([TopicPartition(tp.topic, tp.partition) for tp in assigned])

    return _Adapter()


def _offset_and_metadata(offset: int):
    """kafka-python 2.0.2's OffsetAndMetadata is (offset, metadata); newer
    releases added leader_epoch (/root/reference/setup.py:9 pins >=2.0.2, so
    both shapes exist in the wild)."""
    try:
        return _kafka.OffsetAndMetadata(offset, None, -1)
    except TypeError:
        return _kafka.OffsetAndMetadata(offset, None)


class KafkaConsumer(ConsumerIterMixin):
    """Consumer-protocol adapter over kafka-python.

    ``assignment=[TopicPartition(...), ...]`` selects manual (mesh-aligned)
    assignment via ``consumer.assign``; otherwise topics are subscribed and
    the broker's group protocol assigns partitions (the reference's mode).
    """

    def __init__(
        self,
        topics: str | Sequence[str] | None = None,
        *,
        pattern: str | None = None,
        assignment: Sequence[TopicPartition] | None = None,
        rebalance_listener=None,
        **kafka_kwargs,
    ) -> None:
        if not HAVE_KAFKA_PYTHON:  # pragma: no cover
            raise ImportError(
                "kafka-python is not installed; install it or use "
                "torchkafka_tpu.source.memory.MemoryConsumer"
            )
        # The invariant the whole framework exists for
        # (/root/reference/src/kafka_dataset.py:201): offsets are committed by
        # the commit barrier, never by a background auto-commit timer.
        kafka_kwargs["enable_auto_commit"] = False
        if kafka_kwargs.get("group_id") is None:
            # Same contract as MemoryConsumer (commits are per-group), and
            # it surfaces here as a clear error instead of kafka-python's
            # bare `assert group_id` at the first commit.
            raise ValueError("group_id is required (commits are per-group)")
        if pattern is not None and (topics is not None or assignment is not None):
            raise ValueError("pattern is exclusive with topics/assignment")
        if pattern is None and topics is None and assignment is None:
            # Same contract as the memory double: a consumer subscribed to
            # nothing would poll [] forever with no error.
            raise ValueError("one of topics, pattern, or assignment is required")
        topics = (
            []
            if topics is None
            else [topics] if isinstance(topics, str) else list(topics)
        )
        self._closed = False
        self._group_id = kafka_kwargs.get("group_id")
        self._any_paused = False  # O(1) hint for ConsumerIterMixin's hot loop
        # Iteration is built on poll() via ConsumerIterMixin, so the
        # iterator-ending timeout and the yielded-position tracking both live
        # here, not in kafka-python's own (unused) iterator.
        self._consumer_timeout_ms = kafka_kwargs.pop("consumer_timeout_ms", None)
        self._last_yielded: dict[TopicPartition, int] = {}
        if assignment is not None:
            if rebalance_listener is not None:
                raise ValueError(
                    "rebalance_listener is group-mode only (manual "
                    "assignment never rebalances)"
                )
            self._consumer = _kafka.KafkaConsumer(**kafka_kwargs)
            self._consumer.assign(
                [_ktp(tp) for tp in assignment]
            )
        elif pattern is not None:
            self._consumer = _kafka.KafkaConsumer(**kafka_kwargs)
            if rebalance_listener is not None:
                self._consumer.subscribe(
                    pattern=pattern, listener=_wrap_listener(rebalance_listener)
                )
            else:
                self._consumer.subscribe(pattern=pattern)
        elif rebalance_listener is not None:
            # Listener requires the explicit subscribe() path; topics in the
            # constructor would bypass it.
            self._consumer = _kafka.KafkaConsumer(**kafka_kwargs)
            self._consumer.subscribe(
                topics=topics, listener=_wrap_listener(rebalance_listener)
            )
        else:
            self._consumer = _kafka.KafkaConsumer(*topics, **kafka_kwargs)

    @staticmethod
    def _to_record(r) -> Record:
        return Record(
            topic=r.topic,
            partition=r.partition,
            offset=r.offset,
            value=r.value,
            key=r.key,
            timestamp_ms=r.timestamp,
            headers=tuple(r.headers or ()),
        )

    def _check_open(self) -> None:
        """Same closed-consumer contract as the memory double (and the
        transport-conformance suite): a closed consumer refuses the whole
        surface with ConsumerClosedError instead of leaking kafka-python's
        post-close behavior."""
        if self._closed:
            raise errors.ConsumerClosedError("consumer is closed")

    def poll(self, max_records: int = 500, timeout_ms: int = 0) -> list[Record]:
        self._check_open()
        batches = self._consumer.poll(timeout_ms=timeout_ms, max_records=max_records)
        out: list[Record] = []
        for recs in batches.values():
            out.extend(self._to_record(r) for r in recs)
        return out

    def commit(self, offsets: Mapping[TopicPartition, int] | None = None) -> None:
        self._check_open()
        if offsets is None and self._last_yielded:
            # Iterator mode: commit the records handed to the user, NOT the
            # whole fetched buffer (poll() advanced kafka-python's position
            # past records still sitting in the mixin's buffer; committing
            # positions here would lose them on crash).
            offsets = dict(self._last_yielded)
        try:
            if offsets is None:
                self._consumer.commit()
            else:
                self._consumer.commit(
                    {
                        _ktp(tp):
                            _offset_and_metadata(off)
                        for tp, off in offsets.items()
                    }
                )
        except _kafka_errors.CommitFailedError as e:
            # Re-raise as the framework's transport-independent type; callers
            # treat it as non-fatal (/root/reference/src/kafka_dataset.py:131-135).
            raise errors.CommitFailedError(str(e)) from e

    def committed(self, tp: TopicPartition) -> int | None:
        self._check_open()
        return self._consumer.committed(_ktp(tp))

    @property
    def group_id(self) -> str | None:
        return self._group_id

    @property
    def member_id(self) -> str | None:
        """Group metadata parity with MemoryConsumer: None — on Kafka the
        transaction coordinator fences transactional offset commits
        broker-side, so the client presents only the group id."""
        return None

    @property
    def generation(self) -> int | None:
        return None

    def position(self, tp: TopicPartition) -> int:
        self._check_open()
        return self._consumer.position(_ktp(tp))

    def seek(self, tp: TopicPartition, offset: int) -> None:
        self._check_open()
        self._consumer.seek(_ktp(tp), offset)

    def assignment(self) -> list[TopicPartition]:
        self._check_open()
        return [TopicPartition(tp.topic, tp.partition) for tp in self._consumer.assignment()]

    def offsets_for_times(
        self, times: Mapping[TopicPartition, int]
    ) -> dict[TopicPartition, int | None]:
        found = self._consumer.offsets_for_times(
            {
                _ktp(tp): int(ts)
                for tp, ts in times.items()
            }
        )
        # kafka-python returns {ktp: OffsetAndTimestamp | None}.
        return {
            TopicPartition(ktp.topic, ktp.partition):
                (None if ot is None else int(ot.offset))
            for ktp, ot in found.items()
        }

    def end_offsets(self, tps: Sequence[TopicPartition]) -> dict[TopicPartition, int]:
        ends = self._consumer.end_offsets([_ktp(tp) for tp in tps])
        return {
            TopicPartition(ktp.topic, ktp.partition): int(off)
            for ktp, off in ends.items()
        }

    def lag(self) -> dict[TopicPartition, int]:
        """Per-assigned-partition lag: end offset minus position."""
        tps = self.assignment()
        ends = self.end_offsets(tps)
        return {
            tp: max(0, ends[tp] - self.position(tp)) for tp in tps
        }

    def _check_assigned(self, tps) -> None:
        """Match the memory double's contract (NotAssignedError) instead of
        leaking kafka-python's internal KeyError/IllegalStateError."""
        stray = set(tps) - set(self.assignment())
        if stray:
            raise errors.NotAssignedError(f"not assigned: {sorted(stray)}")

    def pause(self, *tps: TopicPartition) -> None:
        self._check_open()
        self._check_assigned(tps)
        self._consumer.pause(*(_ktp(tp) for tp in tps))
        self._any_paused = True

    def resume(self, *tps: TopicPartition) -> None:
        self._check_open()
        self._check_assigned(tps)
        self._consumer.resume(*(_ktp(tp) for tp in tps))
        # Recompute rather than clear: a partial resume may leave others
        # paused. Rebalances can also drop paused partitions underneath us,
        # so the flag is conservative (may say True when nothing is paused —
        # has_paused callers then pay one full paused() and see the truth).
        self._any_paused = bool(self._consumer.paused())

    def paused(self) -> list[TopicPartition]:
        return sorted(
            TopicPartition(tp.topic, tp.partition) for tp in self._consumer.paused()
        )

    def has_paused(self) -> bool:
        return self._any_paused

    def heartbeat(self) -> None:
        """Interface parity with ``MemoryConsumer.heartbeat``: kafka-python
        maintains the group heartbeat on its own background thread (the
        broker's real session.timeout.ms reaper does the fencing), so the
        explicit renewal is a no-op here — the call exists so fleet code
        written against the memory transport runs unchanged on Kafka."""
        return None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # autocommit=False: never commit on teardown — uncommitted work must
        # be re-delivered (/root/reference/src/kafka_dataset.py:89).
        self._consumer.close(autocommit=False)

    def __iter__(self) -> Iterator[Record]:
        return super().__iter__()


class _KafkaSendHandle:
    """Wraps kafka-python's FutureRecordMetadata behind SendHandle.get."""

    def __init__(self, future) -> None:
        self._future = future

    def get(self, timeout_s: float | None = None):
        from torchkafka_tpu.source.producer import RecordMetadata

        md = self._future.get(timeout=timeout_s)
        return RecordMetadata(md.topic, md.partition, md.offset)


class KafkaProducer:
    """Producer-protocol adapter over kafka-python's KafkaProducer.

    Same kwargs-passthrough philosophy as the consumer adapter: every
    keyword flows verbatim to the client. ``send`` returns a handle whose
    ``get`` blocks until the broker acks (at the client's configured
    ``acks`` level) — pair with ``flush()`` before committing consumer
    offsets when producing derived records (the classic consume-transform-
    produce ordering: derived records durable BEFORE the source offsets
    commit, so a crash re-derives rather than loses).
    """

    def __init__(self, **kafka_kwargs) -> None:
        if not HAVE_KAFKA_PYTHON:  # pragma: no cover
            raise ImportError(
                "kafka-python is not installed; install it or use "
                "torchkafka_tpu.source.producer.MemoryProducer"
            )
        self._closed = False
        self._producer = _kafka.KafkaProducer(**kafka_kwargs)

    def send(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        partition: int | None = None,
        timestamp_ms: int | None = None,
        headers: tuple[tuple[str, bytes], ...] = (),
    ) -> _KafkaSendHandle:
        if self._closed:
            raise errors.ProducerClosedError("producer is closed")
        fut = self._producer.send(
            topic,
            value=value,
            key=key,
            partition=partition,
            timestamp_ms=timestamp_ms,
            # kafka-python takes list[(str, bytes)]; None when absent
            # (older client versions reject an empty list on old brokers).
            headers=list(headers) or None,
        )
        return _KafkaSendHandle(fut)

    def flush(self, timeout_s: float | None = None) -> None:
        if self._closed:
            raise errors.ProducerClosedError("producer is closed")
        self._producer.flush(timeout=timeout_s)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._producer.close()


def _fenced_error_types():
    """kafka-python's producer-fencing error classes, where they exist
    (the transactional API landed in kafka-python 2.1; older releases
    have neither the methods nor the errors)."""
    return tuple(
        t for t in (
            getattr(_kafka_errors, "ProducerFenced", None),
            getattr(_kafka_errors, "ProducerFencedError", None),
            getattr(_kafka_errors, "InvalidProducerEpochError", None),
        ) if t is not None
    )


class KafkaTransactionalProducer:
    """``TransactionalProducer``'s surface mapped onto kafka-python's
    NATIVE transactional API (KafkaProducer(transactional_id=...) +
    init_transactions/begin_transaction/send_offsets_to_transaction/
    commit_transaction/abort_transaction) — Kafka's own EOS does the
    heavy lifting; this adapter only translates types: framework
    ``TopicPartition`` offsets cross into kafka-python's, and the
    client's fencing errors surface as the framework's terminal
    ``ProducerFencedError`` so callers classify identically on every
    transport. Requires kafka-python >= 2.1 (the release that grew
    transactions); constructing on an older client raises a clear error
    rather than failing method-by-method."""

    def __init__(self, transactional_id: str, **kafka_kwargs) -> None:
        if not HAVE_KAFKA_PYTHON:  # pragma: no cover
            raise ImportError(
                "kafka-python is not installed; install it or use "
                "torchkafka_tpu.source.producer.TransactionalProducer "
                "over an InMemoryBroker/BrokerClient"
            )
        if not hasattr(_kafka.KafkaProducer, "init_transactions"):
            raise RuntimeError(
                "this kafka-python has no transactional API "
                "(init_transactions et al. landed in 2.1); upgrade the "
                "client to use KafkaTransactionalProducer"
            )
        self._closed = False
        self._in_txn = False
        self._txn_id = transactional_id
        self._producer = _kafka.KafkaProducer(
            transactional_id=transactional_id, **kafka_kwargs
        )
        self._translate(self._producer.init_transactions)

    def _translate(self, fn, *args, **kwargs):
        fenced = _fenced_error_types()
        try:
            return fn(*args, **kwargs)
        except fenced as e:  # pragma: no cover - needs a live broker race
            raise errors.ProducerFencedError(str(e)) from e
        except _kafka_errors.CommitFailedError as e:  # pragma: no cover
            raise errors.CommitFailedError(str(e)) from e

    @property
    def transactional_id(self) -> str:
        return self._txn_id

    @property
    def in_transaction(self) -> bool:
        return self._in_txn

    def _check_open(self) -> None:
        if self._closed:
            raise errors.ProducerClosedError("producer is closed")

    def begin(self) -> None:
        self._check_open()
        self._translate(self._producer.begin_transaction)
        self._in_txn = True

    def send(
        self,
        topic: str,
        value: bytes,
        *,
        key: bytes | None = None,
        partition: int | None = None,
        timestamp_ms: int | None = None,
        headers: tuple[tuple[str, bytes], ...] = (),
    ) -> _KafkaSendHandle:
        self._check_open()
        if not self._in_txn:
            raise errors.TransactionStateError(
                "send outside a transaction; call begin() first"
            )
        fut = self._translate(
            self._producer.send, topic, value=value, key=key,
            partition=partition, timestamp_ms=timestamp_ms,
            headers=list(headers) or None,
        )
        return _KafkaSendHandle(fut)

    def send_offsets(
        self,
        group_id: str,
        offsets: Mapping[TopicPartition, int],
        *,
        member_id: str | None = None,
        generation: int | None = None,
    ) -> None:
        """``member_id``/``generation`` are accepted for surface parity
        and ignored: kafka-python's send_offsets_to_transaction carries
        the group id, and the BROKER's transaction coordinator does the
        generation fencing (the memory transport checks in-process)."""
        self._check_open()
        if not self._in_txn:
            raise errors.TransactionStateError(
                "send_offsets outside a transaction; call begin() first"
            )
        converted = {
            _ktp(tp): _offset_and_metadata(off) for tp, off in offsets.items()
        }
        self._translate(
            self._producer.send_offsets_to_transaction, converted, group_id
        )

    def commit(self) -> None:
        self._check_open()
        if not self._in_txn:
            raise errors.TransactionStateError("no transaction to commit")
        try:
            self._translate(self._producer.commit_transaction)
        finally:
            self._in_txn = False

    def abort(self) -> bool:
        self._check_open()
        if not self._in_txn:
            return False
        try:
            self._translate(self._producer.abort_transaction)
        finally:
            self._in_txn = False
        return True

    def flush(self, timeout_s: float | None = None) -> None:
        self._check_open()
        self._producer.flush(timeout=timeout_s)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._in_txn:  # pragma: no cover - teardown best-effort
            try:
                self._producer.abort_transaction()
            except Exception:  # noqa: BLE001
                pass
            self._in_txn = False
        self._producer.close()

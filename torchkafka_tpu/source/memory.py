"""In-memory Kafka-semantics broker and consumer.

The reference has no test double at all (SURVEY.md §4: no tests anywhere in
the tree); every commit-ordering behavior it implements is only exercisable
against a live broker. This module is the seam SURVEY.md §4 calls for: a
faithful in-process implementation of the consumer surface the framework uses
— partitioned logs, consumer groups with generation-checked commits,
rebalance-on-membership-change, at-least-once re-delivery — so the entire
commit path is testable and benchmarkable hermetically (the environment has
no network egress and no broker).

Semantics mirrored from the Kafka group protocol (behavior the reference
depends on implicitly via kafka-python):

- Partitions of subscribed topics are range-assigned across the group's
  members; any join/leave bumps the group *generation* and reassigns.
  This is the mechanism behind the reference's data-parallel sharding
  (/root/reference/src/kafka_dataset.py:208-233 — one consumer per DataLoader
  worker, disjoint partitions each).
- A commit carrying a stale generation (i.e. issued after a rebalance took
  the partitions away) raises CommitFailedError — exactly the error the
  reference swallows as non-fatal (/root/reference/src/kafka_dataset.py:131-135).
- Committed offsets are the group's durable resume state: a new consumer in
  the same group starts at the committed offset (the reference's
  checkpoint/resume story, SURVEY.md §5).

``commit_log_path`` additionally appends every successful commit as a JSON
line; this makes commits observable across forked processes, which is how the
torch-DataLoader compat path is tested.
"""

from __future__ import annotations

import itertools
import json
import logging
import re
import threading
import time
import zlib
from typing import Any, Iterable, Mapping, Sequence

from torchkafka_tpu.errors import (
    CommitFailedError,
    ConsumerClosedError,
    FencedMemberError,
    NotAssignedError,
    ProducerFencedError,
    TransactionStateError,
    UnknownTopicError,
)
from torchkafka_tpu.source.consumer import ConsumerIterMixin
from torchkafka_tpu.source.records import Record, TopicPartition
from torchkafka_tpu.source import wal as _wal

_member_counter = itertools.count()


class _Group:
    """One consumer group: membership, generation, assignment, offsets."""

    def __init__(self, gid: str = "") -> None:
        self.gid = gid
        self.generation = 0
        # member_id -> subscription: a frozenset of topic names, or a
        # compiled regex (pattern subscription) resolved at rebalance time
        self.members: dict[str, "frozenset[str] | re.Pattern"] = {}
        self.assignment: dict[str, list[TopicPartition]] = {}
        self.committed: dict[TopicPartition, int] = {}
        # member_id -> lease expiry deadline (broker clock). Populated only
        # when the broker has a session timeout; renewed by heartbeat().
        self.leases: dict[str, float] = {}
        # Members evicted by lease expiry or an explicit fence() — kept so
        # a zombie's later heartbeat gets FencedMemberError (Kafka's
        # UNKNOWN_MEMBER_ID) rather than a confusing KeyError.
        self.fenced: set[str] = set()
        self.fence_count = 0


class _Txn:
    """One in-flight transaction: the records appended under it (by log
    position) and the offset commits buffered to land atomically with
    them."""

    __slots__ = ("seq", "records", "offsets")

    def __init__(self, seq: int) -> None:
        self.seq = seq
        self.records: list[tuple[TopicPartition, int]] = []
        # group_id -> (offsets, member_id, generation); last write per
        # group wins (Kafka's sendOffsetsToTransaction semantics).
        self.offsets: dict[str, tuple[dict, str | None, int | None]] = {}


class _TxnProducer:
    """Broker-side state for one ``transactional.id``: the current
    producer id + epoch, the open transaction (if any), and the last
    completed outcome (for idempotent commit retries)."""

    __slots__ = ("txn_id", "pid", "epoch", "open", "last")

    def __init__(self, txn_id: str, pid: int) -> None:
        self.txn_id = txn_id
        self.pid = pid
        self.epoch = 0
        self.open: _Txn | None = None
        self.last: tuple[int, str] | None = None  # (epoch, outcome)


class InMemoryBroker:
    """Thread-safe partitioned log store with consumer-group semantics."""

    def __init__(
        self,
        commit_log_path: str | None = None,
        *,
        session_timeout_s: float | None = None,
        clock=None,
        wal_dir: str | None = None,
        wal_durability: str | None = "batch",
        wal_segment_bytes: int = 4 * 1024 * 1024,
    ) -> None:
        """``session_timeout_s``: opt-in heartbeat leases for group
        members (None, the default, preserves lease-free semantics —
        membership changes only via join/leave). With a timeout set,
        ``join`` grants each member a lease that only ``heartbeat``
        renews; a member whose lease expires is FENCED — evicted with a
        rebalance — the next time any group-mutating traffic arrives
        (another member's heartbeat or join, its own commit, or an
        explicit ``fence``). Fencing on the zombie's own COMMIT is the
        integrity half: a merely-slow member that missed heartbeats gets
        its commit rejected (records re-deliver), never merged.
        ``clock``: the lease clock (default ``time.monotonic``);
        injectable so lease tests run on a ``ManualClock``.

        ``wal_dir``: opt-in DURABILITY (None, the default, keeps the
        broker fully in-memory — nothing on disk, nothing recovered).
        With a directory set, every state change that the broker ever
        acknowledges — produced records, committed offset snapshots,
        group membership/generation mutations, transaction begin/commit/
        abort markers, producer-id inits — is appended to a segmented
        CRC-framed write-ahead log (source/wal.py) BEFORE the ack, and
        construction over a non-empty ``wal_dir`` RECOVERS: the log
        replays into identical topics/records/offsets/generations, a
        transaction with a begin but no commit marker is ABORTED (its
        producer's next ``init_producer_id`` already expects that — the
        epoch fence from the process fleet), the LSO recomputes so
        ``read_committed`` consumers never see a half-recovered
        transaction, and restored group members get FRESH leases (a
        member that is really dead just expires one session timeout
        later, exactly like any other silent peer). ``wal_durability``:
        the fsync discipline (``"commit"``/``"batch"``/None — see
        source/wal.py; process death never loses acknowledged events
        under any of them, only machine death reaches the knob)."""
        if session_timeout_s is not None and session_timeout_s <= 0:
            raise ValueError(
                f"session_timeout_s must be > 0 or None, got {session_timeout_s}"
            )
        from torchkafka_tpu.utils.metrics import BrokerMetrics

        self._lock = threading.RLock()
        self._data_arrived = threading.Condition(self._lock)
        self._logs: dict[TopicPartition, list[Record]] = {}
        self._topics: dict[str, int] = {}  # topic -> partition count
        self._groups: dict[str, _Group] = {}
        self._rr: dict[str, int] = {}  # per-topic round-robin produce cursor
        self._commit_log_path = commit_log_path
        self._session_timeout_s = session_timeout_s
        self._clock = clock if clock is not None else time.monotonic
        # Transactions (KIP-98 shape): producers keyed by transactional
        # id; per-partition side table mapping offset -> txn sequence for
        # TRANSACTIONAL records only (non-transactional records have no
        # entry and are stable the moment they append); txn sequence ->
        # lifecycle status. Records append to the real log immediately
        # (read_uncommitted sees them, like Kafka); the committed view is
        # computed by ``fetch_stable``.
        self._txn_producers: dict[str, _TxnProducer] = {}
        self._txn_by_pid: dict[int, _TxnProducer] = {}
        self._txn_pid_counter = itertools.count(1000)
        self._txn_seq_counter = itertools.count(1)
        self._txn_status: dict[int, str] = {}  # seq -> open|committed|aborted
        self._rec_txn: dict[TopicPartition, dict[int, int]] = {}
        self.metrics = BrokerMetrics()
        self.recovery_info: dict | None = None
        self._wal: _wal.WriteAheadLog | None = None
        # "quorum" is a CELL-level ack discipline, not a new fsync mode:
        # each replica keeps the default batch fsync locally and the ack
        # gate moves to the replicator (a mutation returns only once a
        # majority of replicas appended its frame). A bare broker with
        # no replicator attached just runs the local half — the cell
        # (source/cluster.py) attaches the quorum gate after recovery.
        self.wal_durability = wal_durability
        if wal_durability == "quorum":
            wal_durability = "batch"
        self.replicator = None
        if wal_dir is not None:
            self._recover_from_wal(
                wal_dir, wal_durability, wal_segment_bytes
            )

    # ------------------------------------------------------ WAL + recovery

    @property
    def wal(self) -> "_wal.WriteAheadLog | None":
        return self._wal

    def _wal_append(self, kind: str, event: dict) -> None:
        # The closed-WAL guard covers teardown stragglers (a drain-path
        # mutation landing after close()): in-memory semantics proceed,
        # durability is over — the broker is already being discarded.
        if self._wal is not None and not self._wal.closed:
            self._wal.append(kind, event)
        # Quorum gate: with a replicator attached, the local append is
        # only half the ack — ship() returns on majority and RAISES
        # otherwise, aborting the in-memory apply before the caller could
        # observe a mutation the cell cannot durably prove.
        rep = self.replicator
        if rep is not None:
            rep.ship(kind, event)

    def repl_ping(self) -> dict:
        """Leader-liveness probe for the cell's heartbeat loop: answers
        iff this broker's server is reachable, and reports the epoch it
        is serving under (0 for a bare, cell-less broker)."""
        rep = self.replicator
        return {
            "epoch": rep.epoch if rep is not None else 0,
            "frames": len(rep.log) if rep is not None else 0,
        }

    def close(self) -> None:
        """Flush + close the write-ahead log (clean shutdown; a crash
        skips this by definition and recovery covers it). No-op for the
        pure in-memory broker."""
        if self._wal is not None and not self._wal.closed:
            self._wal.close()

    def _recover_from_wal(
        self, wal_dir: str, durability: str | None, segment_bytes: int
    ) -> None:
        """Rebuild broker state from the log: replay the clean frame
        prefix (a torn tail is truncated, never replayed), settle every
        transaction the log left unsettled (begin without commit/abort →
        ABORT — its records drop out of the committed view and its
        buffered offsets vanish), advance the id counters past everything
        replayed, grant restored members fresh leases, then open the log
        for append and write the recovery abort markers so the on-disk
        log states what recovery decided."""
        from torchkafka_tpu.resilience.crashpoint import crash_hook

        t0 = time.perf_counter()
        events, truncated = _wal.replay(wal_dir, repair=True)
        replayed_records = 0
        for kind, event in events:
            # Recovery is read-only until the replay completes: a death
            # here leaves the log byte-identical, so the next recovery
            # reproduces the identical state (the crash matrix kills a
            # recovering broker exactly here to prove it).
            crash_hook("recovery_mid_replay")
            self._apply_wal_event(kind, event)
            if kind == "produce":
                replayed_records += 1
        aborted: list[tuple[str, int, int]] = []
        for st in self._txn_producers.values():
            if st.open is not None:
                self._txn_status[st.open.seq] = "aborted"
                st.last = (st.epoch, "aborted")
                aborted.append((st.txn_id, st.epoch, st.open.seq))
                st.open = None
        if self._txn_by_pid:
            self._txn_pid_counter = itertools.count(
                max(self._txn_by_pid) + 1
            )
        if self._txn_status:
            self._txn_seq_counter = itertools.count(
                max(self._txn_status) + 1
            )
        if self._session_timeout_s is not None:
            # Restored members get fresh leases dated from recovery: a
            # live worker's reconnecting heartbeat renews in time, a dead
            # one silently expires one session timeout later — the normal
            # fencing path, no special casing. This is what lets a
            # process fleet ride a broker restart without re-joining.
            now = self._clock()
            for g in self._groups.values():
                for m in g.members:
                    g.leases[m] = now + self._session_timeout_s
        else:
            # A lease-less broker has NO liveness protocol that could
            # ever reap a dead member: restored memberships would be
            # immortal ghosts squatting on their partitions. Kafka's own
            # coordinator failover makes members REJOIN; mirror that —
            # drop memberships (committed offsets keep, they are the
            # durable resume state) with one final rebalance per group,
            # so a pre-crash zombie's stale-generation commit still
            # bounces off the moved generation.
            for g in self._groups.values():
                if g.members:
                    g.members.clear()
                    g.leases.clear()
                    self._rebalance(g)
        recovery_ms = (time.perf_counter() - t0) * 1e3
        self._wal = _wal.WriteAheadLog(
            wal_dir, durability=durability, segment_bytes=segment_bytes,
            metrics=self.metrics,
        )
        for txn_id, epoch, seq in aborted:
            self._wal_append("txn_abort", {
                "txn_id": txn_id, "epoch": epoch, "seq": seq,
                "recovery": True,
            })
        m = self.metrics
        m.recoveries.add(1)
        m.recovery_replayed_events.add(len(events))
        m.recovery_replayed_records.add(replayed_records)
        m.recovery_aborted_txns.add(len(aborted))
        m.recovery_truncated_bytes.add(truncated)
        m.recovery_ms.set(recovery_ms)
        self.recovery_info = {
            "replayed_events": len(events),
            "replayed_records": replayed_records,
            "aborted_txns": len(aborted),
            "truncated_bytes": truncated,
            "recovery_ms": round(recovery_ms, 3),
        }

    def _apply_wal_event(self, kind: str, d: dict) -> None:
        """One replayed event → the same state mutation the original
        call made, minus re-logging and lease bookkeeping (leases are
        wall-clock state; recovery re-grants them wholesale). Raw-state
        application keeps replay byte-exact: record timestamps, offsets,
        the round-robin produce cursor, and group generations all come
        out identical to the pre-crash broker."""
        if kind == "topic":
            topic, parts = d["topic"], d["partitions"]
            self._topics[topic] = parts
            for p in range(parts):
                self._logs[TopicPartition(topic, p)] = []
            for g in self._groups.values():
                if any(
                    isinstance(sub, re.Pattern) and sub.match(topic)
                    for sub in g.members.values()
                ):
                    self._rebalance(g)
        elif kind == "produce":
            tp = TopicPartition(d["topic"], d["partition"])
            log = self._logs[tp]
            rec = Record(
                topic=d["topic"], partition=d["partition"],
                offset=len(log), value=d["value"], key=d["key"],
                timestamp_ms=d["ts"], headers=tuple(d["headers"]),
            )
            log.append(rec)
            if d.get("rr"):
                self._rr[d["topic"]] = d["partition"] + 1
            if d.get("seq") is not None:
                self._rec_txn.setdefault(tp, {})[rec.offset] = d["seq"]
        elif kind == "group":
            g = self._group(d["group"])
            member = d["member"]
            if d["op"] == "join":
                g.members[member] = (
                    re.compile(d["pattern"])
                    if d.get("pattern") is not None
                    else frozenset(d["topics"])
                )
                g.fenced.discard(member)
                self._rebalance(g)
            elif d["op"] == "leave":
                if member in g.members:
                    del g.members[member]
                    self._rebalance(g)
            elif d["op"] == "fence":
                if member in g.members:
                    del g.members[member]
                    g.fenced.add(member)
                    g.fence_count += 1
                    self._rebalance(g)
        elif kind == "commit":
            self._group(d["group"]).committed.update(d["offsets"])
        elif kind == "init_pid":
            st = self._txn_producers.get(d["txn_id"])
            if st is None:
                st = _TxnProducer(d["txn_id"], d["pid"])
                self._txn_producers[d["txn_id"]] = st
                self._txn_by_pid[st.pid] = st
            st.epoch = d["epoch"]
        elif kind == "txn_begin":
            st = self._txn_producers[d["txn_id"]]
            txn = _Txn(d["seq"])
            self._txn_status[txn.seq] = "open"
            st.open = txn
        elif kind == "txn_commit":
            self._txn_status[d["seq"]] = "committed"
            st = self._txn_producers.get(d["txn_id"])
            if st is not None:
                if st.open is not None and st.open.seq == d["seq"]:
                    st.open = None
                st.last = (d["epoch"], "committed")
            for gid, offsets in d["offsets"].items():
                self._group(gid).committed.update(offsets)
        elif kind == "txn_abort":
            self._txn_status[d["seq"]] = "aborted"
            st = self._txn_producers.get(d["txn_id"])
            if st is not None:
                if st.open is not None and st.open.seq == d["seq"]:
                    st.open = None
                st.last = (d["epoch"], "aborted")
        else:  # pragma: no cover - forward-compat guard
            logging.getLogger(__name__).warning(
                "ignoring unknown WAL event kind %r", kind
            )

    # ------------------------------------------------------------- topics

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            if topic in self._topics:
                raise ValueError(f"topic {topic!r} already exists")
            self._wal_append("topic", {
                "topic": topic, "partitions": partitions,
            })
            self._topics[topic] = partitions
            for p in range(partitions):
                self._logs[TopicPartition(topic, p)] = []
            # Pattern subscribers pick up matching NEW topics via a
            # rebalance (Kafka's metadata-refresh path).
            for g in self._groups.values():
                if any(
                    isinstance(sub, re.Pattern) and sub.match(topic)
                    for sub in g.members.values()
                ):
                    self._rebalance(g)

    def partitions_for(self, topic: str) -> int:
        with self._lock:
            if topic not in self._topics:
                raise UnknownTopicError(topic)
            return self._topics[topic]

    def produce(
        self,
        topic: str,
        value: bytes,
        key: bytes | None = None,
        partition: int | None = None,
        timestamp_ms: int | None = None,
        headers: tuple[tuple[str, bytes], ...] = (),
        _txn_seq: int | None = None,
    ) -> Record:
        """Append one record; partition chosen by explicit arg, key hash, or
        round-robin (Kafka's default partitioner behavior). ``_txn_seq``
        is internal (``txn_produce``): the owning transaction's sequence,
        journaled WITH the record so recovery restores the association."""
        with self._lock:
            n = self.partitions_for(topic)
            was_rr = partition is None and key is None
            if partition is None:
                if key is not None:
                    partition = zlib.crc32(key) % n
                else:
                    partition = self._rr.get(topic, 0) % n
                    self._rr[topic] = partition + 1
            if not 0 <= partition < n:
                raise ValueError(f"partition {partition} out of range for {topic!r}")
            tp = TopicPartition(topic, partition)
            log = self._logs[tp]
            ts = int(time.time() * 1000) if timestamp_ms is None else int(timestamp_ms)
            if log:
                # LogAppendTime semantics: timestamps are monotone per
                # partition (clamped, like a broker with its own clock) —
                # the invariant offset_for_time's bisect relies on.
                ts = max(ts, log[-1].timestamp_ms)
            rec = Record(
                topic=topic,
                partition=partition,
                offset=len(log),
                value=value,
                key=key,
                timestamp_ms=ts,
                headers=tuple(headers),
            )
            # Write-ahead: the record is durable before the append is
            # acknowledged (an unlogged append dies with the process and
            # was never acked — the producer's retry is the recovery).
            self._wal_append("produce", {
                "topic": topic, "partition": partition, "value": value,
                "key": key, "ts": ts, "headers": tuple(headers),
                "rr": was_rr, "seq": _txn_seq,
            })
            log.append(rec)
            if _txn_seq is not None:
                self._rec_txn.setdefault(tp, {})[rec.offset] = _txn_seq
            self._data_arrived.notify_all()
            return rec

    def produce_many(self, topic: str, values: Iterable[bytes], **kw) -> list[Record]:
        return [self.produce(topic, v, **kw) for v in values]

    def end_offset(self, tp: TopicPartition) -> int:
        with self._lock:
            if tp not in self._logs:
                raise UnknownTopicError(tp)
            return len(self._logs[tp])

    def fetch(self, tp: TopicPartition, offset: int, max_records: int) -> list[Record]:
        with self._lock:
            if tp not in self._logs:
                raise UnknownTopicError(tp)
            log = self._logs[tp]
            return log[offset : offset + max_records]

    def offset_for_time(self, tp: TopicPartition, timestamp_ms: int) -> int | None:
        """Earliest offset whose record timestamp >= ``timestamp_ms``; None
        if every record is older. Produce order gives monotone timestamps
        per partition (as Kafka's log-append time does), so bisect applies."""
        import bisect

        with self._lock:
            if tp not in self._logs:
                raise UnknownTopicError(tp)
            log = self._logs[tp]
            i = bisect.bisect_left(log, timestamp_ms, key=lambda r: r.timestamp_ms)
            return log[i].offset if i < len(log) else None

    # -------------------------------------------------------- transactions

    def init_producer_id(self, transactional_id: str) -> tuple[int, int]:
        """Register (or re-register) a transactional producer; returns
        ``(producer_id, epoch)``. Re-initializing an EXISTING
        transactional id is the fencing act (KIP-98): the epoch bumps —
        every operation still carrying the old epoch raises
        ``ProducerFencedError`` from here on — and any transaction the
        old epoch left open is ABORTED (its records drop out of the
        committed view, its buffered offsets are discarded). This is how
        a SIGKILLed producer's in-flight transaction dies: its successor
        (same transactional id — the process fleet keys it by replica
        INDEX, not incarnation) initializes, and the corpse's work
        vanishes atomically."""
        if not transactional_id:
            raise ValueError("transactional_id must be a non-empty string")
        with self._lock:
            st = self._txn_producers.get(transactional_id)
            if st is None:
                st = _TxnProducer(transactional_id, next(self._txn_pid_counter))
                self._txn_producers[transactional_id] = st
                self._txn_by_pid[st.pid] = st
            else:
                st.epoch += 1
                if st.open is not None:
                    self._abort_txn_locked(st)
            # Durable BEFORE the ack: the epoch fence must survive broker
            # death, or a recovered broker would let a SIGKILLed zombie's
            # stale epoch write again.
            self._wal_append("init_pid", {
                "txn_id": transactional_id, "pid": st.pid,
                "epoch": st.epoch,
            })
            return st.pid, st.epoch

    def _txn_state(self, producer_id: int, epoch: int) -> _TxnProducer:
        """Resolve + fence-check. Caller holds the lock."""
        st = self._txn_by_pid.get(producer_id)
        if st is None:
            raise ProducerFencedError(
                f"unknown producer id {producer_id} (never initialized, or "
                "forged); init_producer_id first"
            )
        if epoch != st.epoch:
            raise ProducerFencedError(
                f"producer {st.txn_id!r} epoch {epoch} is "
                f"{'stale' if epoch < st.epoch else 'from the future'} "
                f"(current {st.epoch}): another incarnation holds this "
                "transactional id; this handle is a zombie's"
            )
        return st

    def begin_txn(self, producer_id: int, epoch: int) -> None:
        """Open a transaction. If the SAME epoch already holds one open
        (a client that lost track after a transport fault mid-cycle),
        the stale transaction is aborted first and a fresh one opened —
        self-healing over strictness, since nothing of the old one could
        ever have committed without this epoch asking for it."""
        with self._lock:
            st = self._txn_state(producer_id, epoch)
            if st.open is not None:
                self._abort_txn_locked(st)
            txn = _Txn(next(self._txn_seq_counter))
            self._wal_append("txn_begin", {
                "txn_id": st.txn_id, "epoch": epoch, "seq": txn.seq,
            })
            self._txn_status[txn.seq] = "open"
            st.open = txn

    def txn_produce(
        self,
        producer_id: int,
        epoch: int,
        topic: str,
        value: bytes,
        key: bytes | None = None,
        partition: int | None = None,
        timestamp_ms: int | None = None,
        headers: tuple[tuple[str, bytes], ...] = (),
    ) -> Record:
        """Append one record UNDER the open transaction. The record lands
        in the real log immediately (``read_uncommitted`` consumers see
        it, as on Kafka) but stays invisible to ``read_committed``
        consumers until ``commit_txn`` — and vanishes from their view
        forever on abort."""
        with self._lock:
            st = self._txn_state(producer_id, epoch)
            if st.open is None:
                raise TransactionStateError(
                    f"producer {st.txn_id!r} has no open transaction; "
                    "begin_txn first"
                )
            rec = self.produce(
                topic, value, key=key, partition=partition,
                timestamp_ms=timestamp_ms, headers=headers,
                _txn_seq=st.open.seq,
            )
            st.open.records.append(
                (TopicPartition(rec.topic, rec.partition), rec.offset)
            )
            return rec

    def txn_commit_offsets(
        self,
        producer_id: int,
        epoch: int,
        group_id: str,
        offsets: Mapping[TopicPartition, int],
        member_id: str | None = None,
        generation: int | None = None,
    ) -> None:
        """Buffer consumer offsets INTO the open transaction — they
        become durable atomically with the transaction's records at
        ``commit_txn`` (Kafka's sendOffsetsToTransaction). Validated
        eagerly against the group (stale generation / fenced member /
        unowned partition raises ``CommitFailedError`` NOW, so the
        caller can abort instead of discovering it at commit) and
        re-validated atomically at commit time — a rebalance in between
        aborts the whole transaction, records included. Repeated calls
        for the same group replace the earlier buffer (last wins)."""
        with self._lock:
            st = self._txn_state(producer_id, epoch)
            if st.open is None:
                raise TransactionStateError(
                    f"producer {st.txn_id!r} has no open transaction; "
                    "begin_txn first"
                )
            g = self._group(group_id)
            self._validate_group_commit_locked(
                g, group_id, offsets, member_id, generation
            )
            st.open.offsets[group_id] = (dict(offsets), member_id, generation)

    def commit_txn(self, producer_id: int, epoch: int) -> None:
        """Atomically commit the open transaction: its records become
        visible to ``read_committed`` consumers AND its buffered offsets
        merge into the group watermark(s) — one outcome, never half.
        The offset validation re-runs HERE, inside the same lock that
        flips the records' status: if the group rebalanced since
        ``txn_commit_offsets`` (the member was fenced, the generation
        moved), the ENTIRE transaction aborts and ``CommitFailedError``
        raises — the records never reach the committed view, so the new
        partition owner's re-serve is the only copy (this is the
        exactly-once pivot). A retry of an already-committed transaction
        (transport fault ate the ack) is answered with success."""
        with self._lock:
            st = self._txn_state(producer_id, epoch)
            if st.open is None:
                if st.last == (epoch, "committed"):
                    return  # idempotent retry of an un-acked commit
                raise TransactionStateError(
                    f"producer {st.txn_id!r} has no open transaction to "
                    "commit"
                )
            txn = st.open
            try:
                for gid, (offsets, member_id, generation) in txn.offsets.items():
                    self._validate_group_commit_locked(
                        self._group(gid), gid, offsets, member_id, generation
                    )
            except CommitFailedError:
                # Atomicity means failure is total: records out too.
                self._abort_txn_locked(st)
                raise
            from torchkafka_tpu.resilience.crashpoint import crash_hook

            # The WAL marker IS the commit decision (KIP-98's transaction
            # marker): offsets validated, marker not yet durable — broker
            # death here recovers to an ABORTED transaction (begin with
            # no commit marker), nothing surfaces committed.
            crash_hook("txn_marker_pre_append")
            self._wal_append("txn_commit", {
                "txn_id": st.txn_id, "epoch": epoch, "seq": txn.seq,
                "offsets": {
                    gid: dict(offsets)
                    for gid, (offsets, _m, _g) in txn.offsets.items()
                },
            })
            # Marker durable, memory state not yet flipped / ack not yet
            # sent: broker death here recovers to a COMMITTED transaction
            # (records + offsets atomic), and the producer's retry of
            # commit_txn is answered idempotently via the restored
            # ``last`` outcome.
            crash_hook("txn_marker_post_append_pre_ack")
            self._txn_status[txn.seq] = "committed"
            st.open = None
            st.last = (epoch, "committed")
            for gid, (offsets, member_id, generation) in txn.offsets.items():
                self._apply_commit_locked(gid, offsets, member_id, log=False)
            # Committed records became readable below the (possibly
            # advanced) LSO: wake blocked read_committed pollers.
            self._data_arrived.notify_all()

    def abort_txn(self, producer_id: int, epoch: int) -> bool:
        """Abort the open transaction: its records drop out of the
        committed view permanently, its buffered offsets are discarded,
        and the group watermark is untouched. Idempotent — aborting with
        nothing open returns False (a recovery path must be free to
        abort defensively)."""
        with self._lock:
            st = self._txn_state(producer_id, epoch)
            if st.open is None:
                return False
            self._abort_txn_locked(st)
            return True

    def _abort_txn_locked(self, st: _TxnProducer) -> None:
        self._wal_append("txn_abort", {
            "txn_id": st.txn_id, "epoch": st.epoch, "seq": st.open.seq,
        })
        self._txn_status[st.open.seq] = "aborted"
        st.last = (st.epoch, "aborted")
        st.open = None
        # Aborted records stop gating the LSO: readers blocked on them
        # may now advance.
        self._data_arrived.notify_all()

    def last_stable_offset(self, tp: TopicPartition) -> int:
        """The partition's LSO: everything below it has a settled
        transactional fate (committed, aborted, or was never
        transactional). ``fetch_stable`` never reads at or past it —
        Kafka's read_committed ordering guarantee (a later record never
        surfaces before an earlier still-open transaction decides)."""
        with self._lock:
            if tp not in self._logs:
                raise UnknownTopicError(tp)
            return self._lso_locked(tp)

    def _lso_locked(self, tp: TopicPartition) -> int:
        lso = len(self._logs[tp])
        meta = self._rec_txn.get(tp)
        if meta:
            for off, seq in meta.items():
                if off < lso and self._txn_status[seq] == "open":
                    lso = off
        return lso

    def fetch_stable(
        self, tp: TopicPartition, offset: int, max_records: int
    ) -> tuple[list[Record], int]:
        """The read_committed fetch: records from ``offset`` with
        committed-or-non-transactional status, stopping at the LSO;
        aborted records are skipped (they hold their offsets but never
        surface). Returns ``(records, next_offset)`` — the consumer must
        resume from ``next_offset``, which advances over skipped aborted
        records (unlike plain ``fetch``, the record list alone cannot
        carry the position)."""
        with self._lock:
            if tp not in self._logs:
                raise UnknownTopicError(tp)
            log = self._logs[tp]
            meta = self._rec_txn.get(tp, {})
            lso = self._lso_locked(tp)
            out: list[Record] = []
            pos = max(0, offset)
            while pos < lso and len(out) < max_records:
                seq = meta.get(pos)
                if seq is None or self._txn_status[seq] == "committed":
                    out.append(log[pos])
                pos += 1
            return out, pos

    # -------------------------------------------------------------- groups

    def _group(self, group_id: str) -> _Group:
        return self._groups.setdefault(group_id, _Group(group_id))

    def _fence_locked(self, g: _Group, member_id: str) -> bool:
        """Evict one member (lease expiry or explicit fence) and
        rebalance. Returns True if the member was actually present.
        Caller holds the lock."""
        if member_id not in g.members:
            return False
        self._wal_append("group", {
            "op": "fence", "group": g.gid, "member": member_id,
        })
        del g.members[member_id]
        g.leases.pop(member_id, None)
        g.fenced.add(member_id)
        g.fence_count += 1
        self._rebalance(g)
        return True

    def _reap_locked(self, g: _Group) -> list[str]:
        """Fence every member whose lease has expired. Called from the
        group-MUTATING entry points (join/heartbeat/commit/fence) — read
        paths (group_state/membership) stay pure so a supervisor can
        OBSERVE an expired lease before anything acts on it (the
        ``lease_expired_pre_fence`` window). Caller holds the lock."""
        if self._session_timeout_s is None or not g.leases:
            return []
        now = self._clock()
        expired = [m for m, deadline in g.leases.items() if deadline <= now]
        for m in expired:
            self._fence_locked(g, m)
        return expired

    def join(
        self,
        group_id: str,
        member_id: str,
        topics: frozenset[str],
        pattern: str | None = None,
    ) -> int:
        """Add a member and rebalance; returns the new generation.

        ``pattern``: a regex subscribing the member to every topic whose
        name matches — unanchored ``re.match`` (prefix) semantics, the
        same matching kafka-python's ``subscribe(pattern=...)`` applies;
        anchor with ``$`` for exact names. Includes topics created LATER
        (create_topic triggers the rebalance, Kafka's metadata-refresh
        behavior)."""
        with self._lock:
            g = self._group(group_id)
            self._reap_locked(g)
            self._wal_append("group", {
                "op": "join", "group": group_id, "member": member_id,
                "topics": sorted(topics), "pattern": pattern,
            })
            g.members[member_id] = (
                re.compile(pattern) if pattern is not None else topics
            )
            # A re-join after fencing is a FRESH membership (Kafka's
            # rejoin-with-new-epoch): the fenced mark clears, the old
            # generation stays dead.
            g.fenced.discard(member_id)
            if self._session_timeout_s is not None:
                g.leases[member_id] = self._clock() + self._session_timeout_s
            self._rebalance(g)
            return g.generation

    def _member_topics(self, sub) -> set[str]:
        if isinstance(sub, re.Pattern):
            return {t for t in self._topics if sub.match(t)}
        return set(sub)

    def leave(self, group_id: str, member_id: str) -> None:
        with self._lock:
            g = self._group(group_id)
            if member_id in g.members:
                self._wal_append("group", {
                    "op": "leave", "group": group_id, "member": member_id,
                })
                del g.members[member_id]
                g.leases.pop(member_id, None)
                self._rebalance(g)

    def heartbeat(
        self, group_id: str, member_id: str, generation: int | None = None,
    ) -> int:
        """Renew ``member_id``'s lease; returns the CURRENT group
        generation so the caller can cheaply detect a rebalance. Any
        member's heartbeat also reaps peers with expired leases — the
        self-healing sweep that hands a SIGKILLed member's partitions to
        survivors without waiting for a supervisor. Raises
        ``FencedMemberError`` if the member itself was fenced (or never
        joined): the zombie learns it is dead instead of serving into the
        void. ``generation`` is advisory (diagnostics); lease renewal is
        keyed on identity, not generation — a member mid-rebalance-sync
        is alive, just behind."""
        with self._lock:
            g = self._group(group_id)
            self._reap_locked(g)
            if member_id not in g.members:
                raise FencedMemberError(
                    f"member {member_id!r} is not in group {group_id!r} "
                    "(lease expired or fenced); re-join to resume"
                )
            if self._session_timeout_s is not None:
                g.leases[member_id] = self._clock() + self._session_timeout_s
            return g.generation

    def fence(self, group_id: str, member_id: str) -> bool:
        """Explicitly evict a member (the supervisor's response to an
        observed lease expiry): rebalance hands its partitions to
        survivors, and its stale-generation commits are rejected from
        here on. Idempotent — fencing an already-gone member returns
        False. Also reaps any other expired leases while it is here."""
        with self._lock:
            g = self._group(group_id)
            fenced = self._fence_locked(g, member_id)
            self._reap_locked(g)
            return fenced

    def membership(self, group_id: str) -> dict:
        """Read-only membership snapshot for supervisors/observability:
        generation, member ids, per-member lease seconds REMAINING
        (negative = expired but not yet reaped; None when leases are
        off), and the cumulative fence count. Deliberately performs no
        reaping — observing an expired lease must not race the observer's
        own response to it."""
        with self._lock:
            g = self._group(group_id)
            now = self._clock()
            return {
                "generation": g.generation,
                "members": sorted(g.members),
                "leases": {
                    m: (
                        g.leases[m] - now if m in g.leases else None
                    )
                    for m in g.members
                },
                "session_timeout_s": self._session_timeout_s,
                "fenced": sorted(g.fenced),
                "fence_count": g.fence_count,
            }

    def _rebalance(self, g: _Group) -> None:
        """Range-assign every subscribed partition across members, bump generation.

        Deterministic: members sorted by id, partitions sorted by
        (topic, partition). A member that held partitions before the
        rebalance may lose them — its in-flight commit then fails with
        CommitFailedError, which is the re-delivery trigger."""
        g.generation += 1
        g.assignment = {m: [] for m in g.members}
        members = sorted(g.members)
        if not members:
            return
        resolved = {m: self._member_topics(g.members[m]) for m in members}
        topics = sorted({t for ts in resolved.values() for t in ts})
        all_tps = [
            TopicPartition(t, p)
            for t in topics
            for p in range(self._topics.get(t, 0))
        ]
        # Only members subscribed to a topic are eligible for its partitions.
        for t in topics:
            eligible = [m for m in members if t in resolved[m]]
            tps = [tp for tp in all_tps if tp.topic == t]
            for i, tp in enumerate(tps):
                g.assignment[eligible[i % len(eligible)]].append(tp)

    def group_state(self, group_id: str, member_id: str) -> tuple[int, list[TopicPartition]]:
        """Current (generation, assignment) for a member — polled by consumers
        to detect rebalances."""
        with self._lock:
            g = self._group(group_id)
            return g.generation, list(g.assignment.get(member_id, []))

    def commit(
        self,
        group_id: str,
        offsets: Mapping[TopicPartition, int],
        member_id: str | None = None,
        generation: int | None = None,
    ) -> None:
        """Durably record next-read offsets for a group.

        Group-managed members must present the generation they last synced;
        a stale generation or an unowned partition raises CommitFailedError
        (what Kafka raises after a rebalance). Standalone (manually-assigned)
        consumers pass member_id=None and skip the check, matching Kafka's
        ``assign()`` mode."""
        with self._lock:
            g = self._group(group_id)
            self._validate_group_commit_locked(
                g, group_id, offsets, member_id, generation
            )
            self._apply_commit_locked(group_id, offsets, member_id)

    def _validate_group_commit_locked(
        self, g: _Group, group_id: str, offsets, member_id, generation,
    ) -> None:
        """The generation/ownership discipline, shared by plain commits
        and transactional offset commits (validated at buffer time AND
        re-run atomically inside commit_txn). Caller holds the lock."""
        if member_id is None:
            return  # standalone (manual-assignment) mode skips the check
        # Lease discipline first: a member whose own lease lapsed
        # is fenced BY this very commit attempt — the "merely
        # slow" zombie gets a clean CommitFailedError (records
        # re-deliver to whoever owns the partitions now), never a
        # merged watermark.
        self._reap_locked(g)
        if member_id not in g.members:
            raise CommitFailedError(
                f"member {member_id!r} fenced/evicted from group "
                f"{group_id!r} (lease expired or rebalanced away); "
                "offsets not committed"
            )
        if generation != g.generation:
            raise CommitFailedError(
                f"generation {generation} != current {g.generation} "
                f"(group rebalanced); offsets not committed"
            )
        owned = set(g.assignment.get(member_id, []))
        stray = set(offsets) - owned
        if stray:
            raise CommitFailedError(f"partitions not owned: {sorted(stray)}")

    def _apply_commit_locked(
        self, group_id: str, offsets, member_id, log: bool = True,
    ) -> None:
        """``log=False``: the caller (``commit_txn``) already made the
        durability decision with its transaction marker — the offsets
        ride THAT frame, not a second one."""
        if log:
            self._wal_append("commit", {
                "group": group_id, "offsets": dict(offsets),
                "member": member_id,
            })
        self._group(group_id).committed.update(offsets)
        if self._commit_log_path:
            entry = {
                "group": group_id,
                "member": member_id,
                "offsets": {f"{tp.topic}:{tp.partition}": o for tp, o in offsets.items()},
                "ts": time.time(),
            }
            with open(self._commit_log_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(entry) + "\n")

    def committed(self, group_id: str, tp: TopicPartition) -> int | None:
        with self._lock:
            return self._group(group_id).committed.get(tp)

    # ------------------------------------------------------------- waiting

    def wait_for_data(self, timeout_s: float) -> None:
        """Block until any produce happens (or timeout). Used by polling
        consumers so empty polls don't spin."""
        with self._data_arrived:
            self._data_arrived.wait(timeout=timeout_s)


class MemoryConsumer(ConsumerIterMixin):
    """Consumer over an InMemoryBroker implementing the Consumer protocol.

    Two assignment modes, matching kafka-python's subscribe()/assign() split:

    - ``group-managed`` (default): join the group, receive a range assignment,
      commits are generation-checked. This is what the reference's per-worker
      consumers do (/root/reference/src/kafka_dataset.py:208-233).
    - ``manual``: pass ``assignment=[...]``; no group membership, commits are
      unchecked. This is the mesh-aligned mode used on TPU pods, where
      partition → jax.process_index() mapping is static (SURVEY.md §2 TPU
      equivalents table).

    Group mode also accepts ``pattern=`` (a regex; unanchored ``re.match``
    prefix semantics like kafka-python's ``subscribe(pattern=...)`` — add
    ``$`` for exact names) instead of explicit topics. The subscription
    covers matching topics created LATER too, via rebalance.

    Never auto-commits, by construction: there is no code path that commits
    except the explicit ``commit()`` — the invariant the reference enforces by
    forcing ``enable_auto_commit=False`` (/root/reference/src/kafka_dataset.py:201).
    """

    def __init__(
        self,
        broker: InMemoryBroker,
        topics: str | Sequence[str] | None = None,
        group_id: str | None = None,
        *,
        pattern: str | None = None,
        assignment: Sequence[TopicPartition] | None = None,
        auto_offset_reset: str = "earliest",
        member_id: str | None = None,
        consumer_timeout_ms: int | None = None,
        rebalance_listener: Any | None = None,
        isolation_level: str = "read_uncommitted",
    ) -> None:
        if auto_offset_reset not in ("earliest", "latest"):
            raise ValueError(f"auto_offset_reset must be earliest|latest, got {auto_offset_reset!r}")
        if isolation_level not in ("read_uncommitted", "read_committed"):
            raise ValueError(
                "isolation_level must be read_uncommitted|read_committed, "
                f"got {isolation_level!r}"
            )
        if group_id is None:
            # Loud, not a shared "" group: omitting group_id would silently
            # make unrelated consumers rebalance each other and share a
            # committed-offset namespace.
            raise ValueError("group_id is required (commits are per-group)")
        if pattern is not None and (topics is not None or assignment is not None):
            raise ValueError("pattern is exclusive with topics/assignment")
        if pattern is None and topics is None and assignment is None:
            raise ValueError("one of topics, pattern, or assignment is required")
        if rebalance_listener is not None and assignment is not None:
            # Same contract as the kafka adapter: manual assignment never
            # rebalances, so a listener there would silently never fire.
            raise ValueError(
                "rebalance_listener is group-mode only (manual assignment "
                "never rebalances)"
            )
        self._broker = broker
        if topics is not None:
            self._topics = frozenset([topics] if isinstance(topics, str) else topics)
        elif assignment is not None:
            # Assignment-only construction (the kafka adapter allows it too);
            # the topic set exists for the eager existence check below.
            self._topics = frozenset(tp.topic for tp in assignment)
        else:
            self._topics = frozenset()
        self._group_id = group_id
        self._auto_offset_reset = auto_offset_reset
        # "read_committed": polls go through ``fetch_stable`` — only
        # records whose transactional fate is COMMITTED (or that were
        # never transactional) are delivered, never past the LSO, with
        # aborted records silently skipped. The default preserves the
        # pre-transaction behavior byte-for-byte (plain ``fetch``).
        self._isolation = isolation_level
        self._closed = False
        self._positions: dict[TopicPartition, int] = {}
        self._fetch_rr = 0  # round-robin cursor across assigned partitions
        # kafka-python semantics: iteration (not poll) gives up after this
        # long with no records; None = iterate forever.
        self._consumer_timeout_ms = consumer_timeout_ms
        # Positions of records handed out via the iterator (see
        # ConsumerIterMixin): commit(None) prefers these over poll positions.
        self._last_yielded: dict[TopicPartition, int] = {}
        self._paused: set[TopicPartition] = set()
        # Object with optional on_partitions_revoked / on_partitions_assigned
        # methods (kafka-python's ConsumerRebalanceListener shape).
        self._rebalance_listener = rebalance_listener
        self._pending_initial_assign = rebalance_listener is not None

        # Topics must exist either way; surfaces config errors eagerly.
        for t in self._topics:
            broker.partitions_for(t)

        if assignment is not None:
            self._manual = True
            self._member_id = None
            self._generation: int | None = None
            self._assignment = list(assignment)
        else:
            self._manual = False
            self._member_id = member_id or f"member-{next(_member_counter)}"
            self._generation, self._assignment = 0, []
            self._generation = broker.join(
                self._group_id, self._member_id, self._topics, pattern=pattern
            )
            _, self._assignment = broker.group_state(self._group_id, self._member_id)

    # ---------------------------------------------------------------- state

    def _check_open(self) -> None:
        if self._closed:
            raise ConsumerClosedError("consumer is closed")

    def _sync_group(self) -> None:
        """Pick up a new assignment if the group rebalanced.

        Models Kafka's eager rebalance: ALL partitions are revoked and
        re-acquired, so every position re-resolves from the committed offset —
        anything fetched but uncommitted is re-delivered (at-least-once).
        A registered rebalance listener sees revoked(old) then
        assigned(new), in that order — the kafka-python
        ConsumerRebalanceListener contract; the revoked callback runs
        BEFORE local state clears, so it may still read positions (but a
        commit there can already fail generation-checked, exactly as a
        real broker mid-rebalance — re-delivery covers it)."""
        if self._manual:
            return
        gen, assign = self._broker.group_state(self._group_id, self._member_id)
        listener = self._rebalance_listener
        if self._pending_initial_assign:
            # The initial join's assigned callback fires on the first sync
            # AFTER construction (kafka-python's timing) — so a listener
            # holding a reference to this consumer can seek() in the hook.
            self._pending_initial_assign = False
            if listener is not None:
                self._call_listener(
                    listener, "on_partitions_assigned", self._assignment
                )
        if gen != self._generation:
            # Adopt the new generation BEFORE the revoked hook: a listener
            # that calls assignment()/lag()/pause() re-enters _sync_group,
            # and a stale generation there would recurse into the hooks
            # unboundedly. The hook still observes the OLD assignment and
            # positions — they are replaced after it returns.
            old, self._generation = list(self._assignment), gen
            if listener is not None:
                self._call_listener(listener, "on_partitions_revoked", old)
            self._assignment = assign
            self._positions.clear()
            self._last_yielded.clear()
            # Kafka clients rebuild partition state on reassignment: a
            # revoked-then-reacquired partition comes back UNpaused, and a
            # paused flag must never outlive the assignment that set it.
            self._paused.clear()
            if listener is not None:
                self._call_listener(listener, "on_partitions_assigned", assign)

    @staticmethod
    def _call_listener(listener, hook: str, tps) -> None:
        """A raising listener must not wedge the consumer mid-rebalance
        (kafka-python logs and continues the same way)."""
        fn = getattr(listener, hook, None)
        if fn is None:
            return
        try:
            fn(list(tps))
        except Exception:  # noqa: BLE001 - listener errors are not ours
            logging.getLogger(__name__).exception(
                "rebalance listener %s raised", hook
            )

    def _resolve_position(self, tp: TopicPartition) -> int:
        if tp not in self._positions:
            committed = self._broker.committed(self._group_id, tp)
            if committed is not None:
                self._positions[tp] = committed
            elif self._auto_offset_reset == "earliest":
                self._positions[tp] = 0
            else:
                self._positions[tp] = self._broker.end_offset(tp)
        return self._positions[tp]

    # ----------------------------------------------------------------- api

    def poll(self, max_records: int = 500, timeout_ms: int = 0) -> list[Record]:
        self._check_open()
        from torchkafka_tpu.errors import BrokerUnavailableError

        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            self._sync_group()
            out: list[Record] = []
            tps = self._assignment
            if tps:
                # Round-robin across partitions for fairness, like the Kafka
                # fetcher; per-partition order is always preserved.
                start = self._fetch_rr % len(tps)
                order = tps[start:] + tps[:start]
                self._fetch_rr += 1
                budget = max_records
                for tp in order:
                    if budget <= 0:
                        break
                    if tp in self._paused:
                        continue
                    try:
                        pos = self._resolve_position(tp)
                        if self._isolation == "read_committed":
                            # fetch_stable returns the resume position
                            # explicitly: it can advance over SKIPPED
                            # aborted records, which the record list
                            # cannot express.
                            recs, nxt = self._broker.fetch_stable(
                                tp, pos, budget
                            )
                            if nxt != pos:
                                self._positions[tp] = nxt
                            out.extend(recs)
                            budget -= len(recs)
                        else:
                            recs = self._broker.fetch(tp, pos, budget)
                            if recs:
                                self._positions[tp] = recs[-1].offset + 1
                                out.extend(recs)
                                budget -= len(recs)
                    except BrokerUnavailableError:
                        # Poll atomicity under transport faults: positions
                        # have already advanced for the records in ``out``
                        # — raising now would DROP them (the caller never
                        # sees records a retried poll will never re-fetch:
                        # silent per-consumer loss, found by the broker
                        # crash-restart drill). Return the partial poll;
                        # the failed partition's fetch retries next poll
                        # from its unmoved position. An empty partial
                        # carries nothing, so the fault surfaces.
                        if out:
                            return out
                        raise
            if out or timeout_ms <= 0:
                return out
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return []
            self._broker.wait_for_data(min(remaining, 0.05))

    def commit(self, offsets: Mapping[TopicPartition, int] | None = None) -> None:
        self._check_open()
        if offsets is None:
            # Iterator mode: commit what the user was handed; poll mode:
            # commit the poll positions (everything returned by poll), both
            # matching kafka-python's notion of "consumed".
            offsets = dict(self._last_yielded) if self._last_yielded else dict(self._positions)
        if self._manual:
            stray = set(offsets) - set(self._assignment)
            if stray:
                raise NotAssignedError(f"not assigned: {sorted(stray)}")
            self._broker.commit(self._group_id, offsets)
        else:
            self._broker.commit(
                self._group_id, offsets,
                member_id=self._member_id, generation=self._generation,
            )

    def heartbeat(self) -> int | None:
        """Renew this member's broker-side lease; returns the group's
        current generation (None in manual-assignment mode, which has no
        membership to keep alive). Raises ``FencedMemberError`` once the
        broker has evicted this member — the caller must re-join (a fresh
        ``MemoryConsumer``) or exit and be respawned; continuing to serve
        would be zombie work whose commits are all doomed. The process
        fleet's replica loop calls this every ``heartbeat_interval_s``;
        the ``heartbeat_pre_send`` crash point pins the window where a
        replica dies between decode progress and the renewal that would
        have proven it alive."""
        if self._manual:
            return None
        self._check_open()
        from torchkafka_tpu.resilience.crashpoint import crash_hook

        crash_hook("heartbeat_pre_send")
        return self._broker.heartbeat(
            self._group_id, self._member_id, self._generation
        )

    def committed(self, tp: TopicPartition) -> int | None:
        self._check_open()
        return self._broker.committed(self._group_id, tp)

    @property
    def group_id(self) -> str:
        return self._group_id

    @property
    def member_id(self) -> str | None:
        """This member's group identity (None in manual-assignment mode,
        which has no membership). With ``generation`` below, this is the
        group metadata a transactional producer presents so its offset
        commit is fenced exactly like a plain commit would be (Kafka's
        ConsumerGroupMetadata handed to sendOffsetsToTransaction)."""
        return self._member_id

    @property
    def generation(self) -> int | None:
        """The generation this consumer last synced (None in manual
        mode). Callers building a transactional offset commit should
        sync first (``assignment()``) so a rebalance is adopted before
        the commit burns a doomed attempt."""
        return self._generation

    def position(self, tp: TopicPartition) -> int:
        self._check_open()
        return self._resolve_position(tp)

    def seek(self, tp: TopicPartition, offset: int) -> None:
        self._check_open()
        if tp not in set(self._assignment):
            raise NotAssignedError(str(tp))
        self._positions[tp] = offset

    def assignment(self) -> list[TopicPartition]:
        self._check_open()
        self._sync_group()
        return list(self._assignment)

    def offsets_for_times(
        self, times: Mapping[TopicPartition, int]
    ) -> dict[TopicPartition, int | None]:
        """Earliest offset with record timestamp >= the given epoch-ms per
        partition (None if every record is older) — kafka-python's
        ``offsets_for_times`` over the in-memory log. Timestamps are
        produce-assigned and monotone per partition here, so bisect applies."""
        self._check_open()
        out: dict[TopicPartition, int | None] = {}
        for tp, ts in times.items():
            out[tp] = self._broker.offset_for_time(tp, int(ts))
        return out

    def end_offsets(self, tps: Sequence[TopicPartition]) -> dict[TopicPartition, int]:
        self._check_open()
        return {tp: self._broker.end_offset(tp) for tp in tps}

    def lag(self) -> dict[TopicPartition, int]:
        """Per-assigned-partition consumer lag: log end minus position —
        the records fetched-side still ahead of this consumer (the
        operator's 'are we keeping up' number)."""
        self._check_open()
        self._sync_group()
        return {
            tp: max(0, self._broker.end_offset(tp) - self._resolve_position(tp))
            for tp in self._assignment
        }

    def pause(self, *tps: TopicPartition) -> None:
        self._check_open()
        self._sync_group()  # validate against the CURRENT assignment
        stray = set(tps) - set(self._assignment)
        if stray:
            raise NotAssignedError(f"not assigned: {sorted(stray)}")
        self._paused.update(tps)

    def resume(self, *tps: TopicPartition) -> None:
        self._check_open()
        self._sync_group()
        stray = set(tps) - set(self._assignment)
        if stray:  # same contract as the kafka adapter's _check_assigned
            raise NotAssignedError(f"not assigned: {sorted(stray)}")
        self._paused.difference_update(tps)

    def paused(self) -> list[TopicPartition]:
        self._check_open()
        return sorted(self._paused)

    def has_paused(self) -> bool:
        return bool(self._paused)

    def close(self) -> None:
        """Release assignment. Never commits (the reference's
        close(autocommit=False), /root/reference/src/kafka_dataset.py:89)."""
        if self._closed:
            return
        self._closed = True
        if not self._manual:
            self._broker.leave(self._group_id, self._member_id)

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:  # pragma: no cover - interpreter teardown
            pass

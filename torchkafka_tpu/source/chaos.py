"""Deterministic fault injection over any Consumer — chaos for tests.

The reference's failure story is implicit (SURVEY.md §5: recovery IS the
consumer-group protocol) and it ships no way to exercise it. This wrapper
makes failure a first-class test input: wrap any transport and inject
commit failures, transient empty polls, and poll latency — all driven by a
seeded RNG, so a failing fuzz case replays exactly.

    chaos = ChaosConsumer(consumer, seed=7, commit_failure_rate=0.3)
    # stream/commit code runs unchanged; ~30% of commits raise
    # CommitFailedError exactly as a rebalancing broker would.

The invariants under chaos are the framework's core contract: commit
failures are survivable (the reference swallows CommitFailedError,
/root/reference/src/kafka_dataset.py:131-135), no record is lost, and the
committed watermark never overtakes what was actually processed.
"""

from __future__ import annotations

import time
from typing import Mapping

import numpy as np

from torchkafka_tpu.errors import CommitFailedError
from torchkafka_tpu.source.consumer import Consumer, ConsumerIterMixin
from torchkafka_tpu.source.records import Record, TopicPartition


class ChaosConsumer(ConsumerIterMixin):
    """Wraps a Consumer; forwards everything, injecting faults on the way.

    Parameters
    ----------
    commit_failure_rate: probability a ``commit`` raises CommitFailedError
        WITHOUT committing (the broker-rebalanced case — offsets stay
        uncommitted, records re-deliver on restart).
    poll_empty_rate: probability a ``poll`` returns [] despite available
        records (transient fetch hiccup).
    poll_delay_ms: (lo, hi) uniform latency added to every poll — models a
        slow/jittery broker link.
    seed: the determinism handle; same seed → same fault schedule.
    """

    def __init__(
        self,
        inner: Consumer,
        *,
        seed: int = 0,
        commit_failure_rate: float = 0.0,
        poll_empty_rate: float = 0.0,
        poll_delay_ms: tuple[float, float] = (0.0, 0.0),
    ) -> None:
        for name, rate in (
            ("commit_failure_rate", commit_failure_rate),
            ("poll_empty_rate", poll_empty_rate),
        ):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {rate}")
        self._inner = inner
        self._rng = np.random.default_rng(seed)
        self._commit_failure_rate = commit_failure_rate
        self._poll_empty_rate = poll_empty_rate
        self._poll_delay_ms = poll_delay_ms
        self.injected_commit_failures = 0
        self.injected_empty_polls = 0

    def poll(self, max_records: int = 500, timeout_ms: int = 0) -> list[Record]:
        lo, hi = self._poll_delay_ms
        if hi > 0:
            time.sleep(self._rng.uniform(lo, hi) / 1e3)
        if self._poll_empty_rate and self._rng.random() < self._poll_empty_rate:
            self.injected_empty_polls += 1
            return []
        return self._inner.poll(max_records=max_records, timeout_ms=timeout_ms)

    def commit(self, offsets: Mapping[TopicPartition, int] | None = None) -> None:
        if (
            self._commit_failure_rate
            and self._rng.random() < self._commit_failure_rate
        ):
            self.injected_commit_failures += 1
            # Fail WITHOUT committing: exactly what a generation-bumped
            # broker does — the offsets stay wherever they were.
            raise CommitFailedError("injected fault: group rebalanced")
        self._inner.commit(offsets)

    # Everything else is the inner transport's business.
    def committed(self, tp: TopicPartition) -> int | None:
        return self._inner.committed(tp)

    def position(self, tp: TopicPartition) -> int:
        return self._inner.position(tp)

    def seek(self, tp: TopicPartition, offset: int) -> None:
        self._inner.seek(tp, offset)

    def assignment(self):
        return self._inner.assignment()

    def offsets_for_times(self, times):
        return self._inner.offsets_for_times(times)

    def end_offsets(self, tps):
        return self._inner.end_offsets(tps)

    def lag(self):
        return self._inner.lag()

    def pause(self, *tps: TopicPartition) -> None:
        self._inner.pause(*tps)

    def resume(self, *tps: TopicPartition) -> None:
        self._inner.resume(*tps)

    def paused(self):
        return self._inner.paused()

    def has_paused(self) -> bool:
        # The fast-path hint is optional on duck-typed consumers — don't
        # turn its absence on the inner into a crash.
        fn = getattr(self._inner, "has_paused", None)
        return bool(self._inner.paused()) if fn is None else fn()

    def close(self) -> None:
        self._inner.close()

    # Iteration comes from ConsumerIterMixin over SELF.poll, so the
    # record-at-a-time path (the reference's canonical loop shape) goes
    # through the fault injector too — delegating to iter(inner) would
    # silently bypass every fault. The mixin's state hooks proxy to the
    # inner transport so closed/timeout/yield-position semantics match.

    @property
    def _closed(self) -> bool:
        return bool(getattr(self._inner, "_closed", False))

    @property
    def _consumer_timeout_ms(self):
        return getattr(self._inner, "_consumer_timeout_ms", None)

    @property
    def _last_yielded(self):
        return getattr(self._inner, "_last_yielded", None)

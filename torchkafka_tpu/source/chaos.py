"""Deterministic fault injection over any Consumer/Producer — chaos for tests.

The reference's failure story is implicit (SURVEY.md §5: recovery IS the
consumer-group protocol) and it ships no way to exercise it. This wrapper
makes failure a first-class test input: wrap any transport and inject
commit failures, transient empty polls, poll latency, broker-outage
windows, and record corruption — all driven by seeded RNGs, so a failing
fuzz case replays exactly.

    chaos = ChaosConsumer(consumer, seed=7, commit_failure_rate=0.3)
    # stream/commit code runs unchanged; ~30% of commits raise
    # CommitFailedError exactly as a rebalancing broker would.

Determinism is per FAULT TYPE: each fault mode draws from its own RNG
stream, derived from the root seed via ``np.random.SeedSequence`` spawn
keys. That independence is load-bearing for replayable fuzzing — with the
old single shared RNG, adding any new fault mode (or enabling a second
one) consumed draws from the one stream and silently reshuffled the fault
schedule of every existing seed. Now ``seed=7``'s commit-failure schedule
is identical whether or not corruption is also enabled, and future fault
modes append new streams without disturbing these.

The invariants under chaos are the framework's core contract: commit
failures are survivable (the reference swallows CommitFailedError,
/root/reference/src/kafka_dataset.py:131-135), no record is lost, the
committed watermark never overtakes what was actually processed — and,
with the resilience layer on top (torchkafka_tpu/resilience), outages
degrade instead of crash and poison records exit to a DLQ.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Collection, Mapping, Sequence

import numpy as np

from torchkafka_tpu.errors import (
    BrokerUnavailableError,
    CommitFailedError,
    OutputDeliveryError,
)
from torchkafka_tpu.source.consumer import Consumer, ConsumerIterMixin
from torchkafka_tpu.source.records import Record, TopicPartition

# Registry of per-fault-type RNG streams. ORDER IS FROZEN: stream k is
# derived from spawn key (k,), so appending new fault types preserves
# every existing stream; reordering or inserting would reshuffle replay
# schedules for all existing seeds. Append only.
_FAULT_STREAMS = (
    "commit_failure",  # 0: commit -> CommitFailedError
    "poll_empty",      # 1: poll -> [] despite available records
    "poll_delay",      # 2: poll latency
    "outage",          # 3: broker-outage window start/duration draws
    "send_failure",    # 4: producer send raises (transient)
    "delivery_failure",  # 5: producer handle.get raises (terminal, record lost)
)


def fault_rngs(seed: int) -> dict[str, np.random.Generator]:
    """One independent, deterministic RNG per fault type, derived from the
    root seed (SeedSequence spawn keys — the documented mechanism for
    non-overlapping child streams)."""
    root = np.random.SeedSequence(seed)
    children = root.spawn(len(_FAULT_STREAMS))
    return {
        name: np.random.default_rng(child)
        for name, child in zip(_FAULT_STREAMS, children)
    }


class ChaosConsumer(ConsumerIterMixin):
    """Wraps a Consumer; forwards everything, injecting faults on the way.

    Parameters
    ----------
    commit_failure_rate: probability a ``commit`` raises CommitFailedError
        WITHOUT committing (the broker-rebalanced case — offsets stay
        uncommitted, records re-deliver on restart).
    poll_empty_rate: probability a ``poll`` returns [] despite available
        records (transient fetch hiccup).
    poll_delay_ms: (lo, hi) uniform latency added to every poll — models a
        slow/jittery broker link.
    outages: explicit broker-outage windows as ``(start_op, n_ops)``
        pairs, measured in this consumer's poll+commit call count (the
        deterministic unit — wall time would make replay depend on host
        speed). While an op falls inside a window, poll AND commit raise
        ``BrokerUnavailableError`` — the retryable transport fault the
        resilience layer absorbs.
    outage_rate / outage_ops: seeded outage schedule — each op outside a
        window starts one with probability ``outage_rate``, lasting
        uniform-integer ``outage_ops=(lo, hi)`` ops. Actual windows are
        recorded in ``outage_log`` for replay assertions.
    corrupt_rate: probability a polled record's VALUE is replaced with
        garbage. The draw is a pure function of (seed, topic, partition,
        offset) — NOT of poll order — so a corrupted record re-delivers
        corrupted, exactly like a genuinely poisoned payload on a real
        log (the property the quarantine's retry budget is tested
        against). Corrupted keys are recorded in ``corrupted``.
    corrupt_offsets: explicit poison set of ``(topic, partition, offset)``
        tuples — corrupt exactly these, no RNG involved.
    seed: the determinism handle; same seed → same fault schedule, per
        fault type independently.
    """

    def __init__(
        self,
        inner: Consumer,
        *,
        seed: int = 0,
        commit_failure_rate: float = 0.0,
        poll_empty_rate: float = 0.0,
        poll_delay_ms: tuple[float, float] = (0.0, 0.0),
        outages: Sequence[tuple[int, int]] = (),
        outage_rate: float = 0.0,
        outage_ops: tuple[int, int] = (4, 16),
        corrupt_rate: float = 0.0,
        corrupt_offsets: Collection[tuple[str, int, int]] = (),
    ) -> None:
        for name, rate in (
            ("commit_failure_rate", commit_failure_rate),
            ("poll_empty_rate", poll_empty_rate),
            ("outage_rate", outage_rate),
            ("corrupt_rate", corrupt_rate),
        ):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {rate}")
        if outage_ops[0] < 1 or outage_ops[1] < outage_ops[0]:
            raise ValueError(
                f"outage_ops must be 1 <= lo <= hi, got {outage_ops}"
            )
        for start, n in outages:
            if start < 0 or n < 1:
                raise ValueError(
                    f"outage windows need start >= 0, n_ops >= 1, got "
                    f"({start}, {n})"
                )
        self._inner = inner
        self._seed = seed
        self._rngs = fault_rngs(seed)
        self._commit_failure_rate = commit_failure_rate
        self._poll_empty_rate = poll_empty_rate
        self._poll_delay_ms = poll_delay_ms
        self._outages = tuple(outages)
        self._outage_rate = outage_rate
        self._outage_ops = outage_ops
        self._corrupt_rate = corrupt_rate
        self._corrupt_offsets = set(corrupt_offsets)
        self._op = 0  # poll+commit call counter: the outage timeline
        self._outage_until: int | None = None  # seeded window end (exclusive)
        self.injected_commit_failures = 0
        self.injected_empty_polls = 0
        self.injected_outage_faults = 0
        self.injected_corruptions = 0
        #: Seeded windows actually started, as (start_op, n_ops) — compare
        #: across runs to prove same-seed schedule replay.
        self.outage_log: list[tuple[int, int]] = []
        #: Every (topic, partition, offset) whose value was corrupted.
        self.corrupted: set[tuple[str, int, int]] = set()

    # ------------------------------------------------------------- outages

    def _outage_check(self) -> None:
        """Advance the op clock; raise if this op falls in an outage."""
        op = self._op
        self._op += 1
        for start, n in self._outages:
            if start <= op < start + n:
                self.injected_outage_faults += 1
                raise BrokerUnavailableError(
                    f"injected fault: broker outage (op {op} in explicit "
                    f"window [{start}, {start + n}))"
                )
        if self._outage_until is not None:
            if op < self._outage_until:
                self.injected_outage_faults += 1
                raise BrokerUnavailableError(
                    f"injected fault: broker outage (op {op} < "
                    f"{self._outage_until})"
                )
            self._outage_until = None
        if self._outage_rate and self._rngs["outage"].random() < self._outage_rate:
            lo, hi = self._outage_ops
            n = int(self._rngs["outage"].integers(lo, hi + 1))
            self._outage_until = op + n  # this op is the window's first
            self.outage_log.append((op, n))
            self.injected_outage_faults += 1
            raise BrokerUnavailableError(
                f"injected fault: broker outage starting at op {op} "
                f"for {n} ops"
            )

    # ---------------------------------------------------------- corruption

    def _is_corrupt(self, rec: Record) -> bool:
        key = (rec.topic, rec.partition, rec.offset)
        if key in self._corrupt_offsets:
            return True
        if not self._corrupt_rate:
            return False
        # Derived per-record stream: a pure function of (seed, record
        # identity). Poll order, redelivery, and other fault draws cannot
        # change whether THIS record is poisoned — like a real bad payload.
        draw = np.random.default_rng(
            (self._seed, 0xC0FFEE, zlib.crc32(rec.topic.encode()),
             rec.partition, rec.offset)
        ).random()
        return draw < self._corrupt_rate

    def _maybe_corrupt(self, rec: Record) -> Record:
        if not (self._corrupt_rate or self._corrupt_offsets):
            return rec
        if not self._is_corrupt(rec):
            return rec
        self.injected_corruptions += 1
        self.corrupted.add((rec.topic, rec.partition, rec.offset))
        # Deterministic garbage with a WRONG length: breaks fixed-width
        # decoders and length-prefixed schemas alike, identically on every
        # redelivery.
        garbled = b"\xde\xad" + rec.value[: max(0, len(rec.value) // 2)]
        return dataclasses.replace(rec, value=garbled)

    # ---------------------------------------------------------------- api

    def poll(self, max_records: int = 500, timeout_ms: int = 0) -> list[Record]:
        self._outage_check()
        lo, hi = self._poll_delay_ms
        if hi > 0:
            time.sleep(self._rngs["poll_delay"].uniform(lo, hi) / 1e3)
        if (
            self._poll_empty_rate
            and self._rngs["poll_empty"].random() < self._poll_empty_rate
        ):
            self.injected_empty_polls += 1
            return []
        records = self._inner.poll(max_records=max_records, timeout_ms=timeout_ms)
        if self._corrupt_rate or self._corrupt_offsets:
            records = [self._maybe_corrupt(r) for r in records]
        return records

    def commit(self, offsets: Mapping[TopicPartition, int] | None = None) -> None:
        self._outage_check()
        if (
            self._commit_failure_rate
            and self._rngs["commit_failure"].random() < self._commit_failure_rate
        ):
            self.injected_commit_failures += 1
            # Fail WITHOUT committing: exactly what a generation-bumped
            # broker does — the offsets stay wherever they were.
            raise CommitFailedError("injected fault: group rebalanced")
        self._inner.commit(offsets)

    # Everything else is the inner transport's business.
    def committed(self, tp: TopicPartition) -> int | None:
        return self._inner.committed(tp)

    def position(self, tp: TopicPartition) -> int:
        return self._inner.position(tp)

    def seek(self, tp: TopicPartition, offset: int) -> None:
        self._inner.seek(tp, offset)

    def assignment(self):
        return self._inner.assignment()

    def offsets_for_times(self, times):
        return self._inner.offsets_for_times(times)

    def end_offsets(self, tps):
        return self._inner.end_offsets(tps)

    def lag(self):
        return self._inner.lag()

    def pause(self, *tps: TopicPartition) -> None:
        self._inner.pause(*tps)

    def resume(self, *tps: TopicPartition) -> None:
        self._inner.resume(*tps)

    def paused(self):
        return self._inner.paused()

    def has_paused(self) -> bool:
        # The fast-path hint is optional on duck-typed consumers — don't
        # turn its absence on the inner into a crash.
        fn = getattr(self._inner, "has_paused", None)
        return bool(self._inner.paused()) if fn is None else fn()

    def close(self) -> None:
        self._inner.close()

    # Iteration comes from ConsumerIterMixin over SELF.poll, so the
    # record-at-a-time path (the reference's canonical loop shape) goes
    # through the fault injector too — delegating to iter(inner) would
    # silently bypass every fault. The mixin's state hooks proxy to the
    # inner transport so closed/timeout/yield-position semantics match.

    @property
    def _closed(self) -> bool:
        return bool(getattr(self._inner, "_closed", False))

    @property
    def _consumer_timeout_ms(self):
        return getattr(self._inner, "_consumer_timeout_ms", None)

    @property
    def _last_yielded(self):
        return getattr(self._inner, "_last_yielded", None)


@dataclasses.dataclass(frozen=True, slots=True)
class _DoomedSend:
    """A send handle whose record was LOST in flight: get() raises, and
    the record was never appended (unlike a real slow failure, there is
    deliberately nothing to recover — the test point is the caller's
    fail-stop discipline)."""

    reason: str

    def get(self, timeout_s: float | None = None):
        raise OutputDeliveryError(self.reason)


class ChaosProducer:
    """Seeded delivery-fault injection over any Producer.

    - ``send_failure_rate``: ``send`` itself raises
      ``BrokerUnavailableError`` (transient: buffer full against an
      unreachable broker). Nothing was enqueued; the caller's
      leave-uncommitted-and-continue path (serve.py's per-record send
      guard) is what this exercises.
    - ``delivery_failure_rate``: ``send`` returns a handle whose
      ``get()`` raises ``OutputDeliveryError`` and the record is NOT
      produced (terminal: too large, authorization, retries exhausted
      broker-side). This is the fail-stop path — flush/get must refuse
      to commit source offsets past the lost output.

    Independent per-fault RNG streams from the shared registry
    (``fault_rngs``), so producer chaos composes with consumer chaos on
    the same root seed without either reshuffling the other.
    """

    def __init__(
        self,
        inner,
        *,
        seed: int = 0,
        send_failure_rate: float = 0.0,
        delivery_failure_rate: float = 0.0,
    ) -> None:
        for name, rate in (
            ("send_failure_rate", send_failure_rate),
            ("delivery_failure_rate", delivery_failure_rate),
        ):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {rate}")
        self._inner = inner
        self._rngs = fault_rngs(seed)
        self._send_failure_rate = send_failure_rate
        self._delivery_failure_rate = delivery_failure_rate
        self.injected_send_failures = 0
        self.injected_delivery_failures = 0

    def send(self, topic, value, **kw):
        if (
            self._send_failure_rate
            and self._rngs["send_failure"].random() < self._send_failure_rate
        ):
            self.injected_send_failures += 1
            raise BrokerUnavailableError(
                "injected fault: producer buffer full, broker unreachable"
            )
        if (
            self._delivery_failure_rate
            and self._rngs["delivery_failure"].random()
            < self._delivery_failure_rate
        ):
            self.injected_delivery_failures += 1
            return _DoomedSend(
                "injected fault: record terminally failed delivery "
                "(never appended)"
            )
        return self._inner.send(topic, value, **kw)

    def flush(self, timeout_s: float | None = None) -> None:
        self._inner.flush(timeout_s)

    def close(self) -> None:
        self._inner.close()

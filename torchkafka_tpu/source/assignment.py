"""Mesh-aligned partition assignment.

The reference shards consumption by letting the Kafka group protocol spread
partitions across DataLoader worker processes (/root/reference/src/kafka_dataset.py:208-233).
On a TPU pod the data-parallel topology is *static* — one ingest process per
host, ``jax.process_count()`` hosts — so the TPU-native design uses manual,
deterministic assignment aligned to the mesh's data axis instead: no
rebalance churn, no generation races, and every host knows exactly which
partitions feed its shard of the global batch. Group-managed mode remains
available for elasticity (MemoryConsumer/KafkaConsumer both support it).
"""

from __future__ import annotations

from torchkafka_tpu.source.records import TopicPartition


def partitions_for_process(
    topic: str,
    num_partitions: int,
    process_index: int,
    process_count: int,
) -> list[TopicPartition]:
    """Strided partition assignment: process i owns partitions {p : p % N == i}.

    Strided (not range) so that adding partitions to a topic spreads new load
    evenly across hosts without remapping existing ones.
    """
    if not 0 <= process_index < process_count:
        raise ValueError(f"process_index {process_index} out of range [0, {process_count})")
    return [
        TopicPartition(topic, p)
        for p in range(num_partitions)
        if p % process_count == process_index
    ]


def local_batch_size(global_batch_size: int, process_count: int, process_index: int | None = None) -> int:
    """Per-host share of a global batch; requires even divisibility because
    XLA needs identical static shapes on every host."""
    if global_batch_size % process_count != 0:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by {process_count} processes"
        )
    return global_batch_size // process_count

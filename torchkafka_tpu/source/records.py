"""Transport-independent record primitives.

The reference iterates raw kafka-python ``ConsumerRecord`` objects straight out
of the consumer (/root/reference/src/kafka_dataset.py:156) and hands them to
the user's ``_process`` (:159,:173-186). We instead define our own small record
type so that every transport (in-memory broker, kafka-python adapter, future
native client) presents an identical surface to the transform layer, and so
records can cross thread/process boundaries cheaply.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Sequence

import numpy as np


class TopicPartition(NamedTuple):
    """A (topic, partition) pair — the unit of assignment and offset commit."""

    topic: str
    partition: int


@dataclasses.dataclass(frozen=True, slots=True)
class Record:
    """One immutable record fetched from a partition.

    ``offset`` is the record's position in its partition log. Commits use
    *next-offset* semantics: committing offset N means "records < N are done",
    matching Kafka's OffsetAndMetadata convention.
    """

    topic: str
    partition: int
    offset: int
    value: bytes
    key: bytes | None = None
    timestamp_ms: int = 0
    headers: tuple[tuple[str, bytes], ...] = ()

    @property
    def tp(self) -> TopicPartition:
        return TopicPartition(self.topic, self.partition)


#: One partition's contiguous poll run: (tp, first_offset, count).
Span = tuple[TopicPartition, int, int]


class ChunkIndex:
    """Columnar identity of one poll chunk: which (partition, offset) each
    row is, without per-row Python objects.

    The ingest hot path's cost at millions of records/sec is not decoding —
    it is per-record bookkeeping (attribute reads, dict hits). A ChunkIndex
    carries the same information as a list[Record] for accounting purposes
    in three arrays built from per-partition spans with O(spans) Python work,
    so the ledger and batcher can operate on slices.
    """

    __slots__ = ("spans", "tps", "tp_idx", "offsets")

    def __init__(
        self,
        spans: list[Span],
        tps: list[TopicPartition],
        tp_idx: np.ndarray,
        offsets: np.ndarray,
    ) -> None:
        self.spans = spans
        self.tps = tps  # unique partitions; tp_idx indexes into this
        self.tp_idx = tp_idx  # [N] int32
        self.offsets = offsets  # [N] int64

    def __len__(self) -> int:
        return int(self.offsets.shape[0])

    @classmethod
    def from_spans(cls, spans: list[Span]) -> "ChunkIndex":
        tps: list[TopicPartition] = []
        ids: dict[TopicPartition, int] = {}
        idx_parts = []
        off_parts = []
        for tp, start, count in spans:
            i = ids.get(tp)
            if i is None:
                i = ids[tp] = len(tps)
                tps.append(tp)
            idx_parts.append(np.full(count, i, np.int32))
            off_parts.append(np.arange(start, start + count, dtype=np.int64))
        if not idx_parts:
            return cls([], [], np.empty(0, np.int32), np.empty(0, np.int64))
        return cls(spans, tps, np.concatenate(idx_parts), np.concatenate(off_parts))

    @classmethod
    def from_records(cls, records: Sequence[Record]) -> "ChunkIndex":
        """Fallback for transports without a span-aware poll: one attribute
        pass over the records, splitting runs on partition change or offset
        gap (compacted topics / transaction markers leave gaps)."""
        spans: list[Span] = []
        run_tp: TopicPartition | None = None
        run_start = 0
        prev = 0
        for r in records:
            tp = r.tp
            if tp != run_tp or r.offset != prev + 1:
                if run_tp is not None:
                    spans.append((run_tp, run_start, prev - run_start + 1))
                run_tp, run_start = tp, r.offset
            prev = r.offset
        if run_tp is not None:
            spans.append((run_tp, run_start, prev - run_start + 1))
        return cls.from_spans(spans)

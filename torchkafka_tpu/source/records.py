"""Transport-independent record primitives.

The reference iterates raw kafka-python ``ConsumerRecord`` objects straight out
of the consumer (/root/reference/src/kafka_dataset.py:156) and hands them to
the user's ``_process`` (:159,:173-186). We instead define our own small record
type so that every transport (in-memory broker, kafka-python adapter, future
native client) presents an identical surface to the transform layer, and so
records can cross thread/process boundaries cheaply.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple


class TopicPartition(NamedTuple):
    """A (topic, partition) pair — the unit of assignment and offset commit."""

    topic: str
    partition: int


@dataclasses.dataclass(frozen=True, slots=True)
class Record:
    """One immutable record fetched from a partition.

    ``offset`` is the record's position in its partition log. Commits use
    *next-offset* semantics: committing offset N means "records < N are done",
    matching Kafka's OffsetAndMetadata convention.
    """

    topic: str
    partition: int
    offset: int
    value: bytes
    key: bytes | None = None
    timestamp_ms: int = 0
    headers: tuple[tuple[str, bytes], ...] = ()

    @property
    def tp(self) -> TopicPartition:
        return TopicPartition(self.topic, self.partition)

"""Socket-RPC transport for ``InMemoryBroker``: one broker, many processes.

The in-memory broker (source/memory.py) implements the full consumer-group
protocol — range assignment, generations, eager rebalance, generation-checked
commits — but lives inside one Python process. Real elasticity questions
("a member LEAVES mid-stream; do the survivors absorb its partitions and do
its uncommitted records re-deliver?") are multi-PROCESS questions: each group
member is its own OS process, exactly like the reference's per-DataLoader-
worker consumers (/root/reference/src/kafka_dataset.py:208-233) and like one
consumer per TPU pod host.

``BrokerServer`` hosts an ``InMemoryBroker`` behind a localhost socket;
``BrokerClient`` exposes the same *broker* surface over RPC. Because
``MemoryConsumer`` talks only to that surface (join/leave/group_state/fetch/
commit/...), the UNCHANGED consumer — including all its rebalance-sync logic
— runs against a shared cross-process broker: the group protocol itself is
what gets exercised, not a reimplementation of it.

Scope: a hermetic test/pod-harness transport on a TRUSTED channel. Framing is
length-prefixed pickle (the payloads are this package's own Record /
TopicPartition values and broker exceptions); never expose the port beyond
localhost or a trusted fabric — production traffic belongs to real Kafka via
source/kafka.py.
"""

from __future__ import annotations

import pickle
import random
import socket
import struct
import threading
import time
from typing import Any

from torchkafka_tpu.source.memory import InMemoryBroker

_LEN = struct.Struct(">I")

# The broker surface MemoryConsumer + tests use. An explicit allowlist: the
# server dispatches nothing else (no arbitrary attribute access over the
# wire).
_METHODS = frozenset(
    {
        "create_topic",
        "partitions_for",
        "produce",
        "end_offset",
        "fetch",
        "offset_for_time",
        "join",
        "leave",
        "group_state",
        "commit",
        "committed",
        "wait_for_data",
        "heartbeat",
        "fence",
        "membership",
        # Transactions (KIP-98 surface): epoch fencing crosses the wire
        # as the marshalled terminal ProducerFencedError; transport
        # faults stay the retryable BrokerUnavailableError — the same
        # retryable-vs-terminal split every other RPC rides.
        "init_producer_id",
        "begin_txn",
        "txn_produce",
        "txn_commit_offsets",
        "commit_txn",
        "abort_txn",
        "fetch_stable",
        "last_stable_offset",
        # Replication (broker-cell surface): the leader ships WAL frames
        # to FollowerReplica objects served by this same BrokerServer, and
        # the cell probes liveness/position over the same wire. Stale-
        # epoch fencing crosses as the marshalled terminal StaleEpochError;
        # transport faults stay the retryable BrokerUnavailableError.
        "repl_append",
        "repl_status",
        "repl_ping",
    }
)


def _send(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv(sock: socket.socket) -> Any:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        raise ConnectionError("broker connection closed")
    (n,) = _LEN.unpack(header)
    payload = _recv_exact(sock, n)
    if payload is None:
        raise ConnectionError("broker connection closed mid-frame")
    return pickle.loads(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class WireFaults:
    """Seeded socket-layer fault plan for :class:`ChaosTransport`.

    The transport-level complement to ``ChaosConsumer`` (source/chaos.py):
    where that injects faults at the *consumer API* boundary, this
    injects them at the *wire* — a request frame cut off mid-write, a
    connection reset while the reply is in flight, an op-counted stall —
    so broker outages are reproducible at the socket layer without
    killing any process. One instance carries the RNG and the op counter
    ACROSS reconnects (a reconnecting client keeps consuming the same
    schedule), so a seeded run replays identically.

    All rates default to 0.0 and all op sets to empty: a zero-fault plan
    is a pure pass-through, asserted contract-transparent by the
    transport-conformance suite.

    - ``reset_rate`` / ``reset_at_ops``: the request's ``sendall`` is cut
      short — a seeded PARTIAL prefix of the frame is written (the torn
      bytes the server must discard), then the connection resets. The
      RPC provably never executed.
    - ``recv_reset_rate`` / ``recv_reset_at_ops``: the request was sent
      (and likely executed broker-side) but the reply is lost mid-read —
      the lost-ack hazard; only idempotent/at-least-once-tolerant
      operations survive retries of this, which is exactly the
      transport's documented contract.
    - ``stall_rate`` / ``stall_at_ops`` (+ ``stall_s``): latency
      injection before the request goes out.

    An *op* is one RPC request (one ``sendall``); ``*_at_ops`` sets fire
    deterministically at those op indices (0-based), composing with the
    seeded rates."""

    def __init__(
        self,
        seed: int = 0,
        *,
        reset_rate: float = 0.0,
        recv_reset_rate: float = 0.0,
        stall_rate: float = 0.0,
        stall_s: float = 0.005,
        reset_at_ops: tuple[int, ...] = (),
        recv_reset_at_ops: tuple[int, ...] = (),
        stall_at_ops: tuple[int, ...] = (),
    ) -> None:
        for name, rate in (("reset_rate", reset_rate),
                           ("recv_reset_rate", recv_reset_rate),
                           ("stall_rate", stall_rate)):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {rate}")
        self._rng = random.Random(seed)
        self.reset_rate = reset_rate
        self.recv_reset_rate = recv_reset_rate
        self.stall_rate = stall_rate
        self.stall_s = stall_s
        self.reset_at_ops = frozenset(reset_at_ops)
        self.recv_reset_at_ops = frozenset(recv_reset_at_ops)
        self.stall_at_ops = frozenset(stall_at_ops)
        self.ops = 0  # RPC requests seen, across reconnects
        self.faults_injected = 0

    def next_op(self) -> int:
        op = self.ops
        self.ops += 1
        return op

    def send_cut(self, op: int, nbytes: int) -> int | None:
        """None = write goes through; else the seeded prefix length to
        write before resetting."""
        if op in self.reset_at_ops or (
            self.reset_rate and self._rng.random() < self.reset_rate
        ):
            self.faults_injected += 1
            return self._rng.randrange(nbytes) if nbytes else 0
        return None

    def recv_reset(self, op: int) -> bool:
        if op in self.recv_reset_at_ops or (
            self.recv_reset_rate
            and self._rng.random() < self.recv_reset_rate
        ):
            self.faults_injected += 1
            return True
        return False

    def stall(self, op: int) -> bool:
        return op in self.stall_at_ops or (
            self.stall_rate and self._rng.random() < self.stall_rate
        )


class ChaosTransport:
    """A connected socket wrapped with a :class:`WireFaults` plan.

    Implements exactly the surface ``BrokerClient``'s framing uses
    (``sendall``/``recv``/``close``), forwarding to the real socket and
    consulting the plan per RPC. Injected failures surface as
    ``ConnectionResetError`` — indistinguishable from a real peer reset,
    so the client's translation to the retryable
    ``BrokerUnavailableError`` (and a ``RetryPolicy``'s reconnects) get
    exercised by the genuine code path, not a simulation of it."""

    def __init__(self, sock: socket.socket, faults: WireFaults) -> None:
        self._sock = sock
        self._faults = faults
        # The reply-loss decision is drawn ONCE per RPC (at request
        # time), not per recv chunk — chunk counts are data-dependent
        # and would desynchronize the seeded schedule.
        self._pending_recv_reset = False

    def sendall(self, data: bytes) -> None:
        f = self._faults
        op = f.next_op()
        cut = f.send_cut(op, len(data))
        if cut is not None:
            try:
                self._sock.sendall(data[:cut])
            finally:
                self.close()
            raise ConnectionResetError(
                f"chaos: connection reset after {cut}/{len(data)} bytes "
                f"of request (op {op})"
            )
        if f.stall(op):
            time.sleep(f.stall_s)
        self._pending_recv_reset = f.recv_reset(op)
        self._sock.sendall(data)

    def recv(self, n: int) -> bytes:
        if self._pending_recv_reset:
            self._pending_recv_reset = False
            self.close()
            raise ConnectionResetError(
                "chaos: connection reset mid-reply (request may have "
                "executed broker-side — the lost-ack hazard)"
            )
        return self._sock.recv(n)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class BrokerServer:
    """Serve an ``InMemoryBroker`` on a localhost socket.

    One thread per connection: ``wait_for_data`` blocks server-side, so a
    long-polling client must not starve others. The underlying broker is
    already thread-safe (RLock).
    """

    def __init__(
        self, broker: InMemoryBroker | None = None, host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.broker = broker if broker is not None else InMemoryBroker()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.host, self.port = self._sock.getsockname()
        self._closing = False
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="broker-server-accept", daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="broker-server-conn", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                try:
                    method, args, kwargs = _recv(conn)
                except (ConnectionError, OSError):
                    return
                # A client that disconnects mid-request makes the reply
                # _send raise (EBADF/EPIPE); without this guard the "ok"
                # send's failure would route into the except branch whose
                # _send raises AGAIN and escapes the handler thread
                # (ADVICE r4). A vanished client just closes its handler.
                try:
                    if method not in _METHODS:
                        _send(conn, ("err", ValueError(f"unknown method {method!r}")))
                        continue
                    try:
                        value = getattr(self.broker, method)(*args, **kwargs)
                        reply = ("ok", value)
                    except Exception as exc:  # noqa: BLE001 - marshalled to client
                        reply = ("err", exc)
                    _send(conn, reply)
                except (ConnectionError, OSError):
                    return
        finally:
            conn.close()

    def close(self) -> None:
        self._closing = True
        try:
            # shutdown() BEFORE close(): the accept thread blocked in
            # accept() holds a kernel reference to the listening socket,
            # so a bare close() leaves the listener alive (and accepting!)
            # until that syscall returns — a "closed" server that still
            # answers is exactly the zombie the fencing tests exist to
            # rule out. shutdown() wakes the acceptor with an error.
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # already disconnected/never listening
            self._sock.close()
        finally:
            with self._lock:
                conns, self._conns = self._conns, []
            for c in conns:
                try:
                    c.close()
                except OSError:
                    pass

    def __enter__(self) -> "BrokerServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class BrokerClient:
    """The ``InMemoryBroker`` surface, proxied over a ``BrokerServer`` socket.

    Drop-in where a broker object is expected:
    ``MemoryConsumer(BrokerClient(host, port), topic, group_id=...)`` gives a
    group-managed consumer whose membership lives in the SERVER process —
    several OS processes doing this share one real consumer group.

    Thread-safe via a per-client request lock (one in-flight RPC per
    client); a raising broker call re-raises the marshalled exception
    (CommitFailedError and friends cross the wire intact).

    Transport faults (connection reset/refused, socket timeout, a frame
    cut mid-read) surface as the RETRYABLE ``BrokerUnavailableError`` —
    never a raw ``OSError`` — and mark the socket dead so the next call
    reconnects. Pass ``retry`` (a ``resilience.RetryPolicy``) and the
    client retries such faults itself, reconnecting with the policy's
    jittered backoff: group membership lives broker-side, so a reconnect
    resumes the same member identity (the lease, if any, still has to be
    renewed in time — a retry storm longer than the session timeout gets
    fenced, exactly as it should). Safe because every proxied operation
    is idempotent or at-least-once-tolerant: polls re-fetch from the
    consumer position, commits carry absolute offsets, a re-sent produce
    can at worst duplicate a record the downstream is already required
    to tolerate.
    """

    def __init__(
        self, host: str, port: int, timeout_s: float = 30.0, retry=None,
        faults: WireFaults | None = None,
    ) -> None:
        self._host = host
        self._port = port
        self._timeout_s = timeout_s
        self._retry = retry
        # Wire-fault injection (ChaosTransport): every connection this
        # client opens — including reconnects — is wrapped with the SAME
        # plan, so the seeded schedule spans the client's whole life.
        self._faults = faults
        self._lock = threading.Lock()
        self._closed = False
        self._sock: socket.socket | None = None
        # Eager connect: config errors (wrong port) surface at
        # construction — through the policy, so a racing server start is
        # absorbed too.
        if retry is not None:
            retry.run(self._ensure_connected)
        else:
            self._ensure_connected()

    def _ensure_connected(self) -> None:
        with self._lock:
            if self._closed:
                raise ConnectionError("broker client is closed")
            self._connect_locked()

    def _connect_locked(self) -> None:
        if self._sock is not None:
            return
        from torchkafka_tpu.errors import BrokerUnavailableError

        try:
            sock = socket.create_connection(
                (self._host, self._port), timeout=self._timeout_s
            )
        except OSError as exc:
            raise BrokerUnavailableError(
                f"broker {self._host}:{self._port} unreachable: {exc}"
            ) from exc
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = (
            ChaosTransport(sock, self._faults)
            if self._faults is not None else sock
        )

    def _call_once(self, method: str, args: tuple, kwargs: dict) -> Any:
        from torchkafka_tpu.errors import BrokerUnavailableError

        with self._lock:
            if self._closed:
                raise ConnectionError("broker client is closed")
            self._connect_locked()
            try:
                _send(self._sock, (method, args, kwargs))
                status, value = _recv(self._sock)
            except (ConnectionError, OSError, EOFError) as exc:
                # The socket is in an unknown framing state: drop it so
                # the next attempt reconnects cleanly.
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                raise BrokerUnavailableError(
                    f"broker RPC {method!r} failed mid-flight: {exc}"
                ) from exc
        if status == "err":
            raise value
        return value

    def _call(self, method: str, *args: Any, **kwargs: Any) -> Any:
        if self._retry is None:
            return self._call_once(method, args, kwargs)
        return self._retry.run(lambda: self._call_once(method, args, kwargs))

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None

    def __enter__(self) -> "BrokerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- proxied broker surface (kept explicit: greppable + type-friendly)

    def create_topic(self, topic, partitions=1):
        return self._call("create_topic", topic, partitions)

    def partitions_for(self, topic):
        return self._call("partitions_for", topic)

    def produce(self, topic, value, **kw):
        return self._call("produce", topic, value, **kw)

    def end_offset(self, tp):
        return self._call("end_offset", tp)

    def fetch(self, tp, offset, max_records):
        return self._call("fetch", tp, offset, max_records)

    def offset_for_time(self, tp, timestamp_ms):
        return self._call("offset_for_time", tp, timestamp_ms)

    def join(self, group_id, member_id, topics, pattern=None):
        return self._call("join", group_id, member_id, topics, pattern=pattern)

    def leave(self, group_id, member_id):
        return self._call("leave", group_id, member_id)

    def group_state(self, group_id, member_id):
        return self._call("group_state", group_id, member_id)

    def commit(self, group_id, offsets, member_id=None, generation=None):
        return self._call(
            "commit", group_id, offsets,
            member_id=member_id, generation=generation,
        )

    def committed(self, group_id, tp):
        return self._call("committed", group_id, tp)

    def heartbeat(self, group_id, member_id, generation=None):
        return self._call("heartbeat", group_id, member_id, generation)

    def fence(self, group_id, member_id):
        return self._call("fence", group_id, member_id)

    def membership(self, group_id):
        return self._call("membership", group_id)

    def wait_for_data(self, timeout_s):
        # Cap the server-side block below the socket timeout so a quiet
        # broker never looks like a dead one.
        return self._call("wait_for_data", min(timeout_s, 5.0))

    # ---- transactions (KIP-98 surface over the socket) ----

    def init_producer_id(self, transactional_id):
        return self._call("init_producer_id", transactional_id)

    def begin_txn(self, producer_id, epoch):
        return self._call("begin_txn", producer_id, epoch)

    def txn_produce(self, producer_id, epoch, topic, value, **kw):
        return self._call("txn_produce", producer_id, epoch, topic, value, **kw)

    def txn_commit_offsets(
        self, producer_id, epoch, group_id, offsets,
        member_id=None, generation=None,
    ):
        return self._call(
            "txn_commit_offsets", producer_id, epoch, group_id, offsets,
            member_id=member_id, generation=generation,
        )

    def commit_txn(self, producer_id, epoch):
        return self._call("commit_txn", producer_id, epoch)

    def abort_txn(self, producer_id, epoch):
        return self._call("abort_txn", producer_id, epoch)

    def fetch_stable(self, tp, offset, max_records):
        return self._call("fetch_stable", tp, offset, max_records)

    def last_stable_offset(self, tp):
        return self._call("last_stable_offset", tp)

    # ---- replication (broker-cell surface over the socket) ----

    def repl_append(self, epoch, base, frames):
        return self._call("repl_append", epoch, base, frames)

    def repl_status(self, epoch=None):
        return self._call("repl_status", epoch)

    def repl_ping(self):
        return self._call("repl_ping")

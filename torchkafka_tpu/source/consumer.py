"""The Consumer protocol — the only transport surface the framework uses.

The reference touches exactly four points of kafka-python's ``KafkaConsumer``:
iteration (/root/reference/src/kafka_dataset.py:156), ``commit()`` (:130),
``close()`` (:89) and construction with ``enable_auto_commit=False`` forced
(:201). This protocol is that surface, made explicit and offset-precise:
``commit`` takes an explicit ``{TopicPartition: next_offset}`` map rather than
"whatever was polled", which is what lets the commit layer commit *exactly*
the records of one batch (SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

from typing import Iterator, Mapping, Protocol, Sequence, runtime_checkable

from torchkafka_tpu.source.records import Record, TopicPartition


@runtime_checkable
class Consumer(Protocol):
    """Minimal consumer surface. All transports implement this."""

    def poll(self, max_records: int = 500, timeout_ms: int = 0) -> list[Record]:
        """Fetch up to ``max_records`` across assigned partitions.

        Records are returned in per-partition offset order (partitions may be
        interleaved). Returns an empty list if nothing arrived within
        ``timeout_ms``. Never auto-commits — the reference's core invariant
        (/root/reference/src/kafka_dataset.py:201).
        """
        ...

    def commit(self, offsets: Mapping[TopicPartition, int] | None = None) -> None:
        """Commit explicit next-read offsets; ``None`` commits current positions.

        Raises CommitFailedError if the group rebalanced underneath us; callers
        treat that as non-fatal (records get re-delivered).
        """
        ...

    def committed(self, tp: TopicPartition) -> int | None:
        """Last committed next-read offset for ``tp`` in this group, if any."""
        ...

    def position(self, tp: TopicPartition) -> int:
        """Next offset ``poll`` would fetch for ``tp``."""
        ...

    def seek(self, tp: TopicPartition, offset: int) -> None: ...

    def assignment(self) -> Sequence[TopicPartition]:
        """Partitions currently owned by this consumer."""
        ...

    def close(self) -> None:
        """Release assignment. NEVER commits on close — uncommitted work must
        be re-delivered (/root/reference/src/kafka_dataset.py:89)."""
        ...

    def __iter__(self) -> Iterator[Record]: ...


class ConsumerIterMixin:
    """Provides record-at-a-time iteration on top of ``poll`` (the reference's
    ``for record in consumer`` hot-loop shape, /root/reference/src/kafka_dataset.py:156)."""

    _ITER_TIMEOUT_MS = 100

    def __iter__(self) -> Iterator[Record]:
        buf: list[Record] = []
        while True:
            if not buf:
                if getattr(self, "_closed", False):
                    return
                buf = list(self.poll(timeout_ms=self._ITER_TIMEOUT_MS))  # type: ignore[attr-defined]
                if not buf:
                    continue
                buf.reverse()  # pop from the end, preserve order
            yield buf.pop()

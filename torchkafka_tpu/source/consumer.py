"""The Consumer protocol — the only transport surface the framework uses.

The reference touches exactly four points of kafka-python's ``KafkaConsumer``:
iteration (/root/reference/src/kafka_dataset.py:156), ``commit()`` (:130),
``close()`` (:89) and construction with ``enable_auto_commit=False`` forced
(:201). This protocol is that surface, made explicit and offset-precise:
``commit`` takes an explicit ``{TopicPartition: next_offset}`` map rather than
"whatever was polled", which is what lets the commit layer commit *exactly*
the records of one batch (SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

from typing import Iterator, Mapping, Protocol, Sequence, runtime_checkable

from torchkafka_tpu.source.records import Record, TopicPartition


@runtime_checkable
class Consumer(Protocol):
    """Minimal consumer surface. All transports implement this."""

    def poll(self, max_records: int = 500, timeout_ms: int = 0) -> list[Record]:
        """Fetch up to ``max_records`` across assigned partitions.

        Records are returned in per-partition offset order (partitions may be
        interleaved). Returns an empty list if nothing arrived within
        ``timeout_ms``. Never auto-commits — the reference's core invariant
        (/root/reference/src/kafka_dataset.py:201).
        """
        ...

    def commit(self, offsets: Mapping[TopicPartition, int] | None = None) -> None:
        """Commit explicit next-read offsets; ``None`` commits current positions.

        Raises CommitFailedError if the group rebalanced underneath us; callers
        treat that as non-fatal (records get re-delivered).
        """
        ...

    def committed(self, tp: TopicPartition) -> int | None:
        """Last committed next-read offset for ``tp`` in this group, if any."""
        ...

    def position(self, tp: TopicPartition) -> int:
        """Next offset ``poll`` would fetch for ``tp``."""
        ...

    def seek(self, tp: TopicPartition, offset: int) -> None: ...

    def assignment(self) -> Sequence[TopicPartition]:
        """Partitions currently owned by this consumer."""
        ...

    def offsets_for_times(
        self, times: Mapping[TopicPartition, int]
    ) -> dict[TopicPartition, int | None]:
        """For each partition, the earliest offset whose record timestamp is
        >= the given epoch-ms — ``None`` if every record is older
        (kafka-python's ``offsets_for_times`` surface). Feed the result to
        ``seek`` to replay from a point in time."""
        ...

    def end_offsets(self, tps: Sequence[TopicPartition]) -> dict[TopicPartition, int]:
        """Next-offset-to-be-produced per partition (the log end)."""
        ...

    def lag(self) -> dict[TopicPartition, int]:
        """Per-assigned-partition lag: log end minus position."""
        ...

    def pause(self, *tps: TopicPartition) -> None:
        """Stop fetching from these partitions (``poll`` skips them) without
        losing the assignment — per-partition backpressure."""
        ...

    def resume(self, *tps: TopicPartition) -> None:
        """Undo ``pause``."""
        ...

    def paused(self) -> Sequence[TopicPartition]: ...

    def has_paused(self) -> bool:
        """Cheap O(1) probe for "is anything paused?". The per-record
        iterator hot loop consults this before paying for ``paused()``
        (which allocates a sorted list per call) — pause is rare, the loop
        is not (ADVICE r2)."""
        ...

    def close(self) -> None:
        """Release assignment. NEVER commits on close — uncommitted work must
        be re-delivered (/root/reference/src/kafka_dataset.py:89)."""
        ...

    def __iter__(self) -> Iterator[Record]: ...


def seek_to_timestamp(consumer: Consumer, timestamp_ms: int) -> dict[TopicPartition, int]:
    """Position every assigned partition at the first record at/after
    ``timestamp_ms``. Partitions whose records are ALL older seek to their
    log end — otherwise a fresh consumer (no committed offsets) would
    resolve them to ``auto_offset_reset`` and replay the entire stale
    partition, the opposite of "replay from this point in time" (the
    standard Kafka pattern: seek the ``offsets_for_times`` result, end
    offset where it returns None). Returns the offsets seeked to.

    The time-travel analog of the reference's "restart with the same
    group_id" resume story (/root/reference/README.md:92-96): instead of
    resuming at the last commit, replay from a wall-clock point.
    """
    assigned = list(consumer.assignment())
    if not assigned:
        # A group-managed consumer has no assignment until the join
        # completes (first poll); silently seeking nothing would replay the
        # entire stale stream — the exact failure this function prevents.
        from torchkafka_tpu.errors import NotAssignedError

        raise NotAssignedError(
            "no partitions assigned — with a group-managed consumer, poll "
            "once (completing the group join) before seek_to_timestamp"
        )
    found = consumer.offsets_for_times({tp: timestamp_ms for tp in assigned})
    missing = [tp for tp, off in found.items() if off is None]
    ends = consumer.end_offsets(missing) if missing else {}
    seeked: dict[TopicPartition, int] = {}
    for tp, offset in found.items():
        offset = ends[tp] if offset is None else offset
        consumer.seek(tp, offset)
        seeked[tp] = offset
    return seeked


class ConsumerIterMixin:
    """Provides record-at-a-time iteration on top of ``poll`` (the reference's
    ``for record in consumer`` hot-loop shape, /root/reference/src/kafka_dataset.py:156).

    If the instance has a ``_consumer_timeout_ms`` attribute (kafka-python's
    ``consumer_timeout_ms`` semantics), iteration ends after that long with no
    records; otherwise it blocks until the consumer is closed.
    """

    _ITER_TIMEOUT_MS = 100

    def __iter__(self) -> Iterator[Record]:
        import time as _time

        buf: list[Record] = []
        # Records fetched before their partition was paused: withheld here
        # (kafka-python retains fetched-but-paused records the same way) and
        # re-injected ahead of new fetches once the partition resumes —
        # while paused, poll skips the partition, so nothing newer can
        # overtake them and per-partition order holds. Consults the
        # transport's public paused() so it works for ANY transport; native
        # withholding (kafka-python) only covers records poll hasn't
        # surfaced yet, not ones already in this buffer.
        stash: dict[TopicPartition, list[Record]] = {}
        paused_fn = getattr(self, "paused", None)
        # O(1) "anything paused?" probe — skips the per-record paused()
        # sorted-list allocation in the (overwhelmingly common) case where
        # pause is never used. A non-empty stash forces the full check so
        # resumed partitions re-inject promptly.
        has_paused_fn = getattr(self, "has_paused", None)
        idle_limit_ms = getattr(self, "_consumer_timeout_ms", None)
        # kafka-python semantics: the timeout clock measures time spent
        # *waiting for the next record*, not wall time since the last fetch —
        # time the caller spends processing buffered records must not count.
        wait_start: float | None = None
        while True:
            closed = getattr(self, "_closed", False)
            if paused_fn is None or closed:
                paused = ()
            elif stash or has_paused_fn is None or has_paused_fn():
                paused = set(paused_fn())
            else:
                paused = ()
            if stash:
                for tp in [tp for tp in stash if tp not in paused]:
                    resumed = stash.pop(tp)
                    resumed.reverse()
                    buf.extend(resumed)  # popped (from the end) before new polls
            if not buf:
                if getattr(self, "_closed", False):
                    return
                if wait_start is None:
                    wait_start = _time.monotonic()
                buf = list(self.poll(timeout_ms=self._ITER_TIMEOUT_MS))  # type: ignore[attr-defined]
                if not buf:
                    if (
                        idle_limit_ms is not None
                        and (_time.monotonic() - wait_start) * 1000 >= idle_limit_ms
                    ):
                        return
                    continue
                wait_start = None
                buf.reverse()  # pop from the end, preserve order
            rec = buf.pop()
            if rec.tp in paused:
                stash.setdefault(rec.tp, []).append(rec)
                continue
            # kafka-python iterator semantics: the consumed position advances
            # per record *yielded to the user*, not per record fetched into
            # the buffer — so commit(offsets=None) after iteration covers
            # exactly what the user saw (transports keep _last_yielded).
            ly = getattr(self, "_last_yielded", None)
            if ly is not None:
                ly[rec.tp] = rec.offset + 1
            yield rec

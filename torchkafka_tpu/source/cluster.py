"""BrokerCell: one leader + N followers, leases, elections, promotion.

source/replication.py provides the two halves of the data plane — the
leader's quorum ship and the follower's prefix apply. This module is the
CONTROL plane that composes them into a highly-available broker cell:

- **Topology.** One ``InMemoryBroker`` (the leader) serves clients on
  the cell's single ADVERTISED host:port; each follower is a
  ``FollowerReplica`` behind its own ``BrokerServer``, and the leader
  ships every acked WAL frame to them over real sockets. Workers never
  learn follower addresses — the advertised port is the cell.

- **Lease.** Followers heartbeat the leader (``repl_ping`` over the
  wire); every answered beat renews the leader lease. A leader that
  stops answering lets the lease lapse — the same expiry discipline the
  group-membership leases already use for replicas, applied one level
  up.

- **Election.** An expired lease bumps the cell EPOCH and stamps it on
  every reachable follower (``repl_status(epoch)``), which is the
  instant the old leader becomes a zombie: its late ships now meet
  ``StaleEpochError`` and fail their quorum, exactly like a fenced
  replica's commits. The follower holding the LONGEST applied prefix
  wins — majority-acked frames live on ≥ quorum replicas, so the winner
  holds every frame any client was ever acked.

- **Promotion.** The winner replays its WAL through the PR-11 recovery
  path verbatim (``InMemoryBroker(wal_dir=...)``: dangling transactions
  aborted, LSO recomputed, counters advanced) and takes over the
  advertised port with the same close-then-rebind discipline
  ``ProcessFleet.restart_broker`` proved. Clients ride the gap through
  ``RetryPolicy``/``BrokerUnavailableError`` and reconnect unfenced —
  same port, same group state, zero committed-record loss.

``kill_leader()`` is the built-in failover drill: drop the leader the
way SIGKILL would (server gone mid-conversation, WAL abandoned unsynced
— its unbuffered writes are already kernel-side, the honest crash
analog), run the election, and return forensics the way
``ProcessFleet.kill_replica`` does.
"""

from __future__ import annotations

import os
import time

from torchkafka_tpu.errors import BrokerUnavailableError, QuorumLostError
from torchkafka_tpu.resilience.crashpoint import crash_hook
from torchkafka_tpu.source import wal as _wal
from torchkafka_tpu.source.memory import InMemoryBroker
from torchkafka_tpu.source.netbroker import BrokerClient, BrokerServer
from torchkafka_tpu.source.replication import (
    FollowerReplica,
    ReplicationConfig,
    Replicator,
)


class _Member:
    """One follower slot: the replica, its server, and the leader's
    client link to it."""

    __slots__ = ("idx", "wal_dir", "replica", "server", "client")

    def __init__(self, idx, wal_dir, replica, server, client):
        self.idx = idx
        self.wal_dir = wal_dir
        self.replica = replica
        self.server = server
        self.client = client


class BrokerCell:
    """A replicated broker: construct with ``replicas=N`` and use
    ``cell.broker`` / the advertised ``cell.host``/``cell.port`` exactly
    like a single ``InMemoryBroker`` + ``BrokerServer`` pair. Mutations
    ack on majority (``wal_durability="quorum"``); ``kill_leader()``
    fails over with zero committed-record loss."""

    def __init__(
        self,
        workdir: str | os.PathLike,
        *,
        replicas: int | None = None,
        config: ReplicationConfig | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        session_timeout_s: float | None = None,
        clock=None,
    ) -> None:
        if config is None:
            config = ReplicationConfig(
                replicas=replicas if replicas is not None else 3
            )
        elif replicas is not None and replicas != config.replicas:
            raise ValueError(
                f"replicas={replicas} contradicts config.replicas="
                f"{config.replicas}"
            )
        self.config = config
        self.workdir = os.fspath(workdir)
        self.session_timeout_s = session_timeout_s
        self._clock = clock if clock is not None else time.monotonic
        self._dead: set[int] = set()
        self.leader_idx = 0
        self.epoch = 1
        self.elections = 0
        os.makedirs(self.workdir, exist_ok=True)
        # Followers first: the leader's replicator needs their addresses.
        self._followers: dict[int, _Member] = {}
        for i in range(1, config.replicas):
            self._followers[i] = self._open_follower(i)
        self.broker = self._open_leader(0)
        self.server = BrokerServer(self.broker, host=host, port=port)
        self.host, self.port = self.server.host, self.server.port
        self._lease_deadline = self._clock() + config.lease_timeout_s
        self._last_beat = float("-inf")

    # ------------------------------------------------------------ build

    def member_dir(self, idx: int) -> str:
        return os.path.join(self.workdir, f"member-{idx:02d}")

    def _open_follower(self, idx: int) -> _Member:
        wal_dir = self.member_dir(idx)
        replica = FollowerReplica(
            wal_dir,
            durability=self.config.durability,
            segment_bytes=self.config.segment_bytes,
        )
        server = BrokerServer(replica)
        client = BrokerClient(
            server.host, server.port, timeout_s=self.config.rpc_timeout_s
        )
        return _Member(idx, wal_dir, replica, server, client)

    def _open_leader(self, idx: int) -> InMemoryBroker:
        """Recover a broker from ``member_dir(idx)`` (PR-11 replay:
        dangling txns aborted, LSO recomputed) and attach the quorum
        replicator, seeded with the replayed frame log so follower
        cursors and catch-up re-ships index into the same history the
        recovery appends (its abort markers included) just wrote."""
        broker = InMemoryBroker(
            session_timeout_s=self.session_timeout_s,
            clock=self._clock if self.session_timeout_s is not None else None,
            wal_dir=self.member_dir(idx),
            wal_durability="quorum",
            wal_segment_bytes=self.config.segment_bytes,
        )
        events, _ = _wal.replay(self.member_dir(idx), repair=False)
        rep = Replicator(
            epoch=self.epoch,
            quorum=self.config.quorum,
            log=list(events),
            metrics=broker.metrics,
        )
        for m in self._followers.values():
            try:
                st = m.client.repl_status(self.epoch)
                acked = st["applied"]
            except (BrokerUnavailableError, ConnectionError, OSError):
                acked = 0
            rep.add_follower(m.idx, m.client, acked=acked)
        broker.replicator = rep
        rep.sync()
        return broker

    # ------------------------------------------------------- lease loop

    def heartbeat(self) -> bool:
        """One round of follower→leader heartbeats: each live follower
        pings the ADVERTISED port (the wire a client would use — a
        leader that answers here is a leader clients can reach); any
        answer renews the lease. Returns True iff the lease is live."""
        now = self._clock()
        try:
            with BrokerClient(
                self.host, self.port, timeout_s=self.config.rpc_timeout_s
            ) as cli:
                cli.repl_ping()
            answered = True
        except (BrokerUnavailableError, ConnectionError, OSError):
            answered = False
        if answered:
            self._lease_deadline = now + self.config.lease_timeout_s
        return now <= self._lease_deadline

    def poll(self) -> dict | None:
        """Supervisor tick: heartbeat on the configured cadence; if the
        leader lease has lapsed, run the election and return its
        forensics (None on a quiet tick)."""
        now = self._clock()
        if now - self._last_beat < self.config.heartbeat_interval_s:
            return None
        self._last_beat = now
        if self.heartbeat():
            return None
        return self._elect()

    # --------------------------------------------------------- failover

    def kill_leader(self) -> dict:
        """Failover drill: drop the leader exactly as SIGKILL would —
        its server vanishes mid-conversation and its WAL is abandoned
        without a clean close (the unbuffered frame writes are already
        in the kernel, which is precisely what process death preserves)
        — then run the epoch-bumped election. Returns forensics."""
        t0 = time.perf_counter()
        victim = self.leader_idx
        old_epoch = self.epoch
        self.server.close()
        self._dead.add(victim)
        deposed = self.broker.replicator
        if deposed is not None:
            deposed.deposed = True  # a real corpse cannot ship either
        self.broker.replicator = None
        fx = self._elect()
        fx.update(
            victim_idx=victim,
            old_epoch=old_epoch,
            victim_wal_dir=self.member_dir(victim),
            failover_ms=(time.perf_counter() - t0) * 1e3,
        )
        return fx

    def _elect(self) -> dict:
        """Epoch-bumped election + promotion. Stamping the bumped epoch
        on every reachable follower FENCES the old leader before the
        winner serves a single request; the longest applied prefix wins
        so no majority-acked frame can be on the losing side."""
        t0 = time.perf_counter()
        new_epoch = self.epoch + 1
        candidates: dict[int, int] = {}
        for m in self._followers.values():
            try:
                st = m.client.repl_status(new_epoch)
            except (BrokerUnavailableError, ConnectionError, OSError):
                continue
            candidates[m.idx] = st["applied"]
        # The respondents (winner included — it is one of them) must form
        # a majority of the FULL membership, or the cell stays leaderless
        # (retryable — a rejoining replica can complete a later round).
        if len(candidates) < self.config.quorum:
            raise QuorumLostError(
                f"election for epoch {new_epoch} reached only "
                f"{len(candidates)} of {self.config.replicas - 1} followers"
                f" (need {self.config.quorum} voters)"
            )
        # Longest applied prefix wins; ties break to the lowest index so
        # the outcome is deterministic under replay.
        winner_idx = min(
            candidates, key=lambda i: (-candidates[i], i)
        )
        crash_hook("election_pre_promote")
        winner = self._followers.pop(winner_idx)
        winner.client.close()
        winner.server.close()
        winner.replica.close()
        self.epoch = new_epoch
        self.leader_idx = winner_idx
        self.elections += 1
        # Same-port takeover, the restart_broker discipline: close(d)
        # listener above, rebind the advertised address around the
        # recovered broker — clients reconnect, unfenced, to the same
        # group state.
        self.broker = self._open_leader(winner_idx)
        self.server = BrokerServer(self.broker, host=self.host, port=self.port)
        self.broker.metrics.elections.add(1)
        self._lease_deadline = self._clock() + self.config.lease_timeout_s
        return {
            "winner_idx": winner_idx,
            "epoch": new_epoch,
            "candidates": candidates,
            "recovery": dict(self.broker.recovery_info or {}),
            "election_ms": (time.perf_counter() - t0) * 1e3,
        }

    # ------------------------------------------------------------ drill

    def forge_deposed_frame(self) -> None:
        """Replay the deposed leader's move: ship a frame carrying the
        PREVIOUS epoch straight at a live follower. The follower must
        raise ``StaleEpochError`` — the append is rejected, never
        applied. (With no live follower, the zombie cannot even reach a
        quorum of one — raise QuorumLostError for symmetry.)"""
        stale_epoch = self.epoch - 1
        for m in self._followers.values():
            st = m.client.repl_status()
            m.client.repl_append(
                stale_epoch, st["applied"], [("produce", {"forged": True})]
            )
            return
        raise QuorumLostError("no live follower to forge at")

    # ---------------------------------------------------------- queries

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)

    @property
    def quorum(self) -> int:
        return self.config.quorum

    def status(self) -> dict:
        out = {
            "leader_idx": self.leader_idx,
            "epoch": self.epoch,
            "elections": self.elections,
            "quorum": self.config.quorum,
            "replicas": self.config.replicas,
            "dead": sorted(self._dead),
            "frames": len(self.broker.replicator.log)
            if self.broker.replicator is not None else 0,
            "followers": {},
        }
        for m in self._followers.values():
            try:
                out["followers"][m.idx] = m.client.repl_status()
            except (BrokerUnavailableError, ConnectionError, OSError):
                out["followers"][m.idx] = None
        return out

    def client(self, **kw) -> BrokerClient:
        return BrokerClient(self.host, self.port, **kw)

    def close(self) -> None:
        self.server.close()
        self.broker.close()
        for m in self._followers.values():
            try:
                m.client.close()
            except OSError:
                pass
            m.server.close()
            m.replica.close()
        self._followers.clear()

    def __enter__(self) -> "BrokerCell":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

"""torchkafka_tpu — TPU-native Kafka streaming-ingest framework.

A ground-up JAX/XLA rebuild of the capabilities of Bendabir/torch-kafka
(reference at /root/reference): stream records from Kafka into
accelerator-ready global jax.Arrays with manual, commit-after-step offset
semantics (at-least-once delivery), scaled from one process to a TPU pod.

The reference exports exactly two names — ``KafkaDataset`` and ``auto_commit``
(/root/reference/src/__init__.py:17-18). We export the TPU-native core
(KafkaStream and friends) plus a drop-in compatibility surface for migrating
reference users (torchkafka_tpu.compat).
"""

from torchkafka_tpu.checkpoint import StreamCheckpointer
from torchkafka_tpu.utils import ShutdownSignal
from torchkafka_tpu.commit import (
    CommitBarrier,
    CommitToken,
    LocalBarrier,
    OffsetLedger,
)
from torchkafka_tpu.errors import (
    BarrierError,
    BrokerUnavailableError,
    CommitFailedError,
    ConsumerClosedError,
    FencedMemberError,
    JournalLockedError,
    OutputDeliveryError,
    PoisonRecordError,
    ProducerClosedError,
    ProducerFencedError,
    QuorumLostError,
    StaleEpochError,
    TpuKafkaError,
    TransactionStateError,
)
from torchkafka_tpu.journal import DecodeJournal, JournalEntry
from torchkafka_tpu.kvcache import (
    HostTier,
    KVBackend,
    PagedKVConfig,
    TierConfig,
    resolve_kv_backend,
)
from torchkafka_tpu.obs import (
    BurnRateMonitor,
    MetricsExporter,
    ObsConfig,
    RecordTrace,
    RecordTracer,
    SLOTarget,
)
from torchkafka_tpu.parallel import batch_sharding, global_batch, make_mesh
from torchkafka_tpu.pipeline import KafkaStream, stream
from torchkafka_tpu.resilience import (
    CircuitBreaker,
    ManualClock,
    PoisonQuarantine,
    ResilientConsumer,
    RetryPolicy,
)
from torchkafka_tpu.source import (
    ChaosConsumer,
    ChaosProducer,
    ChaosTransport,
    Consumer,
    BrokerCell,
    BrokerClient,
    BrokerServer,
    FollowerReplica,
    InMemoryBroker,
    ReplicationConfig,
    Replicator,
    KafkaConsumer,
    KafkaProducer,
    KafkaTransactionalProducer,
    MemoryConsumer,
    MemoryProducer,
    Producer,
    RecordMetadata,
    TransactionalProducer,
    dead_letter_to_topic,
    seek_to_timestamp,
    Record,
    TopicPartition,
    WireFaults,
    WriteAheadLog,
    partitions_for_process,
)
from torchkafka_tpu.workload import (
    ChaosSchedule,
    WorkloadConfig,
    WorkloadGenerator,
)
from torchkafka_tpu.transform import (
    Batch,
    Batcher,
    chunk_of,
    chunked,
    compose,
    encode_png_rgb,
    fixed_width,
    json_field,
    json_tokens,
    png_images,
    raw_bytes,
)

__version__ = "0.21.0"

__all__ = [
    "BarrierError",
    "Batch",
    "Batcher",
    "BrokerUnavailableError",
    "CircuitBreaker",
    "CommitBarrier",
    "CommitFailedError",
    "CommitToken",
    "ChaosConsumer",
    "ChaosProducer",
    "ChaosTransport",
    "Consumer",
    "ConsumerClosedError",
    "DecodeJournal",
    "FencedMemberError",
    "JournalEntry",
    "JournalLockedError",
    "HostTier",
    "KVBackend",
    "PagedKVConfig",
    "TierConfig",
    "resolve_kv_backend",
    "BrokerCell",
    "BrokerClient",
    "BrokerServer",
    "FollowerReplica",
    "InMemoryBroker",
    "KafkaConsumer",
    "KafkaProducer",
    "KafkaTransactionalProducer",
    "KafkaStream",
    "LocalBarrier",
    "ManualClock",
    "MemoryConsumer",
    "MemoryProducer",
    "MetricsExporter",
    "ObsConfig",
    "OutputDeliveryError",
    "PoisonQuarantine",
    "PoisonRecordError",
    "Producer",
    "ProducerClosedError",
    "ProducerFencedError",
    "QuorumLostError",
    "ReplicationConfig",
    "Replicator",
    "StaleEpochError",
    "BurnRateMonitor",
    "ChaosSchedule",
    "RecordMetadata",
    "RecordTrace",
    "RecordTracer",
    "ResilientConsumer",
    "RetryPolicy",
    "SLOTarget",
    "WorkloadConfig",
    "WorkloadGenerator",
    "dead_letter_to_topic",
    "seek_to_timestamp",
    "OffsetLedger",
    "Record",
    "ShutdownSignal",
    "StreamCheckpointer",
    "TopicPartition",
    "TpuKafkaError",
    "TransactionStateError",
    "TransactionalProducer",
    "WireFaults",
    "WriteAheadLog",
    "batch_sharding",
    "chunk_of",
    "chunked",
    "compose",
    "encode_png_rgb",
    "fixed_width",
    "global_batch",
    "json_field",
    "json_tokens",
    "make_mesh",
    "partitions_for_process",
    "png_images",
    "raw_bytes",
    "stream",
]

"""Headline benchmark: sustained ingest throughput, records/sec.

Measures the BASELINE.md metric (records/sec sustained ingest through the
full transactional loop: poll → transform → batch → device → step → barrier →
commit) for two implementations over the SAME in-memory Kafka-semantics
broker and the SAME records:

- **baseline**: the reference's architecture — our drop-in compat layer
  running the reference's exact single-process pattern (KafkaDataset
  subclass → torch DataLoader collation → auto_commit generator,
  /root/reference/README.md:86-102). The reference publishes no numbers
  (SURVEY.md §6), so its own design measured on the same hardware IS the
  baseline.
- **ours**: the TPU-native KafkaStream (threaded poll/transform pipeline,
  fixed-shape batcher, async device transfer, commit tokens), with each
  batch consumed by a real jitted reduction on the accelerator and offsets
  committed per batch via the barrier.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "records/sec", "vs_baseline": N}

Env knobs: BENCH_RECORDS (ours, default 1_000_000), BENCH_BASELINE_RECORDS
(default 150_000), BENCH_BATCH (default 4096), BENCH_SEQ (tokens/record, 32).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

SEQ = int(os.environ.get("BENCH_SEQ", "32"))
N_OURS = int(os.environ.get("BENCH_RECORDS", "1000000"))
N_BASE = int(os.environ.get("BENCH_BASELINE_RECORDS", "150000"))
BATCH = int(os.environ.get("BENCH_BATCH", "8192"))
COMMIT_EVERY = int(os.environ.get("BENCH_COMMIT_EVERY", "16"))
N_PARTS = 8


def fill_broker(tk, n_records: int):
    """One topic, N_PARTS partitions, fixed-width int32-token payloads."""
    broker = tk.InMemoryBroker()
    broker.create_topic("bench", partitions=N_PARTS)
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 32000, size=(256, SEQ), dtype=np.int32)
    # Round so the total divides evenly into BATCH-row batches: the stream
    # then ends on a full batch and the timed region has no idle-flush tail.
    step = max(BATCH // N_PARTS, 1) if BATCH % N_PARTS == 0 else 1
    per_part = max(n_records // N_PARTS // step, 1) * step
    for p in range(N_PARTS):
        broker.produce_many(
            "bench",
            (payload[i % 256].tobytes() for i in range(per_part)),
            partition=p,
        )
    return broker, per_part * N_PARTS


def bench_ours(n_records: int) -> float:
    import jax
    import jax.numpy as jnp

    import torchkafka_tpu as tk

    broker, total = fill_broker(tk, n_records)
    consumer = tk.MemoryConsumer(
        broker,
        "bench",
        group_id="bench-tpu",
        assignment=tk.partitions_for_process("bench", N_PARTS, 0, 1),
    )

    processor = tk.fixed_width(SEQ, dtype=np.int32)

    @jax.jit
    def step(tokens):
        return jnp.sum(tokens, dtype=jnp.int32)

    rows = 0
    acc = None
    with tk.KafkaStream(
        consumer,
        processor,
        batch_size=BATCH,
        mesh=None,
        pad_policy="pad",
        prefetch=4,
        max_poll_records=16384,
        idle_timeout_ms=2000,
        transform_threads=0,
        owns_consumer=True,
    ) as stream:
        # Warm the compile outside the timed region.
        jax.block_until_ready(step(jnp.zeros((BATCH, SEQ), jnp.int32)))
        fut = None
        n_batches = 0
        t0 = time.perf_counter()
        for batch, token in stream:
            acc = step(batch.data)
            rows += batch.valid_count
            n_batches += 1
            # Commit cadence: every COMMIT_EVERY batches (async, FIFO commit
            # thread) — a later token's offsets subsume the uncommitted
            # earlier ones, so this is the standard Kafka commit-interval
            # pattern with an at-least-once window of COMMIT_EVERY batches.
            # Proving step retirement costs a device fetch (~100 ms of pure
            # latency on tunneled transports), so per-batch cadence is a
            # latency benchmark, not a throughput one.
            if n_batches % COMMIT_EVERY == 0 or rows >= total:
                fut = token.commit_async(wait_for=acc)
            if rows >= total:  # deterministic end: no idle-timeout tail in the timing
                break
        if fut is not None:
            assert fut.result(timeout=120)  # last commit durable inside the timing
        elapsed = time.perf_counter() - t0
    assert rows == total, f"consumed {rows} != produced {total}"
    return rows / elapsed


def bench_reference_pattern(n_records: int) -> float:
    """The reference's single-process flow via the compat layer
    (/root/reference/README.md:86-102): DataLoader batching + commit-per-batch."""
    import torch
    from torch.utils.data import DataLoader

    import torchkafka_tpu as tk
    from torchkafka_tpu.compat import KafkaDataset, auto_commit

    broker, total = fill_broker(tk, n_records)

    class BenchDataset(KafkaDataset):
        def _process(self, record):
            return torch.from_numpy(
                np.frombuffer(record.value, dtype=np.int32).copy()
            )

        @classmethod
        def new_consumer(cls, *args, **kwargs):
            kwargs.pop("_is_placeholder", None)
            return tk.MemoryConsumer(
                broker,
                *args,
                assignment=tk.partitions_for_process("bench", N_PARTS, 0, 1),
                consumer_timeout_ms=500,
                **kwargs,
            )

    dataset = BenchDataset("bench", group_id="bench-ref")
    loader = DataLoader(dataset, batch_size=BATCH)
    rows = 0
    t0 = time.perf_counter()
    for batch in auto_commit(loader):
        rows += int(batch.shape[0])
        batch.sum()  # the user's "work" — same reduction as ours, on CPU torch
        if rows >= total:  # symmetric deterministic end
            break
    elapsed = time.perf_counter() - t0
    assert rows == total, f"consumed {rows} != produced {total}"
    return rows / elapsed


def main() -> None:
    trials = int(os.environ.get("BENCH_TRIALS", "5"))
    # Best-of-k: ingest is a sustained-throughput metric; transient scheduler
    # noise (this box shares cores with the TPU tunnel) only ever subtracts.
    ours = max(bench_ours(N_OURS) for _ in range(trials))
    base = max(bench_reference_pattern(N_BASE) for _ in range(trials))
    print(
        json.dumps(
            {
                "metric": "sustained_ingest_throughput",
                "value": round(ours, 1),
                "unit": "records/sec",
                "vs_baseline": round(ours / base, 3),
            }
        )
    )
    print(
        f"ours={ours:,.0f} rec/s  reference-pattern={base:,.0f} rec/s  "
        f"records={N_OURS:,}/{N_BASE:,} batch={BATCH} seq={SEQ}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()

"""Headline benchmark: sustained ingest throughput, records/sec.

Measures the BASELINE.md metric (records/sec sustained ingest through the
full transactional loop: poll → transform → batch → device → step → barrier →
commit) for two implementations over the SAME in-memory Kafka-semantics
broker and the SAME records:

- **baseline**: the reference's architecture — our drop-in compat layer
  running the reference's exact single-process pattern (KafkaDataset
  subclass → torch DataLoader collation → auto_commit generator,
  /root/reference/README.md:86-102). The reference publishes no numbers
  (SURVEY.md §6), so its own design measured on the same hardware IS the
  baseline.
- **ours**: the TPU-native KafkaStream (threaded poll/transform pipeline,
  fixed-shape batcher, async device transfer, commit tokens), with each
  batch consumed by a REAL device step — the flagship transformer's forward
  loss (bf16 MXU matmuls) over the ingested tokens — and offsets
  committed via the barrier (async, every COMMIT_EVERY batches).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "records/sec", "vs_baseline": N}

Env knobs: BENCH_RECORDS (ours, default 1_000_000), BENCH_BASELINE_RECORDS
(default 150_000), BENCH_BATCH (default 32768), BENCH_SEQ (tokens/record, 32),
BENCH_TRIALS (default 5), BENCH_COMMIT_EVERY (default 16).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

SEQ = int(os.environ.get("BENCH_SEQ", "32"))
N_OURS = int(os.environ.get("BENCH_RECORDS", "1000000"))
N_BASE = int(os.environ.get("BENCH_BASELINE_RECORDS", "150000"))
# Batch 32768 = ~2 MB uint16 wire transfers: host→device dispatch is
# latency-dominated on tunneled transports (~45 ms for 0.5 MB, ~80 ms for
# 2 MB), so larger batches quadruple rows-per-roundtrip for ~2x the cost.
BATCH = int(os.environ.get("BENCH_BATCH", "32768"))
COMMIT_EVERY = int(os.environ.get("BENCH_COMMIT_EVERY", "16"))
N_PARTS = 8


def fill_broker(tk, n_records: int):
    """One topic, N_PARTS partitions, fixed-width int32-token payloads."""
    broker = tk.InMemoryBroker()
    broker.create_topic("bench", partitions=N_PARTS)
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 32000, size=(256, SEQ), dtype=np.int32)
    # Round so the total divides evenly into BATCH-row batches: the stream
    # then ends on a full batch and the timed region has no idle-flush tail.
    step = max(BATCH // N_PARTS, 1) if BATCH % N_PARTS == 0 else 1
    per_part = max(n_records // N_PARTS // step, 1) * step
    for p in range(N_PARTS):
        broker.produce_many(
            "bench",
            (payload[i % 256].tobytes() for i in range(per_part)),
            partition=p,
        )
    return broker, per_part * N_PARTS


_STEP_CACHE: dict = {}


def _device_step():
    """A REAL device step: embed the ingested tokens and run a bf16 MLP
    tower (~34 GFLOP/batch of MXU matmuls) to a scalar loss — not a
    decorative reduction. MXU-shaped on purpose: seq-32 records make
    per-head [32, 32] attention matmuls (scenario 3 trains the full
    transformer and reports MFU at seq 512); an ingest-side consumer of
    short records is matmul-tower shaped. Sized so the bench stays an
    ingest benchmark: a few ms per batch, overlapped with host polling via
    the async dispatch queue."""
    import jax
    import jax.numpy as jnp

    if "step" in _STEP_CACHE:
        return _STEP_CACHE["step"]
    d_embed, d_h = 128, 512
    ks = jax.random.split(jax.random.key(0), 4)
    params = {
        "embed": jax.random.normal(ks[0], (512, d_embed), jnp.bfloat16) * 0.02,
        "w1": jax.random.normal(ks[1], (SEQ * d_embed, d_h), jnp.bfloat16) * 0.02,
        "w2": jax.random.normal(ks[2], (d_h, d_h), jnp.bfloat16) * 0.02,
        "w3": jax.random.normal(ks[3], (d_h, 1), jnp.bfloat16) * 0.02,
    }

    @jax.jit
    def step(tokens):
        x = params["embed"][tokens % 512].reshape(tokens.shape[0], -1)
        h = jax.nn.gelu(x @ params["w1"])
        h = jax.nn.gelu(h @ params["w2"])
        return jnp.mean((h @ params["w3"]).astype(jnp.float32) ** 2)

    _STEP_CACHE["step"] = step
    return step


def bench_ours(n_records: int) -> float:
    import jax
    import jax.numpy as jnp

    import torchkafka_tpu as tk

    broker, total = fill_broker(tk, n_records)
    consumer = tk.MemoryConsumer(
        broker,
        "bench",
        group_id="bench-tpu",
        assignment=tk.partitions_for_process("bench", N_PARTS, 0, 1),
    )

    # Token ids are < 32000: ship them as uint16 — host→device wire bytes
    # are the scarce resource (see fixed_width's wire_dtype note).
    processor = tk.fixed_width(SEQ, dtype=np.int32, wire_dtype=np.uint16)
    step = _device_step()

    rows = 0
    acc = None
    with tk.KafkaStream(
        consumer,
        processor,
        batch_size=BATCH,
        mesh=None,
        pad_policy="pad",
        prefetch=4,
        max_poll_records=16384,
        idle_timeout_ms=2000,
        transform_threads=0,
        owns_consumer=True,
    ) as stream:
        # Warm the compile outside the timed region (strict: scalar fetch —
        # block_until_ready alone returns early through the tunnel).
        float(step(jnp.zeros((BATCH, SEQ), jnp.uint16)))
        fut = None
        n_batches = 0
        t0 = time.perf_counter()
        for batch, token in stream:
            acc = step(batch.data)
            rows += batch.valid_count
            n_batches += 1
            # Commit cadence: every COMMIT_EVERY batches (async, FIFO commit
            # thread) — a later token's offsets subsume the uncommitted
            # earlier ones, so this is the standard Kafka commit-interval
            # pattern with an at-least-once window of COMMIT_EVERY batches.
            # Proving step retirement costs a device fetch (~100 ms of pure
            # latency on tunneled transports), so per-batch cadence is a
            # latency benchmark, not a throughput one.
            if n_batches % COMMIT_EVERY == 0 or rows >= total:
                fut = token.commit_async(wait_for=acc)
            if rows >= total:  # deterministic end: no idle-timeout tail in the timing
                break
        if fut is not None:
            assert fut.result(timeout=120)  # last commit durable inside the timing
        elapsed = time.perf_counter() - t0
    assert rows == total, f"consumed {rows} != produced {total}"
    return rows / elapsed


def bench_reference_pattern(n_records: int) -> float:
    """The reference's single-process flow via the compat layer
    (/root/reference/README.md:86-102): DataLoader batching + commit-per-batch.

    SAME device step and SAME uint16 wire cast as ours — reference users
    ship their batches to an accelerator too, so both loops pay identical
    transfer + compute costs and the ratio isolates the INGEST ARCHITECTURE
    (threaded chunk pipeline + async commits vs DataLoader iteration +
    per-batch signal commits), not the transport du jour."""
    import jax
    import jax.numpy as jnp
    import torch
    from torch.utils.data import DataLoader

    import torchkafka_tpu as tk
    from torchkafka_tpu.compat import KafkaDataset, auto_commit

    broker, total = fill_broker(tk, n_records)

    class BenchDataset(KafkaDataset):
        def _process(self, record):
            return torch.from_numpy(
                np.frombuffer(record.value, dtype=np.int32).copy()
            )

        @classmethod
        def new_consumer(cls, *args, **kwargs):
            kwargs.pop("_is_placeholder", None)
            return tk.MemoryConsumer(
                broker,
                *args,
                assignment=tk.partitions_for_process("bench", N_PARTS, 0, 1),
                consumer_timeout_ms=500,
                **kwargs,
            )

    dataset = BenchDataset("bench", group_id="bench-ref")
    loader = DataLoader(dataset, batch_size=BATCH)
    step = _device_step()
    float(step(jnp.zeros((BATCH, SEQ), jnp.uint16)))  # warm outside timing
    rows = 0
    acc = None
    t0 = time.perf_counter()
    for batch in auto_commit(loader):
        rows += int(batch.shape[0])
        # The user's work: same uint16 wire cast, same transfer, same MLP
        # step as ours (torch -> numpy -> device is the torch-user path).
        acc = step(jnp.asarray(batch.numpy().astype(np.uint16)))
        if rows >= total:  # symmetric deterministic end
            break
    if acc is not None:
        float(acc)  # strict completion proof inside the timing, like ours
    elapsed = time.perf_counter() - t0
    assert rows == total, f"consumed {rows} != produced {total}"
    return rows / elapsed


def probe_wire_mb_s() -> float:
    """Measured host→device throughput for one batch-sized transfer (median
    of 3). Context for the headline: on tunneled dev transports this is
    ~10-30 MB/s and bounds the whole loop; real TPU hosts see GB/s."""
    import time as _time

    import jax
    import jax.numpy as jnp

    a = np.random.default_rng(0).integers(0, 100, (BATCH, SEQ), dtype=np.uint16)
    s = jax.jit(lambda x: jnp.sum(x, dtype=jnp.int32))
    int(s(jnp.asarray(a)))  # warm compile + connection
    mb = a.nbytes / 1e6
    rates = []
    for i in range(3):
        t0 = _time.perf_counter()
        int(s(jax.device_put(a + i)))
        rates.append(mb / (_time.perf_counter() - t0))
    return float(np.median(rates))


def _one_trial(fn, label: str, budget: list) -> float | None:
    """One trial, tolerating transient transport failures (bounded by the
    shared retry budget)."""
    while budget[0] > 0:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - transient transport errors
            budget[0] -= 1
            print(f"{label} trial failed ({e!r}); retrying", file=sys.stderr)
            time.sleep(5)
    return None


def main() -> None:
    trials = int(os.environ.get("BENCH_TRIALS", "5"))
    # Headline = MEDIAN over trials (robust to scheduler noise on this shared
    # box without crediting the best outlier); best and spread reported
    # alongside so the distribution is visible.
    try:
        wire = probe_wire_mb_s()
    except Exception as e:  # noqa: BLE001
        print(f"wire probe failed ({e!r})", file=sys.stderr)
        wire = -1.0
    # INTERLEAVED trials: the shared box's conditions drift minute-to-minute,
    # so alternating sides samples the same conditions for both and keeps the
    # ratio honest; a bounded retry budget covers transient transport drops.
    budget = [trials + 4]
    ours_all: list[float] = []
    base_all: list[float] = []
    for _ in range(trials):
        r = _one_trial(lambda: bench_ours(N_OURS), "ours", budget)
        if r is not None:
            ours_all.append(r)
        r = _one_trial(
            lambda: bench_reference_pattern(N_BASE), "reference-pattern", budget
        )
        if r is not None:
            base_all.append(r)
    if not ours_all or not base_all:
        raise RuntimeError("no successful trials on one side")
    ours_all.sort()
    base_all.sort()
    ours = float(np.median(ours_all))
    base = float(np.median(base_all))
    print(
        json.dumps(
            {
                "metric": "sustained_ingest_throughput",
                "value": round(ours, 1),
                "unit": "records/sec",
                "vs_baseline": round(ours / base, 3),
                "trials": trials,
                "spread": [round(ours_all[0], 1), round(ours_all[-1], 1)],
                "best": round(ours_all[-1], 1),
                "baseline_median": round(base, 1),
                "wire_mb_s": round(wire, 1),
            }
        )
    )
    print(
        f"ours median={ours:,.0f} rec/s (min {ours_all[0]:,.0f}, max "
        f"{ours_all[-1]:,.0f})  reference-pattern median={base:,.0f} rec/s  "
        f"records={N_OURS:,}/{N_BASE:,} batch={BATCH} seq={SEQ} "
        f"device-step=mlp-tower  wire={wire:.1f} MB/s",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()

"""Headline benchmark: sustained ingest throughput, records/sec.

Measures the BASELINE.md metric (records/sec sustained ingest through the
full transactional loop: poll → transform → batch → device → step → barrier →
commit) for two implementations over the SAME in-memory Kafka-semantics
broker and the SAME records:

- **baseline**: the reference's architecture — our drop-in compat layer
  running the reference's exact single-process pattern (KafkaDataset
  subclass → torch DataLoader collation → auto_commit generator,
  /root/reference/README.md:86-102). The reference publishes no numbers
  (SURVEY.md §6), so its own design measured on the same hardware IS the
  baseline.
- **ours**: the TPU-native KafkaStream (threaded poll/transform pipeline,
  fixed-shape batcher, async device transfer, commit tokens), with each
  batch consumed by a REAL device step — the flagship transformer's forward
  loss (bf16 MXU matmuls) over the ingested tokens — and offsets
  committed via the barrier (async, every COMMIT_EVERY batches).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "records/sec", "vs_baseline": N}

Trial protocol (VERDICT r2): trials are INTERLEAVED ours/baseline pairs over
EQUAL record counts, each pair preceded by a wire probe, and ``vs_baseline``
is the MEDIAN OF PER-PAIR RATIOS — adjacent runs sample the same transport
conditions, so the ratio stays stable even when absolute throughput swings
several× across the run (every trial's wire speed is emitted for post-hoc
normalisation).

Env knobs: BENCH_RECORDS (default 1_000_000 — both sides),
BENCH_BASELINE_RECORDS (override the baseline side only), BENCH_BATCH
(default 32768), BENCH_SEQ (tokens/record, 32), BENCH_TRIALS (default 5),
BENCH_SLICES (alternating slices per trial, 4), BENCH_COMMIT_EVERY (16),
BENCH_WIRE (ours' wire format: "pack15" — 15-bit packed tokens, device-side
unpack, the framework's sub-byte codec — or "uint16"; default pack15).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

SEQ = int(os.environ.get("BENCH_SEQ", "32"))
# Equal records per side by default: asymmetric trial lengths sample a
# drifting wire differently even when interleaved (the r2 spread problem).
N_OURS = int(os.environ.get("BENCH_RECORDS", "1000000"))
N_BASE = int(os.environ.get("BENCH_BASELINE_RECORDS", str(N_OURS)))
# Batch 32768 = ~2 MB uint16 wire transfers: host→device dispatch is
# latency-dominated on tunneled transports (~45 ms for 0.5 MB, ~80 ms for
# 2 MB), so larger batches quadruple rows-per-roundtrip for ~2x the cost.
BATCH = int(os.environ.get("BENCH_BATCH", "32768"))
COMMIT_EVERY = int(os.environ.get("BENCH_COMMIT_EVERY", "16"))
N_PARTS = 8
# Ours' wire format. "pack15": tokens < 32768 ride the wire as a dense
# 15-bit stream (fixed_width wire_bits=15 → C-packed on host, unpacked
# on-device by ops.bitpack — a framework codec the reference pattern has no
# analog for) = 60 bytes/record vs uint16's 64. The baseline side always
# ships uint16 — the narrowest NUMPY-native cast a torch user would write;
# sub-byte packing requires the codec itself, which IS part of the ingest
# architecture under test.
WIRE = os.environ.get("BENCH_WIRE", "pack15")
if WIRE not in ("pack15", "uint16"):
    raise SystemExit(f"BENCH_WIRE must be pack15|uint16, got {WIRE!r}")


def fill_broker(tk, n_records: int):
    """One topic, N_PARTS partitions, fixed-width int32-token payloads."""
    broker = tk.InMemoryBroker()
    broker.create_topic("bench", partitions=N_PARTS)
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 32000, size=(256, SEQ), dtype=np.int32)
    # Round so the total divides evenly into BATCH-row batches: the stream
    # then ends on a full batch and the timed region has no idle-flush tail.
    step = max(BATCH // N_PARTS, 1) if BATCH % N_PARTS == 0 else 1
    per_part = max(n_records // N_PARTS // step, 1) * step
    for p in range(N_PARTS):
        broker.produce_many(
            "bench",
            (payload[i % 256].tobytes() for i in range(per_part)),
            partition=p,
        )
    return broker, per_part * N_PARTS


_STEP_CACHE: dict = {}


def _device_step(packed: bool = False):
    """A REAL device step: embed the ingested tokens and run a bf16 MLP
    tower (~34 GFLOP/batch of MXU matmuls) to a scalar loss — not a
    decorative reduction. MXU-shaped on purpose: seq-32 records make
    per-head [32, 32] attention matmuls (scenario 3 trains the full
    transformer and reports MFU at seq 512); an ingest-side consumer of
    short records is matmul-tower shaped. Sized so the bench stays an
    ingest benchmark: a few ms per batch, overlapped with host polling via
    the async dispatch queue.

    ``packed``: the batch arrives as the 15-bit wire stream and the step's
    first op is the on-device unpack (ops.bitpack) — bit twiddling is free
    next to the matmul tower, which is the codec's whole premise."""
    import jax
    import jax.numpy as jnp

    key = "step-packed" if packed else "step"
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]
    d_embed, d_h = 128, 512
    ks = jax.random.split(jax.random.key(0), 4)
    params = {
        "embed": jax.random.normal(ks[0], (512, d_embed), jnp.bfloat16) * 0.02,
        "w1": jax.random.normal(ks[1], (SEQ * d_embed, d_h), jnp.bfloat16) * 0.02,
        "w2": jax.random.normal(ks[2], (d_h, d_h), jnp.bfloat16) * 0.02,
        "w3": jax.random.normal(ks[3], (d_h, 1), jnp.bfloat16) * 0.02,
    }

    @jax.jit
    def step(tokens):
        if packed:
            from torchkafka_tpu.ops.bitpack import unpack_bits

            tokens = unpack_bits(tokens, 15, SEQ)
        x = params["embed"][tokens % 512].reshape(tokens.shape[0], -1)
        h = jax.nn.gelu(x @ params["w1"])
        h = jax.nn.gelu(h @ params["w2"])
        return jnp.mean((h @ params["w3"]).astype(jnp.float32) ** 2)

    _STEP_CACHE[key] = step
    return step


_BROKERS: dict = {}
# Unique consumer-group id per bench invocation: groups carry committed
# offsets on the shared broker, so a retried trial reusing a group would
# resume mid-stream instead of replaying from 0.
_GROUP_SEQ = iter(range(10**9))


def _shared_broker(side: str, n_records: int):
    """Fill each side's broker ONCE and re-read it with a fresh consumer
    group per trial: refilling 600k records per trial put ~30s between the
    two sides of an interleaved pair, long enough for the shared box's wire
    to drift and reopen the ratio spread the pairing exists to close."""
    import torchkafka_tpu as tk

    if side not in _BROKERS:
        _BROKERS[side] = fill_broker(tk, n_records)
    return _BROKERS[side]


def bench_ours(n_records: int) -> float:
    import jax
    import jax.numpy as jnp

    import torchkafka_tpu as tk

    broker, total = _shared_broker("ours", n_records)
    consumer = tk.MemoryConsumer(
        broker,
        "bench",
        group_id=f"bench-tpu-{next(_GROUP_SEQ)}",
        assignment=tk.partitions_for_process("bench", N_PARTS, 0, 1),
    )

    # Token ids are < 32000: host→device wire bytes are the scarce
    # resource. pack15 ships them as a dense 15-bit stream (60 B/record);
    # uint16 is the byte-aligned fallback (64 B/record).
    packed = WIRE == "pack15"
    processor = (
        tk.fixed_width(SEQ, dtype=np.int32, wire_bits=15)
        if packed
        else tk.fixed_width(SEQ, dtype=np.int32, wire_dtype=np.uint16)
    )
    step = _device_step(packed=packed)

    rows = 0
    acc = None
    with tk.KafkaStream(
        consumer,
        processor,
        batch_size=BATCH,
        mesh=None,
        pad_policy="pad",
        prefetch=4,
        max_poll_records=16384,
        idle_timeout_ms=2000,
        transform_threads=0,
        owns_consumer=True,
    ) as stream:
        # Warm the compile AND the host→device transfer route outside the
        # timed region (strict: scalar fetch — block_until_ready alone
        # returns early through the tunnel). jnp.zeros would materialise
        # on-device and leave the transfer path cold for the first batch.
        if packed:
            from torchkafka_tpu.native import packed_width

            warm_in = np.zeros((BATCH, packed_width(SEQ, 15)), np.uint8)
        else:
            warm_in = np.zeros((BATCH, SEQ), np.uint16)
        float(step(jnp.asarray(warm_in)))
        fut = None
        n_batches = 0
        t0 = time.perf_counter()
        for batch, token in stream:
            acc = step(batch.data)
            rows += batch.valid_count
            n_batches += 1
            # Commit cadence: every COMMIT_EVERY batches (async, FIFO commit
            # thread) — a later token's offsets subsume the uncommitted
            # earlier ones, so this is the standard Kafka commit-interval
            # pattern with an at-least-once window of COMMIT_EVERY batches.
            # Proving step retirement costs a device fetch (~100 ms of pure
            # latency on tunneled transports), so per-batch cadence is a
            # latency benchmark, not a throughput one.
            if n_batches % COMMIT_EVERY == 0 or rows >= total:
                fut = token.commit_async(wait_for=acc)
            if rows >= total:  # deterministic end: no idle-timeout tail in the timing
                break
        if fut is not None:
            assert fut.result(timeout=120)  # last commit durable inside the timing
        elapsed = time.perf_counter() - t0
    assert rows == total, f"consumed {rows} != produced {total}"
    return rows / elapsed


def bench_reference_pattern(n_records: int) -> float:
    """The reference's single-process flow via the compat layer
    (/root/reference/README.md:86-102): DataLoader batching + commit-per-batch.

    SAME device step and SAME uint16 wire cast as ours — reference users
    ship their batches to an accelerator too, so both loops pay identical
    transfer + compute costs and the ratio isolates the INGEST ARCHITECTURE
    (threaded chunk pipeline + async commits vs DataLoader iteration +
    per-batch signal commits), not the transport du jour."""
    import jax
    import jax.numpy as jnp
    import torch
    from torch.utils.data import DataLoader

    import torchkafka_tpu as tk
    from torchkafka_tpu.compat import KafkaDataset, auto_commit

    broker, total = _shared_broker("ref", n_records)

    class BenchDataset(KafkaDataset):
        def _process(self, record):
            return torch.from_numpy(
                np.frombuffer(record.value, dtype=np.int32).copy()
            )

        @classmethod
        def new_consumer(cls, *args, **kwargs):
            kwargs.pop("_is_placeholder", None)
            return tk.MemoryConsumer(
                broker,
                *args,
                assignment=tk.partitions_for_process("bench", N_PARTS, 0, 1),
                consumer_timeout_ms=500,
                **kwargs,
            )

    dataset = BenchDataset("bench", group_id=f"bench-ref-{next(_GROUP_SEQ)}")
    loader = DataLoader(dataset, batch_size=BATCH)
    step = _device_step()
    # Warm compile + transfer route outside timing (symmetric with ours).
    float(step(jnp.asarray(np.zeros((BATCH, SEQ), np.uint16))))
    rows = 0
    acc = None
    t0 = time.perf_counter()
    for batch in auto_commit(loader):
        rows += int(batch.shape[0])
        # The user's work: same uint16 wire cast, same transfer, same MLP
        # step as ours (torch -> numpy -> device is the torch-user path).
        acc = step(jnp.asarray(batch.numpy().astype(np.uint16)))
        if rows >= total:  # symmetric deterministic end
            break
    if acc is not None:
        float(acc)  # strict completion proof inside the timing, like ours
    elapsed = time.perf_counter() - t0
    assert rows == total, f"consumed {rows} != produced {total}"
    return rows / elapsed


def probe_wire_mb_s() -> float:
    """Measured host→device throughput for one batch-sized transfer (median
    of 3). Context for the headline: on tunneled dev transports this is
    ~10-30 MB/s and bounds the whole loop; real TPU hosts see GB/s."""
    import time as _time

    import jax
    import jax.numpy as jnp

    a = np.random.default_rng(0).integers(0, 100, (BATCH, SEQ), dtype=np.uint16)
    s = jax.jit(lambda x: jnp.sum(x, dtype=jnp.int32))
    int(s(jnp.asarray(a)))  # warm compile + connection
    mb = a.nbytes / 1e6
    rates = []
    for i in range(3):
        t0 = _time.perf_counter()
        int(s(jax.device_put(a + i)))
        rates.append(mb / (_time.perf_counter() - t0))
    return float(np.median(rates))


def _one_trial(fn, label: str, budget: list) -> float | None:
    """One trial, tolerating transient transport failures (bounded by the
    shared retry budget)."""
    while budget[0] > 0:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - transient transport errors
            budget[0] -= 1
            print(f"{label} trial failed ({e!r}); retrying", file=sys.stderr)
            time.sleep(5)
    return None


def main() -> None:
    trials = int(os.environ.get("BENCH_TRIALS", "5"))
    # Headline = MEDIAN over trials (robust to scheduler noise on this shared
    # box without crediting the best outlier); best and spread reported
    # alongside so the distribution is visible.
    budget = [2 * trials + 6]
    slices = max(1, int(os.environ.get("BENCH_SLICES", "4")))
    n_o, n_b = N_OURS // slices, N_BASE // slices
    # Untimed warmup slice per side, BEFORE the first wire probe (r3: the
    # only losing pair was the FIRST — first-contact costs land there
    # otherwise: broker fill + allocator growth, XLA compiles, transfer-
    # route ramp, branch-cold Python; and the probe must sample pair 1's
    # conditions, not pre-warmup conditions). Result discarded.
    _one_trial(lambda: bench_ours(n_o), "ours-warmup", budget)
    _one_trial(lambda: bench_reference_pattern(n_b), "ref-warmup", budget)
    try:
        wire = probe_wire_mb_s()
    except Exception as e:  # noqa: BLE001
        print(f"wire probe failed ({e!r})", file=sys.stderr)
        wire = -1.0
    # INTERLEAVED ours/baseline pairs: the shared box's conditions drift
    # minute-to-minute, so adjacent runs sample (nearly) the same transport
    # and the PER-PAIR ratio cancels the drift that swamps absolute numbers.
    # A wire probe before each pair records the conditions it ran under.
    ours_all: list[float] = []
    base_all: list[float] = []
    pair_ratios: list[float] = []
    wires: list[float] = [wire]
    # Each trial runs SLICES slices per side, alternating O/B/O/B…: the two
    # sides of a slice pair execute within seconds of each other, so the
    # per-trial ratio (sum of timed regions per side) samples near-identical
    # wire conditions even though the wire drifts several× across the run.
    for i in range(trials):
        if i > 0:
            try:
                wires.append(probe_wire_mb_s())
            except Exception:  # noqa: BLE001
                wires.append(-1.0)
        o_time = b_time = 0.0
        o_rows = b_rows = 0
        for _ in range(slices):
            r = _one_trial(lambda: bench_ours(n_o), "ours", budget)
            if r is not None:
                o_time += n_o / r
                o_rows += n_o
            r = _one_trial(
                lambda: bench_reference_pattern(n_b), "reference-pattern",
                budget,
            )
            if r is not None:
                b_time += n_b / r
                b_rows += n_b
        o = o_rows / o_time if o_time else None
        b = b_rows / b_time if b_time else None
        if o is not None:
            ours_all.append(o)
        if b is not None:
            base_all.append(b)
        if o is not None and b is not None:
            pair_ratios.append(o / b)
    if not ours_all or not base_all:
        raise RuntimeError("no successful trials on one side")
    if not pair_ratios:
        raise RuntimeError("no complete ours/baseline pair succeeded")
    ours_sorted = sorted(ours_all)
    base = float(np.median(base_all))
    ours = float(np.median(ours_all))
    ratios = sorted(pair_ratios)
    # Median over SUCCESSFUL probes only — folding the -1.0 failure
    # sentinel into the median would fabricate a wire figure.
    wire_ok = [w for w in wires if w > 0]
    wire_med = float(np.median(wire_ok)) if wire_ok else -1.0
    print(
        json.dumps(
            {
                "metric": "sustained_ingest_throughput",
                "value": round(ours, 1),
                "unit": "records/sec",
                # Median of per-interleaved-pair ratios: robust to wire
                # drift across the run (each pair saw the same conditions).
                "vs_baseline": round(float(np.median(ratios)), 3),
                "trials": trials,
                "spread": [round(ours_sorted[0], 1), round(ours_sorted[-1], 1)],
                "best": round(ours_sorted[-1], 1),
                "baseline_median": round(base, 1),
                "pair_ratios": [round(r, 3) for r in pair_ratios],
                "ratio_spread": [round(ratios[0], 3), round(ratios[-1], 3)],
                "records_per_trial": [N_OURS, N_BASE],
                "wire_format": WIRE,
                "wire_mb_s": round(wire_med, 1),
                "wire_mb_s_per_pair": [round(w, 1) for w in wires],
            }
        )
    )
    print(
        f"ours median={ours:,.0f} rec/s (min {ours_sorted[0]:,.0f}, max "
        f"{ours_sorted[-1]:,.0f})  reference-pattern median={base:,.0f} rec/s  "
        f"pair ratios={[f'{r:.2f}' for r in pair_ratios]}  "
        f"records={N_OURS:,}/{N_BASE:,} batch={BATCH} seq={SEQ} "
        f"device-step=mlp-tower  wire(median)={wire_med:.1f} MB/s",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()

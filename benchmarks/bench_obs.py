"""Paired observability-overhead measurement (torchkafka_tpu/obs).

Two questions, answered the way the resilience wrapper's ~3.5 ns/record
number was (bench_pod --overhead: paired, interleaved, medians):

1. **Disabled path** — what does a server built with ``tracer=None`` pay?
   The serving hot path guards every emit site with ``is not None``; this
   bench times the exact per-record guard sequence (the 6 stage sites a
   completed record crosses) plus the per-token site against an empty
   loop, so the number is the WHOLE disabled-path tax. Acceptance budget:
   ≤ 50 ns/record.
2. **Enabled tiers** — what do the ring sink and the streaming JSONL sink
   cost per record / per token, measured two ways: the same micro loop
   over a full record lifecycle (poll → QoS → active → K token events →
   finish → commit), and a paired END-TO-END serve of the tiny model
   (tracing off vs ring on vs JSONL on, interleaved repetitions), with
   token-exactness asserted between every pair of modes — tracing must
   observe serving, never change it.

Usage: python benchmarks/bench_obs.py [--records 64] [--reps 5]
                                      [--micro-iters 200000]
Prints a markdown table + one JSON line; writes OBS_BENCH.json.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time


_BATCH = 16  # records per poll/commit quantum in the modeled hot path


def _disabled_loop(tracer, iters: int) -> float:
    """The disabled path's guard pattern at the server's ACTUAL call-site
    granularity (serve.py with defaults, max_new=8, ticks_per_sync=4):
    per record — one QoS-select guard, the overload-hook guard next to
    it (fleet/qos.py ``overload is not None``), two token-sync guards,
    two output-budget guards (serve.py ``max_new_of is not None`` at the
    same syncs), one retire guard; per 16-record batch — the hoisted
    note_fetched guard, the post-dispatch slot_active guard, the
    commit-cadence guard, and the fleet round's burn-monitor guard
    (fleet.py ``monitor is not None``). With ``tracer=None`` every
    guard is one ``is not None`` check."""
    t0 = time.perf_counter()
    done = 0
    while done < iters:
        if tracer is not None:  # note_fetched (hoisted, per poll batch)
            pass
        for _ in range(_BATCH):
            if tracer is not None:  # AdmissionQueue.select, per record
                pass
            if tracer is not None:  # overload hook, same select sweep
                pass
            if tracer is not None:  # step token sync 1 (K of max_new)
                pass
            if tracer is not None:  # output budget check, sync 1
                pass
            if tracer is not None:  # step token sync 2
                pass
            if tracer is not None:  # output budget check, sync 2
                pass
            if tracer is not None:  # _retire_completion
                pass
        if tracer is not None:  # admit dispatch slot_active block
            pass
        if tracer is not None:  # _commit note_commit (cadence)
            pass
        if tracer is not None:  # fleet round burn-monitor evaluate
            pass
        done += _BATCH
    return time.perf_counter() - t0


def _base_loop(iters: int) -> float:
    t0 = time.perf_counter()
    done = 0
    while done < iters:
        for _ in range(_BATCH):
            pass
        done += _BATCH
    return time.perf_counter() - t0


def _enabled_loop(tracer, recs, iters: int, tokens_per_record: int) -> float:
    """Full lifecycle EMISSION per record (the enabled tiers): polled →
    qos_admitted → slot_active → two token syncs → finished, plus one
    commit sweep per batch — 6 events + the SLO derivations."""
    half = tokens_per_record // 2
    commit = {("bench", 0): len(recs)}
    t0 = time.perf_counter()
    done = 0
    while done < iters:
        for rec in recs:
            tracer.polled(rec)
            tracer.qos_admitted(rec, "batch", 0.0)
            tracer.slot_active(rec)
            tracer.tokens(rec, half)
            tracer.tokens(rec, tokens_per_record - half)
            tracer.finished(rec, tokens_per_record)
        tracer.note_commit(commit)
        done += len(recs)
    return time.perf_counter() - t0


def micro_bench(iters: int, tokens_per_record: int = 8,
                reps: int = 5) -> dict:
    from torchkafka_tpu.obs import ObsConfig, RecordTracer
    from torchkafka_tpu.source.records import Record

    recs = [
        Record("bench", 0, o, b"payload", key=b"tenant%d" % (o % 3))
        for o in range(_BATCH)
    ]

    def med(fn):
        return sorted(fn() for _ in range(reps))[reps // 2]

    base_s = med(lambda: _base_loop(iters))
    off_s = med(lambda: _disabled_loop(None, iters))
    # Enabled tiers emit 6 events/record — far fewer iterations resolve
    # the (µs-scale) cost without dominating the bench's wall clock.
    en_iters = max(2048, iters // 20)

    def ring_run():
        tr = RecordTracer(ObsConfig(capacity=4096))
        return _enabled_loop(tr, recs, en_iters, tokens_per_record)

    ring_s = med(ring_run)
    en_base_s = med(lambda: _base_loop(en_iters))

    def jsonl_run():
        with tempfile.NamedTemporaryFile(suffix=".jsonl") as f:
            tr = RecordTracer(ObsConfig(capacity=4096, jsonl_path=f.name))
            try:
                return _enabled_loop(tr, recs, en_iters, tokens_per_record)
            finally:
                tr.close()

    jsonl_s = med(jsonl_run)
    return {
        "iters": iters,
        "tokens_per_record": tokens_per_record,
        "disabled_ns_per_record": round((off_s - base_s) / iters * 1e9, 2),
        "ring_ns_per_record": round(
            (ring_s - en_base_s) / en_iters * 1e9, 1),
        "ring_ns_per_event": round(
            (ring_s - en_base_s) / en_iters / 6 * 1e9, 1),
        "jsonl_ns_per_record": round(
            (jsonl_s - en_base_s) / en_iters * 1e9, 1),
    }


def _build_serving(size_tokens: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchkafka_tpu.models.transformer import (
        TransformerConfig, init_params,
    )

    P, MAX_NEW, VOCAB = 8, size_tokens, 64
    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, n_kv_heads=1,
        d_ff=64, max_seq_len=P + MAX_NEW, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(7)
    return cfg, params, P, MAX_NEW, rng.integers


def serve_bench(records: int, reps: int) -> dict:
    """Paired end-to-end serve: off vs ring vs jsonl, interleaved reps,
    token-exactness asserted between modes every repetition."""
    import numpy as np

    import torchkafka_tpu as tk
    from torchkafka_tpu.obs import ObsConfig, RecordTracer
    from torchkafka_tpu.serve import StreamingGenerator

    cfg, params, P, MAX_NEW, randint = _build_serving(8)
    prompts = randint(0, cfg.vocab_size, (records, P), dtype=np.int32)

    def run(tracer):
        broker = tk.InMemoryBroker()
        broker.create_topic("b", partitions=2)
        for i in range(records):
            broker.produce("b", prompts[i].tobytes(), partition=i % 2,
                           key=b"t%d" % (i % 3))
        consumer = tk.MemoryConsumer(broker, "b", group_id="g")
        server = StreamingGenerator(
            consumer, params, cfg, slots=4, prompt_len=P, max_new=MAX_NEW,
            commit_every=8, tracer=tracer,
        )
        server.warmup()
        t0 = time.perf_counter()
        out = {}
        for rec, toks in server.run(max_records=records):
            out[(rec.partition, rec.offset)] = np.asarray(toks)
        elapsed = time.perf_counter() - t0
        consumer.close()
        return elapsed, out, server.metrics.tokens.count

    def modes():
        with tempfile.NamedTemporaryFile(suffix=".jsonl") as f:
            yield "off", None
            yield "ring", RecordTracer(ObsConfig())
            tr = RecordTracer(ObsConfig(jsonl_path=f.name))
            yield "jsonl", tr
            tr.close()

    times: dict[str, list[float]] = {"off": [], "ring": [], "jsonl": []}
    ref_out = None
    tokens = 0
    for _ in range(reps):  # interleaved: drift hits every mode equally
        for name, tracer in modes():
            elapsed, out, tokens = run(tracer)
            times[name].append(elapsed)
            if ref_out is None:
                ref_out = out
            else:
                assert set(out) == set(ref_out)
                for k in out:  # tracing must never change serving
                    np.testing.assert_array_equal(out[k], ref_out[k])

    def med(name):
        s = sorted(times[name])
        return s[len(s) // 2]

    off = med("off")
    out = {"records": records, "tokens": tokens, "reps": reps,
           "e2e_off_s": round(off, 4)}
    for name in ("ring", "jsonl"):
        delta = med(name) - off
        out[f"e2e_{name}_s"] = round(med(name), 4)
        out[f"e2e_{name}_us_per_record"] = round(delta / records * 1e6, 2)
        out[f"e2e_{name}_ns_per_token"] = round(delta / tokens * 1e9, 1)
    return out


def main() -> None:
    ap = argparse.ArgumentParser(
        description="paired observability overhead bench")
    ap.add_argument("--records", type=int, default=64)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--micro-iters", type=int, default=200_000)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "OBS_BENCH.json"))
    args = ap.parse_args()

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(1)

    micro = micro_bench(args.micro_iters, reps=args.reps)
    e2e = serve_bench(args.records, args.reps)
    result = {"micro": micro, "serve": e2e}

    print("| path | per record | per event/token |")
    print("|---|---|---|")
    print(f"| disabled (guards only) | "
          f"{micro['disabled_ns_per_record']} ns | — |")
    print(f"| ring sink (micro) | {micro['ring_ns_per_record']} ns | "
          f"{micro['ring_ns_per_event']} ns/event |")
    print(f"| jsonl sink (micro) | {micro['jsonl_ns_per_record']} ns | — |")
    print(f"| ring sink (e2e serve) | "
          f"{e2e['e2e_ring_us_per_record']} µs | "
          f"{e2e['e2e_ring_ns_per_token']} ns/token |")
    print(f"| jsonl sink (e2e serve) | "
          f"{e2e['e2e_jsonl_us_per_record']} µs | "
          f"{e2e['e2e_jsonl_ns_per_token']} ns/token |")
    print(json.dumps(result))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    budget = 50.0
    ok = micro["disabled_ns_per_record"] <= budget
    print(f"disabled-path budget (<= {budget} ns/record): "
          f"{'OK' if ok else 'EXCEEDED'}")


if __name__ == "__main__":
    main()

"""Pod commit-barrier cost curve: 1/2/4/8 localhost processes.

Measures what the north-star extrapolation ("per-host ingest × hosts, the
barrier amortises", PERF.md) actually costs: steady-state ingest throughput
per process and per-commit barrier latency as the pod grows, on real
``jax.distributed`` processes (localhost coordinator, CPU backend — the
same coordination path a TPU pod takes over DCN, minus the wire).

Every process streams its own partitions of a deterministic broker, runs a
jitted global-mean step (a real cross-host psum) per batch, and commits
EVERY batch through the pod barrier (worst-case cadence — production
commits every N batches, so per-commit cost amortises further).

Usage: python benchmarks/bench_pod.py [--procs 1,2,4,8] [--batches 40]
Prints one markdown table row per pod size, plus a JSON line per size.

``--overhead`` instead runs the PAIRED resilience measurement: the same
poll+commit drain loop over a raw MemoryConsumer vs the identical
consumer wrapped in ``ResilientConsumer`` with no faults firing —
interleaved repetitions, medians reported — so the wrapper's no-fault
hot-path cost (one breaker ``allow()`` + one try/except + one
``record_success()`` per op) is a measured number in PERF.md, not a
claim.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

BATCH = 256
SEQ = 16
N_PARTS = 8
TOPIC = "podbench"


def build_broker(tk, n_records: int):
    """Deterministic content: every process builds identical topic state."""
    import numpy as np

    broker = tk.InMemoryBroker()
    broker.create_topic(TOPIC, partitions=N_PARTS)
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 1000, size=(64, SEQ), dtype=np.int32)
    broker.produce_many(
        TOPIC, (payload[i % 64].tobytes() for i in range(n_records))
    )
    return broker


def worker(
    pid: int, nproc: int, port: int, outdir: str, n_batches: int,
    commit_every: int,
) -> None:
    import jax

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(2)
    if nproc > 1:
        jax.distributed.initialize(
            coordinator_address=f"localhost:{port}",
            num_processes=nproc,
            process_id=pid,
        )
    import jax.numpy as jnp
    import numpy as np

    import torchkafka_tpu as tk

    # Each process consumes a disjoint stride of partitions (8/nproc of
    # them); records are spread round-robin, so sizing the topic at
    # n_batches × BATCH × nproc gives every process exactly n_batches
    # full batches.
    n_records = n_batches * BATCH * nproc
    broker = build_broker(tk, n_records)
    consumer = tk.MemoryConsumer(
        broker,
        TOPIC,
        group_id="podbench",
        assignment=tk.partitions_for_process(TOPIC, N_PARTS, pid, nproc),
    )
    mesh = tk.make_mesh({"data": 2 * nproc})

    @jax.jit
    def step(x):
        return jnp.mean(x)  # global mean: a true cross-host reduction

    commit_s: list[float] = []
    drain_s: list[float] = []  # device-queue retirement wait (pipeline
    # drain): the step this commit gates, plus everything queued behind it
    barrier_s: list[float] = []  # sync_global_devices + offset commit
    # alone, measured AFTER the retirement wait already completed — the
    # true coordination cost (VERDICT r5 weak #5: the cadence-16 "commit"
    # numbers were drain + barrier conflated)
    batch_times: list[float] = []
    n = 0
    commits_seen = 0
    with tk.KafkaStream(
        consumer,
        tk.fixed_width(SEQ, np.int32),
        batch_size=BATCH,
        mesh=mesh,
        idle_timeout_ms=3000,
        owns_consumer=True,
    ) as stream:
        t_prev = None
        for batch, token in stream:
            loss = step(batch.data)
            n += 1
            # Commit cadence: every batch is the worst case (barrier per
            # batch); production commits every k batches and a later
            # token's offsets subsume the earlier uncommitted ones.
            if n % commit_every == 0 or n >= n_batches:
                t0 = time.perf_counter()
                # SPLIT the commit wall into its two physically distinct
                # parts. 1) retirement: wait out the pipelined device
                # queue behind this step (block_until_ready + the same
                # one-scalar fetch the strict barrier demands).
                jax.block_until_ready(loss)
                float(jax.device_get(loss))
                t1 = time.perf_counter()
                # 2) barrier+commit: the pod-wide sync_global_devices and
                # the offset commit, with nothing left to retire (the
                # barrier's own block_until_ready returns immediately).
                ok = token.commit(wait_for=loss)
                t2 = time.perf_counter()
                assert ok, f"commit failed at batch {n}"
                commits_seen += 1
                # Steady state only: skip compile/pipeline fill, the FIRST
                # commit at any cadence (its cold path — first host fetch,
                # first lock — measured ~50× the steady cost, and at deep
                # cadences it used to be half the sample set), AND the
                # final flush commit (it waits out the whole remaining
                # device queue, which is drain cost, not barrier cost).
                if (
                    n > 2 and commits_seen > 1
                    and n % commit_every == 0 and n < n_batches
                ):
                    drain_s.append(t1 - t0)
                    barrier_s.append(t2 - t1)
                    commit_s.append(t2 - t0)
            else:
                t2 = time.perf_counter()
            if n > 2 and t_prev is not None:
                batch_times.append(t2 - t_prev)
            t_prev = t2
            if n >= n_batches:
                break

    import numpy as np

    cs = np.asarray(commit_s)
    ds = np.asarray(drain_s)
    bs = np.asarray(barrier_s)
    bt = np.asarray(batch_times)
    if not cs.size:
        raise SystemExit(
            f"no steady-state commits at cadence {commit_every} over "
            f"{n_batches} batches — raise --batches above 2+2×cadence"
        )
    out = {
        "pid": pid,
        "nproc": nproc,
        "commit_every": commit_every,
        "batches": n,
        "commit_samples": int(cs.size),
        "rows_per_s": BATCH / float(bt.mean()) if bt.size else 0.0,
        "commit_p50_ms": float(np.percentile(cs, 50) * 1e3),
        "commit_p99_ms": float(np.percentile(cs, 99) * 1e3),
        "commit_mean_ms": float(cs.mean() * 1e3),
        # The split (same commit points): retirement wait vs barrier.
        "drain_p50_ms": float(np.percentile(ds, 50) * 1e3),
        "drain_mean_ms": float(ds.mean() * 1e3),
        "barrier_p50_ms": float(np.percentile(bs, 50) * 1e3),
        "barrier_p99_ms": float(np.percentile(bs, 99) * 1e3),
        "barrier_mean_ms": float(bs.mean() * 1e3),
        "stream_metrics": stream.metrics.summary(),
    }
    with open(os.path.join(outdir, f"pod_{nproc}_{pid}.json"), "w") as f:
        json.dump(out, f)


def _validate(nproc: int, n_batches: int, commit_every: int) -> None:
    """Shared guard for main()'s up-front sweep check and run_pod."""
    if N_PARTS % nproc:
        # Uneven partition strides give members unequal batch counts; the
        # short member stops committing while the rest wedge in the pod
        # barrier until the watchdog kills them. Fail fast instead.
        raise SystemExit(f"--procs must divide {N_PARTS} partitions, got {nproc}")
    if n_batches < 2 + 3 * commit_every:
        # 3×: the first steady-cadence commit is ALSO discarded (cold
        # path), so a sample needs the third commit to land before the
        # final-flush batch.
        raise SystemExit(
            f"--batches {n_batches} leaves no steady-state commit samples "
            f"at cadence {commit_every}"
        )


def run_overhead(n_records: int = 200_000, reps: int = 5) -> dict:
    """Paired resilience-on/off poll+commit drain over one broker.

    Reps interleave (raw, wrapped, raw, wrapped, ...) so OS noise and
    allocator state hit both arms equally; each rep drains the full topic
    under a fresh consumer group (positions reset, the log does not).
    Reports median rows/s per arm and the per-(poll+commit) overhead."""
    import uuid

    import numpy as np

    import torchkafka_tpu as tk
    from torchkafka_tpu.resilience import ResilientConsumer

    broker = tk.InMemoryBroker()
    broker.create_topic(TOPIC, partitions=N_PARTS)
    payload = b"\x00" * 64
    broker.produce_many(TOPIC, (payload for _ in range(n_records)))
    tps = [tk.TopicPartition(TOPIC, p) for p in range(N_PARTS)]

    def one_pass(wrap: bool) -> dict:
        consumer = tk.MemoryConsumer(
            broker, TOPIC, group_id=f"ovh-{uuid.uuid4().hex[:8]}",
            assignment=tps,
        )
        if wrap:
            consumer = ResilientConsumer(consumer)
        rows = ops = 0
        t0 = time.perf_counter()
        while True:
            recs = consumer.poll(max_records=512, timeout_ms=0)
            ops += 1
            if not recs:
                break
            rows += len(recs)
            consumer.commit()
            ops += 1
        dt = time.perf_counter() - t0
        consumer.close()
        assert rows == n_records, f"drained {rows} != produced {n_records}"
        return {"rows_per_s": rows / dt, "ops": ops, "dt": dt}

    one_pass(False)  # warmup both code paths outside the timed reps
    one_pass(True)
    raw, wrapped = [], []
    for _ in range(reps):
        raw.append(one_pass(False))
        wrapped.append(one_pass(True))
    r = float(np.median([x["rows_per_s"] for x in raw]))
    w = float(np.median([x["rows_per_s"] for x in wrapped]))
    dt_r = float(np.median([x["dt"] for x in raw]))
    dt_w = float(np.median([x["dt"] for x in wrapped]))
    ops = raw[0]["ops"]
    return {
        "mode": "resilience-overhead",
        "records": n_records,
        "reps": reps,
        "ops_per_rep": ops,
        "raw_rows_per_s": r,
        "resilient_rows_per_s": w,
        "ratio": w / r,
        "overhead_us_per_op": (dt_w - dt_r) / ops * 1e6,
    }


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def run_pod(nproc: int, n_batches: int, outdir: str, commit_every: int) -> dict:
    _validate(nproc, n_batches, commit_every)
    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = []
    for pid in range(nproc):
        log = open(os.path.join(outdir, f"pod_{nproc}_{pid}.log"), "wb")
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, os.path.abspath(__file__), "--worker",
                    str(pid), str(nproc), str(port), outdir,
                    "--batches", str(n_batches),
                    "--commit-every", str(commit_every),
                ],
                env=env, stdout=log, stderr=subprocess.STDOUT,
            )
        )
    deadline = time.time() + 600
    for p in procs:
        p.wait(timeout=max(1, deadline - time.time()))
    assert all(p.returncode == 0 for p in procs), (
        f"pod {nproc}: exit codes {[p.returncode for p in procs]} "
        f"(see {outdir}/pod_{nproc}_*.log)"
    )
    import numpy as np

    per = []
    for pid in range(nproc):
        with open(os.path.join(outdir, f"pod_{nproc}_{pid}.json")) as f:
            per.append(json.load(f))
    return {
        "nproc": nproc,
        "commit_every": commit_every,
        "rows_per_s_per_proc": float(np.mean([p["rows_per_s"] for p in per])),
        "rows_per_s_total": float(np.sum([p["rows_per_s"] for p in per])),
        "commit_p50_ms": float(np.median([p["commit_p50_ms"] for p in per])),
        "commit_p99_ms": float(np.max([p["commit_p99_ms"] for p in per])),
        "commit_mean_ms": float(np.mean([p["commit_mean_ms"] for p in per])),
        "drain_mean_ms": float(np.mean([p["drain_mean_ms"] for p in per])),
        "drain_p50_ms": float(np.median([p["drain_p50_ms"] for p in per])),
        "barrier_mean_ms": float(np.mean([p["barrier_mean_ms"] for p in per])),
        "barrier_p50_ms": float(np.median([p["barrier_p50_ms"] for p in per])),
        "barrier_p99_ms": float(np.max([p["barrier_p99_ms"] for p in per])),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", nargs=4, metavar=("PID", "NPROC", "PORT", "OUT"))
    ap.add_argument("--procs", default="1,2,4,8")
    ap.add_argument("--batches", type=int, default=40)
    ap.add_argument("--commit-every", type=int, default=1)
    ap.add_argument("--cadences", default="1,16")
    ap.add_argument("--overhead", action="store_true",
                    help="paired resilience-on/off poll+commit overhead "
                    "measurement (no faults firing) instead of the pod sweep")
    ap.add_argument("--records", type=int, default=200_000,
                    help="--overhead: records drained per repetition")
    ap.add_argument("--reps", type=int, default=5,
                    help="--overhead: interleaved repetitions per arm")
    args = ap.parse_args()
    if args.overhead:
        r = run_overhead(args.records, args.reps)
        print("| records | raw rows/s | resilient rows/s | ratio | "
              "overhead/op |")
        print("|---|---|---|---|---|")
        print(
            f"| {r['records']:,} | {r['raw_rows_per_s']:,.0f} | "
            f"{r['resilient_rows_per_s']:,.0f} | {r['ratio']:.3f} | "
            f"{r['overhead_us_per_op']:.2f} us |"
        )
        print(json.dumps(r), file=sys.stderr)
        return
    if args.worker:
        pid, nproc, port, outdir = args.worker
        worker(
            int(pid), int(nproc), int(port), outdir, args.batches,
            args.commit_every,
        )
        return

    import tempfile

    # Validate the whole sweep up front — an invalid (procs, cadence) pair
    # must not abort mid-sweep after earlier pods already spent minutes.
    proc_list = [int(x) for x in args.procs.split(",")]
    cadence_list = [int(x) for x in args.cadences.split(",")]
    for nproc in proc_list:
        for cadence in cadence_list:
            _validate(nproc, args.batches, cadence)
    outdir = tempfile.mkdtemp(prefix="tk-pod-bench-")
    print(f"logs/results in {outdir}", file=sys.stderr)
    # drain = pipeline-retirement wait; barrier = sync_global_devices +
    # offset commit with nothing left to retire. Their sum is the old
    # conflated "commit" wall (still printed for continuity).
    print("| procs | commit cadence | rows/s/proc | rows/s total | "
          "drain mean | drain p50 | barrier mean | barrier p50 | "
          "barrier p99 | commit(=drain+barrier) mean |")
    print("|---|---|---|---|---|---|---|---|---|---|")
    for nproc in proc_list:
        for cadence in cadence_list:
            r = run_pod(nproc, args.batches, outdir, cadence)
            print(
                f"| {r['nproc']} | every {r['commit_every']} | "
                f"{r['rows_per_s_per_proc']:,.0f} | "
                f"{r['rows_per_s_total']:,.0f} | "
                f"{r['drain_mean_ms']:.2f} ms | {r['drain_p50_ms']:.2f} ms | "
                f"{r['barrier_mean_ms']:.2f} ms | "
                f"{r['barrier_p50_ms']:.2f} ms | "
                f"{r['barrier_p99_ms']:.2f} ms | "
                f"{r['commit_mean_ms']:.2f} ms |"
            )
            print(json.dumps(r), file=sys.stderr)


if __name__ == "__main__":
    main()

"""Online draft distillation cost, measured on the serving path.

What the self-improving speculation loop (torchkafka_tpu/distill) costs
the traffic it learns from, and what it provably does NOT cost:

1. **Paired publisher slice**: the SAME seeded prompt storm is served
   twice by a 2-replica speculative fleet — once plain, once with the
   distill publisher staging every committed completion onto the distill
   topic (commit-gated framing, the corpus the trainer learns from).
   The committed views must be BYTE-IDENTICAL, so the reported goodput
   ratio is pure publisher machinery (framing + the post-commit
   produce), zero token drift. The corpus itself is audited: one decoded
   frame per completion, tokens equal to the committed output.

2. **Trainer slice**: DistillTrainer throughput over a pre-staged
   corpus — train steps/s and corpus records/s on the layer-truncated
   draft (the rate the fleet can learn at), plus the cost of one
   versioned draft-checkpoint publish.

3. **Closed-loop refresh slice**: a speculative server boots on a STALE
   draft (layer-truncated from an unrelated checkpoint — chance-level
   acceptance) with the publisher on; after half the storm a
   DistillTrainer trains that same stale tree on the fleet's OWN
   committed completions and ``swap_draft_params`` installs the result
   between ticks (no quiesce — the draft only proposes). Reported:
   realized α before/after the self-taught refresh and the swap cost.
   Asserted: the committed tokens equal a stale-only reference run —
   the loop moves α and nothing else.

All slices assert exactness inline (zero lost, zero duplicates,
byte-identical committed views) before any number is reported.

Usage: python benchmarks/bench_distill.py [--records 48] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

P, MAX_NEW, VOCAB = 8, 16, 64
REPLICAS, SLOTS, COMMIT_EVERY = 2, 2, 4
SPEC_K = 3
DRAFT_LAYERS = 1
TOPIC = "p"
DISTILL_TOPIC = "dl"


def _build_model(seed: int = 0):
    import jax
    import jax.numpy as jnp

    from torchkafka_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, n_kv_heads=1,
        d_ff=64, max_seq_len=P + MAX_NEW, dtype=jnp.float32,
    )
    return cfg, init_params(jax.random.key(seed), cfg)


def _produce(broker, n: int, *, parts: int = 4, start: int = 0):
    rng = np.random.default_rng(42)
    prompts = rng.integers(0, VOCAB, (start + n, P), dtype=np.int32)
    for i in range(start, start + n):
        broker.produce(
            TOPIC, prompts[i].tobytes(), partition=i % parts,
            key=str(i).encode(),
        )
    return prompts


def _fleet(broker, model, *, distill: bool):
    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import ServingFleet
    from torchkafka_tpu.serve_spec import SpecStreamingGenerator

    cfg, params = model
    factory = lambda rid: tk.MemoryConsumer(broker, TOPIC, group_id="bench")
    gen_kwargs = {"k": SPEC_K, "draft_layers": DRAFT_LAYERS}
    if distill:
        gen_kwargs["distill_topic"] = DISTILL_TOPIC
        gen_kwargs["distill_producer"] = tk.MemoryProducer(broker)
    return ServingFleet(
        factory, params, cfg, prompt_len=P, max_new=MAX_NEW,
        replicas=REPLICAS, slots=SLOTS, commit_every=COMMIT_EVERY,
        generator_cls=SpecStreamingGenerator, gen_kwargs=gen_kwargs,
    )


def _run_fleet_side(model, n: int, *, distill: bool) -> dict:
    import torchkafka_tpu as tk

    broker = tk.InMemoryBroker()
    broker.create_topic(TOPIC, partitions=4)
    broker.create_topic(DISTILL_TOPIC, partitions=1)
    _produce(broker, n)
    fleet = _fleet(broker, model, distill=distill)
    out = {}
    t0 = time.perf_counter()
    for _rid, rec, toks in fleet.serve(max_records=n):
        key = (rec.partition, rec.offset)
        assert key not in out, f"duplicate completion {key}"
        out[key] = np.asarray(toks)
    wall = time.perf_counter() - t0
    alpha = sum(
        r.gen.spec_stats()["accepted"] for r in fleet.replicas
    ) / max(1, sum(r.gen.spec_stats()["proposed"] for r in fleet.replicas))
    fleet.close()
    assert len(out) == n, f"lost records: {len(out)}/{n}"
    return {
        "broker": broker,
        "outputs": out,
        "wall_s": round(wall, 3),
        "goodput_tok_s": round(n * MAX_NEW / wall, 1),
        "alpha": round(alpha, 4),
    }


def _audit_corpus(broker, outputs_by_key: dict, expected: int) -> None:
    """Every distill frame decodes and carries exactly its completion's
    committed tokens; one frame per completion."""
    from torchkafka_tpu.distill import decode_completion
    from torchkafka_tpu.source.records import TopicPartition

    tp = TopicPartition(DISTILL_TOPIC, 0)
    frames = broker.fetch(tp, 0, 100000)
    assert len(frames) == expected, (len(frames), expected)
    seen = set()
    for rec in frames:
        f = decode_completion(rec.value)
        key = f["tenant"]
        assert key not in seen, f"duplicate corpus frame {key!r}"
        seen.add(key)
        np.testing.assert_array_equal(
            np.asarray(f["tokens"], np.int32), outputs_by_key[key],
            err_msg=f"corpus frame {key!r} diverges from committed output",
        )
    assert seen == set(outputs_by_key)


def _trainer_slice(model, corpus_broker, n_records: int) -> dict:
    """Trainer throughput over the publisher slice's real corpus."""
    import torchkafka_tpu as tk
    from torchkafka_tpu.distill import DistillTrainer

    cfg, params = model
    corpus_broker.create_topic("ck", partitions=1)
    consumer = tk.MemoryConsumer(
        corpus_broker, DISTILL_TOPIC, group_id="bench-trainer"
    )
    trainer = DistillTrainer(
        consumer, params, cfg, seq_len=P + MAX_NEW, batch_size=8,
        draft_layers=DRAFT_LAYERS, broker=corpus_broker, ckpt_topic="ck",
        publish_every=0,
    )
    t0 = time.perf_counter()
    report = trainer.run(idle_timeout_ms=200)
    train_wall = time.perf_counter() - t0
    trainer._publish_every = 1  # publish cost measured separately
    t0 = time.perf_counter()
    trainer.publish()
    publish_ms = (time.perf_counter() - t0) * 1e3
    consumer.close()
    assert report["records"] == n_records, report
    return {
        "steps": report["steps"],
        "records": report["records"],
        "batch_size": 8,
        "final_loss": round(report["loss"], 4),
        "steps_per_s": round(report["steps"] / train_wall, 2),
        "records_per_s": round(report["records"] / train_wall, 1),
        "publish_checkpoint_ms": round(publish_ms, 3),
    }


def _closed_loop(n: int) -> dict:
    """Stale draft → serve half (publisher on) → train the SAME stale
    tree on the fleet's own committed completions → swap → serve rest."""
    import torchkafka_tpu as tk
    from torchkafka_tpu.distill import DistillTrainer
    from torchkafka_tpu.models.spec_decode import truncated_draft
    from torchkafka_tpu.serve_spec import SpecStreamingGenerator

    cfg, params = _build_model(0)
    _, stale_src = _build_model(9)
    stale_draft, stale_dcfg = truncated_draft(stale_src, cfg, DRAFT_LAYERS)
    half = n // 2

    def _gen(broker, producer):
        c = tk.MemoryConsumer(broker, TOPIC, group_id="loop")
        return SpecStreamingGenerator(
            c, params, cfg, draft_params=stale_draft, draft_cfg=stale_dcfg,
            k=SPEC_K, slots=SLOTS, prompt_len=P, max_new=MAX_NEW,
            ticks_per_sync=1, commit_every=COMMIT_EVERY,
            output_producer=producer, output_topic="out",
            distill_topic=DISTILL_TOPIC,
        )

    # Stale-only reference: the byte truth ANY draft must reproduce.
    broker = tk.InMemoryBroker()
    for t, pn in ((TOPIC, 2), ("out", 1), (DISTILL_TOPIC, 1)):
        broker.create_topic(t, partitions=pn)
    _produce(broker, n, parts=2)
    gen = _gen(broker, tk.MemoryProducer(broker))
    ref = {}
    for rec, toks in gen.run(max_records=n):
        ref[(rec.partition, rec.offset)] = np.asarray(toks)
    gen.close()
    assert len(ref) == n

    # The measured loop: produce just-in-time so the first half's poll
    # cannot run past the refresh boundary.
    broker = tk.InMemoryBroker()
    for t, pn in ((TOPIC, 2), ("out", 1), (DISTILL_TOPIC, 1)):
        broker.create_topic(t, partitions=pn)
    _produce(broker, half, parts=2)
    gen = _gen(broker, tk.MemoryProducer(broker))
    out = {}
    for rec, toks in gen.run(max_records=half):
        out[(rec.partition, rec.offset)] = np.asarray(toks)
    st_before = gen.spec_stats()

    # Teach the stale tree from the traffic it just served.
    consumer = tk.MemoryConsumer(broker, DISTILL_TOPIC, group_id="loop-tr")
    trainer = DistillTrainer(
        consumer, params, cfg, seq_len=P + MAX_NEW, batch_size=4,
        draft_params=stale_draft, draft_cfg=stale_dcfg,
        learning_rate=5e-3,
    )
    t0 = time.perf_counter()
    report = trainer.run(idle_timeout_ms=200)
    train_wall = time.perf_counter() - t0
    consumer.close()
    assert report["records"] == half, report
    t0 = time.perf_counter()
    gen.swap_draft_params(trainer.draft_params)
    swap_ms = (time.perf_counter() - t0) * 1e3

    _produce(broker, n - half, parts=2, start=half)
    for rec, toks in gen.run(max_records=n - half):
        out[(rec.partition, rec.offset)] = np.asarray(toks)
    st_after = gen.spec_stats()
    gen.close()

    assert len(out) == n, f"closed loop lost records: {len(out)}/{n}"
    for k in out:
        np.testing.assert_array_equal(out[k], ref[k], err_msg=str(k))
    acc = st_after["accepted"] - st_before["accepted"]
    prop = st_after["proposed"] - st_before["proposed"]
    assert prop > 0
    alpha_before = st_before["acceptance"]
    alpha_after = round(acc / prop, 4)
    assert alpha_after > alpha_before, (
        f"self-distilled refresh did not raise acceptance: "
        f"{alpha_before} -> {alpha_after}"
    )
    return {
        "k": SPEC_K,
        "draft_layers": DRAFT_LAYERS,
        "records": n,
        "alpha_stale_before_refresh": alpha_before,
        "alpha_after_self_distilled_refresh": alpha_after,
        "trainer_steps": report["steps"],
        "trainer_steps_per_s": round(report["steps"] / train_wall, 2),
        "swap_draft_params_ms": round(swap_ms, 3),
        "committed_identical_to_stale_only": True,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=48)
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(__file__), "..", "DISTILL_BENCH.json"
        ),
    )
    args = ap.parse_args()

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(1)
    model = _build_model(0)

    plain = _run_fleet_side(model, args.records, distill=False)
    publishing = _run_fleet_side(model, args.records, distill=True)
    # Byte-identity across the pair: the publisher is invisible in token
    # space, so the goodput delta is pure staging overhead.
    assert set(plain["outputs"]) == set(publishing["outputs"])
    for k in plain["outputs"]:
        np.testing.assert_array_equal(
            plain["outputs"][k], publishing["outputs"][k], err_msg=str(k)
        )
    # Prompt i landed on partition i % 4 at offset i // 4: invert to
    # match corpus frames (keyed by prompt key) to committed outputs.
    by_key = {
        str(o * 4 + p).encode(): toks
        for (p, o), toks in publishing["outputs"].items()
    }
    _audit_corpus(publishing["broker"], by_key, args.records)
    ratio = round(
        plain["goodput_tok_s"] / publishing["goodput_tok_s"], 3
    )
    assert ratio < 1.5, f"publisher overhead {ratio}x"

    trainer = _trainer_slice(model, publishing["broker"], args.records)
    loop = _closed_loop(max(16, args.records // 2))

    for side in (plain, publishing):
        side.pop("outputs")
        side.pop("broker")
    result = {
        "bench": "distill",
        "records": args.records,
        "model": {
            "vocab": VOCAB, "d_model": 32, "n_layers": 2,
            "prompt_len": P, "max_new": MAX_NEW,
            "replicas": REPLICAS, "slots": SLOTS,
            "commit_every": COMMIT_EVERY, "k": SPEC_K,
            "draft_layers": DRAFT_LAYERS,
        },
        "plain": plain,
        "publishing": publishing,
        "plain_over_publishing_goodput": ratio,
        "byte_identical": True,
        "zero_lost": True,
        "duplicates": 0,
        "corpus_matches_committed": True,
        "trainer": trainer,
        "closed_loop": loop,
    }

    print("\n| slice | goodput tok/s | alpha |")
    print("|---|---|---|")
    for name in ("plain", "publishing"):
        s = result[name]
        print(f"| {name} | {s['goodput_tok_s']} | {s['alpha']} |")
    print(f"\npublisher overhead: {ratio}x")
    print(f"trainer: {trainer['steps_per_s']} steps/s, "
          f"{trainer['records_per_s']} records/s, "
          f"publish {trainer['publish_checkpoint_ms']} ms")
    print(f"closed loop: alpha {loop['alpha_stale_before_refresh']} -> "
          f"{loop['alpha_after_self_distilled_refresh']} "
          f"(swap {loop['swap_draft_params_ms']} ms)")
    print(json.dumps(result))

    out_path = os.path.abspath(args.out)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

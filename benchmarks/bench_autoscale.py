"""Paired autoscaling bench: fixed fleet vs the closed control loop.

The question TRAFFIC_BENCH.json left open: its fixed 2-replica fleet
degrades gracefully under overload (goodput 0.96 → 0.60 → 0.48 at
1×/2×/4×), but graceful degradation is what you accept when you CANNOT
add capacity. This bench closes the loop (fleet/autoscale.py): the same
workload slices run twice — once on the fixed baseline fleet, once with
the burn-rate + queue-depth controller driving per-role scaling (decode
replicas via ``ServingFleet.scale_to``, prefill workers via
``PrefillPool.scale_to`` over the disaggregated handoff plane) — and
the autoscaled 4× goodput must land STRICTLY above the fixed-fleet
cliff.

Slices: the PR-8 1×/2×/4× sustained-overload sweep, a step-load storm
(time-to-goodput-recovery after the step, read off the burn-state
transition events), and a diurnal swell (scale-down exercised as much
as scale-up). Exactness is asserted inside every slice, the repo's
bench discipline: zero lost records (served == produced, ledger
audited), and every autoscaled slice runs TWICE at the same seed with
the WHOLE control loop — arrivals, burn transitions, controller
decisions, scale events, completions, commit ledger — byte-identical.
Hysteresis is asserted, not hoped: the decision count per run is
bounded under the seeded Poisson burst noise.

Usage: python benchmarks/bench_autoscale.py [--records 48] [--base-rate 300]
Prints markdown tables + one JSON line; writes AUTOSCALE_BENCH.json.
"""

from __future__ import annotations

import argparse
import json
import os
import time

TICK_DT = 0.002
SLOTS = 2
BASE_REPLICAS = 2
COMMIT_EVERY = 4
DECODE_MAX = 6
PREFILL_MAX = 3
SETTLE_ROUNDS = 200
DECISION_BOUND = 16  # hysteresis acceptance: decisions per run


def _build_model():
    import jax
    import jax.numpy as jnp

    from torchkafka_tpu.models.transformer import (
        TransformerConfig, init_params,
    )

    P, MAX_NEW, VOCAB = 16, 8, 64
    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, n_kv_heads=1,
        d_ff=64, max_seq_len=P + MAX_NEW, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)
    return cfg, params, P, MAX_NEW


def _run_once(cfg, params, P, MAX_NEW, wcfg, *, autoscale: bool):
    import numpy as np

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import (
        AutoscaleController, FleetAutoscaler, PrefillPool, QoSConfig,
        RolePolicy, ServingFleet,
    )
    from torchkafka_tpu.obs import SLOTarget
    from torchkafka_tpu.resilience import ManualClock
    from torchkafka_tpu.source.records import TopicPartition
    from torchkafka_tpu.workload import WorkloadGenerator
    from torchkafka_tpu.workload.generator import header_max_new

    gen = WorkloadGenerator(
        wcfg, prompt_len=P, max_new=MAX_NEW, vocab_size=cfg.vocab_size,
    )
    mc = ManualClock()
    broker = tk.InMemoryBroker()
    broker.create_topic("traffic", partitions=4)
    pages = {
        "block_size": 4,
        "num_blocks": SLOTS * -(-(P + MAX_NEW) // 4) + 16,
    }
    targets = [SLOTarget(
        metric="ttft", threshold_s=TICK_DT * 12, objective=0.75,
        fast_window_s=TICK_DT * 32, slow_window_s=TICK_DT * 128,
        min_samples=4,
    )]
    kw = {}
    pool = None
    if autoscale:
        broker.create_topic("handoff", partitions=1)
        kw = dict(
            handoff_consumer_factory=lambda rid: tk.MemoryConsumer(
                broker, "handoff", group_id=f"ho-{rid}",
            ),
            route_patience=4,
        )
    fleet = ServingFleet(
        gen.consumer_factory(broker, "traffic", "gas", clock=mc),
        params, cfg, replicas=BASE_REPLICAS, prompt_len=P,
        max_new=MAX_NEW, slots=SLOTS, commit_every=COMMIT_EVERY,
        clock=mc.now, qos=QoSConfig(),
        gen_kwargs={"kv_pages": pages, "max_new_of": header_max_new},
        obs=True, slo_targets=targets, **kw,
    )
    scaler = None
    ctrl = None
    if autoscale:
        pool = PrefillPool(
            broker, "traffic", "gas-prefill", "handoff", params, cfg,
            workers=1, slots=SLOTS, prompt_len=P, max_new=MAX_NEW,
            kv_pages=pages, commit_every=2,
        )
        ctrl = AutoscaleController({
            "decode": RolePolicy(
                min_replicas=1, max_replicas=DECODE_MAX,
                queue_high=8.0, queue_low=1.0,
                up_cooldown_s=TICK_DT * 12, down_cooldown_s=TICK_DT * 24,
                down_confirm=6,
            ),
            "prefill": RolePolicy(
                min_replicas=1, max_replicas=PREFILL_MAX,
                queue_high=6.0, queue_low=1.0,
                up_cooldown_s=TICK_DT * 8, down_cooldown_s=TICK_DT * 24,
                down_confirm=6, burn_up=False,
            ),
        }, clock=mc.now, tracer=fleet.tracer, metrics=fleet.metrics)
        scaler = FleetAutoscaler(fleet, ctrl, prefill=pool)
        pool.warmup()
    fleet.warmup()
    peak = {"decode": fleet.live_count(), "prefill": 1 if pool else 0}

    def on_round(f, _served):
        if pool is not None:
            pool.pump_once()
        if scaler is not None:
            scaler.step()
            peak["decode"] = max(peak["decode"], f.live_count())
            peak["prefill"] = max(peak["prefill"], pool.live_count())

    t0 = time.perf_counter()
    report = gen.drive(
        fleet, broker, "traffic", clock=mc, tick_dt=TICK_DT,
        settle_rounds=SETTLE_ROUNDS,
        on_round=on_round if autoscale else None,
    )
    wall_s = time.perf_counter() - t0
    order = [
        (rid, rec.partition, rec.offset, tuple(np.asarray(t).tolist()))
        for rid, rec, t in report["completions"]
    ]
    committed = {
        p: broker.committed("gas", TopicPartition("traffic", p)) or 0
        for p in range(4)
    }
    produced = {
        (p, o) for p in range(4)
        for o in range(broker.end_offset(TopicPartition("traffic", p)))
    }
    served = {(p, o) for _rid, p, o, _t in order}
    # Exactness audited INSIDE the run: nothing produced went unserved,
    # the schedule fully arrived, and the ledger covers every partition.
    assert served == produced, "lost records"
    assert report["all_arrived"], "schedule never finished"
    # Burn trajectory → time-to-goodput-recovery: last transition back
    # to ok on the global scope, relative to when burning first began.
    burn_start = burn_ok = None
    for e in fleet.tracer.events:
        if e.stage != "burn_state":
            continue
        attrs = dict(e.attrs)
        if attrs["dim"] != "":
            continue
        if attrs["to"] in ("burning", "shedding") and burn_start is None:
            burn_start = e.t
        if attrs["to"] == "ok":
            burn_ok = e.t
    g = fleet.monitor.goodput_summary()
    s = fleet.metrics.summary(fleet.replicas)
    out = {
        "order": order,
        "events": list(fleet.tracer.events),
        "committed": committed,
        "goodput": g,
        "duplicates": report["duplicates"],
        "unique": report["unique_served"],
        "rounds": report["rounds"],
        "end_time_s": report["end_time_s"],
        "wall_s": wall_s,
        "burn_start_t": burn_start,
        "burn_ok_t": burn_ok,
        "end_burn": fleet.monitor.worst_state(),
        "adopted": s["disagg"]["adopted_slots"],
        "peak": dict(peak),
        "drains": fleet.metrics.drains.count,
        "ctrl": ctrl.summary() if ctrl is not None else None,
        "digest": ctrl.decision_digest() if ctrl is not None else None,
    }
    fleet.close()
    if pool is not None:
        pool.close()
    fleet.tracer.close()
    return out


def _distill(run, *, t_load_start=0.0):
    g = run["goodput"]
    recovery = None
    if run["burn_start_t"] is not None and run["burn_ok_t"] is not None \
            and run["burn_ok_t"] > run["burn_start_t"]:
        recovery = round(run["burn_ok_t"] - run["burn_start_t"], 4)
    out = {
        "goodput_ratio": g["goodput_ratio"],
        "within_slo": g["within_slo"],
        "completed": g["completed"],
        "deferred": g["deferred"],
        "unique": run["unique"],
        "duplicates": run["duplicates"],
        "offered_span_s": round(run["end_time_s"], 3),
        "wall_s": round(run["wall_s"], 2),
        "burned": run["burn_start_t"] is not None,
        "recovery_s": recovery,
        "end_burn_state": run["end_burn"],
    }
    if run["ctrl"] is not None:
        out.update({
            "decisions": run["ctrl"]["decisions"],
            "by_reason": run["ctrl"]["by_reason"],
            "peak_decode": run["peak"]["decode"],
            "peak_prefill": run["peak"]["prefill"],
            "final_targets": run["ctrl"]["targets"],
            "adopted_slots": run["adopted"],
            "drained_members": run["drains"],
        })
    return out


def _slice(cfg, params, P, MAX_NEW, wcfg, label):
    """One paired slice: fixed baseline once, autoscaled TWICE (the
    same-seed replay must be byte-identical across the whole control
    loop)."""
    fixed = _run_once(cfg, params, P, MAX_NEW, wcfg, autoscale=False)
    a = _run_once(cfg, params, P, MAX_NEW, wcfg, autoscale=True)
    b = _run_once(cfg, params, P, MAX_NEW, wcfg, autoscale=True)
    assert a["order"] == b["order"], f"{label}: completion order diverged"
    assert a["events"] == b["events"], f"{label}: trace diverged"
    assert a["committed"] == b["committed"], f"{label}: ledger diverged"
    assert a["digest"] == b["digest"], f"{label}: decisions diverged"
    assert a["ctrl"]["decisions"] <= DECISION_BOUND, (
        f"{label}: {a['ctrl']['decisions']} decisions — the hysteresis "
        f"is flapping (bound {DECISION_BOUND})"
    )
    return {
        "replay_identical": True,
        "fixed": _distill(fixed),
        "autoscaled": _distill(a),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description="autoscaling control-loop bench")
    ap.add_argument("--records", type=int, default=48)
    ap.add_argument("--base-rate", type=float, default=300.0,
                    help="1x offered load, records/sec of synthetic time")
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "AUTOSCALE_BENCH.json"))
    args = ap.parse_args()

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(1)

    from torchkafka_tpu.workload import (
        WorkloadConfig, diurnal_load, step_load,
    )

    cfg, params, P, MAX_NEW = _build_model()

    def wcfg(rate, schedule=()):
        return WorkloadConfig(
            tenants=args.tenants, zipf_s=1.2,
            total_records=args.records, arrival_rate=rate,
            burst_mean=3.0, interactive_fraction=0.4,
            mean_suffix=max(4.0, P / 3), mean_output=MAX_NEW * 0.75,
            seed=args.seed, rate_schedule=schedule,
        )

    result = {
        "config": {
            "records": args.records, "base_rate": args.base_rate,
            "tenants": args.tenants, "base_replicas": BASE_REPLICAS,
            "decode_max": DECODE_MAX, "prefill_max": PREFILL_MAX,
            "slots": SLOTS, "commit_every": COMMIT_EVERY,
            "tick_dt_s": TICK_DT, "ttft_target_ms": TICK_DT * 12 * 1e3,
            "objective": 0.75, "decision_bound": DECISION_BOUND,
            "seed": args.seed,
        },
        "slices": {},
    }
    for factor in (1, 2, 4):
        label = f"{factor}x"
        result["slices"][label] = _slice(
            cfg, params, P, MAX_NEW, wcfg(args.base_rate * factor), label,
        )
        s = result["slices"][label]
        print(f"[{label}] fixed goodput "
              f"{s['fixed']['goodput_ratio']} → autoscaled "
              f"{s['autoscaled']['goodput_ratio']} "
              f"(peak decode {s['autoscaled']['peak_decode']}, "
              f"decisions {s['autoscaled']['decisions']})")
    result["slices"]["step"] = _slice(
        cfg, params, P, MAX_NEW,
        wcfg(args.base_rate, step_load(0.04, 6.0, 0.2)), "step",
    )
    result["slices"]["diurnal"] = _slice(
        cfg, params, P, MAX_NEW,
        wcfg(args.base_rate, diurnal_load(0.16, 4.0, phases=8, cycles=1)),
        "diurnal",
    )

    # ---- acceptance -----------------------------------------------------
    fixed4 = result["slices"]["4x"]["fixed"]["goodput_ratio"]
    auto4 = result["slices"]["4x"]["autoscaled"]["goodput_ratio"]
    assert auto4 > 0.48, (
        f"autoscaled 4x goodput {auto4} does not beat the 0.48 "
        "fixed-fleet baseline"
    )
    assert auto4 > fixed4, (
        f"autoscaled 4x goodput {auto4} <= this box's fixed fleet {fixed4}"
    )
    # Per-role: the prefill role scaled and the handoff plane carried.
    role_seen = {"up": False, "down": False}
    prefill_seen = False
    adopted = 0
    for label, s in result["slices"].items():
        br = s["autoscaled"].get("by_reason", {})
        for key, cnt in br.items():
            role, direction, _reason = key.split("/")
            if cnt > 0:
                role_seen[direction] = True
                if role == "prefill":
                    prefill_seen = True
        adopted += s["autoscaled"].get("adopted_slots", 0)
    assert role_seen["up"] and role_seen["down"], (
        "the sweep never exercised both scale directions"
    )
    assert prefill_seen, "the prefill role never scaled"
    assert adopted > 0, "the handoff plane never carried an adoption"
    # The step storm: the controller either PREVENTED the burn outright
    # or recovered from it with the recovery time on record — and ends
    # clean either way. (The fixed baseline's trajectory rides along in
    # the slice for comparison.)
    step_auto = result["slices"]["step"]["autoscaled"]
    assert (not step_auto["burned"]) or step_auto["recovery_s"] is not None, (
        "the step burned under the controller and never recovered"
    )
    assert step_auto["end_burn_state"] == "ok"

    def burn_cell(side):
        if not side["burned"]:
            return "never"
        return f"{side['recovery_s']}s to recover"

    print("\n| slice | goodput fixed → autoscaled | SLO burned "
          "(fixed → autoscaled) | peak decode/prefill | decisions "
          "| dups |")
    print("|---|---|---|---|---|---|")
    for label, s in result["slices"].items():
        a = s["autoscaled"]
        print(f"| {label} | {s['fixed']['goodput_ratio']} → "
              f"{a['goodput_ratio']} | {burn_cell(s['fixed'])} → "
              f"{burn_cell(a)} | {a['peak_decode']}/{a['peak_prefill']} "
              f"| {a['decisions']} | {a['duplicates']} |")
    print(json.dumps(result))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")


if __name__ == "__main__":
    main()

"""Tiered radix cache + disaggregated prefill paired bench.

Two questions, each answered with paired runs over IDENTICAL broker
content (the repo's pairing discipline — absolute numbers on a
contended CPU box drift; paired counts and ratios are the signal), with
token + commit-ledger exactness asserted inside every slice:

1. TIER — a Zipf tenant population at tenant counts where the HBM-only
   radix tree THRASHES (far more distinct tenant prefixes than pool
   blocks: every prefix is evicted before its next hit — the
   TRAFFIC_BENCH hit-by-rank cliff at production scale). Per tenant
   count: prefix hit rate, prompt tokens actually prefilled, TTFT
   p50/p99 (RecordTracer-derived), HBM-only vs host-RAM-tiered — the
   tier's claim is hits and prefill tokens, i.e. the effective cache
   capacity becomes host memory instead of pool blocks.

2. DISAGG — a 4x prompt storm (records >> fleet slots) served
   monolithic (decode replicas run their own chunked prefills) vs
   DISAGGREGATED (a prefill-role worker fills KV and publishes
   handoffs; the decode server adopts and never runs a prompt pass).
   Per mode: decode inter-token latency p50/p99 (the number prompt
   storms are supposed to stop touching), TTFT, decode-side prefill
   tokens (0 when disaggregated), wall. CPU caveat as everywhere: one
   box timeshares both roles, so disagg wall is not a speedup claim —
   the signal is decode ITL and the decode-side prefill-token count.

Usage: python benchmarks/bench_tiered.py [--tenants 4,16,48]
       [--prompts 96] [--storm-prompts 32] [--json PATH]
Prints one markdown row per slice plus a JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

VOCAB = 512
P, MAX_NEW, BS = 16, 8, 4


def _model(jnp, jax):
    from torchkafka_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=P + MAX_NEW, dtype=jnp.float32,
    )
    return cfg, init_params(jax.random.key(0), cfg)


def _zipf_stream(np, n_tenants: int, n_prompts: int, seed: int):
    """Zipf(1.1)-weighted tenant draws; each tenant owns one fixed
    P-token prompt (the system-prompt shape the radix tree shares)."""
    from torchkafka_tpu.workload.generator import zipf_weights

    rng = np.random.default_rng(seed)
    tenant_prompts = rng.integers(0, VOCAB, (n_tenants, P), dtype=np.int32)
    w = zipf_weights(n_tenants, 1.1)
    picks = rng.choice(n_tenants, size=n_prompts, p=w)
    return tenant_prompts, picks


def _fill(tk, np, tenant_prompts, picks):
    broker = tk.InMemoryBroker()
    broker.create_topic("bench", partitions=2)
    for i, t in enumerate(picks):
        broker.produce(
            "bench", tenant_prompts[t].tobytes(), partition=i % 2,
            key=f"t{t}".encode(),
        )
    return broker


def _serve_tier(tk, np, cfg, params, broker, n, *, num_blocks, kv_tier):
    from torchkafka_tpu.obs import RecordTracer
    from torchkafka_tpu.serve import StreamingGenerator

    tr = RecordTracer(capacity=1 << 16, token_events=False)
    consumer = tk.MemoryConsumer(broker, "bench", group_id="b")
    server = StreamingGenerator(
        consumer, params, cfg, slots=4, prompt_len=P, max_new=MAX_NEW,
        commit_every=8, kv_pages={"block_size": BS, "num_blocks": num_blocks},
        kv_tier=kv_tier, tracer=tr,
    )
    server.warmup()
    out = {}
    t0 = time.perf_counter()
    for rec, toks in server.run(max_records=n):
        out[(rec.partition, rec.offset)] = np.asarray(toks)
    elapsed = time.perf_counter() - t0
    committed = {
        p: broker.committed("b", tk.TopicPartition("bench", p))
        for p in range(2)
    }
    consumer.close()
    cache = server.metrics.cache_summary()
    ttft = tr.slo.summary()["ttft"]["all"]
    return {
        "out": out, "committed": committed, "elapsed_s": elapsed,
        "hit_rate": cache["hit_rate"], "prefill_tokens":
        cache["prefill_tokens"], "tier": cache["tier"],
        "ttft_p50_ms": ttft["p50_ms"], "ttft_p99_ms": ttft["p99_ms"],
    }


def tier_sweep(tk, np, cfg, params, tenant_counts, n_prompts, num_blocks):
    rows = []
    for n_tenants in tenant_counts:
        tenant_prompts, picks = _zipf_stream(np, n_tenants, n_prompts, 13)
        hbm = _serve_tier(
            tk, np, cfg, params, _fill(tk, np, tenant_prompts, picks),
            n_prompts, num_blocks=num_blocks, kv_tier=None,
        )
        tier = _serve_tier(
            tk, np, cfg, params, _fill(tk, np, tenant_prompts, picks),
            n_prompts, num_blocks=num_blocks,
            kv_tier={"capacity_bytes": 64 << 20},
        )
        # Exactness asserted INSIDE the bench: tokens + ledger identical.
        assert set(hbm["out"]) == set(tier["out"])
        for k in hbm["out"]:
            assert np.array_equal(hbm["out"][k], tier["out"][k]), k
        assert hbm["committed"] == tier["committed"]
        row = {
            "tenants": n_tenants,
            "prompts": n_prompts,
            "pool_blocks": num_blocks - 1,
            "hbm_only": {
                k: hbm[k] for k in (
                    "hit_rate", "prefill_tokens", "ttft_p50_ms",
                    "ttft_p99_ms", "elapsed_s",
                )
            },
            "tiered": {
                k: tier[k] for k in (
                    "hit_rate", "prefill_tokens", "ttft_p50_ms",
                    "ttft_p99_ms", "elapsed_s",
                )
            },
            "tier_traffic": tier["tier"],
            "prefill_tokens_saved_vs_hbm": (
                hbm["prefill_tokens"] - tier["prefill_tokens"]
            ),
            "exact": True,
        }
        rows.append(row)
        print(
            f"| tier | tenants={n_tenants:3d} | "
            f"hit {hbm['hit_rate'] or 0:.2f}->{tier['hit_rate'] or 0:.2f} | "
            f"prefill {hbm['prefill_tokens']}->{tier['prefill_tokens']} | "
            f"ttft p99 {hbm['ttft_p99_ms']:.1f}->{tier['ttft_p99_ms']:.1f} "
            f"ms | demote/promote {tier['tier']['demotions']}/"
            f"{tier['tier']['promotions']} | exact |"
        )
    return rows


# The storm slices use LONG prompts (a prompt storm is prefill-heavy by
# definition): chunk auto width = slots x prompt_len rows riding each
# admission tick, which is exactly the decode-latency pressure
# disaggregation exists to remove.
STORM_P, STORM_MAX_NEW = 48, 16


def _storm_model(jnp, jax):
    from torchkafka_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=STORM_P + STORM_MAX_NEW, dtype=jnp.float32,
    )
    return cfg, init_params(jax.random.key(0), cfg)


def _storm_prompts(np, n, seed=29):
    rng = np.random.default_rng(seed)
    prompts = rng.integers(0, VOCAB, (n, STORM_P), dtype=np.int32)
    prompts[:, :6] = np.arange(6)
    return prompts


def _storm_budget(rec):
    """Deterministic per-record output budget (keyed by record id):
    STAGGERED completions, so admissions refill slots WHILE other slots
    decode — the regime where monolithic chunk ticks ride live decode
    (ITL pressure) and disaggregated adoption does not."""
    i = int(rec.key.decode())
    return 4 + (i * 7) % (STORM_MAX_NEW - 4)


def _mono_storm(tk, np, cfg, params, prompts):
    from torchkafka_tpu.obs import RecordTracer
    from torchkafka_tpu.serve import StreamingGenerator

    n = prompts.shape[0]
    broker = tk.InMemoryBroker()
    broker.create_topic("p", partitions=2)
    for i in range(n):
        broker.produce("p", prompts[i].tobytes(), partition=i % 2,
                       key=str(i).encode())
    tr = RecordTracer(capacity=1 << 16)
    c = tk.MemoryConsumer(broker, "p", group_id="g")
    gen = StreamingGenerator(
        c, params, cfg, slots=4, prompt_len=STORM_P, max_new=STORM_MAX_NEW,
        commit_every=8, kv_pages={"block_size": BS, "num_blocks": 128},
        tracer=tr, max_new_of=_storm_budget,
    )
    gen.warmup()
    out = {}
    t0 = time.perf_counter()
    for rec, toks in gen.run(max_records=n):
        out[(rec.partition, rec.offset)] = np.asarray(toks)
    elapsed = time.perf_counter() - t0
    committed = {
        p: broker.committed("g", tk.TopicPartition("p", p)) for p in range(2)
    }
    c.close()
    return out, committed, elapsed, tr, gen


def _disagg_storm(tk, np, cfg, params, prompts):
    from torchkafka_tpu.fleet.prefill import (
        PrefillRouter,
        PrefillWorker,
        drain_handoffs,
    )
    from torchkafka_tpu.obs import RecordTracer
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.producer import MemoryProducer

    import threading

    n = prompts.shape[0]
    broker = tk.InMemoryBroker()
    broker.create_topic("p", partitions=2)
    broker.create_topic("ho", partitions=1)
    for i in range(n):
        broker.produce("p", prompts[i].tobytes(), partition=i % 2,
                       key=str(i).encode())
    pages = {"block_size": BS, "num_blocks": 128}
    pc = tk.MemoryConsumer(broker, "p", group_id="pf")
    pgen = StreamingGenerator(
        pc, params, cfg, slots=4, prompt_len=STORM_P,
        max_new=STORM_MAX_NEW, commit_every=8, kv_pages=dict(pages),
        prefill_role=True,
    )
    pgen.warmup()
    worker = PrefillWorker(pgen, pc, MemoryProducer(broker), "ho")
    tr = RecordTracer(capacity=1 << 16)
    dc = tk.MemoryConsumer(broker, "p", group_id="g")
    dgen = StreamingGenerator(
        dc, params, cfg, slots=4, prompt_len=STORM_P,
        max_new=STORM_MAX_NEW, commit_every=8, kv_pages=dict(pages),
        tracer=tr, max_new_of=_storm_budget,
    )
    dgen.warmup()
    ho_c = tk.MemoryConsumer(broker, "ho", group_id="ho-d")
    router = PrefillRouter(dgen, patience=10**6)
    out = {}
    pending = []

    # The prefill worker runs on its OWN thread — the in-process stand-in
    # for its own process (scenario 21 is the real-process version). The
    # decode loop below never executes a prompt pass; its ITL is pure
    # decode-tick cadence.
    stop = threading.Event()

    def prefill_loop():
        idle = 0
        while not stop.is_set() and idle < 200:
            published = worker.pump()
            idle = 0 if (published or not worker.idle()) else idle + 1

    pt = threading.Thread(target=prefill_loop, daemon=True)
    t0 = time.perf_counter()
    pt.start()
    for _ in range(200000):
        drain_handoffs(ho_c, dgen)
        free = dgen.free_slots() - dgen.pending_admissions
        if free > len(pending):
            recs = dc.poll(max_records=free - len(pending), timeout_ms=0)
            if recs:
                dgen.note_fetched(recs)
                pending.extend(recs)
        take = []
        while pending and len(take) < free:
            if router.should_hold(pending[0]):
                break
            take.append(pending.pop(0))
        if take or (dgen.pending_admissions and dgen.free_slots()):
            dgen.admit_records(take)
        ticked = False
        for rec, toks in dgen.step():
            ticked = True
            out[(rec.partition, rec.offset)] = np.asarray(toks)
        if len(out) == n:
            break
        if not ticked and not dgen.has_active():
            time.sleep(0.0005)  # waiting on the transfer plane, not busy
    elapsed = time.perf_counter() - t0
    stop.set()
    pt.join(timeout=30)
    dgen.flush_commits()
    committed = {
        p: broker.committed("g", tk.TopicPartition("p", p)) for p in range(2)
    }
    for cl in (pc, dc, ho_c):
        cl.close()
    return out, committed, elapsed, tr, dgen, pgen


def disagg_storm(tk, np, jnp, jax, n):
    cfg, params = _storm_model(jnp, jax)
    prompts = _storm_prompts(np, n)
    m_out, m_comm, m_wall, m_tr, m_gen = _mono_storm(
        tk, np, cfg, params, prompts
    )
    d_out, d_comm, d_wall, d_tr, d_gen, p_gen = _disagg_storm(
        tk, np, cfg, params, prompts
    )
    # Exactness asserted INSIDE the bench.
    assert set(m_out) == set(d_out)
    for k in m_out:
        assert np.array_equal(m_out[k], d_out[k]), k
    assert m_comm == d_comm

    def slo(tr):
        s = tr.slo.summary()
        return {
            "itl_p50_ms": s["itl"]["all"]["p50_ms"],
            "itl_p99_ms": s["itl"]["all"]["p99_ms"],
            "ttft_p50_ms": s["ttft"]["all"]["p50_ms"],
            "ttft_p99_ms": s["ttft"]["all"]["p99_ms"],
        }

    row = {
        "storm_prompts": n,
        "decode_slots": 4,
        "oversubscription": round(n / 4, 1),
        "monolithic": {
            **slo(m_tr), "wall_s": round(m_wall, 3),
            "decode_prefill_tokens": m_gen.metrics.prefill_tokens.count,
        },
        "disaggregated": {
            **slo(d_tr), "wall_s": round(d_wall, 3),
            "decode_prefill_tokens": d_gen.metrics.prefill_tokens.count,
            "adopted_slots": d_gen.metrics.adopted_slots.count,
            "handoffs_published": p_gen.metrics.handoffs_published.count,
        },
        "exact": True,
    }
    m, d = row["monolithic"], row["disaggregated"]
    print(
        f"| disagg | {n} prompts / 4 slots | decode prefill tokens "
        f"{m['decode_prefill_tokens']}->{d['decode_prefill_tokens']} | "
        f"itl p99 {m['itl_p99_ms']:.2f}->{d['itl_p99_ms']:.2f} ms | "
        f"adopted {d['adopted_slots']}/{n} | exact |"
    )
    return row


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tenants", default="4,16,48")
    ap.add_argument("--prompts", type=int, default=96)
    ap.add_argument("--pool-blocks", type=int, default=17)
    ap.add_argument("--storm-prompts", type=int, default=32)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np  # noqa: F401

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(1)
    import torchkafka_tpu as tk

    globals()["np"] = np
    cfg, params = _model(jnp, jax)
    doc = {
        "tiered": tier_sweep(
            tk, np, cfg, params,
            [int(t) for t in args.tenants.split(",")],
            args.prompts, args.pool_blocks,
        ),
        "disagg": disagg_storm(tk, np, jnp, jax, args.storm_prompts),
    }
    line = json.dumps(doc)
    print(line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    sys.exit(main())

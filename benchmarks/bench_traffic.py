"""Paired traffic-observatory bench: goodput under overload, SLOs per
tenant, cache locality, and the duplicate bound under chaos.

The workload generator (torchkafka_tpu/workload) drives the FULL serving
stack — 2-replica fleet, QoS lanes, paged KV + chunked prefill, burn-rate
monitor, per-record output budgets — on a ManualClock, at 1x/2x/4x the
base offered load. Offered-load scaling changes ONLY the arrival
instants (SeedSequence stream independence), so the slices serve the
same tenants, prompts, and output budgets and their SLO/goodput numbers
are directly comparable.

Exactness is asserted per slice, the repo's bench discipline: every
slice runs TWICE at the same seed and must replay byte-identically —
completion order (duplicates included), commit ledger, and the tracer's
event stream including timestamps. A separate chaos slice (replica kill
through the journal warm-failover path + an op-counted broker outage)
verifies the duplicate-output bound: duplicated completions cannot
exceed the victim's uncommitted work ceiling (commit cadence + slot
pool).

Acceptance shape (asserted here, recorded in TRAFFIC_BENCH.json):
goodput must degrade GRACEFULLY — overload deferrals rise with offered
load while completed-within-SLO never collapses to zero at 2x.

Usage: python benchmarks/bench_traffic.py [--records 48] [--base-rate 300]
Prints markdown tables + one JSON line; writes TRAFFIC_BENCH.json.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _build_model():
    import jax
    import jax.numpy as jnp

    from torchkafka_tpu.models.transformer import (
        TransformerConfig, init_params,
    )

    P, MAX_NEW, VOCAB = 16, 8, 64
    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, n_kv_heads=1,
        d_ff=64, max_seq_len=P + MAX_NEW, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)
    return cfg, params, P, MAX_NEW


TICK_DT = 0.002
SLOTS = 2
REPLICAS = 2
COMMIT_EVERY = 4


def _run_once(cfg, params, P, MAX_NEW, wcfg):
    import numpy as np

    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import QoSConfig, ServingFleet
    from torchkafka_tpu.obs import SLOTarget
    from torchkafka_tpu.resilience import ManualClock
    from torchkafka_tpu.source.records import TopicPartition
    from torchkafka_tpu.workload import WorkloadGenerator
    from torchkafka_tpu.workload.generator import header_max_new

    gen = WorkloadGenerator(
        wcfg, prompt_len=P, max_new=MAX_NEW, vocab_size=cfg.vocab_size,
    )
    mc = ManualClock()
    broker = tk.InMemoryBroker()
    broker.create_topic("traffic", partitions=4)
    pages = {
        "block_size": 4,
        "num_blocks": SLOTS * -(-(P + MAX_NEW) // 4) + 16,
    }
    targets = [SLOTarget(
        metric="ttft", threshold_s=TICK_DT * 12, objective=0.75,
        fast_window_s=TICK_DT * 32, slow_window_s=TICK_DT * 128,
        min_samples=4,
    )]
    fleet = ServingFleet(
        gen.consumer_factory(broker, "traffic", "gtraffic", clock=mc),
        params, cfg, replicas=REPLICAS, prompt_len=P, max_new=MAX_NEW,
        slots=SLOTS, commit_every=COMMIT_EVERY, clock=mc.now,
        qos=QoSConfig(),
        gen_kwargs={"kv_pages": pages, "max_new_of": header_max_new},
        obs=True, slo_targets=targets,
    )
    fleet.warmup()
    t0 = time.perf_counter()
    report = gen.drive(fleet, broker, "traffic", clock=mc, tick_dt=TICK_DT)
    wall_s = time.perf_counter() - t0
    order = [
        (rid, rec.partition, rec.offset, tuple(np.asarray(t).tolist()))
        for rid, rec, t in report["completions"]
    ]
    committed = {
        p: broker.committed("gtraffic", tk.TopicPartition("traffic", p))
        for p in range(4)
    }
    produced = {
        (p, o) for p in range(4)
        for o in range(broker.end_offset(TopicPartition("traffic", p)))
    }
    s = fleet.metrics.summary(fleet.replicas)
    tenant_cache: dict = {}
    for rep in fleet.replicas:
        for t, v in rep.gen.metrics.tenant_cache_summary().items():
            agg = tenant_cache.setdefault(t, {"hits": 0, "misses": 0})
            agg["hits"] += v["hits"]
            agg["misses"] += v["misses"]
    for agg in tenant_cache.values():
        tot = agg["hits"] + agg["misses"]
        agg["hit_rate"] = round(agg["hits"] / tot, 4) if tot else None
    events = list(fleet.tracer.events)
    mon = fleet.monitor.summary()
    fleet.close()
    fleet.tracer.close()
    return {
        "digest": gen.schedule_digest(),
        "order": order,
        "events": events,
        "committed": committed,
        "produced": produced,
        "summary": s,
        "monitor": mon,
        "tenant_cache": tenant_cache,
        "report": report,
        "wall_s": wall_s,
        "span_s": report["end_time_s"],
        "tenant_names": gen.tenant_names,
    }


def _slice_result(a, b, label):
    """Assert byte-identical replay between the paired runs, then distill
    run A into the recorded slice."""
    assert a["digest"] == b["digest"], f"{label}: schedule diverged"
    assert a["order"] == b["order"], f"{label}: completion order diverged"
    assert a["events"] == b["events"], f"{label}: trace diverged"
    assert a["committed"] == b["committed"], f"{label}: ledger diverged"
    served = {(p, o) for _rid, p, o, _t in a["order"]}
    assert served == a["produced"], f"{label}: lost records"
    assert a["report"]["all_arrived"], f"{label}: schedule never finished"
    s = a["summary"]
    slo = s["slo"]

    def pct(leaf):
        return {
            "count": leaf["count"],
            "p50_ms": round(leaf["p50_ms"], 3),
            "p99_ms": round(leaf["p99_ms"], 3),
        }

    zero = {"count": 0, "p50_ms": 0.0, "p99_ms": 0.0}
    per_tenant = {
        t: {
            "ttft": pct(slo["ttft"]["by_tenant"].get(t, zero)),
            "itl": pct(slo["itl"]["by_tenant"].get(t, zero)),
        }
        for t in a["tenant_names"]
    }
    g = s["goodput"]
    return {
        "replay_identical": True,
        "records": a["report"]["unique_served"],
        "duplicates": a["report"]["duplicates"],
        "offered_span_s": round(a["span_s"], 3),
        "wall_s": round(a["wall_s"], 2),
        "ttft": pct(slo["ttft"]["all"]),
        "itl": pct(slo["itl"]["all"]),
        "queue_wait": pct(slo["queue_wait"]["all"]),
        "e2e": pct(slo["e2e"]["all"]),
        "per_tenant": per_tenant,
        "goodput": {
            "completed": g["completed"],
            "within_slo": g["within_slo"],
            "deferred": g["deferred"],
            "quarantined": g["quarantined"],
            "goodput_ratio": g["goodput_ratio"],
        },
        "burn_transitions": a["monitor"]["transitions"],
        "cache_hit_rate": s["prefix_cache"]["hit_rate"],
        "cache_by_tenant": a["tenant_cache"],
        "step_time_ms_p50": round(s["serving"]["step_time"]["p50_ms"], 3),
        "output_capped": s["serving"]["output_capped"],
    }


def main() -> None:
    ap = argparse.ArgumentParser(description="traffic observatory bench")
    ap.add_argument("--records", type=int, default=48)
    ap.add_argument("--base-rate", type=float, default=300.0,
                    help="1x offered load, records/sec of synthetic time")
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "TRAFFIC_BENCH.json"))
    args = ap.parse_args()

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(1)

    from torchkafka_tpu.workload import ChaosSchedule, WorkloadConfig

    cfg, params, P, MAX_NEW = _build_model()

    def wcfg(rate, chaos=None):
        return WorkloadConfig(
            tenants=args.tenants, zipf_s=1.2,
            total_records=args.records, arrival_rate=rate,
            burst_mean=3.0, interactive_fraction=0.4,
            mean_suffix=max(4.0, P / 3), mean_output=MAX_NEW * 0.75,
            seed=args.seed, chaos=chaos or ChaosSchedule(),
        )

    result = {
        "config": {
            "records": args.records, "base_rate": args.base_rate,
            "tenants": args.tenants, "replicas": REPLICAS, "slots": SLOTS,
            "commit_every": COMMIT_EVERY, "tick_dt_s": TICK_DT,
            "ttft_target_ms": TICK_DT * 12 * 1e3, "objective": 0.75,
            "seed": args.seed,
        },
        "slices": {},
    }
    for factor in (1, 2, 4):
        label = f"{factor}x"
        w = wcfg(args.base_rate * factor)
        a = _run_once(cfg, params, P, MAX_NEW, w)
        b = _run_once(cfg, params, P, MAX_NEW, w)
        result["slices"][label] = _slice_result(a, b, label)
        print(f"[{label}] goodput "
              f"{result['slices'][label]['goodput']} "
              f"ttft p99 {result['slices'][label]['ttft']['p99_ms']} ms")

    # Graceful-degradation acceptance: deferrals rise with offered load;
    # within-SLO completions never collapse to zero at 2x.
    g1 = result["slices"]["1x"]["goodput"]
    g2 = result["slices"]["2x"]["goodput"]
    g4 = result["slices"]["4x"]["goodput"]
    assert g2["within_slo"] > 0, "goodput collapsed to 0 at 2x overload"
    assert g4["deferred"] >= g2["deferred"] >= g1["deferred"], (
        "deferrals did not rise with offered load"
    )
    assert g4["deferred"] > g1["deferred"], (
        "4x overload never deferred — the overload hook did not engage"
    )

    # Chaos slice: seeded replica kill (journal warm-failover path) + an
    # op-counted broker outage at 1x. Duplicate-output bound: only the
    # victim's uncommitted completions can be re-served — at most one
    # commit cadence plus its in-flight slot pool.
    chaos = ChaosSchedule(
        replica_kills=((0.05, 0),),
        broker_outages=((20, 6),),
    )
    w = wcfg(args.base_rate, chaos=chaos)
    a = _run_once(cfg, params, P, MAX_NEW, w)
    b = _run_once(cfg, params, P, MAX_NEW, w)
    chaos_slice = _slice_result(a, b, "chaos")
    assert a["report"]["kills_fired"] == b["report"]["kills_fired"]
    kills = len(a["report"]["kills_fired"])
    bound = kills * (COMMIT_EVERY + SLOTS)
    chaos_slice.update({
        "kills_fired": kills,
        "outage_windows": list(chaos.broker_outages),
        "duplicate_bound": bound,
        "duplicate_bound_held": chaos_slice["duplicates"] <= bound,
    })
    assert kills == 1, "the scheduled kill never fired"
    assert chaos_slice["duplicates"] <= bound, (
        f"duplicates {chaos_slice['duplicates']} exceeded the uncommitted-"
        f"work bound {bound}"
    )
    result["chaos"] = chaos_slice

    print("\n| load | ttft p50/p99 ms | completed | within SLO | deferred "
          "| goodput |")
    print("|---|---|---|---|---|---|")
    for label in ("1x", "2x", "4x"):
        s = result["slices"][label]
        g = s["goodput"]
        print(f"| {label} | {s['ttft']['p50_ms']}/{s['ttft']['p99_ms']} | "
              f"{g['completed']} | {g['within_slo']} | {g['deferred']} | "
              f"{g['goodput_ratio']} |")
    c = result["chaos"]
    print(f"\nchaos: kills={c['kills_fired']} duplicates={c['duplicates']} "
          f"(bound {c['duplicate_bound']}), replay identical, "
          f"zero lost records")
    print(json.dumps(result))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")


if __name__ == "__main__":
    main()

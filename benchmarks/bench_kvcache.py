"""Paged KV-cache paired bench: prefix-overlap sweep + memory table.

Two questions, each answered with paired runs over IDENTICAL broker
content (the repo's pairing discipline — absolute numbers on a
contended CPU box drift; paired counts and ratios are the signal):

1. PREFILL SAVINGS — sweep the prompt stream's prefix-overlap rate
   (0 / 50 / 90% of prompt tokens shared via a common system prefix)
   and report, per rate: radix hit rate, prefill tokens actually
   computed vs the dense server's (= n x prompt_len, it re-prefills
   every prompt in full), and the saved fraction. The differential is
   also re-asserted inline: the paged server's tokens and commit ledger
   must be byte-identical to the dense server's in every slice.

2. MEMORY — the dense pool permanently holds slots x max_len tokens of
   KV; the paged pool's PEAK live blocks are measured per overlap rate.
   At the dense pool's byte budget, the headroom factor (dense-equivalent
   blocks / peak used) is how much LONGER an effective context the same
   HBM could serve paged — the 8B long-context OOM lever (VERDICT.md).

The model is deliberately tiny on CPU: prefill-token counts and block
occupancy are exact regardless of scale, and wall-clock here is
host-dispatch-bound (per-record suffix prefills), not a device claim —
tok/s is reported for completeness, ratios only.

Usage: python benchmarks/bench_kvcache.py [--prompts 48] [--slots 4]
       [--overlaps 0,0.5,0.9] [--slices 2] [--json PATH]
Prints one markdown row per overlap rate plus a JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

PROMPT_LEN, MAX_NEW, BLOCK, VOCAB = 32, 16, 8, 512


def build_broker(tk, np, n: int, overlap: float, seed: int):
    broker = tk.InMemoryBroker()
    broker.create_topic("bench", partitions=4)
    rng = np.random.default_rng(seed)
    shared_len = int(round(overlap * PROMPT_LEN))
    shared = rng.integers(0, VOCAB, shared_len, dtype=np.int32)
    for i in range(n):
        tail = rng.integers(0, VOCAB, PROMPT_LEN - shared_len, dtype=np.int32)
        broker.produce(
            "bench", np.concatenate([shared, tail]).tobytes(),
            partition=i % 4,
        )
    return broker


def run_once(tk, np, jax, cfg, params, broker, slots: int, n: int,
             pages: dict | None):
    from torchkafka_tpu.serve import StreamingGenerator

    class PeakTracking(StreamingGenerator):
        """Sample the live footprint at step ENTRY (post-admission,
        pre-release): DISTINCT blocks mapped by slot tables — the
        must-keep bytes. Tree-only cached blocks are excluded because
        eviction is advisory (they free on demand); sampling after
        completions would miss the in-flight peak."""

        peak_blocks = 0

        def step(self):
            if self._kv_pages is not None:
                live = {
                    int(b) for row in self._table_np for b in row if b != 0
                }
                self.peak_blocks = max(self.peak_blocks, len(live))
            return super().step()

    consumer = tk.MemoryConsumer(broker, "bench", group_id="b")
    server = PeakTracking(
        consumer, params, cfg, slots=slots, prompt_len=PROMPT_LEN,
        max_new=MAX_NEW, commit_every=8, kv_pages=pages,
    )
    server.warmup()
    out = {}
    toks = 0
    t0 = time.perf_counter()
    for rec, gen in server.run(max_records=n):
        out[(rec.partition, rec.offset)] = np.asarray(gen)
        toks += int(gen.shape[0])
    elapsed = time.perf_counter() - t0
    committed = {
        p: broker.committed("b", tk.TopicPartition("bench", p))
        for p in range(4)
    }
    consumer.close()
    return {
        "out": out,
        "committed": committed,
        "elapsed_s": elapsed,
        "tok_s": toks / elapsed if elapsed else None,
        "cache": server.metrics.cache_summary(),
        "peak_blocks": server.peak_blocks,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--prompts", type=int, default=48)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--overlaps", default="0,0.5,0.9")
    ap.add_argument("--slices", type=int, default=2)
    ap.add_argument("--json", default=None, help="also write the JSON here")
    args = ap.parse_args()
    overlaps = [float(x) for x in args.overlaps.split(",")]

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(8)
    import torchkafka_tpu as tk
    from torchkafka_tpu.models.transformer import (
        TransformerConfig, init_params,
    )

    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=PROMPT_LEN + MAX_NEW, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)
    n, slots = args.prompts, args.slots
    max_len = PROMPT_LEN + MAX_NEW
    nblk_slot = -(-max_len // BLOCK)
    # The paged pool gets the DENSE pool's block-equivalent budget plus
    # the sink: same bytes, so the memory rows compare at fixed budget.
    dense_blocks = slots * nblk_slot
    pages = {"block_size": BLOCK, "num_blocks": dense_blocks + 1}
    kv_elem_bytes = jnp.dtype(cfg.dtype).itemsize
    block_bytes = (
        2 * cfg.n_layers * BLOCK * cfg.n_kv_heads * cfg.head_dim
        * kv_elem_bytes
    )

    print(
        f"# bench_kvcache — {n} prompts, {slots} slots, prompt {PROMPT_LEN} "
        f"+ new {MAX_NEW}, block {BLOCK}, {args.slices} paired slices",
    )
    header = (
        "| overlap | hit rate | prefill tok (paged/dense) | saved | "
        "peak blocks (vs dense) | context headroom | paged/dense wall |"
    )
    print(header)
    print("|---|---|---|---|---|---|---|")
    results = []
    for overlap in overlaps:
        ratios, row = [], None
        for s in range(args.slices):
            # Fresh identical content per side, dense/paged back to back
            # inside the slice (same box conditions).
            dense = run_once(
                tk, np, jax, cfg, params,
                build_broker(tk, np, n, overlap, seed=s), slots, n, None,
            )
            paged = run_once(
                tk, np, jax, cfg, params,
                build_broker(tk, np, n, overlap, seed=s), slots, n, pages,
            )
            assert set(dense["out"]) == set(paged["out"])
            for k in dense["out"]:
                np.testing.assert_array_equal(
                    dense["out"][k], paged["out"][k],
                    err_msg=f"overlap {overlap} slice {s} prompt {k}",
                )
            assert dense["committed"] == paged["committed"], (
                "commit ledgers diverged"
            )
            ratios.append(paged["elapsed_s"] / dense["elapsed_s"])
            row = (dense, paged)  # counts identical across slices
        dense, paged = row
        cache = paged["cache"]
        prefill_dense = n * PROMPT_LEN
        saved = 1 - cache["prefill_tokens"] / prefill_dense
        headroom = dense_blocks / max(1, paged["peak_blocks"])
        rec = {
            "overlap": overlap,
            "hit_rate": cache["hit_rate"],
            "prefill_tokens_paged": cache["prefill_tokens"],
            "prefill_tokens_dense": prefill_dense,
            "prefix_tokens_saved": cache["prefix_tokens_saved"],
            "saved_frac": round(saved, 4),
            "evictions": cache["evictions"],
            "deferrals": cache["deferrals"],
            "peak_blocks": paged["peak_blocks"],
            "dense_blocks": dense_blocks,
            "pool_bytes": dense_blocks * block_bytes,
            "context_headroom_x": round(headroom, 2),
            "effective_max_len_at_dense_bytes": int(max_len * headroom),
            "paged_over_dense_wall": round(
                float(np.median(ratios)), 2
            ),
            "dense_tok_s": round(dense["tok_s"], 1),
            "paged_tok_s": round(paged["tok_s"], 1),
        }
        results.append(rec)
        print(
            f"| {overlap:.0%} | "
            f"{(cache['hit_rate'] or 0):.2f} | "
            f"{cache['prefill_tokens']} / {prefill_dense} | "
            f"{saved:.0%} | "
            f"{paged['peak_blocks']} / {dense_blocks} | "
            f"{headroom:.2f}x (max_len {rec['effective_max_len_at_dense_bytes']}) | "
            f"{rec['paged_over_dense_wall']:.2f}x |"
        )
    payload = {
        "bench": "kvcache",
        "prompts": n, "slots": slots, "prompt_len": PROMPT_LEN,
        "max_new": MAX_NEW, "block_size": BLOCK,
        "slices": args.slices,
        "token_exact_and_ledger_identical": True,  # asserted per slice
        "results": results,
    }
    print(json.dumps(payload))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=1)
        print(f"wrote {args.json}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Paged KV-cache paired bench: prefix-overlap x chunk sweep + memory.

Three questions, each answered with paired runs over IDENTICAL broker
content (the repo's pairing discipline — absolute numbers on a
contended CPU box drift; paired counts and ratios are the signal):

1. PREFILL SAVINGS — sweep the prompt stream's prefix-overlap rate
   (0 / 50 / 90% of prompt tokens shared via a common system prefix)
   and report, per rate: radix hit rate, prefill tokens actually
   computed vs the dense server's (= n x prompt_len, it re-prefills
   every prompt in full), and the saved fraction. The differential is
   also re-asserted inline: the paged server's tokens and commit ledger
   must be byte-identical to the dense server's in every slice.

2. WALL-CLOCK (the PR-6 headline) — ``--chunk`` sweeps the CHUNKED
   admission width (suffix tokens the fused tick carries alongside
   decode; "auto" = slots x prompt_len, 0 = the legacy PR-4 per-record
   dispatch) and reports the paged/dense wall ratio per (overlap,
   chunk) cell. The PR-4 CPU result was an honest 2-9x LOSS
   (per-record prefill dispatch + per-tick gather, host-bound); the
   chunked tick's job is to flip the prefill-heavy slice positive —
   ``--prefill-heavy`` adds that slice (short decodes, high overlap:
   the admission-dominated regime a prompt storm produces).

3. MEMORY — the dense pool permanently holds slots x max_len tokens of
   KV; the paged pool's PEAK live blocks are measured per overlap rate.
   At the dense pool's byte budget, the headroom factor (dense-equivalent
   blocks / peak used) is how much LONGER an effective context the same
   HBM could serve paged — the 8B long-context OOM lever (VERDICT.md).

The model is deliberately tiny on CPU: prefill-token counts and block
occupancy are exact regardless of scale; CPU wall ratios are a
dispatch-structure signal (the thing PR-6 changed), not a device claim.

Usage: python benchmarks/bench_kvcache.py [--prompts 48] [--slots 4]
       [--overlaps 0,0.5,0.9] [--chunk auto,0] [--max-new 16]
       [--prefill-heavy] [--slices 2] [--json PATH]
Prints one markdown row per (overlap, chunk) cell plus a JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

BLOCK, VOCAB = 8, 512


def build_broker(tk, np, n: int, prompt_len: int, overlap: float, seed: int):
    broker = tk.InMemoryBroker()
    broker.create_topic("bench", partitions=4)
    rng = np.random.default_rng(seed)
    shared_len = int(round(overlap * prompt_len))
    shared = rng.integers(0, VOCAB, shared_len, dtype=np.int32)
    for i in range(n):
        tail = rng.integers(0, VOCAB, prompt_len - shared_len, dtype=np.int32)
        broker.produce(
            "bench", np.concatenate([shared, tail]).tobytes(),
            partition=i % 4,
        )
    return broker


def run_once(tk, np, jax, cfg, params, broker, slots: int, n: int,
             prompt_len: int, max_new: int, pages: dict | None,
             mesh=None, kv_dtype=None, kv_kernel="auto"):
    from torchkafka_tpu.serve import StreamingGenerator

    class PeakTracking(StreamingGenerator):
        """Sample the live footprint at step ENTRY (post-admission,
        pre-release): DISTINCT blocks mapped by slot tables — the
        must-keep bytes. Tree-only cached blocks are excluded because
        eviction is advisory (they free on demand); sampling after
        completions would miss the in-flight peak."""

        peak_blocks = 0

        def step(self):
            if self._kv_pages is not None:
                live = {
                    int(b) for row in self._table_np for b in row if b != 0
                }
                self.peak_blocks = max(self.peak_blocks, len(live))
            return super().step()

    consumer = tk.MemoryConsumer(broker, "bench", group_id="b")
    server = PeakTracking(
        consumer, params, cfg, slots=slots, prompt_len=prompt_len,
        max_new=max_new, commit_every=8, kv_pages=pages, mesh=mesh,
        kv_dtype=kv_dtype, kv_kernel=kv_kernel,
    )
    server.warmup()
    out = {}
    toks = 0
    t0 = time.perf_counter()
    for rec, gen in server.run(max_records=n):
        out[(rec.partition, rec.offset)] = np.asarray(gen)
        toks += int(gen.shape[0])
    elapsed = time.perf_counter() - t0
    committed = {
        p: broker.committed("b", tk.TopicPartition("bench", p))
        for p in range(4)
    }
    consumer.close()
    return {
        "out": out,
        "committed": committed,
        "elapsed_s": elapsed,
        "tok_s": toks / elapsed if elapsed else None,
        "cache": server.metrics.cache_summary(),
        "chunked": server.metrics.chunk_summary(),
        "peak_blocks": server.peak_blocks,
    }


def sweep(tk, np, jax, cfg, params, *, label, n, slots, prompt_len,
          max_new, overlaps, chunks, slices, dense_blocks, block_bytes):
    """One paired (overlap x chunk) grid at a fixed decode length.
    The dense side runs once per (overlap, slice) and every chunk
    width's paged run pairs against it back-to-back inside the slice —
    exactness (tokens + commit ledger) asserted per cell."""
    results = []
    for overlap in overlaps:
        per_chunk: dict = {}
        cells: dict = {}
        for s in range(slices):
            dense = run_once(
                tk, np, jax, cfg, params,
                build_broker(tk, np, n, prompt_len, overlap, seed=s),
                slots, n, prompt_len, max_new, None,
            )
            for chunk in chunks:
                pages = {
                    "block_size": BLOCK, "num_blocks": dense_blocks + 1,
                    "prefill_chunk": chunk,
                }
                paged = run_once(
                    tk, np, jax, cfg, params,
                    build_broker(tk, np, n, prompt_len, overlap, seed=s),
                    slots, n, prompt_len, max_new, pages,
                )
                assert set(dense["out"]) == set(paged["out"])
                for k in dense["out"]:
                    np.testing.assert_array_equal(
                        dense["out"][k], paged["out"][k],
                        err_msg=f"{label} overlap {overlap} chunk {chunk} "
                                f"slice {s} prompt {k}",
                    )
                assert dense["committed"] == paged["committed"], (
                    "commit ledgers diverged"
                )
                per_chunk.setdefault(chunk, []).append(
                    paged["elapsed_s"] / dense["elapsed_s"]
                )
                cells[chunk] = (dense, paged)  # counts identical per slice
        for chunk in chunks:
            dense, paged = cells[chunk]
            cache = paged["cache"]
            prefill_dense = n * prompt_len
            saved = 1 - cache["prefill_tokens"] / prefill_dense
            headroom = dense_blocks / max(1, paged["peak_blocks"])
            max_len = prompt_len + max_new
            rec = {
                "slice": label,
                "overlap": overlap,
                "prefill_chunk": "auto" if chunk is None else chunk,
                "prompt_len": prompt_len,
                "max_new": max_new,
                "hit_rate": cache["hit_rate"],
                "prefill_tokens_paged": cache["prefill_tokens"],
                "prefill_tokens_dense": prefill_dense,
                "prefix_tokens_saved": cache["prefix_tokens_saved"],
                "saved_frac": round(saved, 4),
                "evictions": cache["evictions"],
                "deferrals": cache["deferrals"],
                "chunk_ticks": paged["chunked"]["chunk_ticks"],
                "stall_ticks": paged["chunked"]["stall_ticks"],
                "peak_blocks": paged["peak_blocks"],
                "dense_blocks": dense_blocks,
                "pool_bytes": dense_blocks * block_bytes,
                "context_headroom_x": round(headroom, 2),
                "effective_max_len_at_dense_bytes": int(max_len * headroom),
                "paged_over_dense_wall": round(
                    float(np.median(per_chunk[chunk])), 2
                ),
                "dense_over_paged_wall": round(
                    1.0 / float(np.median(per_chunk[chunk])), 2
                ),
                "dense_tok_s": round(dense["tok_s"], 1),
                "paged_tok_s": round(paged["tok_s"], 1),
            }
            results.append(rec)
            print(
                f"| {label} | {overlap:.0%} | {rec['prefill_chunk']} | "
                f"{(cache['hit_rate'] or 0):.2f} | "
                f"{cache['prefill_tokens']} / {prefill_dense} | "
                f"{saved:.0%} | "
                f"{paged['peak_blocks']} / {dense_blocks} | "
                f"{headroom:.2f}x | "
                f"{rec['paged_over_dense_wall']:.2f}x |"
            )
    return results


def mesh_sweep(tk, np, jax, cfg, params, *, n, slots, prompt_len, max_new,
               overlap, mesh_specs, slices):
    """PR-13 paired MESH slices: for each host-device mesh, the sharded
    PAGED server (and the sharded paged+int8+kernel one) against its
    single-device reference over identical broker content, exactness
    asserted per slice. CPU host-device meshes measure the COMPOSITION
    honestly — cross-"device" collectives on one box are pure overhead,
    so the wall ratio is a lower bound that only a real TPU slice can
    convert into the sharded 8B-at-4096 headline (PERF.md's open
    rows)."""
    from torchkafka_tpu.parallel import make_mesh

    results = []
    for spec in mesh_specs:
        axes = {
            part.split(":")[0]: int(part.split(":")[1])
            for part in spec.split(",")
        }
        ndev = 1
        for v in axes.values():
            ndev *= v
        mesh = make_mesh(axes, devices=jax.devices()[:ndev])
        pages = {"block_size": BLOCK, "num_blocks": 4 * slots *
                 -(-(prompt_len + max_new) // BLOCK)}
        # Pairings: the plain paged slice measures the COMPOSED server
        # against the dense single-device reference (the tests'
        # exactness contract); the int8+kernel slice pairs the sharded
        # server against the SAME backend on one device — the Pallas
        # read is exact vs the XLA gather only up to f32 reduction
        # order, so kernel-vs-gather is not a bitwise pairing at bench
        # scale, while kernel-vs-kernel isolates exactly the mesh
        # delta.
        for mode, base_pages, base_kw, mesh_kw in (
            ("paged", None, {}, dict(kv_pages=pages)),
            ("paged_int8_kernel", pages,
             dict(kv_dtype="int8", kv_kernel=True),
             dict(kv_dtype="int8", kv_kernel=True, kv_pages=pages)),
        ):
            ratios, cell = [], None
            for s in range(slices):
                base = run_once(
                    tk, np, jax, cfg, params,
                    build_broker(tk, np, n, prompt_len, overlap, seed=s),
                    slots, n, prompt_len, max_new, base_pages, **base_kw,
                )
                sharded = run_once(
                    tk, np, jax, cfg, params,
                    build_broker(tk, np, n, prompt_len, overlap, seed=s),
                    slots, n, prompt_len, max_new,
                    mesh_kw.get("kv_pages"), mesh=mesh,
                    kv_dtype=mesh_kw.get("kv_dtype"),
                    kv_kernel=mesh_kw.get("kv_kernel", "auto"),
                )
                assert set(base["out"]) == set(sharded["out"])
                for k in base["out"]:
                    np.testing.assert_array_equal(
                        base["out"][k], sharded["out"][k],
                        err_msg=f"mesh {spec} mode {mode} slice {s} "
                                f"prompt {k}",
                    )
                assert base["committed"] == sharded["committed"], (
                    "commit ledgers diverged"
                )
                ratios.append(sharded["elapsed_s"] / base["elapsed_s"])
                cell = (base, sharded)
            base, sharded = cell
            rec = {
                "slice": "mesh",
                "mesh": spec,
                "mode": mode,
                "overlap": overlap,
                "prompt_len": prompt_len,
                "max_new": max_new,
                "hit_rate": sharded["cache"]["hit_rate"],
                "prefix_tokens_saved": sharded["cache"][
                    "prefix_tokens_saved"],
                "sharded_over_single_wall": round(
                    float(np.median(ratios)), 2
                ),
                "single_tok_s": round(base["tok_s"], 1),
                "sharded_tok_s": round(sharded["tok_s"], 1),
                "token_exact_and_ledger_identical": True,  # asserted above
            }
            results.append(rec)
            print(
                f"| mesh {spec} | {mode} | "
                f"{(sharded['cache']['hit_rate'] or 0):.2f} | "
                f"{rec['sharded_tok_s']} vs {rec['single_tok_s']} tok/s | "
                f"{rec['sharded_over_single_wall']:.2f}x wall |"
            )
    return results


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--prompts", type=int, default=48)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--overlaps", default="0,0.5,0.9")
    ap.add_argument("--chunk", default="auto,0",
                    help="comma list of chunked-admission widths: 'auto' "
                    "(slots x prompt_len), ints, or 0 (legacy per-record "
                    "PR-4 admission — the paired baseline)")
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--prefill-heavy", action="store_true",
                    help="add the admission-dominated slice (prompt 256, "
                    "max_new 8, overlap 0.9 — the system-prompt storm "
                    "regime the chunked tick exists to flip positive)")
    ap.add_argument("--slices", type=int, default=2)
    ap.add_argument("--mesh", default=None,
                    help="semicolon list of host-device mesh specs for the "
                    "PR-13 sharded-paged slices (e.g. "
                    "'data:2;tp:2;data:2,tp:2'): each runs the sharded "
                    "paged server — and the paged+int8+Pallas-kernel one — "
                    "paired against its single-device reference, exactness "
                    "asserted in-bench")
    ap.add_argument("--json", default=None, help="also write the JSON here")
    args = ap.parse_args()
    overlaps = [float(x) for x in args.overlaps.split(",")]
    chunks = [
        None if c.strip() == "auto" else int(c)
        for c in args.chunk.split(",")
    ]

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(8)
    import torchkafka_tpu as tk
    from torchkafka_tpu.models.transformer import (
        TransformerConfig, init_params,
    )

    def model_for(prompt_len: int, max_new: int):
        cfg = TransformerConfig(
            vocab_size=VOCAB, d_model=64, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=128, max_seq_len=prompt_len + max_new,
            dtype=jnp.float32,
        )
        return cfg, init_params(jax.random.key(0), cfg)

    n, slots = args.prompts, args.slots

    print(
        f"# bench_kvcache — {n} prompts, {slots} slots, mixed slice "
        f"prompt 32 + new {args.max_new} (prefill-heavy: 256 + 8), "
        f"block {BLOCK}, chunks {args.chunk}, {args.slices} paired slices",
    )
    print(
        "| slice | overlap | chunk | hit rate | prefill tok (paged/dense) "
        "| saved | peak blocks | headroom | paged/dense wall |"
    )
    print("|---|---|---|---|---|---|---|---|---|")
    results = []
    kv_elem_bytes = 4  # f32 toy
    for label, prompt_len, max_new, ovl in (
        ("mixed", 32, args.max_new, overlaps),
        # The admission-dominated regime the chunked tick exists to
        # flip: LONG shared-prefix prompts, short outputs — a tenant
        # system-prompt storm.
        *((("prefill_heavy", 256, 8, [0.9]),) if args.prefill_heavy else ()),
    ):
        cfg, params = model_for(prompt_len, max_new)
        max_len = prompt_len + max_new
        dense_blocks = slots * -(-max_len // BLOCK)
        block_bytes = (
            2 * cfg.n_layers * BLOCK * cfg.n_kv_heads * cfg.head_dim
            * kv_elem_bytes
        )
        results += sweep(
            tk, np, jax, cfg, params, label=label, n=n, slots=slots,
            prompt_len=prompt_len, max_new=max_new, overlaps=ovl,
            chunks=chunks, slices=args.slices, dense_blocks=dense_blocks,
            block_bytes=block_bytes,
        )
    if args.mesh:
        cfg, params = model_for(32, args.max_new)
        print(
            "| mesh | mode | hit rate | sharded vs single tok/s | "
            "wall ratio |"
        )
        print("|---|---|---|---|---|")
        results += mesh_sweep(
            tk, np, jax, cfg, params, n=n, slots=slots, prompt_len=32,
            max_new=args.max_new, overlap=0.5,
            mesh_specs=args.mesh.split(";"), slices=args.slices,
        )
    payload = {
        "bench": "kvcache",
        "prompts": n, "slots": slots,
        "max_new": args.max_new, "block_size": BLOCK,
        "chunks": [c if c is not None else "auto" for c in chunks],
        "slices": args.slices,
        "token_exact_and_ledger_identical": True,  # asserted per cell
        "results": results,
    }
    print(json.dumps(payload))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=1)
        print(f"wrote {args.json}", file=sys.stderr)


if __name__ == "__main__":
    main()

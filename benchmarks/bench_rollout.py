"""Rolling hot-swap cost, measured on the serving fleet.

What a live weight rollout (fleet/rollout.py) costs the traffic it rolls
under, and what it provably does NOT cost:

1. **Paired static-vs-rolling slice**: the SAME seeded prompt storm is
   served twice — once by a frozen 2-replica fleet, once by a fleet that
   hot-swaps every replica mid-storm (canary shadow-serve first, then
   one drain-swap at a time) to a checkpoint holding the incumbent's own
   bytes. Rolling to identical weights makes the strongest claim
   checkable: the committed view must be BYTE-IDENTICAL to the static
   run's, so every reported cost is pure swap machinery (quiesce, flush,
   rebind), zero of it token drift. Reported per side: goodput (tok/s),
   TTFT/ITL percentiles from the record-lifecycle tracer, and — rolling
   side only — the swap pause per replica (pause_admission →
   resume_admission, the window that replica admits nothing) plus
   TTFT/ITL of just the records whose lifecycle overlaps the swap window
   (the traffic that actually paid for the rollout).

2. **Spec-draft refresh slice** (ROADMAP item 1's delivery path): a
   speculative server boots on a STALE draft (layer-truncated from an
   unrelated checkpoint — chance-level acceptance), serves half the
   storm, then ``swap_draft_params`` installs the self-truncated draft
   of its own target between ticks (no quiesce — the draft only
   proposes; verification commits). Reported: realized α before/after
   the refresh. Asserted: the committed tokens of the swapped run equal
   BOTH a stale-only and a fresh-only reference run — a draft refresh
   moves α and nothing else.

Both slices assert exactness inline (every produced record served
exactly once, rollout converged, no divergent bytes committed) before
any number is reported.

Usage: python benchmarks/bench_rollout.py [--records 48] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

P, MAX_NEW, VOCAB = 8, 16, 64
REPLICAS, SLOTS, COMMIT_EVERY = 2, 2, 4
CANARY_SLICE = 3
SPEC_K = 3
DRAFT_LAYERS = 1
TOPIC = "p"


def _build_model(seed: int = 0):
    import jax
    import jax.numpy as jnp

    from torchkafka_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, n_kv_heads=1,
        d_ff=64, max_seq_len=P + MAX_NEW, dtype=jnp.float32,
    )
    return cfg, init_params(jax.random.key(seed), cfg)


def _produce(broker, n: int, *, parts: int = 4, start: int = 0):
    """Deterministic prompt storm; ``start`` lets a second batch continue
    the same seeded sequence (the spec slice produces just-in-time so
    no record is over-polled past a swap boundary)."""
    rng = np.random.default_rng(42)
    prompts = rng.integers(0, VOCAB, (start + n, P), dtype=np.int32)
    for i in range(start, start + n):
        broker.produce(TOPIC, prompts[i].tobytes(), partition=i % parts)
    return prompts


def _fleet(broker, model, **kw):
    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet import ServingFleet

    cfg, params = model
    factory = lambda rid: tk.MemoryConsumer(broker, TOPIC, group_id="bench")
    return ServingFleet(
        factory, params, cfg, prompt_len=P, max_new=MAX_NEW,
        replicas=REPLICAS, slots=SLOTS, commit_every=COMMIT_EVERY,
        obs=True, **kw,
    )


def _slo_cell(slo: dict, metric: str) -> dict:
    s = slo[metric]["all"]
    return {
        "count": s["count"],
        "p50_ms": round(s["p50_ms"], 3),
        "p99_ms": round(s["p99_ms"], 3),
    }


class _TimedDriver:
    """InProcessRolloutDriver wrapper that clocks each replica's swap
    pause (pause_admission → resume_admission) on the tracer's clock
    (time.monotonic), so the pause window is directly comparable with
    record-lifecycle event timestamps."""

    def __init__(self, inner):
        self._inner = inner
        self._pause_t0: dict = {}
        self.swap_pause_s: dict = {}
        orig_dispatch = inner._dispatch
        orig_try_swap = inner._try_swap

        def dispatch(directives):
            for d in directives:
                if d.get("t") == "swap":
                    self._pause_t0[d["member"]] = time.monotonic()
            orig_dispatch(directives)

        def try_swap():
            rid, _v = inner._pending_swap
            orig_try_swap()
            # A landed swap either clears _pending_swap or (via the ack
            # it dispatches) replaces it with the NEXT member's swap.
            landed = (
                inner._pending_swap is None
                or inner._pending_swap[0] != rid
            )
            if landed and rid in self._pause_t0:
                self.swap_pause_s[rid] = (
                    time.monotonic() - self._pause_t0.pop(rid)
                )

        inner._dispatch = dispatch
        inner._try_swap = try_swap

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _run_static(model, n: int) -> dict:
    import torchkafka_tpu as tk

    broker = tk.InMemoryBroker()
    broker.create_topic(TOPIC, partitions=4)
    _produce(broker, n)
    fleet = _fleet(broker, model)
    out = {}
    t0 = time.perf_counter()
    for _rid, rec, toks in fleet.serve_all(max_records=n):
        key = (rec.partition, rec.offset)
        assert key not in out, f"duplicate completion {key}"
        out[key] = np.asarray(toks)
    wall = time.perf_counter() - t0
    slo = fleet.tracer.slo.summary()
    fleet.close()
    assert len(out) == n, f"static run lost records: {len(out)}/{n}"
    return {
        "outputs": out,
        "wall_s": round(wall, 3),
        "goodput_tok_s": round(n * MAX_NEW / wall, 1),
        "ttft": _slo_cell(slo, "ttft"),
        "itl": _slo_cell(slo, "itl"),
    }


def _run_rolling(model, n: int, static_out: dict) -> dict:
    import torchkafka_tpu as tk
    from torchkafka_tpu.fleet.rollout import COMPLETE
    from torchkafka_tpu.obs.trace import SWAPPED

    broker = tk.InMemoryBroker()
    broker.create_topic(TOPIC, partitions=4)
    _produce(broker, n)
    fleet = _fleet(broker, model)
    cfg, params = model
    # Target version 1 carries the incumbent's own bytes: the committed
    # view must match the static run EXACTLY, isolating swap overhead.
    drv = _TimedDriver(fleet.start_rollout(
        1, {0: params, 1: params}, canary_slice=CANARY_SLICE,
    ))
    out = {}
    t0 = time.perf_counter()
    for rid, rec, toks in fleet.serve(max_records=n,
                                      on_round=drv.on_round):
        drv.observe(rid, rec, toks)
        key = (rec.partition, rec.offset)
        assert key not in out, f"duplicate completion {key}"
        out[key] = np.asarray(toks)
    # The storm may drain before the last replica swaps: the rollout
    # tail rides an idle fleet (every replica quiesces instantly).
    for _ in range(20):
        if drv.done:
            break
        drv.on_round(fleet, n)
    wall = time.perf_counter() - t0
    slo = fleet.tracer.slo.summary()

    # ---- exactness: rollout converged, committed view byte-identical.
    assert drv.controller.phase == COMPLETE, drv.controller.phase
    versions = [r.gen.model_version for r in fleet.replicas]
    assert versions == [1] * REPLICAS, versions
    swapped_events = [e for e in fleet.tracer.events if e.stage == SWAPPED]
    assert len(swapped_events) == REPLICAS
    assert len(out) == n, f"rolling run lost records: {len(out)}/{n}"
    assert set(out) == set(static_out)
    for k in static_out:
        np.testing.assert_array_equal(out[k], static_out[k], err_msg=str(k))

    # ---- the traffic that paid for the swap: records whose lifecycle
    # overlaps [first pause_admission, last resume_admission].
    assert len(drv.swap_pause_s) == REPLICAS, drv.swap_pause_s
    # Swap events and pause durations share the tracer's monotonic
    # clock: the window opens at (first swap - its pause) and closes at
    # the last swap.
    swap_ts = sorted(e.t for e in swapped_events)
    w0 = swap_ts[0] - max(drv.swap_pause_s.values())
    w1 = swap_ts[-1]
    in_window_ttft, in_window_itl = [], []
    for (p, o) in out:
        rt = fleet.tracer.record_trace(TOPIC, p, o)
        if rt is None or not rt.events:
            continue
        if rt.events[-1].t < w0 or rt.events[0].t > w1:
            continue
        if rt.ttft_s is not None:
            in_window_ttft.append(rt.ttft_s)
        in_window_itl.extend(rt.itl_s)
    fleet.close()

    def pct(xs, q):
        return round(float(np.percentile(xs, q)) * 1e3, 3) if xs else None

    return {
        "outputs": out,
        "wall_s": round(wall, 3),
        "goodput_tok_s": round(n * MAX_NEW / wall, 1),
        "ttft": _slo_cell(slo, "ttft"),
        "itl": _slo_cell(slo, "itl"),
        "swap_pause_ms": {
            str(r): round(drv.swap_pause_s[r] * 1e3, 3)
            for r in sorted(drv.swap_pause_s)
        },
        "swap_window": {
            "span_ms": round((w1 - w0) * 1e3, 3),
            "records_overlapping": len(in_window_ttft),
            "ttft_p50_ms": pct(in_window_ttft, 50),
            "ttft_p99_ms": pct(in_window_ttft, 99),
            "itl_p50_ms": pct(in_window_itl, 50),
            "itl_p99_ms": pct(in_window_itl, 99),
        },
    }


def _spec_refresh(n: int) -> dict:
    """α before/after a mid-stream ``swap_draft_params`` refresh, with
    the committed tokens pinned against stale-only and fresh-only
    reference runs (a draft refresh must move α and nothing else)."""
    import torchkafka_tpu as tk
    from torchkafka_tpu.models.spec_decode import truncated_draft
    from torchkafka_tpu.serve_spec import SpecStreamingGenerator

    cfg, params = _build_model(0)
    stale_src_cfg, stale_src = _build_model(9)
    stale_draft, stale_dcfg = truncated_draft(stale_src, cfg, DRAFT_LAYERS)
    fresh_draft, fresh_dcfg = truncated_draft(params, cfg, DRAFT_LAYERS)
    half = n // 2

    def _gen(broker, draft, dcfg):
        c = tk.MemoryConsumer(broker, TOPIC, group_id="spec")
        return SpecStreamingGenerator(
            c, params, cfg, draft_params=draft, draft_cfg=dcfg, k=SPEC_K,
            slots=SLOTS, prompt_len=P, max_new=MAX_NEW, ticks_per_sync=1,
            commit_every=COMMIT_EVERY,
        )

    def _reference(draft, dcfg):
        broker = tk.InMemoryBroker()
        broker.create_topic(TOPIC, partitions=2)
        _produce(broker, n, parts=2)
        gen = _gen(broker, draft, dcfg)
        out = {}
        for rec, toks in gen.run(max_records=n):
            out[(rec.partition, rec.offset)] = np.asarray(toks)
        assert len(out) == n
        return out, gen.spec_stats()

    ref_stale, st_stale = _reference(stale_draft, stale_dcfg)
    ref_fresh, st_fresh = _reference(fresh_draft, fresh_dcfg)
    # The contract swap_draft_params is built on: the draft only
    # proposes, so ANY draft yields identical committed tokens.
    for k in ref_stale:
        np.testing.assert_array_equal(ref_stale[k], ref_fresh[k])

    # Swapped run: produce just-in-time so the first half's poll cannot
    # run past the swap boundary.
    broker = tk.InMemoryBroker()
    broker.create_topic(TOPIC, partitions=2)
    _produce(broker, half, parts=2)
    gen = _gen(broker, stale_draft, stale_dcfg)
    out = {}
    for rec, toks in gen.run(max_records=half):
        out[(rec.partition, rec.offset)] = np.asarray(toks)
    st_before = gen.spec_stats()
    t0 = time.perf_counter()
    gen.swap_draft_params(fresh_draft, fresh_dcfg)
    swap_ms = (time.perf_counter() - t0) * 1e3
    _produce(broker, n - half, parts=2, start=half)
    for rec, toks in gen.run(max_records=n - half):
        out[(rec.partition, rec.offset)] = np.asarray(toks)
    st_after = gen.spec_stats()

    assert len(out) == n, f"spec slice lost records: {len(out)}/{n}"
    for k in out:
        np.testing.assert_array_equal(out[k], ref_stale[k], err_msg=str(k))
    acc = st_after["accepted"] - st_before["accepted"]
    prop = st_after["proposed"] - st_before["proposed"]
    assert prop > 0
    alpha_before = st_before["acceptance"]
    alpha_after = round(acc / prop, 4)
    assert alpha_after > alpha_before, (
        f"draft refresh did not raise acceptance: "
        f"{alpha_before} -> {alpha_after}"
    )
    return {
        "k": SPEC_K,
        "draft_layers": DRAFT_LAYERS,
        "alpha_stale_full_run": st_stale["acceptance"],
        "alpha_fresh_full_run": st_fresh["acceptance"],
        "alpha_before_refresh": alpha_before,
        "alpha_after_refresh": alpha_after,
        "swap_draft_params_ms": round(swap_ms, 3),
        "committed_identical_across_drafts": True,
        "records": n,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=48)
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(__file__), "..", "ROLLOUT_BENCH.json"
        ),
    )
    args = ap.parse_args()

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(1)
    model = _build_model(0)

    static = _run_static(model, args.records)
    rolling = _run_rolling(model, args.records, static["outputs"])
    static.pop("outputs")
    rolling.pop("outputs")
    spec = _spec_refresh(max(16, args.records // 2))

    # Acceptance: the rollout must be invisible in token space (asserted
    # inside _run_rolling) and cheap — the whole-run goodput under a
    # full 2-replica rollout stays within 2x of static (the pause is a
    # per-replica drain, not a fleet stall).
    ratio = round(static["goodput_tok_s"] / rolling["goodput_tok_s"], 3)
    assert ratio < 2.0, f"rolling goodput degraded {ratio}x vs static"

    result = {
        "bench": "rollout",
        "records": args.records,
        "model": {
            "vocab": VOCAB, "d_model": 32, "n_layers": 2,
            "prompt_len": P, "max_new": MAX_NEW,
            "replicas": REPLICAS, "slots": SLOTS,
            "commit_every": COMMIT_EVERY, "canary_slice": CANARY_SLICE,
        },
        "static": static,
        "rolling": rolling,
        "static_over_rolling_goodput": ratio,
        "byte_identical": True,
        "zero_lost": True,
        "duplicates": 0,
        "spec_draft_refresh": spec,
    }

    print("\n| slice | goodput tok/s | TTFT p50/p99 ms | ITL p50/p99 ms |")
    print("|---|---|---|---|")
    for name in ("static", "rolling"):
        s = result[name]
        print(f"| {name} | {s['goodput_tok_s']} "
              f"| {s['ttft']['p50_ms']} / {s['ttft']['p99_ms']} "
              f"| {s['itl']['p50_ms']} / {s['itl']['p99_ms']} |")
    sw = rolling["swap_window"]
    print(f"\nswap pause per replica (ms): {rolling['swap_pause_ms']}")
    print(f"swap window: {sw['span_ms']} ms, "
          f"{sw['records_overlapping']} records overlapping, "
          f"TTFT p50 {sw['ttft_p50_ms']} ms, ITL p50 {sw['itl_p50_ms']} ms")
    print(f"draft refresh: alpha {spec['alpha_before_refresh']} -> "
          f"{spec['alpha_after_refresh']} "
          f"(swap_draft_params {spec['swap_draft_params_ms']} ms)")
    print(json.dumps(result))

    out_path = os.path.abspath(args.out)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Where do the flagship train step's FLOPs go, and what's the MFU?

Times the 45.4M-parameter flagship transformer's jitted train step on the
real TPU (strict completion: chained steps, scalar loss fetch), comparing
the fused blocked CE (default) against the round-2 dense CE
(`ce_block_size=0`), and decomposing a step into trunk / head+CE / backward
/ optimizer by timing nested jits. Writes a markdown table to stdout for
PERF.md.

Usage:  python benchmarks/mfu_breakdown.py [--batches 8,32,64] [--steps 20]
        python benchmarks/mfu_breakdown.py --long-ctx   # B=4/S=2048, B=1/S=16384
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from torchkafka_tpu.models import Transformer, TransformerConfig, make_train_step
from torchkafka_tpu.models.transformer import count_params

V5E_BF16_PEAK = 197e12  # TPU v5e bf16 peak FLOP/s


def train_flops_per_step(cfg: TransformerConfig, batch: int, seq: int) -> float:
    """6·N·tokens (N = matmul params incl. head, excl. embedding gather)
    + attention 6·L·d·B·S² — the CAUSAL-halved count (non-causal would be
    12·L·d·B·S²: QK^T + PV at 2 FLOPs/MAC × 3 fwd+bwd passes); the flash
    kernels skip the masked half, so this matches executed FLOPs. Same
    convention as PERF.md round 2."""
    n = (
        cfg.n_layers
        * (
            cfg.d_model * (cfg.n_heads + 2 * cfg.n_kv_heads) * cfg.head_dim
            + cfg.n_heads * cfg.head_dim * cfg.d_model
            + 3 * cfg.d_model * cfg.d_ff
        )
        + cfg.d_model * cfg.vocab_size
    )
    tokens = batch * seq
    return 6.0 * n * tokens + 6.0 * cfg.n_layers * cfg.d_model * batch * seq * seq


def timed(fn, *args, steps: int, fetch) -> float:
    """Two-point-slope over PYTHON-LOOP chains of jitted calls.

    The slope cancels the constant fetch round trip but NOT the per-call
    host dispatch cost (~10 ms/call through the dev tunnel), which scales
    with the chain length: any piece whose device time is below the
    dispatch cost reads as ~dispatch-rate here. Used only by
    ``decompose``, whose output is presented as RELATIVE shares — for
    honest device absolutes use ``utils.timing.device_step_seconds``
    (fori-chained inside one jit), as ``run_config`` does."""
    from torchkafka_tpu.utils.timing import two_point_slope

    outs = fn(*args)
    fetch(outs)  # compile + warmup

    def window(k: int) -> float:
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = fn(*args)
        fetch(o)
        return time.perf_counter() - t0

    shorts, longs = [], []
    for _ in range(3):  # interleaved so drift can't flip the slope
        shorts.append(window(steps))
        longs.append(window(3 * steps))
    per_iter, _ov, ok = two_point_slope(
        float(np.median(shorts)), float(np.median(longs)), steps, 3 * steps
    )
    if not ok:
        raise RuntimeError("transport drift swamped the timing slope; rerun")
    return per_iter


def run_config(cfg: TransformerConfig, batch: int, seq: int, steps: int) -> dict:
    """Pure device step via the fori-chained slope (utils.timing): a
    Python-loop chain of jitted calls on an RPC-dispatch transport
    measures the HOST dispatch rate (~10 ms/call), not the device —
    wall/step falls forever as the window grows instead of converging.
    ``--steps`` sets the LONG window's loop length (short = a quarter)."""
    from torchkafka_tpu.utils.timing import device_step_seconds

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
    init_fn, step_fn = make_train_step(cfg, mesh, optax.adamw(3e-4))
    params, opt_state = init_fn(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.float32)
    k_long = max(8, steps)
    dt, ok = device_step_seconds(
        step_fn, params, opt_state, tokens, mask,
        k_short=max(2, k_long // 4), k_long=k_long,
    )
    if not ok:
        raise RuntimeError("transport drift swamped the timing slope; rerun")
    fl = train_flops_per_step(cfg, batch, seq)
    return {"ms": dt * 1e3, "tflop": fl / 1e12, "mfu": fl / dt / V5E_BF16_PEAK}


def decompose(cfg: TransformerConfig, batch: int, seq: int, steps: int) -> dict:
    """Forward-only pieces + full fwd+bwd, each as its own jit."""
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    params = jax.device_put(params)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.float32)

    trunk = jax.jit(lambda p, t: model.trunk(p, t)[0].sum())
    lossf = jax.jit(lambda p, t, m: model.loss(p, t, m))
    gradf = jax.jit(lambda p, t, m: jax.grad(model.loss)(p, t, m))

    t_trunk = timed(trunk, params, tokens, steps=steps, fetch=lambda o: float(o))
    t_loss = timed(lossf, params, tokens, mask, steps=steps, fetch=lambda o: float(o))
    t_grad = timed(
        gradf, params, tokens, mask, steps=steps,
        fetch=lambda o: float(jax.tree_util.tree_leaves(o)[0].ravel()[0]),
    )
    return {
        "trunk_fwd_ms": t_trunk * 1e3,
        "loss_fwd_ms": t_loss * 1e3,
        "headce_fwd_ms": (t_loss - t_trunk) * 1e3,
        "fwd_bwd_ms": t_grad * 1e3,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="8,32,64")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--long-ctx", action="store_true")
    ap.add_argument("--decompose", action="store_true")
    args = ap.parse_args()

    print(f"backend={jax.default_backend()} devices={jax.devices()}")
    if args.long_ctx:
        combos = [
            (TransformerConfig(max_seq_len=2048, attn_impl="flash"), 4, 2048),
            (
                TransformerConfig(max_seq_len=16384, attn_impl="flash", remat=True),
                1, 16384,
            ),
        ]
        for cfg, b, s in combos:
            for blk in (None, 0):
                import dataclasses

                c = dataclasses.replace(cfg, ce_block_size=blk)
                label = "fused" if blk is None else "dense"
                try:
                    r = run_config(c, b, s, max(4, args.steps // 4))
                    print(
                        f"B={b} S={s} ce={label}: {r['ms']:.1f} ms/step, "
                        f"{r['tflop']:.2f} TFLOP, MFU {r['mfu'] * 100:.1f}%"
                    )
                except Exception as e:  # noqa: BLE001 — report OOMs inline
                    print(f"B={b} S={s} ce={label}: FAILED {type(e).__name__}: {e}")
        return

    import dataclasses

    cfg = TransformerConfig()
    n_params = count_params(Transformer(cfg).init(jax.random.key(0)))
    print(f"flagship params: {n_params / 1e6:.1f}M, seq {cfg.max_seq_len}")
    for b in [int(x) for x in args.batches.split(",")]:
        for blk in (None, 0):
            c = dataclasses.replace(cfg, ce_block_size=blk)
            label = "fused" if blk is None else "dense"
            r = run_config(c, b, cfg.max_seq_len, args.steps)
            print(
                f"B={b} ce={label}: {r['ms']:.1f} ms/step, {r['tflop']:.2f} "
                f"TFLOP/step, MFU {r['mfu'] * 100:.1f}%"
            )
        if args.decompose:
            d = decompose(cfg, b, cfg.max_seq_len, args.steps)
            print(
                f"  decompose B={b}: trunk fwd {d['trunk_fwd_ms']:.1f} ms, "
                f"+head+CE {d['headce_fwd_ms']:.1f} ms, full fwd "
                f"{d['loss_fwd_ms']:.1f} ms, fwd+bwd {d['fwd_bwd_ms']:.1f} ms"
            )


if __name__ == "__main__":
    main()

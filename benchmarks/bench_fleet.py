"""Fleet-throughput curve: 1 vs 2 vs 4 replicas on the in-memory broker.

Paired same-window runs: replica counts alternate round-robin over
``--slices`` rounds against freshly-built identical broker content, so
box contention hits every configuration equally and the per-round RATIOS
(R replicas vs 1) are the stable signal — the same pairing discipline
bench.py and the harness pairs use. Absolute rows/s on a contended CPU
box swing widely; the ratio answers the question the fleet exists for:
does work actually spread across replicas?

The model is deliberately tiny on CPU (the point is the SCHEDULING path:
group assignment, QoS admission, per-replica commits — not the decode
FLOPs; a real fleet puts each replica on its own accelerator, where pump
cost is device-bound and replicas scale across chips).

Usage: python benchmarks/bench_fleet.py [--replicas 1,2,4] [--prompts 96]
       [--slices 3]
Prints one markdown row per replica count plus a JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build(tk, cfg, params, n_prompts: int, replicas: int, vocab: int,
          prompt_len: int):
    import numpy as np

    broker = tk.InMemoryBroker()
    parts = max(4, replicas)
    broker.create_topic("bench", partitions=parts)
    rng = np.random.default_rng(0)
    for i in range(n_prompts):
        broker.produce(
            "bench",
            rng.integers(0, vocab, prompt_len, dtype=np.int32).tobytes(),
            partition=i % parts,
        )
    from torchkafka_tpu.fleet import ServingFleet

    return broker, ServingFleet(
        lambda rid: tk.MemoryConsumer(broker, "bench", group_id="bench"),
        params, cfg, replicas=replicas, prompt_len=prompt_len,
        max_new=16, slots=8, commit_every=8,
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", default="1,2,4")
    ap.add_argument("--prompts", type=int, default=96)
    ap.add_argument("--slices", type=int, default=3)
    args = ap.parse_args()
    counts = [int(x) for x in args.replicas.split(",")]

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(8)
    import torchkafka_tpu as tk
    from torchkafka_tpu.models.transformer import (
        TransformerConfig, init_params,
    )

    prompt_len, vocab = 16, 512
    cfg = TransformerConfig(
        vocab_size=vocab, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=prompt_len + 16, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)

    # Warm the jit cache once so slice 0 of the first config doesn't pay
    # compile while the others hit the cache (pairing would be broken).
    _, fleet = build(tk, cfg, params, 8, 1, vocab, prompt_len)
    fleet.warmup()
    fleet.serve_all(max_records=8)
    fleet.close()

    per: dict[int, list[float]] = {r: [] for r in counts}
    for s in range(args.slices):
        for r in counts:  # interleaved: every config samples every window
            broker, fleet = build(
                tk, cfg, params, args.prompts, r, vocab, prompt_len
            )
            t0 = time.perf_counter()
            done = len(fleet.serve_all(max_records=args.prompts))
            dt = time.perf_counter() - t0
            fleet.close()
            assert done == args.prompts, (r, done)
            per[r].append(args.prompts / dt)
            print(f"slice {s} replicas {r}: {per[r][-1]:,.1f} prompts/s",
                  file=sys.stderr)

    base = [per[counts[0]][i] for i in range(args.slices)]
    print("| replicas | prompts/s (median) | ratio vs "
          f"{counts[0]} (median of paired) |")
    print("|---|---|---|")
    out = {}
    for r in counts:
        rates = per[r]
        ratios = [rates[i] / base[i] for i in range(args.slices)]
        med = float(np.median(rates))
        med_ratio = float(np.median(ratios))
        out[r] = {"prompts_per_s": med, "ratio": med_ratio,
                  "slices": [round(x, 1) for x in rates]}
        print(f"| {r} | {med:,.1f} | {med_ratio:.2f}× |")
    print(json.dumps(out), file=sys.stderr)


if __name__ == "__main__":
    main()

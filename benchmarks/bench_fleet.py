"""Fleet-throughput curve: 1 vs 2 vs 4 replicas on the in-memory broker.

Paired same-window runs: replica counts alternate round-robin over
``--slices`` rounds against freshly-built identical broker content, so
box contention hits every configuration equally and the per-round RATIOS
(R replicas vs 1) are the stable signal — the same pairing discipline
bench.py and the harness pairs use. Absolute rows/s on a contended CPU
box swing widely; the ratio answers the question the fleet exists for:
does work actually spread across replicas?

The model is deliberately tiny on CPU (the point is the SCHEDULING path:
group assignment, QoS admission, per-replica commits — not the decode
FLOPs; a real fleet puts each replica on its own accelerator, where pump
cost is device-bound and replicas scale across chips).

Usage: python benchmarks/bench_fleet.py [--replicas 1,2,4] [--prompts 96]
       [--slices 3]
Prints one markdown row per replica count plus a JSON line.

``--failover``: the warm-failover differential instead — the SAME seeded
mid-generation replica kill with the decode journal off (cold replay)
vs on (warm resume), paired over ``--slices`` rounds. Reported signal:
tokens re-decoded after the death, cold vs warm — the journal's whole
value proposition — with byte-exactness vs a no-kill reference ASSERTED
for every run before its numbers count (a fast failover that changed an
output would be a bug, not a result).

``--procs 1,2,4``: the REAL-PROCESS fleet curve (fleet/supervisor.py):
R worker processes over the socket broker, one consumer group, measured
from all-ready (per-process jit warmup excluded via the readiness
markers) to fully-committed. Paired interleaved slices; per-slice
exactness asserted against the in-process reference before any number
counts. NOTE the honest caveat: on an N-core box this measures real
OS-process scheduling + socket-RPC overhead — R processes only scale
when R cores exist (a 1-core container shows ≈flat-to-negative, and
PERF.md says so).

``--wal``: the durable-broker WAL tax — the same transactional serving
run over broker durability memory / None / batch / commit (paired,
interleaved, exactness + exactly-once committed view asserted inside
every slice) plus a recovery-time vs WAL-size curve with recovered
state asserted equal to the pre-death broker at every point. Appends
rows to FAILOVER_BENCH.json via --json-out.

``--quorum``: the replicated-cell differential — (a) the commit-latency
micro re-run with the broker being a 3-replica ``BrokerCell`` leader
(``wal_durability="quorum"``: every frame ships to 2 followers over real
sockets before the ack), paired in the same window against the
at-least-once and exactly-once in-memory floors the --txn table
recorded; (b) ``kill_leader()`` failover-to-goodput — the time from the
kill instant to the first COMMITTED transaction through a wire client on
the same advertised port — vs scenario 19's 2.5 s single-broker
ride-through. Zero committed-record loss + an exactly-once committed
view asserted inside every slice. Appends a "quorum" key to
FAILOVER_BENCH.json via --json-out.

``--procs-failover``: the CROSS-PROCESS warm-failover differential — a
real SIGKILL of one worker process mid-storm, journals shared (warm:
the survivor loads the victim's file across the process boundary) vs
private-throwaway (cold), paired per slice. Signal: survivor-side
decoded tokens, cold vs warm; exactness asserted every run. Appends
rows to FAILOVER_BENCH.json via --json-out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build(tk, cfg, params, n_prompts: int, replicas: int, vocab: int,
          prompt_len: int):
    import numpy as np

    broker = tk.InMemoryBroker()
    parts = max(4, replicas)
    broker.create_topic("bench", partitions=parts)
    rng = np.random.default_rng(0)
    for i in range(n_prompts):
        broker.produce(
            "bench",
            rng.integers(0, vocab, prompt_len, dtype=np.int32).tobytes(),
            partition=i % parts,
        )
    from torchkafka_tpu.fleet import ServingFleet

    return broker, ServingFleet(
        lambda rid: tk.MemoryConsumer(broker, "bench", group_id="bench"),
        params, cfg, replicas=replicas, prompt_len=prompt_len,
        max_new=16, slots=8, commit_every=8,
    )


def run_failover(tk, cfg, params, args, vocab: int, prompt_len: int,
                 max_new: int) -> None:
    import tempfile

    import numpy as np

    from torchkafka_tpu.fleet import ReplicaChaos, ServingFleet

    n, parts = args.prompts, 4
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, vocab, (n, prompt_len), dtype=np.int32)

    def fresh_broker():
        broker = tk.InMemoryBroker()
        broker.create_topic("bench", partitions=parts)
        for i in range(n):
            broker.produce("bench", prompts[i].tobytes(), partition=i % parts)
        return broker

    def serve(broker, journal_dir, chaos):
        fleet = ServingFleet(
            lambda rid: tk.MemoryConsumer(broker, "bench", group_id="b"),
            params, cfg, replicas=2, prompt_len=prompt_len, max_new=max_new,
            slots=4, commit_every=10**6,  # kill provably redelivers
            journal_dir=journal_dir, journal_cadence=args.cadence,
        )
        fleet.warmup()
        got = {}
        for _rid, rec, toks in fleet.serve(idle_timeout_ms=2000, chaos=chaos):
            got[(rec.partition, rec.offset)] = toks
        redecoded = sum(
            rep.gen.metrics.decoded_tokens.count for rep in fleet.replicas
        )
        summary = fleet.metrics.summary(fleet.replicas)["journal"]
        fleet.close()
        return got, redecoded, summary

    ref, _, _ = serve(fresh_broker(), None, None)

    def killed_run(warm: bool, seed: int):
        chaos = ReplicaChaos(seed=seed, min_completions=2, max_completions=5)
        with tempfile.TemporaryDirectory() as td:
            got, redecoded, jn = serve(
                fresh_broker(), td if warm else None, chaos
            )
        assert len(chaos.killed) == 1, "the seeded kill never fired"
        # Exactness gate: a differential between two runs that disagree
        # on even one token is meaningless — assert before measuring.
        assert set(got) == set(ref), "coverage broken after kill"
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=str(k))
        return redecoded, jn

    cold, warm = [], []
    for s in range(args.slices):
        seed = 5 + s  # same seed within a pair → identical kill schedule
        c, _ = killed_run(warm=False, seed=seed)
        w, jn = killed_run(warm=True, seed=seed)
        cold.append(c)
        warm.append(w)
        assert w < c, (
            f"slice {s}: warm resume re-decoded {w} tokens vs cold {c} — "
            "the journal saved nothing"
        )
        print(f"slice {s}: re-decoded cold {c} warm {w} "
              f"(saved {c - w}, restored {jn['tokens_restored']}, "
              f"journal-served {jn['served_from_journal']})",
              file=sys.stderr)
    med_c = float(np.median(cold))
    med_w = float(np.median(warm))
    print("| failover | re-decoded tokens (median) | vs cold |")
    print("|---|---|---|")
    print(f"| cold replay (journal off) | {med_c:,.0f} | 1.00× |")
    print(f"| warm resume (cadence {args.cadence}) | {med_w:,.0f} | "
          f"{med_w / med_c:.2f}× |")
    print(json.dumps({
        "prompts": n, "max_new": max_new, "cadence": args.cadence,
        "slices": args.slices, "cold_redecoded": cold,
        "warm_redecoded": warm,
        "median_saved_tokens": med_c - med_w,
        "exactness": "asserted vs no-kill reference, every run",
    }), file=sys.stderr)


MODEL_SPEC = dict(seed=0, vocab_size=512, d_model=64, n_layers=2,
                  n_heads=4, n_kv_heads=2, d_ff=128)


def _proc_reference(tk, cfg, params, prompts, parts, max_new):
    from torchkafka_tpu.serve import StreamingGenerator

    broker = tk.InMemoryBroker()
    broker.create_topic("ref", partitions=parts)
    for i in range(prompts.shape[0]):
        broker.produce("ref", prompts[i].tobytes(), partition=i % parts,
                       key=str(i).encode())
    c = tk.MemoryConsumer(broker, "ref", group_id="ref")
    gen = StreamingGenerator(c, params, cfg, slots=4,
                             prompt_len=prompts.shape[1], max_new=max_new,
                             commit_every=8, ticks_per_sync=1)
    ref = {rec.key: toks for rec, toks in gen.run(idle_timeout_ms=400)}
    c.close()
    return ref


def _build_proc_fleet(tk, workdir, replicas, parts, prompt_len, max_new,
                      journal=True, commit_every=8):
    from torchkafka_tpu.fleet import ProcessFleet

    spec = dict(MODEL_SPEC, max_seq_len=prompt_len + max_new)
    return ProcessFleet(
        spec, topic="bench", prompt_len=prompt_len, max_new=max_new,
        workdir=workdir, replicas=replicas, partitions=parts, slots=4,
        commit_every=commit_every, session_timeout_s=5.0,
        heartbeat_interval_s=0.25, journal_cadence=2, journal=journal,
        respawn=False, group="bench",
    )


def _assert_exact(res, ref, n):
    import numpy as np

    assert set(res) == {str(i).encode() for i in range(n)}, (
        "coverage broken", len(res), n,
    )
    for k, copies in res.items():
        for _member, toks in copies:
            np.testing.assert_array_equal(toks, ref[k], err_msg=str(k))


def run_procs(tk, cfg, params, args, prompt_len, max_new) -> None:
    import tempfile

    import numpy as np

    counts = [int(x) for x in args.procs.split(",")]
    n, parts = args.prompts, max(4, max(counts))
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)
    ref = _proc_reference(tk, cfg, params, prompts, parts, max_new)
    per: dict[int, list[float]] = {r: [] for r in counts}
    for s in range(args.slices):
        for r in counts:  # interleaved: every config samples every window
            with tempfile.TemporaryDirectory() as td:
                fleet = _build_proc_fleet(
                    tk, td, r, parts, prompt_len, max_new
                )
                try:
                    fleet.start()
                    fleet.wait_ready(timeout_s=600)
                    # Measured window: all replicas warm → storm produced
                    # → every prompt durably committed.
                    t0 = time.perf_counter()
                    for i in range(n):
                        fleet.broker.produce(
                            "bench", prompts[i].tobytes(),
                            partition=i % parts, key=str(i).encode(),
                        )
                    fleet.wait(lambda f: f.fully_committed(),
                               timeout_s=600)
                    dt = time.perf_counter() - t0
                    _assert_exact(fleet.results(), ref, n)
                finally:
                    fleet.close()
            per[r].append(n / dt)
            print(f"slice {s} procs {r}: {per[r][-1]:,.1f} prompts/s",
                  file=sys.stderr)

    base = [per[counts[0]][i] for i in range(args.slices)]
    print("| replica processes | prompts/s (median) | ratio vs "
          f"{counts[0]} (median of paired) |")
    print("|---|---|---|")
    out = {}
    for r in counts:
        rates = per[r]
        ratios = [rates[i] / base[i] for i in range(args.slices)]
        out[r] = {
            "prompts_per_s": float(np.median(rates)),
            "ratio": float(np.median(ratios)),
            "slices": [round(x, 1) for x in rates],
        }
        print(f"| {r} | {out[r]['prompts_per_s']:,.1f} "
              f"| {out[r]['ratio']:.2f}× |")
    print(json.dumps({
        "mode": "procs", "prompts": n, "max_new": max_new,
        "cores": os.cpu_count(), "per_procs": out,
        "exactness": "asserted vs in-process reference, every slice",
    }), file=sys.stderr)


def run_procs_failover(tk, cfg, params, args, prompt_len, max_new) -> None:
    import tempfile

    import numpy as np

    from torchkafka_tpu.source.records import TopicPartition

    n, parts = args.prompts, 4
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)
    ref = _proc_reference(tk, cfg, params, prompts, parts, max_new)

    def killed_run(warm: bool):
        with tempfile.TemporaryDirectory() as td:
            fleet = _build_proc_fleet(
                tk, td, 2, parts, prompt_len, max_new, journal=warm,
                # Large cadence: the kill provably re-delivers (nothing
                # committed mid-storm), maximizing the journal's window.
                commit_every=10**6,
            )
            try:
                fleet.start()
                fleet.wait_ready(timeout_s=600)
                for i in range(n):
                    fleet.broker.produce(
                        "bench", prompts[i].tobytes(),
                        partition=i % parts, key=str(i).encode(),
                    )
                victim = None
                deadline = time.monotonic() + 300
                while victim is None:
                    if time.monotonic() > deadline:
                        raise TimeoutError(fleet.diagnose())
                    res = fleet.results()
                    if len(res) >= n:
                        raise RuntimeError("storm drained pre-kill")
                    served = {m for v in res.values() for m, _ in v}
                    for inc in fleet.live():
                        if inc.member in served:
                            victim = fleet.kill_replica(inc.idx)
                            break
                    time.sleep(0.01)
                fleet.wait(
                    lambda f: set(f.results())
                    == {str(i).encode() for i in range(n)},
                    timeout_s=600,
                )
                fleet.drain()
                fleet.wait(
                    lambda f: all(not i.running for i in f.incarnations),
                    timeout_s=300,
                )
                fleet.poll_once()
                assert fleet.fully_committed()
                res = fleet.results()
                _assert_exact(res, ref, n)
                wm = fleet.worker_metrics()
                survivor_decoded = sum(m["decoded_tokens"] for m in wm)
                restored = sum(m["tokens_restored"] for m in wm)
                jserved = sum(m["served_from_journal"] for m in wm)
                dups = sum(len(v) - 1 for v in res.values())
            finally:
                fleet.close()
        return survivor_decoded, restored, jserved, dups

    cold, warm = [], []
    rows = []
    for s in range(args.slices):
        c, _, _, cd = killed_run(warm=False)
        w, restored, jserved, wd = killed_run(warm=True)
        cold.append(c)
        warm.append(w)
        rows.append({
            "slice": s, "cold_survivor_decoded": c,
            "warm_survivor_decoded": w, "tokens_restored": restored,
            "journal_served": jserved,
            "duplicates": {"cold": cd, "warm": wd},
        })
        print(f"slice {s}: survivor decoded cold {c} warm {w} "
              f"(restored {restored}, journal-served {jserved})",
              file=sys.stderr)
    med_c, med_w = float(np.median(cold)), float(np.median(warm))
    print("| cross-process failover | survivor decoded tokens (median) "
          "| vs cold |")
    print("|---|---|---|")
    print(f"| cold (private journals) | {med_c:,.0f} | 1.00× |")
    print(f"| warm (shared journal dir, cadence 2) | {med_w:,.0f} | "
          f"{med_w / med_c:.2f}× |")
    doc = {
        "mode": "procs-failover", "prompts": n, "max_new": max_new,
        "slices": rows, "median_cold": med_c, "median_warm": med_w,
        "ratio": med_w / med_c if med_c else None,
        "exactness": "asserted vs in-process reference, every run",
    }
    print(json.dumps(doc), file=sys.stderr)
    if args.json_out:
        try:
            with open(args.json_out, encoding="utf-8") as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = {}
        existing["cross_process"] = doc
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(existing, f, indent=1)
        print(f"appended cross_process rows to {args.json_out}",
              file=sys.stderr)


def run_txn(tk, cfg, params, args, prompt_len, max_new) -> None:
    """The transaction tax, measured twice.

    (a) PAIRED in-process commit-latency micro: the SAME prompts served
    at-least-once (async sends + flush-then-commit) and exactly-once
    (one transaction per commit window: produces + offsets + atomic
    commit), interleaved per slice; commit p50/p99 from
    ``ServeMetrics.commit_latency`` — the number PERF.md's 0.04–0.07 ms
    baseline row quotes. Exactness asserted inside every slice: both
    modes byte-identical, and the exactly-once run's COMMITTED view
    holds each completion exactly once.

    (b) CROSS-PROCESS SIGKILL failover with transactions: the
    procs-failover storm re-run with ``exactly_once=True`` — a replica
    SIGKILLed while its journal proves uncommitted served work, the
    supervisor's fence aborts its in-flight transaction, the survivor
    re-serves — and committed-view duplicates are asserted == 0 (the
    at-least-once slice of this same file measures 16/run)."""
    import tempfile

    import numpy as np

    from torchkafka_tpu.journal import DecodeJournal
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition

    n, parts = args.prompts, 4
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)

    # ---------------------------------------------- (a) commit-tax micro
    def serve_once(txn: bool):
        broker = tk.InMemoryBroker()
        broker.create_topic("in", partitions=parts)
        broker.create_topic("out", partitions=1)
        for i in range(n):
            broker.produce("in", prompts[i].tobytes(), partition=i % parts,
                           key=str(i).encode())
        consumer = tk.MemoryConsumer(broker, "in", group_id="b")
        producer = (
            tk.TransactionalProducer(broker, "bench-txn")
            if txn else tk.MemoryProducer(broker)
        )
        gen = StreamingGenerator(
            consumer, params, cfg, slots=4, prompt_len=prompt_len,
            max_new=max_new, commit_every=8, ticks_per_sync=1,
            output_producer=producer, output_topic="out",
            exactly_once=txn,
        )
        res = {rec.key: toks for rec, toks in gen.run(idle_timeout_ms=300)}
        assert len(res) == n
        commit = gen.metrics.commit_latency.summary()
        if txn:
            recs, _ = broker.fetch_stable(TopicPartition("out", 0), 0, 10**6)
            keys = [r.key for r in recs]
            assert sorted(keys) == sorted(set(keys)), "committed duplicates"
            assert len(keys) == n, "committed view incomplete"
        consumer.close()
        return res, commit

    ref, _ = serve_once(txn=False)  # jit warm + byte-truth
    rows = {"at_least_once": [], "exactly_once": []}
    for s in range(args.slices):
        for mode, txn in (("at_least_once", False), ("exactly_once", True)):
            res, commit = serve_once(txn)
            assert set(res) == set(ref)
            for k in ref:
                np.testing.assert_array_equal(res[k], ref[k], err_msg=str(k))
            rows[mode].append(commit)
            print(f"slice {s} {mode}: commit p50 {commit['p50_ms']:.4f} ms "
                  f"p99 {commit['p99_ms']:.4f} ms", file=sys.stderr)
    micro = {}
    for mode, commits in rows.items():
        micro[mode] = {
            "commit_p50_ms": float(np.median([c["p50_ms"] for c in commits])),
            "commit_p99_ms": float(np.median([c["p99_ms"] for c in commits])),
            "commits_per_run": commits[0]["count"],
        }
    tax = (
        micro["exactly_once"]["commit_p99_ms"]
        / micro["at_least_once"]["commit_p99_ms"]
        if micro["at_least_once"]["commit_p99_ms"] else None
    )
    print("| commit path | p50 ms (median of slices) | p99 ms |")
    print("|---|---|---|")
    for mode in ("at_least_once", "exactly_once"):
        print(f"| {mode.replace('_', '-')} | "
              f"{micro[mode]['commit_p50_ms']:.4f} | "
              f"{micro[mode]['commit_p99_ms']:.4f} |")

    # ------------------------------- (b) cross-process SIGKILL, dups == 0
    all_keys = {str(i).encode() for i in range(n)}
    ref_proc = _proc_reference(tk, cfg, params, prompts, parts, max_new)

    def killed_txn_run():
        from torchkafka_tpu.fleet import ProcessFleet

        spec = dict(MODEL_SPEC, max_seq_len=prompt_len + max_new)
        td_ctx = tempfile.TemporaryDirectory()
        td = td_ctx.name
        fleet = ProcessFleet(
            spec, topic="bench", prompt_len=prompt_len, max_new=max_new,
            workdir=td, replicas=2, partitions=parts, slots=4,
            commit_every=8, session_timeout_s=5.0,
            heartbeat_interval_s=0.25, journal_cadence=1,
            respawn=False, group="bench", exactly_once=True,
        )
        try:
            fleet.start()
            fleet.wait_ready(timeout_s=600)
            for i in range(n):
                fleet.broker.produce(
                    "bench", prompts[i].tobytes(),
                    partition=i % parts, key=str(i).encode(),
                )

            def has_uncommitted_served(inc) -> bool:
                try:
                    entries = DecodeJournal.load(inc.journal_path)
                except Exception:
                    return False
                for (topic, p, off), e in entries.items():
                    if e.finished and topic == "bench" and off >= (
                        fleet.broker.committed(
                            "bench", TopicPartition("bench", p)
                        ) or 0
                    ):
                        return True
                return False

            victim = None
            deadline = time.monotonic() + 300
            while victim is None:
                if time.monotonic() > deadline:
                    raise TimeoutError(fleet.diagnose())
                if len(fleet.results("read_committed")) >= n:
                    raise RuntimeError("storm drained pre-kill")
                for inc in fleet.live():
                    if has_uncommitted_served(inc):
                        victim = fleet.kill_replica(inc.idx)
                        break
                time.sleep(0.01)

            def covered(f):
                committed = set(f.results("read_committed"))
                if committed >= all_keys:
                    return True
                pending = set()
                for inc in f.live():
                    try:
                        entries = DecodeJournal.load(inc.journal_path)
                    except Exception:
                        continue
                    for (topic, p, off), e in entries.items():
                        if e.finished and topic == "bench":
                            pending.add(str(off * parts + p).encode())
                return committed | pending >= all_keys

            fleet.wait(covered, timeout_s=600)
            fleet.drain()
            fleet.wait(
                lambda f: all(not i.running for i in f.incarnations),
                timeout_s=300,
            )
            fleet.poll_once()
            assert fleet.fully_committed()
            committed_res = fleet.results("read_committed")
            _assert_exact(
                {k: v for k, v in committed_res.items()}, ref_proc, n
            )
            dups = sum(len(v) - 1 for v in committed_res.values())
            assert dups == 0, f"committed duplicates: {dups}"
            wm = fleet.worker_metrics()
            jserved = sum(m["served_from_journal"] for m in wm)
            restored = sum(m["tokens_restored"] for m in wm)
        finally:
            fleet.close()
            td_ctx.cleanup()
        return dups, jserved, restored

    slices = []
    for s in range(args.slices):
        dups, jserved, restored = killed_txn_run()
        slices.append({
            "slice": s, "committed_duplicates": dups,
            "journal_served": jserved, "tokens_restored": restored,
        })
        print(f"slice {s}: committed duplicates {dups} "
              f"(journal-served {jserved}, restored {restored})",
              file=sys.stderr)
    print("| cross-process SIGKILL failover | duplicates (committed view) |")
    print("|---|---|")
    print("| at-least-once (this file's procs-failover rows) | 16/run |")
    print("| exactly-once (asserted, every slice) | 0 |")

    doc = {
        "mode": "txn",
        "prompts": n,
        "max_new": max_new,
        "commit_tax": micro,
        "commit_p99_tax_ratio": tax,
        "failover_slices": slices,
        "committed_duplicates_asserted": 0,
        "exactness": (
            "both modes byte-identical to the reference; exactly-once "
            "committed view asserted one-copy-per-prompt, every slice"
        ),
    }
    print(json.dumps(doc), file=sys.stderr)
    if args.json_out:
        try:
            with open(args.json_out, encoding="utf-8") as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = {}
        existing["txn"] = doc
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(existing, f, indent=1)
        print(f"appended txn rows to {args.json_out}", file=sys.stderr)


def run_wal(tk, cfg, params, args, prompt_len, max_new) -> None:
    """The WAL tax and the recovery curve, measured paired.

    (a) Commit-latency micro: the SAME transactional serving run over
    four broker durabilities — pure in-memory (the 0.217 ms baseline
    row), WAL with ``durability=None`` (unbuffered write, no fsync),
    ``"batch"`` (fsync on commit-class appends), ``"commit"`` (fsync
    every append) — interleaved per slice, byte-exactness + exactly-once
    committed view asserted inside EVERY slice before its numbers count.
    The in-memory mode doubles as the no-regression guard: wal_dir=None
    must not move the baseline.

    (b) Recovery-time vs WAL-size: seeded logs of growing record counts
    recovered cold (``InMemoryBroker(wal_dir=...)``), recovery wall
    clock from the broker's own ``recovery_info``; recovered state
    asserted equal to the original (end offsets, committed offsets,
    committed view) every point."""
    import tempfile

    import numpy as np

    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition

    n, parts = args.prompts, 4
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)

    MODES = (
        ("memory", False, None),
        ("wal_none", True, None),
        ("wal_batch", True, "batch"),
        ("wal_commit", True, "commit"),
    )

    def serve_once(wal: bool, durability):
        with tempfile.TemporaryDirectory() as td:
            broker = tk.InMemoryBroker(
                wal_dir=td if wal else None, wal_durability=durability,
            )
            broker.create_topic("in", partitions=parts)
            broker.create_topic("out", partitions=1)
            for i in range(n):
                broker.produce("in", prompts[i].tobytes(),
                               partition=i % parts, key=str(i).encode())
            consumer = tk.MemoryConsumer(broker, "in", group_id="b")
            producer = tk.TransactionalProducer(broker, "bench-wal")
            gen = StreamingGenerator(
                consumer, params, cfg, slots=4, prompt_len=prompt_len,
                max_new=max_new, commit_every=8, ticks_per_sync=1,
                output_producer=producer, output_topic="out",
                exactly_once=True,
            )
            res = {rec.key: toks
                   for rec, toks in gen.run(idle_timeout_ms=300)}
            assert len(res) == n
            commit = gen.metrics.commit_latency.summary()
            # Exactness inside the bench: committed view exactly-once.
            recs, _ = broker.fetch_stable(TopicPartition("out", 0), 0, 10**6)
            keys = [r.key for r in recs]
            assert sorted(keys) == sorted(set(keys)), "committed duplicates"
            assert len(keys) == n, "committed view incomplete"
            wal_stats = (
                {"bytes": broker.wal.stats.bytes_written,
                 "fsyncs": broker.wal.stats.fsyncs}
                if broker.wal is not None else None
            )
            consumer.close()
            broker.close()
        return res, commit, wal_stats

    ref, _, _ = serve_once(False, None)  # jit warm + byte-truth
    rows: dict[str, list] = {m: [] for m, _w, _d in MODES}
    stats_by_mode: dict[str, dict | None] = {}
    for s in range(args.slices):
        for mode, wal, durability in MODES:
            res, commit, wal_stats = serve_once(wal, durability)
            assert set(res) == set(ref)
            for k in ref:
                np.testing.assert_array_equal(res[k], ref[k], err_msg=str(k))
            rows[mode].append(commit)
            stats_by_mode[mode] = wal_stats
            print(f"slice {s} {mode}: commit p50 {commit['p50_ms']:.4f} ms "
                  f"p99 {commit['p99_ms']:.4f} ms", file=sys.stderr)
    micro = {}
    for mode, commits in rows.items():
        micro[mode] = {
            "commit_p50_ms": float(np.median([c["p50_ms"] for c in commits])),
            "commit_p99_ms": float(np.median([c["p99_ms"] for c in commits])),
            "commits_per_run": commits[0]["count"],
            "wal": stats_by_mode[mode],
        }
    base = micro["memory"]["commit_p99_ms"]
    print("| durability | commit p50 ms | p99 ms | vs in-memory p99 |")
    print("|---|---|---|---|")
    for mode, _w, _d in MODES:
        m = micro[mode]
        ratio = m["commit_p99_ms"] / base if base else float("nan")
        print(f"| {mode} | {m['commit_p50_ms']:.4f} | "
              f"{m['commit_p99_ms']:.4f} | {ratio:.2f}x |")

    # ------------------------------------- (b) recovery-time vs WAL size
    curve = []
    for n_records in (256, 1024, 4096):
        with tempfile.TemporaryDirectory() as td:
            b = tk.InMemoryBroker(wal_dir=td, wal_durability=None)
            b.create_topic("t", partitions=parts)
            payload_rng = np.random.default_rng(n_records)
            for i in range(n_records):
                b.produce(
                    "t",
                    payload_rng.integers(0, 256, 64, np.uint8).tobytes(),
                    partition=i % parts, key=str(i).encode(),
                )
            gen_id = b.join("g", "m0", frozenset({"t"}))
            b.commit("g", {TopicPartition("t", p): n_records // parts
                           for p in range(parts)},
                     member_id="m0", generation=gen_id)
            wal_bytes = b.wal.total_bytes()
            b.close()
            t0 = time.perf_counter()
            r = tk.InMemoryBroker(wal_dir=td)
            cold_ms = (time.perf_counter() - t0) * 1e3
            # Exactness inside the bench: recovered state == original.
            for p in range(parts):
                tp = TopicPartition("t", p)
                assert r.end_offset(tp) == b.end_offset(tp)
                assert [x.value for x in r.fetch(tp, 0, 10**6)] \
                    == [x.value for x in b.fetch(tp, 0, 10**6)]
                assert r.committed("g", tp) == n_records // parts
            row = {
                "records": n_records,
                "wal_bytes": wal_bytes,
                "recovery_ms": r.recovery_info["recovery_ms"],
                "construction_ms": round(cold_ms, 3),
            }
            r.close()
        curve.append(row)
        print(f"recovery: {n_records} records, {wal_bytes} B WAL -> "
              f"{row['recovery_ms']} ms replay", file=sys.stderr)
    print("| WAL records | bytes | recovery ms |")
    print("|---|---|---|")
    for row in curve:
        print(f"| {row['records']} | {row['wal_bytes']:,} | "
              f"{row['recovery_ms']:.2f} |")

    doc = {
        "mode": "wal",
        "prompts": n,
        "max_new": max_new,
        "commit_tax": micro,
        "recovery_curve": curve,
        "exactness": (
            "all four durabilities byte-identical to the reference with "
            "an exactly-once committed view, every slice; recovery curve "
            "points asserted state-equal to the pre-death broker"
        ),
    }
    print(json.dumps(doc), file=sys.stderr)
    if args.json_out:
        try:
            with open(args.json_out, encoding="utf-8") as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = {}
        existing["wal"] = doc
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(existing, f, indent=1)
        print(f"appended wal rows to {args.json_out}", file=sys.stderr)


def run_quorum(tk, cfg, params, args, prompt_len, max_new) -> None:
    """The quorum-replication tax and the failover-to-goodput time.

    (a) Commit-latency micro: the SAME transactional serving run, paired
    and interleaved per slice across four broker shapes — in-memory
    at-least-once (the 0.075 ms floor's shape), in-memory exactly-once
    (the 0.217 ms floor), and a 3-replica ``BrokerCell`` leader at
    per-replica durability None and "batch" (``wal_durability="quorum"``:
    every acked frame is locally logged AND shipped over real sockets to
    2 followers, majority before the ack). The quorum tax is quoted
    against the SAME-WINDOW exactly-once row (pairing discipline; the
    recorded floors are context, not the denominator). Byte-exactness +
    an exactly-once committed view asserted inside every slice.

    (b) Failover-to-goodput: a cell serves the full transactional storm,
    then ``kill_leader()`` — timed from the kill instant to the first
    COMMITTED transaction a wire client lands on the same advertised
    port. Zero committed-record loss asserted: end offsets AND the
    committed output view on the promoted leader must equal the
    pre-kill snapshot byte-for-byte, still one-copy-per-prompt. The
    drill excludes silent-death DETECTION (bounded by
    ``lease_timeout_s``; scenario 23 measures the supervised fleet path
    end to end) — reported next to scenario 19's 2.5 s single-broker
    restart outage, which every worker rides."""
    import tempfile

    import numpy as np

    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.source.records import TopicPartition
    from torchkafka_tpu.source.replication import ReplicationConfig

    n, parts, replicas = args.prompts, 4, 3
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (n, prompt_len),
                           dtype=np.int32)
    # The floors the --txn table recorded (FAILOVER_BENCH.json -> txn),
    # quoted as context; the paired denominator is this window's own row.
    FLOOR_ALO_P99_MS, FLOOR_TXN_P99_MS = 0.075, 0.217
    RIDE_THROUGH_BASELINE_MS = 2500.0  # scenario 19's single-broker outage

    def fill(broker):
        broker.create_topic("in", partitions=parts)
        broker.create_topic("out", partitions=1)
        broker.create_topic("probe", partitions=1)  # goodput probe lane
        for i in range(n):
            broker.produce("in", prompts[i].tobytes(), partition=i % parts,
                           key=str(i).encode())

    def serve(broker, txn):
        consumer = tk.MemoryConsumer(broker, "in", group_id="b")
        producer = (
            tk.TransactionalProducer(broker, "bench-q")
            if txn else tk.MemoryProducer(broker)
        )
        gen = StreamingGenerator(
            consumer, params, cfg, slots=4, prompt_len=prompt_len,
            max_new=max_new, commit_every=8, ticks_per_sync=1,
            output_producer=producer, output_topic="out",
            exactly_once=txn,
        )
        res = {rec.key: toks for rec, toks in gen.run(idle_timeout_ms=300)}
        assert len(res) == n
        commit = gen.metrics.commit_latency.summary()
        consumer.close()
        return res, commit

    def committed_view(broker):
        recs, _ = broker.fetch_stable(TopicPartition("out", 0), 0, 10**6)
        keys = [r.key for r in recs]
        assert sorted(keys) == sorted(set(keys)), "committed duplicates"
        assert len(keys) == n, "committed view incomplete"
        return [(r.key, r.value) for r in recs]

    MODES = ("at_least_once", "exactly_once", "quorum_none", "quorum_batch")

    def serve_mode(mode):
        if mode.startswith("quorum"):
            durability = None if mode.endswith("none") else "batch"
            with tempfile.TemporaryDirectory() as td:
                cell = tk.BrokerCell(
                    os.path.join(td, "cell"),
                    config=ReplicationConfig(
                        replicas=replicas, durability=durability
                    ),
                )
                try:
                    fill(cell.broker)
                    res, commit = serve(cell.broker, txn=True)
                    committed_view(cell.broker)
                    s = cell.broker.metrics.summary()
                    repl = {
                        "frames_shipped": s["repl_frames_shipped"],
                        "quorum_commits": s["repl_quorum_commits"],
                    }
                finally:
                    cell.close()
            return res, commit, repl
        broker = tk.InMemoryBroker()
        fill(broker)
        res, commit = serve(broker, txn=(mode == "exactly_once"))
        if mode == "exactly_once":
            committed_view(broker)
        return res, commit, None

    # ---------------------------------------------- (a) commit-tax micro
    ref, _, _ = serve_mode("at_least_once")  # jit warm + byte-truth
    rows = {m: [] for m in MODES}
    repl_stats: dict | None = None
    for s in range(args.slices):
        for mode in MODES:
            res, commit, repl = serve_mode(mode)
            assert set(res) == set(ref)
            for k in ref:
                np.testing.assert_array_equal(res[k], ref[k], err_msg=str(k))
            rows[mode].append(commit)
            if repl is not None:
                repl_stats = repl
            print(f"slice {s} {mode}: commit p50 {commit['p50_ms']:.4f} ms "
                  f"p99 {commit['p99_ms']:.4f} ms", file=sys.stderr)
    micro = {}
    for mode, commits in rows.items():
        micro[mode] = {
            "commit_p50_ms": float(np.median([c["p50_ms"] for c in commits])),
            "commit_p99_ms": float(np.median([c["p99_ms"] for c in commits])),
            "commits_per_run": commits[0]["count"],
        }
    txn_base = micro["exactly_once"]["commit_p99_ms"]
    print("| commit path (3-replica cell for quorum rows) | p50 ms | "
          "p99 ms | vs same-window exactly-once p99 |")
    print("|---|---|---|---|")
    for mode in MODES:
        m = micro[mode]
        ratio = m["commit_p99_ms"] / txn_base if txn_base else float("nan")
        print(f"| {mode.replace('_', '-')} | {m['commit_p50_ms']:.4f} | "
              f"{m['commit_p99_ms']:.4f} | {ratio:.2f}x |")

    # ------------------------------------------ (b) failover-to-goodput
    def failover_once():
        with tempfile.TemporaryDirectory() as td:
            cell = tk.BrokerCell(
                os.path.join(td, "cell"),
                config=ReplicationConfig(replicas=replicas,
                                         durability="batch"),
            )
            try:
                fill(cell.broker)
                res, _ = serve(cell.broker, txn=True)
                assert set(res) == set(ref)
                for k in ref:
                    np.testing.assert_array_equal(res[k], ref[k],
                                                  err_msg=str(k))
                before_view = committed_view(cell.broker)
                before_ends = {
                    p: cell.broker.end_offset(TopicPartition("in", p))
                    for p in range(parts)
                }
                port = cell.port
                t0 = time.perf_counter()
                fx = cell.kill_leader()
                # Goodput = a COMMITTED transaction through the wire on
                # the same advertised port, not merely a reconnect.
                deadline = time.monotonic() + 60
                while True:
                    try:
                        with cell.client(timeout_s=5) as cli:
                            pid, ep = cli.init_producer_id("probe")
                            cli.begin_txn(pid, ep)
                            cli.txn_produce(pid, ep, "probe", b"alive",
                                            partition=0)
                            cli.commit_txn(pid, ep)
                        break
                    except (tk.BrokerUnavailableError, ConnectionError,
                            OSError):
                        if time.monotonic() > deadline:
                            raise
                        time.sleep(0.005)
                goodput_ms = (time.perf_counter() - t0) * 1e3
                assert cell.port == port  # same-port takeover
                # Zero committed-record loss, still exactly-once.
                after_ends = {
                    p: cell.broker.end_offset(TopicPartition("in", p))
                    for p in range(parts)
                }
                assert after_ends == before_ends, "input records lost"
                assert committed_view(cell.broker) == before_view, (
                    "committed output view changed across failover"
                )
                row = {
                    "goodput_ms": round(goodput_ms, 3),
                    "election_ms": round(fx["election_ms"], 3),
                    "failover_ms": round(fx["failover_ms"], 3),
                    "recovery_ms": fx["recovery"]["recovery_ms"],
                    "replayed_events": fx["recovery"]["replayed_events"],
                    "winner_idx": fx["winner_idx"],
                    "epoch": fx["epoch"],
                }
            finally:
                cell.close()
        return row

    fail_rows = []
    for s in range(args.slices):
        row = failover_once()
        fail_rows.append(row)
        print(f"slice {s}: failover-to-goodput {row['goodput_ms']:.1f} ms "
              f"(election {row['election_ms']:.1f}, recovery "
              f"{row['recovery_ms']} ms, {row['replayed_events']} events)",
              file=sys.stderr)
    med_goodput = float(np.median([r["goodput_ms"] for r in fail_rows]))
    print("| failover | to first committed txn (median) | vs 2.5 s "
          "ride-through |")
    print("|---|---|---|")
    print(f"| single broker restart (scenario 19, ridden by workers) | "
          f"{RIDE_THROUGH_BASELINE_MS:,.0f} ms | 1.00x |")
    print(f"| quorum cell kill_leader -> promoted leader, same port | "
          f"{med_goodput:,.1f} ms | "
          f"{med_goodput / RIDE_THROUGH_BASELINE_MS:.4f}x |")

    doc = {
        "mode": "quorum",
        "prompts": n,
        "max_new": max_new,
        "replicas": replicas,
        "commit_tax": micro,
        "quorum_p99_vs_same_window_exactly_once": {
            m: micro[m]["commit_p99_ms"] / txn_base if txn_base else None
            for m in ("quorum_none", "quorum_batch")
        },
        "recorded_floors_ms": {
            "at_least_once_p99": FLOOR_ALO_P99_MS,
            "exactly_once_p99": FLOOR_TXN_P99_MS,
        },
        "repl": repl_stats,
        "failover": {
            "slices": fail_rows,
            "median_goodput_ms": med_goodput,
            "ride_through_baseline_ms": RIDE_THROUGH_BASELINE_MS,
            "vs_baseline": med_goodput / RIDE_THROUGH_BASELINE_MS,
            "note": (
                "drill excludes silent-death detection (bounded by "
                "lease_timeout_s); scenario 23 measures the supervised "
                "fleet path end to end"
            ),
        },
        "exactness": (
            "every slice byte-identical to the reference with an "
            "exactly-once committed view; failover slices additionally "
            "assert end offsets and the committed output view unchanged "
            "across promotion"
        ),
    }
    print(json.dumps(doc), file=sys.stderr)
    if args.json_out:
        try:
            with open(args.json_out, encoding="utf-8") as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = {}
        existing["quorum"] = doc
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(existing, f, indent=1)
        print(f"appended quorum rows to {args.json_out}", file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", default="1,2,4")
    ap.add_argument("--prompts", type=int, default=96)
    ap.add_argument("--slices", type=int, default=3)
    ap.add_argument("--failover", action="store_true",
                    help="paired cold-vs-warm failover differential")
    ap.add_argument("--cadence", type=int, default=4,
                    help="--failover: journal token cadence")
    ap.add_argument("--procs", default=None,
                    help="real-process fleet curve, e.g. 1,2,4")
    ap.add_argument("--procs-failover", action="store_true",
                    help="cross-process SIGKILL cold-vs-warm differential")
    ap.add_argument("--txn", action="store_true",
                    help="exactly-once transaction tax: paired commit-"
                    "latency micro (at-least-once vs transactional) + "
                    "cross-process SIGKILL failover with committed-view "
                    "duplicates asserted == 0")
    ap.add_argument("--quorum", action="store_true",
                    help="replicated-cell differential: quorum commit-"
                    "latency tax (3-replica BrokerCell vs the in-memory "
                    "at-least-once/exactly-once floors, paired) + "
                    "kill_leader failover-to-goodput vs the 2.5 s "
                    "single-broker ride-through, zero-loss asserted")
    ap.add_argument("--wal", action="store_true",
                    help="durable-broker WAL tax: paired transactional "
                    "commit-latency micro across durability "
                    "memory/None/batch/commit + recovery-time vs "
                    "WAL-size curve, exactness asserted every slice")
    ap.add_argument("--json-out", default=None,
                    help="--procs-failover/--txn/--wal/--quorum: "
                    "FAILOVER_BENCH.json to append")
    args = ap.parse_args()
    counts = [int(x) for x in args.replicas.split(",")]

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchkafka_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(8)
    import torchkafka_tpu as tk
    from torchkafka_tpu.models.transformer import (
        TransformerConfig, init_params,
    )

    prompt_len, vocab = 16, 512
    cfg = TransformerConfig(
        vocab_size=vocab, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=prompt_len + 16, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)

    if args.quorum:
        run_quorum(tk, cfg, params, args, prompt_len, max_new=16)
        return
    if args.wal:
        run_wal(tk, cfg, params, args, prompt_len, max_new=16)
        return
    if args.txn:
        run_txn(tk, cfg, params, args, prompt_len, max_new=16)
        return
    if args.procs:
        run_procs(tk, cfg, params, args, prompt_len, max_new=16)
        return
    if args.procs_failover:
        run_procs_failover(tk, cfg, params, args, prompt_len, max_new=16)
        return
    if args.failover:
        run_failover(tk, cfg, params, args, vocab, prompt_len, max_new=16)
        return

    # Warm the jit cache once so slice 0 of the first config doesn't pay
    # compile while the others hit the cache (pairing would be broken).
    _, fleet = build(tk, cfg, params, 8, 1, vocab, prompt_len)
    fleet.warmup()
    fleet.serve_all(max_records=8)
    fleet.close()

    per: dict[int, list[float]] = {r: [] for r in counts}
    for s in range(args.slices):
        for r in counts:  # interleaved: every config samples every window
            broker, fleet = build(
                tk, cfg, params, args.prompts, r, vocab, prompt_len
            )
            t0 = time.perf_counter()
            done = len(fleet.serve_all(max_records=args.prompts))
            dt = time.perf_counter() - t0
            fleet.close()
            assert done == args.prompts, (r, done)
            per[r].append(args.prompts / dt)
            print(f"slice {s} replicas {r}: {per[r][-1]:,.1f} prompts/s",
                  file=sys.stderr)

    base = [per[counts[0]][i] for i in range(args.slices)]
    print("| replicas | prompts/s (median) | ratio vs "
          f"{counts[0]} (median of paired) |")
    print("|---|---|---|")
    out = {}
    for r in counts:
        rates = per[r]
        ratios = [rates[i] / base[i] for i in range(args.slices)]
        med = float(np.median(rates))
        med_ratio = float(np.median(ratios))
        out[r] = {"prompts_per_s": med, "ratio": med_ratio,
                  "slices": [round(x, 1) for x in rates]}
        print(f"| {r} | {med:,.1f} | {med_ratio:.2f}× |")
    print(json.dumps(out), file=sys.stderr)


if __name__ == "__main__":
    main()

"""Speculative-decoding cost model, measured on the chip.

Random-init weights cannot exhibit a real workload's draft/target
agreement (an independent 45M draft agrees with a 1B target at chance
level), so this bench does NOT claim an end-to-end speedup from a toy
acceptance rate. Instead it measures every term the speedup formula
needs and reports the implied curve:

  speedup(alpha) = E[accepted + 1] · t_target / t_round
  E[accepted + 1] = (1 - alpha^(k+1)) / (1 - alpha)   (greedy, i.i.d.)

The key identity making this honest: a round's COST is
acceptance-independent (every round runs k+1 draft steps and one
verify, whatever gets accepted), so t_round is DIRECTLY MEASURABLE at
the chance-level acceptance random weights give — each round then emits
exactly one token, so seconds/token == seconds/round — and only
E[accepted + 1] (pure arithmetic in alpha) changes with the workload.

- t_target: plain greedy decode seconds/token on the target (slope over
  two max_new lengths — the constant prefill/dispatch cost cancels).
- t_draft → c = t_draft / t_target: same slope on the draft model.
- t_round: the 45M-draft run's seconds/token at chance acceptance
  (= seconds/round, see above); v = (t_round - (k+1)·t_draft)/t_target
  is the implied FULL verify dispatch in target ticks (~1 + multi-query
  overhead), reported as a diagnostic.
- A PERFECT-draft run (draft := target params) regression-checks the
  accept/bonus path at full scale (acceptance ~ 1.0).
- alpha_real: the measured 45M→1B acceptance on random weights —
  reported to show it is chance-level, not used to claim a speedup.

Usage: python benchmarks/bench_spec.py [--batch 8] [--k 4]
       [--short 32] [--long 96]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from torchkafka_tpu.models.generate import generate
from torchkafka_tpu.models.spec_decode import speculative_generate
from torchkafka_tpu.models.zoo import random_serving_params, zoo_config
from torchkafka_tpu.utils.timing import two_point_slope

PROMPT = 32


def _time_tokens(fn, n_short: int, n_long: int, repeats: int = 3):
    """Seconds per TICK (one token across the whole batch — no per-row
    division) via slope over two max_new lengths. fn(max_new) must run
    the whole generation and block. Returns (s_per_tick, ok)."""
    fn(n_short)  # compile+warm both lengths
    fn(n_long)
    shorts, longs = [], []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn(n_short)
        shorts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        fn(n_long)
        longs.append(time.perf_counter() - t0)
    per, _ovh, ok = two_point_slope(
        float(np.median(shorts)), float(np.median(longs)), n_short, n_long
    )
    return per, ok


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--short", type=int, default=32)
    ap.add_argument("--long", type=int, default=96)
    args = ap.parse_args()
    B, k = args.batch, args.k

    tcfg = zoo_config("1b", max_seq_len=PROMPT + args.long + 2 * k + 8)
    dcfg = zoo_config("45m", max_seq_len=PROMPT + args.long + 2 * k + 8)
    t0 = time.perf_counter()
    tparams = random_serving_params(jax.random.key(0), tcfg, quantized=False)
    dparams = random_serving_params(jax.random.key(1), dcfg, quantized=False)
    jax.block_until_ready((tparams, dparams))
    print(f"params in {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, tcfg.vocab_size, (B, PROMPT)), jnp.int32
    )

    plain_t = {}
    plain_ok = {}
    for name, cfg, params in (("target", tcfg, tparams), ("draft", dcfg, dparams)):
        calls = {
            n: jax.jit(lambda p, t, n=n, cfg=cfg: generate(p, cfg, t, n))
            for n in (args.short, args.long)
        }
        per, ok = _time_tokens(
            lambda n: np.asarray(calls[n](params, prompt)),
            args.short, args.long,
        )
        plain_t[name] = per
        plain_ok[name] = ok
        print(f"plain {name}: {per * 1e3:.3f} ms/tick ok={ok}", file=sys.stderr)

    # Jitted callables built ONCE per (draft config, max_new): a fresh
    # jax.jit(lambda) per call would re-trace and re-compile the 1B
    # while_loop program on every timed repeat, burying device time
    # under seconds of compile.
    _spec_jits: dict = {}

    def spec_run(dp, dc, n):
        key = (id(dc), n)
        if key not in _spec_jits:
            _spec_jits[key] = jax.jit(
                lambda tp, dpp, t, n=n, dc=dc: speculative_generate(
                    tp, tcfg, dpp, dc, t, n, k=k
                )
            )
        out, stats = _spec_jits[key](tparams, dp, prompt)
        return np.asarray(out), stats

    stats_box = {}

    def spec_timed(dp, dc, label):
        def run(n):
            out, stats = spec_run(dp, dc, n)
            stats_box[(label, n)] = jax.device_get(stats)
            return out
        per, ok = _time_tokens(run, args.short, args.long)
        st = stats_box[(label, args.long)]
        alpha = float(st.accepted) / max(float(st.proposed), 1.0)
        print(
            f"spec {label}: {per * 1e3:.3f} ms/tick ok={ok} "
            f"acceptance={alpha:.3f} rounds={int(st.rounds)}",
            file=sys.stderr,
        )
        return per, alpha, ok

    # Exactness at scale (bf16: argmax near-ties can legally flip across
    # program shapes, so compare with tolerance on the agreement rate).
    plain_out = np.asarray(
        jax.jit(lambda p, t: generate(p, tcfg, t, args.short))(tparams, prompt)
    )
    spec_out, _ = spec_run(dparams, dcfg, args.short)
    agree = float((plain_out == spec_out).mean())

    per_real, alpha_real, ok_real = spec_timed(dparams, dcfg, "45m-draft")
    per_perfect, alpha_perfect, ok_perfect = spec_timed(
        tparams, tcfg, "perfect-draft"
    )

    t_t, t_d = plain_t["target"], plain_t["draft"]
    # Degenerate slopes must not publish numbers (utils/timing.py's
    # contract): flag and null the derived fields instead.
    slopes_ok = plain_ok["target"] and plain_ok["draft"] and ok_real
    c = t_d / t_t
    # At chance acceptance each round emits one token, so the measured
    # seconds/token IS the acceptance-independent round cost.
    t_round = per_real
    v = (t_round - (k + 1) * t_d) / t_t  # implied full verify, diagnostic
    curve = {}
    if slopes_ok:
        for alpha in (0.5, 0.7, 0.8, 0.9, 1.0):
            e_tok = (
                (1 - alpha ** (k + 1)) / (1 - alpha) if alpha < 1 else k + 1
            )
            curve[str(alpha)] = round(e_tok * t_t / t_round, 3)
    def _num(x, nd=3):
        return round(x, nd) if slopes_ok else None

    print(json.dumps({
        "metric": "speculative_decode_cost_model",
        "slopes_ok": slopes_ok,
        "slope_flags": {
            "target": plain_ok["target"], "draft": plain_ok["draft"],
            "spec_45m": ok_real, "spec_perfect": ok_perfect,
        },
        "batch": B, "k": k, "prompt_len": PROMPT,
        "target_ms_per_tick": _num(t_t * 1e3),
        "draft_ms_per_tick": _num(t_d * 1e3),
        "cost_ratio_c": _num(c, 4),
        "round_ms_45m_draft": _num(t_round * 1e3),
        "verify_full_over_target_v_implied": _num(v),
        "spec_ms_per_tick_45m_draft": (
            round(per_real * 1e3, 3) if ok_real else None
        ),
        "acceptance_45m_draft_random_weights": round(alpha_real, 4),
        "acceptance_perfect_draft": round(alpha_perfect, 4),
        "spec_ms_per_tick_perfect_draft": _num(per_perfect * 1e3),
        "token_agreement_vs_plain_greedy": round(agree, 4),
        "implied_speedup_vs_alpha": curve,
        "note": (
            "random weights give chance-level draft/target agreement; "
            "the curve is E[accepted+1] x t_target / t_round with both "
            "times measured (round cost is acceptance-independent), "
            "not a claimed end-to-end speedup"
        ),
    }))


if __name__ == "__main__":
    main()

"""Speculative-decoding cost model, measured on the chip.

Random-init weights cannot exhibit a real workload's draft/target
agreement (an independent 45M draft agrees with a 1B target at chance
level), so this bench does NOT claim an end-to-end speedup from a toy
acceptance rate. Instead it measures every term the speedup formula
needs and reports the implied curve:

  speedup(alpha) = E[accepted + 1] · t_target / t_round
  E[accepted + 1] = (1 - alpha^(k+1)) / (1 - alpha)   (greedy, i.i.d.)

The key identity making this honest: a round's COST is
acceptance-independent (every round runs k+1 draft steps and one
verify, whatever gets accepted), so t_round is DIRECTLY MEASURABLE at
the chance-level acceptance random weights give — each round then emits
exactly one token, so seconds/token == seconds/round — and only
E[accepted + 1] (pure arithmetic in alpha) changes with the workload.

- t_target: plain greedy decode seconds/token on the target (slope over
  two max_new lengths — the constant prefill/dispatch cost cancels).
- t_draft → c = t_draft / t_target: same slope on the draft model.
- t_round: the 45M-draft run's seconds/token at chance acceptance
  (= seconds/round, see above); v = (t_round - (k+1)·t_draft)/t_target
  is the implied FULL verify dispatch in target ticks (~1 + multi-query
  overhead), reported as a diagnostic.
- A PERFECT-draft run (draft := target params) regression-checks the
  accept/bonus path at full scale (acceptance ~ 1.0).
- alpha_real: the measured 45M→1B acceptance on random weights —
  reported to show it is chance-level, not used to claim a speedup.

Usage: python benchmarks/bench_spec.py [--batch 8] [--k 4]
       [--short 32] [--long 96]

SERVING MODE (--serve): the end-to-end number the cost model only
implies. Trains the 45M flagship for --train-steps on a learnable
streaming task (per-sequence repeated patterns — an induction workload —
produced into an InMemoryBroker and consumed through KafkaStream +
make_train_step, the same machinery as harness scenario 3), then:

1. measures α of the layer-truncated self-draft (LayerSkip-style) on the
   TRAINED checkpoint at several draft depths via speculative_generate's
   counters — a real measured acceptance, not a hypothetical curve point;
2. runs PAIRED serving slices over the SAME prompt topic —
   SpecStreamingGenerator vs plain StreamingGenerator, alternating so
   both sides sample the same box conditions — and reports the REALIZED
   end-to-end tok/s ratio plus the serving-measured α (the numbers
   PERF.md's speculative-serving row publishes).

Usage: python benchmarks/bench_spec.py --serve [--train-steps 300]
       [--draft-layers 2] [--k 4] [--slots 8] [--serve-prompts 48]
       [--pairs 2]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from torchkafka_tpu.models.generate import generate
from torchkafka_tpu.models.spec_decode import speculative_generate
from torchkafka_tpu.models.zoo import random_serving_params, zoo_config
from torchkafka_tpu.utils.timing import two_point_slope

PROMPT = 32


def _time_tokens(fn, n_short: int, n_long: int, repeats: int = 3):
    """Seconds per TICK (one token across the whole batch — no per-row
    division) via slope over two max_new lengths. fn(max_new) must run
    the whole generation and block. Returns (s_per_tick, ok)."""
    fn(n_short)  # compile+warm both lengths
    fn(n_long)
    shorts, longs = [], []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn(n_short)
        shorts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        fn(n_long)
        longs.append(time.perf_counter() - t0)
    per, _ovh, ok = two_point_slope(
        float(np.median(shorts)), float(np.median(longs)), n_short, n_long
    )
    return per, ok


def _pattern_rows(rng, n, seq, vocab, period_lo=4, period_hi=8):
    """Per-sequence repeated patterns: sample a period-p token pattern and
    tile it. After one sight of the pattern every later position is
    deterministic — an induction workload a decoder learns in a few
    hundred steps, which is exactly what gives the layer-truncated draft
    a real (measurable, > chance) acceptance on the trained checkpoint.
    Pattern tokens come from a concentrated band of the vocab (like real
    text's skewed token distribution) so a few hundred CPU steps suffice;
    the lm_head still scores all ``vocab`` classes — chance acceptance
    stays ~1/vocab."""
    band = min(512, vocab)
    for _ in range(n):
        p = int(rng.integers(period_lo, period_hi + 1))
        pat = rng.integers(0, band, p)
        yield np.tile(pat, seq // p + 1)[:seq].astype(np.int32)


def _train_flagship(cfg, steps: int, batch: int, seq: int, lr: float):
    """Train the 45M flagship on the streaming induction task through the
    repo's own machinery (InMemoryBroker → KafkaStream → make_train_step,
    scenario 3's loop shape). Returns (params, losses)."""
    import optax

    import torchkafka_tpu as tk
    from torchkafka_tpu.models.transformer import make_train_step
    from torchkafka_tpu.parallel import make_mesh

    mesh = make_mesh({"data": len(jax.devices())})
    init_fn, step_fn = make_train_step(cfg, mesh, optax.adamw(lr))
    params, opt = init_fn(jax.random.key(0))
    broker = tk.InMemoryBroker()
    broker.create_topic("spec-train", partitions=4)
    rng = np.random.default_rng(0)
    broker.produce_many(
        "spec-train",
        (r.tobytes() for r in
         _pattern_rows(rng, steps * batch, seq, cfg.vocab_size)),
    )
    consumer = tk.MemoryConsumer(
        broker, "spec-train", group_id="spec-train",
        assignment=tk.partitions_for_process("spec-train", 4, 0, 1),
    )
    losses = []
    t0 = time.perf_counter()
    with tk.KafkaStream(
        consumer, tk.fixed_width(seq, np.int32), batch_size=batch,
        mesh=mesh, idle_timeout_ms=2000, owns_consumer=True,
    ) as stream:
        for b, token in stream:
            mask = jnp.broadcast_to(
                jnp.asarray(b.valid_mask().astype(np.int32))[:, None],
                (batch, seq),
            )
            params, opt, loss = step_fn(params, opt, b.data, mask)
            token.commit_async(wait_for=loss)
            losses.append(loss)
            if len(losses) % 25 == 0:
                print(
                    f"step {len(losses)}/{steps} loss {float(loss):.4f} "
                    f"({time.perf_counter() - t0:.0f}s)",
                    file=sys.stderr, flush=True,
                )
            if len(losses) >= steps:
                break
    return params, [float(x) for x in losses]


def serve_main(args) -> None:
    """--serve: measured α on a trained checkpoint + paired spec-vs-plain
    serving over the same prompt window."""
    import torchkafka_tpu as tk
    from torchkafka_tpu.models.spec_decode import truncated_draft
    from torchkafka_tpu.models.transformer import TransformerConfig
    from torchkafka_tpu.serve import StreamingGenerator
    from torchkafka_tpu.serve_spec import SpecStreamingGenerator

    k = args.k
    prompt_len, max_new = args.serve_prompt_len, args.serve_max_new
    seq = args.train_seq
    cfg = TransformerConfig(max_seq_len=max(seq, prompt_len + max_new))
    t0 = time.perf_counter()
    params, losses = _train_flagship(
        cfg, args.train_steps, args.train_batch, seq, args.lr
    )
    train_s = time.perf_counter() - t0
    print(
        f"trained {args.train_steps} steps in {train_s:.0f}s: loss "
        f"{losses[0]:.3f} -> {losses[-1]:.3f}",
        file=sys.stderr, flush=True,
    )

    # -------- measured α of the layer-truncated draft, per draft depth.
    rng = np.random.default_rng(123)  # held-out prompts, same distribution
    prompts_np = np.stack(
        [r[:prompt_len] for r in
         _pattern_rows(rng, args.serve_prompts, prompt_len, cfg.vocab_size)]
    )
    alpha_probe = jnp.asarray(prompts_np[: args.batch], jnp.int32)
    alpha_by_depth = {}
    for nl in range(1, cfg.n_layers):
        dparams, dcfg = truncated_draft(params, cfg, nl)
        _out, stats = jax.jit(
            lambda tp, dp, t, dc=dcfg: speculative_generate(
                tp, cfg, dp, dc, t, max_new, k=k
            )
        )(params, dparams, alpha_probe)
        st = jax.device_get(stats)
        alpha_by_depth[nl] = round(
            float(st.accepted) / max(float(st.proposed), 1.0), 4
        )
    print(f"alpha by draft depth: {alpha_by_depth}", file=sys.stderr,
          flush=True)

    # -------- paired serving: alternating spec/plain slices over the SAME
    # topic (fresh groups re-read from offset 0), bench.py's pairing
    # discipline — the per-pair ratio is the stable signal on a drifting
    # host.
    broker = tk.InMemoryBroker()
    broker.create_topic("spec-serve", partitions=2)
    n = args.serve_prompts
    for i in range(n):
        broker.produce("spec-serve", prompts_np[i].tobytes(), partition=i % 2)

    def serve_slice(spec_mode: bool, group: str):
        consumer = tk.MemoryConsumer(broker, "spec-serve", group_id=group)
        if spec_mode:
            server = SpecStreamingGenerator(
                consumer, params, cfg, slots=args.slots,
                prompt_len=prompt_len, max_new=max_new,
                commit_every=args.slots, k=k,
                draft_layers=args.draft_layers,
                # Full-accept block length; low-α streams take more blocks.
                ticks_per_sync=max(1, -(-(max_new - 1) // (k + 1))),
            )
        else:
            server = StreamingGenerator(
                consumer, params, cfg, slots=args.slots,
                prompt_len=prompt_len, max_new=max_new,
                commit_every=args.slots,
                # One dispatch per generation — the plain side's best case.
                ticks_per_sync=max(1, max_new - 1),
            )
        server.warmup()
        toks = 0
        t0 = time.perf_counter()
        for _rec, out in server.run(max_records=n):
            toks += int(out.shape[0])
        elapsed = time.perf_counter() - t0
        stats = server.spec_stats() if spec_mode else None
        consumer.close()
        return toks / elapsed, stats

    ratios, spec_rates, plain_rates, alphas = [], [], [], []
    for i in range(args.pairs):
        s_rate, st = serve_slice(True, f"pair-spec-{i}")
        p_rate, _ = serve_slice(False, f"pair-plain-{i}")
        spec_rates.append(s_rate)
        plain_rates.append(p_rate)
        ratios.append(s_rate / p_rate)
        alphas.append(st["acceptance"])
        print(
            f"pair {i}: spec {s_rate:.1f} tok/s (alpha "
            f"{st['acceptance']}) vs plain {p_rate:.1f} tok/s -> "
            f"{ratios[-1]:.3f}x",
            file=sys.stderr, flush=True,
        )

    print(json.dumps({
        "metric": "speculative_serving_paired",
        "backend": jax.default_backend(),
        "model": "45m-flagship",
        "train_steps": args.train_steps,
        "train_batch": args.train_batch,
        "train_seq": seq,
        "train_loss_first": round(losses[0], 4),
        "train_loss_last": round(losses[-1], 4),
        "train_seconds": round(train_s, 1),
        "k": k,
        "draft_layers": args.draft_layers,
        "prompt_len": prompt_len,
        "max_new": max_new,
        "slots": args.slots,
        "serve_prompts": n,
        "pairs": args.pairs,
        "alpha_by_draft_depth_generate": alpha_by_depth,
        "alpha_serving_measured": round(float(np.median(alphas)), 4),
        "spec_tok_s": round(float(np.median(spec_rates)), 1),
        "plain_tok_s": round(float(np.median(plain_rates)), 1),
        "realized_ratio": round(float(np.median(ratios)), 3),
        "pair_ratios": [round(r, 3) for r in ratios],
        "note": (
            "alpha measured on the TRAINED checkpoint (induction "
            "workload); realized_ratio is the paired same-window "
            "end-to-end tok/s of SpecStreamingGenerator over plain "
            "StreamingGenerator — an actual measurement, not the "
            "i.i.d.-formula implication"
        ),
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--short", type=int, default=32)
    ap.add_argument("--long", type=int, default=96)
    ap.add_argument("--serve", action="store_true",
                    help="paired serving mode: train the 45M flagship, "
                    "measure the layer-skip draft's alpha on the trained "
                    "checkpoint, and report the realized spec-vs-plain "
                    "serving tok/s ratio")
    ap.add_argument("--train-steps", type=int, default=300)
    ap.add_argument("--train-batch", type=int, default=8)
    ap.add_argument("--train-seq", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--draft-layers", type=int, default=2)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--serve-prompts", type=int, default=48)
    ap.add_argument("--serve-prompt-len", type=int, default=32)
    ap.add_argument("--serve-max-new", type=int, default=32)
    ap.add_argument("--pairs", type=int, default=2)
    args = ap.parse_args()
    if args.serve:
        serve_main(args)
        return
    B, k = args.batch, args.k

    tcfg = zoo_config("1b", max_seq_len=PROMPT + args.long + 2 * k + 8)
    dcfg = zoo_config("45m", max_seq_len=PROMPT + args.long + 2 * k + 8)
    t0 = time.perf_counter()
    tparams = random_serving_params(jax.random.key(0), tcfg, quantized=False)
    dparams = random_serving_params(jax.random.key(1), dcfg, quantized=False)
    jax.block_until_ready((tparams, dparams))
    print(f"params in {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, tcfg.vocab_size, (B, PROMPT)), jnp.int32
    )

    plain_t = {}
    plain_ok = {}
    for name, cfg, params in (("target", tcfg, tparams), ("draft", dcfg, dparams)):
        calls = {
            n: jax.jit(lambda p, t, n=n, cfg=cfg: generate(p, cfg, t, n))
            for n in (args.short, args.long)
        }
        per, ok = _time_tokens(
            lambda n: np.asarray(calls[n](params, prompt)),
            args.short, args.long,
        )
        plain_t[name] = per
        plain_ok[name] = ok
        print(f"plain {name}: {per * 1e3:.3f} ms/tick ok={ok}", file=sys.stderr)

    # Jitted callables built ONCE per (draft config, max_new): a fresh
    # jax.jit(lambda) per call would re-trace and re-compile the 1B
    # while_loop program on every timed repeat, burying device time
    # under seconds of compile.
    _spec_jits: dict = {}

    def spec_run(dp, dc, n):
        key = (id(dc), n)
        if key not in _spec_jits:
            _spec_jits[key] = jax.jit(
                lambda tp, dpp, t, n=n, dc=dc: speculative_generate(
                    tp, tcfg, dpp, dc, t, n, k=k
                )
            )
        out, stats = _spec_jits[key](tparams, dp, prompt)
        return np.asarray(out), stats

    stats_box = {}

    def spec_timed(dp, dc, label):
        def run(n):
            out, stats = spec_run(dp, dc, n)
            stats_box[(label, n)] = jax.device_get(stats)
            return out
        per, ok = _time_tokens(run, args.short, args.long)
        st = stats_box[(label, args.long)]
        alpha = float(st.accepted) / max(float(st.proposed), 1.0)
        print(
            f"spec {label}: {per * 1e3:.3f} ms/tick ok={ok} "
            f"acceptance={alpha:.3f} rounds={int(st.rounds)}",
            file=sys.stderr,
        )
        return per, alpha, ok

    # Exactness at scale (bf16: argmax near-ties can legally flip across
    # program shapes, so compare with tolerance on the agreement rate).
    plain_out = np.asarray(
        jax.jit(lambda p, t: generate(p, tcfg, t, args.short))(tparams, prompt)
    )
    spec_out, _ = spec_run(dparams, dcfg, args.short)
    agree = float((plain_out == spec_out).mean())

    per_real, alpha_real, ok_real = spec_timed(dparams, dcfg, "45m-draft")
    per_perfect, alpha_perfect, ok_perfect = spec_timed(
        tparams, tcfg, "perfect-draft"
    )

    t_t, t_d = plain_t["target"], plain_t["draft"]
    # Degenerate slopes must not publish numbers (utils/timing.py's
    # contract): flag and null the derived fields instead.
    slopes_ok = plain_ok["target"] and plain_ok["draft"] and ok_real
    c = t_d / t_t
    # At chance acceptance each round emits one token, so the measured
    # seconds/token IS the acceptance-independent round cost.
    t_round = per_real
    v = (t_round - (k + 1) * t_d) / t_t  # implied full verify, diagnostic
    curve = {}
    if slopes_ok:
        for alpha in (0.5, 0.7, 0.8, 0.9, 1.0):
            e_tok = (
                (1 - alpha ** (k + 1)) / (1 - alpha) if alpha < 1 else k + 1
            )
            curve[str(alpha)] = round(e_tok * t_t / t_round, 3)
    def _num(x, nd=3):
        return round(x, nd) if slopes_ok else None

    print(json.dumps({
        "metric": "speculative_decode_cost_model",
        "slopes_ok": slopes_ok,
        "slope_flags": {
            "target": plain_ok["target"], "draft": plain_ok["draft"],
            "spec_45m": ok_real, "spec_perfect": ok_perfect,
        },
        "batch": B, "k": k, "prompt_len": PROMPT,
        "target_ms_per_tick": _num(t_t * 1e3),
        "draft_ms_per_tick": _num(t_d * 1e3),
        "cost_ratio_c": _num(c, 4),
        "round_ms_45m_draft": _num(t_round * 1e3),
        "verify_full_over_target_v_implied": _num(v),
        "spec_ms_per_tick_45m_draft": (
            round(per_real * 1e3, 3) if ok_real else None
        ),
        "acceptance_45m_draft_random_weights": round(alpha_real, 4),
        "acceptance_perfect_draft": round(alpha_perfect, 4),
        "spec_ms_per_tick_perfect_draft": _num(per_perfect * 1e3),
        "token_agreement_vs_plain_greedy": round(agree, 4),
        "implied_speedup_vs_alpha": curve,
        "note": (
            "random weights give chance-level draft/target agreement; "
            "the curve is E[accepted+1] x t_target / t_round with both "
            "times measured (round cost is acceptance-independent), "
            "not a claimed end-to-end speedup"
        ),
    }))


if __name__ == "__main__":
    main()

"""Shim package: byte-identical imports for torch-kafka users.

The reference installs as ``torchkafka`` (/root/reference/setup.py:25-30) and
exports ``KafkaDataset`` and ``auto_commit``
(/root/reference/src/__init__.py:17-18). Installing torchkafka-tpu provides
this shim so existing code — ``from torchkafka import KafkaDataset,
auto_commit`` — runs unchanged on the TPU-native core. Do not install both
distributions in one environment: the module name collides by design.
"""

from torchkafka_tpu.compat import KafkaDataset, auto_commit

__all__ = ["KafkaDataset", "auto_commit"]

"""Serving fleet (torchkafka_tpu/fleet/): partitioned multi-replica
serving with QoS admission, replica failover, and graceful drain.

Pins the three fleet-level contracts:

1. **Failover** (the headline differential): a seeded chaos schedule kills
   a replica mid-generation; its partitions reassign, its uncommitted
   prompts re-deliver, and the fleet's union of completions covers every
   produced prompt — duplicates allowed, losses not — with the committed
   watermark provably never covering unfinished work AT EVERY COMMIT
   (audited inside the commit call, not post-hoc).
2. **QoS**: per-tenant token buckets cap the throttled tenant's admit
   rate exactly (fake clock) while an unlimited tenant is unaffected, and
   the interactive lane's p50 queue wait beats batch — all read from
   FleetMetrics.
3. **Drain**: SIGTERM finishes in-flight generations, commits them, and a
   restarted fleet resumes with zero replayed completions (asserted via
   the broker commit log).
"""

import json
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchkafka_tpu as tk
from torchkafka_tpu.fleet import (
    BATCH,
    INTERACTIVE,
    QoSConfig,
    ReplicaChaos,
    ServingFleet,
    TokenBucket,
)
from torchkafka_tpu.models.transformer import TransformerConfig, init_params

P, MAX_NEW, VOCAB = 8, 8, 64


@pytest.fixture(scope="module")
def model():
    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, n_kv_heads=1,
        d_ff=64, max_seq_len=P + MAX_NEW, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _produce(broker, n, parts=4, topic="p", key_of=None, lane_of=None):
    broker.create_topic(topic, partitions=parts)
    rng = np.random.default_rng(7)
    prompts = rng.integers(0, VOCAB, (n, P), dtype=np.int32)
    for i in range(n):
        headers = ()
        if lane_of is not None:
            headers = (("lane", lane_of(i)),)
        broker.produce(
            topic, prompts[i].tobytes(), partition=i % parts,
            key=None if key_of is None else key_of(i), headers=headers,
        )
    return prompts


def _fleet(broker, model, **kw):
    cfg, params = model
    kw.setdefault("replicas", 2)
    kw.setdefault("slots", 2)
    group = kw.pop("group_id", "fleet")
    topic = kw.pop("topic", "p")
    factory = kw.pop("consumer_factory", None) or (
        lambda rid: tk.MemoryConsumer(broker, topic, group_id=group)
    )
    return ServingFleet(
        factory, params, cfg, prompt_len=P, max_new=MAX_NEW, **kw
    )


class ManualClock:
    """Advances only when the test says so — exact token-bucket math."""

    def __init__(self, start: float = 1000.0) -> None:
        self.t = start

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class TestTokenBucket:
    def test_refill_and_burst(self):
        clock = ManualClock()
        b = TokenBucket(rate=2.0, burst=3.0, clock=clock)
        assert [b.try_acquire() for _ in range(4)] == [True] * 3 + [False]
        clock.advance(1.0)  # +2 tokens
        assert b.try_acquire() and b.try_acquire() and not b.try_acquire()
        clock.advance(100.0)  # refill clamps at burst
        assert [b.try_acquire() for _ in range(4)] == [True] * 3 + [False]

    def test_rejects_bad_params(self):
        with pytest.raises(ValueError, match="rate"):
            TokenBucket(rate=0.0)
        with pytest.raises(ValueError, match="burst"):
            TokenBucket(rate=1.0, burst=0.5)


class TestFleetServing:
    def test_covers_commits_and_splits_work(self, model):
        """Two replicas split 4 partitions, serve everything exactly once,
        and the per-partition commits land at the log end."""
        broker = tk.InMemoryBroker()
        _produce(broker, 16)
        fleet = _fleet(broker, model, commit_every=4)
        out = fleet.serve_all(max_records=16)
        fleet.close()
        assert len(out) == 16
        assert fleet.metrics.duplicates.count == 0
        by_rep = {rid: 0 for rid in (0, 1)}
        for rid, _rec, toks in out:
            by_rep[rid] += 1
            assert 1 <= len(toks) <= MAX_NEW
        # Range assignment gives each replica 2 of 4 partitions → 8 each.
        assert by_rep == {0: 8, 1: 8}
        for p in range(4):
            assert broker.committed("fleet", tk.TopicPartition("p", p)) == 4
        # The merged watermark view agrees with the broker.
        assert all(off == 4 for off in fleet.watermarks().values())

    def test_fleet_completions_match_single_server(self, model):
        """Greedy fleet output is token-exact per prompt vs the lockstep
        reference path — replica partitioning must not change tokens."""
        from torchkafka_tpu.models.generate import generate

        cfg, params = model
        broker = tk.InMemoryBroker()
        prompts = _produce(broker, 8)
        expected = np.asarray(
            generate(params, cfg, jnp.asarray(prompts), MAX_NEW)
        )
        fleet = _fleet(broker, model)
        out = fleet.serve_all(max_records=8)
        fleet.close()
        assert len(out) == 8
        for _rid, rec, toks in out:
            idx = rec.offset * 4 + rec.partition
            np.testing.assert_array_equal(
                toks, expected[idx], err_msg=f"prompt {idx}"
            )

    def test_netbroker_fleet(self, model):
        """The same fleet over the socket transport: replicas' consumers
        talk to a BrokerServer through BrokerClient — the cross-process
        deployment shape, one group over the wire."""
        broker = tk.InMemoryBroker()
        _produce(broker, 8, parts=2)
        with tk.BrokerServer(broker) as server:
            clients = []

            def factory(rid):
                c = tk.BrokerClient(server.host, server.port)
                clients.append(c)
                return tk.MemoryConsumer(c, "p", group_id="netfleet")

            fleet = _fleet(broker, model, consumer_factory=factory)
            out = fleet.serve_all(max_records=8)
            fleet.close()
            for c in clients:
                c.close()
        assert len(out) == 8
        for p in range(2):
            assert broker.committed("netfleet", tk.TopicPartition("p", p)) == 4

    def test_spec_fleet(self, model):
        """A speculative fleet (SpecStreamingGenerator replicas) serves
        through the same admission surface, token counters live."""
        from torchkafka_tpu.serve_spec import SpecStreamingGenerator

        broker = tk.InMemoryBroker()
        _produce(broker, 6, parts=2)
        fleet = _fleet(
            broker, model, generator_cls=SpecStreamingGenerator,
            gen_kwargs={"k": 2},
        )
        out = fleet.serve_all(max_records=6)
        fleet.close()
        assert len(out) == 6
        stats = [rep.gen.spec_stats() for rep in fleet.replicas]
        assert sum(s["proposed"] for s in stats) > 0

    def test_rejects_zero_replicas(self, model):
        with pytest.raises(ValueError, match="replicas"):
            _fleet(tk.InMemoryBroker(), model, replicas=0,
                   consumer_factory=lambda rid: object())


class _AuditedConsumer(tk.MemoryConsumer):
    """Asserts, INSIDE every commit, that each offset being committed is
    covered by an already-registered completion or drop — the
    committed-watermark-never-exceeds-completed-work invariant, checked at
    every commit point instead of post-hoc."""

    audit_ref: dict = {}

    def commit(self, offsets=None) -> None:
        completed = self.audit_ref.get("completed")
        assert offsets is not None and completed is not None
        for tp, off in offsets.items():
            for o in range(off):
                assert (tp.topic, tp.partition, o) in completed, (
                    f"commit of {tp}:{off} covers offset {o} with no "
                    "completion registered — watermark corruption"
                )
        super().commit(offsets)


class TestChaosReplicaKill:
    def test_seeded_kill_redelivers_without_loss(self, model):
        """The headline differential: seeded mid-generation replica death.
        Coverage is total, redelivery PROVABLY occurred (≥1 duplicate),
        every commit was audited against completed work, and the victim's
        partitions ended up owned by the survivor."""
        n, parts = 24, 4
        broker = tk.InMemoryBroker()
        _produce(broker, n, parts=parts)
        audit = {"completed": None}

        class Consumer(_AuditedConsumer):
            audit_ref = audit

        fleet = _fleet(
            broker, model,
            consumer_factory=lambda rid: Consumer(
                broker, "p", group_id="chaos"
            ),
            commit_every=100,  # victim's completions stay uncommitted →
            # every one of them must re-serve after the kill
            group_id="chaos",
        )
        audit["completed"] = fleet.completed
        chaos = ReplicaChaos(seed=3, min_completions=2, max_completions=6)
        out = fleet.serve_all(idle_timeout_ms=1000, chaos=chaos)
        served = [(rec.partition, rec.offset) for _rid, rec, _t in out]

        # 1. The kill actually happened, mid-generation, exactly once.
        assert len(chaos.killed) == 1
        assert fleet.metrics.replica_deaths.count == 1
        victim = chaos.killed[0]
        assert fleet.replicas[victim].state == "dead"

        # 2. Coverage: the union of completions is every produced prompt —
        # duplicates allowed, losses not.
        assert set(served) == {(i % parts, i // parts) for i in range(n)}

        # 3. Redelivery occurred: at least one prompt served twice (here:
        # every completion the victim emitted, since none had committed).
        victim_completions = fleet.metrics.replica_completions(victim).count
        assert victim_completions >= 1
        assert fleet.metrics.duplicates.count >= victim_completions >= 1
        assert len(served) == n + fleet.metrics.duplicates.count

        # 4. The victim's partitions were absorbed: the survivor owns all.
        survivor = fleet.replicas[1 - victim]
        assert set(survivor.consumer.assignment()) == {
            tk.TopicPartition("p", p) for p in range(parts)
        }

        # 5. Watermarks: fully committed at the end, and every commit
        # along the way passed the in-commit audit (the _AuditedConsumer
        # asserts inside commit()).
        fleet.close()
        for p in range(parts):
            assert broker.committed("chaos", tk.TopicPartition("p", p)) == (
                n // parts
            )

    def test_same_seed_same_schedule(self, model):
        """Chaos is replayable: the same seed kills the same replica at
        the same fleet completion count."""
        def run():
            broker = tk.InMemoryBroker()
            _produce(broker, 12, parts=2)
            fleet = _fleet(
                broker, model, commit_every=100, group_id="rep",
                consumer_factory=lambda rid: tk.MemoryConsumer(
                    broker, "p", group_id="rep"
                ),
            )
            chaos = ReplicaChaos(seed=11, min_completions=1,
                                 max_completions=4)
            out = fleet.serve_all(idle_timeout_ms=1000, chaos=chaos)
            fleet.close()
            return chaos.killed, [
                (rec.partition, rec.offset) for _r, rec, _t in out
            ]

        k1, s1 = run()
        k2, s2 = run()
        assert k1 == k2
        assert s1 == s2


class TestQoS:
    def test_token_bucket_caps_throttled_tenant(self, model):
        """Saturating two-tenant run: tenant 'slow' (rate-limited) admits
        at most burst + rate × elapsed — the exact bucket bound — and is
        actually throttled; tenant 'fast' (unlimited) is unaffected. All
        read from FleetMetrics. ManualClock advances only between
        completions, so the bound is arithmetic, not timing-dependent."""
        clock = ManualClock()
        broker = tk.InMemoryBroker()
        # Partition by tenant so both replicas see both tenants' queues is
        # not needed — what matters is the SHARED bucket.
        _produce(
            broker, 40, parts=2,
            key_of=lambda i: b"slow" if i % 2 == 0 else b"fast",
        )
        rate, burst = 0.25, 1.0
        fleet = _fleet(
            broker, model, replicas=2, slots=2, group_id="qos",
            consumer_factory=lambda rid: tk.MemoryConsumer(
                broker, "p", group_id="qos"
            ),
            qos=QoSConfig(tenant_rates={"slow": rate}, burst=burst),
            clock=clock,
        )
        t0 = clock.t
        done = 0
        for _rid, _rec, _toks in fleet.serve(idle_timeout_ms=1000):
            done += 1
            clock.advance(1.0)
            if done >= 24:
                break
        elapsed = clock.t - t0
        s = fleet.metrics.summary(fleet.replicas)
        slow, fast = s["tenants"]["slow"], s["tenants"]["fast"]
        # Exact bucket bound (tokens granted can never exceed burst +
        # rate × elapsed; +1 because the last grant may straddle the
        # final advance).
        assert slow["admitted"] <= burst + rate * elapsed + 1
        assert slow["admitted"] >= 2  # throttled ≠ starved: tokens refill
        assert slow["throttled"] > 0
        # The unlimited tenant flowed freely: it got the large majority
        # of the slots while 'slow' waited on tokens.
        assert fast["throttled"] == 0
        assert fast["admitted"] >= 15
        assert fast["admitted"] > slow["admitted"] * 2

    def test_interactive_preempts_batch(self, model):
        """Interactive-lane records admit ahead of already-queued batch
        records: interactive p50 queue wait < batch p50 (FleetMetrics)."""
        clock = ManualClock()
        broker = tk.InMemoryBroker()
        # One partition, one replica, slots=2: a deep queue forms, so lane
        # priority decides who waits.
        _produce(
            broker, 24, parts=1,
            lane_of=lambda i: b"interactive" if i % 3 == 0 else b"batch",
        )
        fleet = _fleet(
            broker, model, replicas=1, slots=2, group_id="lanes",
            consumer_factory=lambda rid: tk.MemoryConsumer(
                broker, "p", group_id="lanes"
            ),
            clock=clock,
        )
        done = 0
        for _ in fleet.serve(max_records=24, idle_timeout_ms=1000):
            done += 1
            clock.advance(1.0)
        fleet.close()
        assert done == 24
        s = fleet.metrics.summary(fleet.replicas)
        assert s["lanes"][INTERACTIVE]["count"] == 8
        assert s["lanes"][BATCH]["count"] == 16
        assert (
            s["lanes"][INTERACTIVE]["p50_ms"] < s["lanes"][BATCH]["p50_ms"]
        )

    def test_backpressure_pauses_and_resumes(self, model):
        """With saturated slots and a bounded admission queue, the replica
        pauses its partitions instead of buffering the topic, resumes at
        the low-water mark, and still serves everything."""
        broker = tk.InMemoryBroker()
        _produce(broker, 32, parts=2)
        fleet = _fleet(
            broker, model, replicas=1, slots=2, group_id="bp",
            consumer_factory=lambda rid: tk.MemoryConsumer(
                broker, "p", group_id="bp"
            ),
            qos=QoSConfig(max_queue_depth=6, resume_queue_depth=2),
            max_poll_records=4,
        )
        out = fleet.serve_all(max_records=32, idle_timeout_ms=1000)
        fleet.close()
        assert len(out) == 32
        assert fleet.metrics.backpressure_pauses.count >= 1
        assert fleet.metrics.backpressure_resumes.count >= 1
        # Bounded: the queue never exceeded the high-water mark.
        for rep in fleet.replicas:
            assert rep.queue.depth() == 0


class TestGracefulDrain:
    def test_sigterm_drains_lossfree_and_restart_replays_nothing(
        self, model, tmp_path
    ):
        """SIGTERM mid-serve: the fleet stops admitting, finishes every
        in-flight generation, commits them, and leaves. A restarted fleet
        serves exactly the remainder — zero replayed completions, asserted
        against the broker commit log."""
        log_path = str(tmp_path / "commits.jsonl")
        broker = tk.InMemoryBroker(commit_log_path=log_path)
        n, parts = 20, 2
        _produce(broker, n, parts=parts)

        fleet1 = _fleet(
            broker, model, group_id="drain", commit_every=100,
            consumer_factory=lambda rid: tk.MemoryConsumer(
                broker, "p", group_id="drain"
            ),
        )
        got1 = []
        with tk.ShutdownSignal() as stop:
            for _rid, rec, _toks in fleet1.serve(
                idle_timeout_ms=2000, shutdown=stop,
            ):
                got1.append((rec.partition, rec.offset))
                if len(got1) == 6:
                    signal.raise_signal(signal.SIGTERM)
        # serve() returned because the drain completed: every replica left
        # cleanly, nothing is in flight.
        assert all(rep.state == "done" for rep in fleet1.replicas)
        assert fleet1.metrics.drains.count == len(fleet1.replicas)
        assert 6 <= len(got1) < n  # finished in-flight work, then stopped

        # Every drained completion is inside the committed watermark: the
        # drain committed exactly the work it finished.
        committed1 = {
            p: broker.committed("drain", tk.TopicPartition("p", p)) or 0
            for p in range(parts)
        }
        assert sum(committed1.values()) == len(got1)
        for p, off in committed1.items():
            assert {(p, o) for o in range(off)} <= set(got1)

        # Restart: the new fleet serves exactly the remainder.
        fleet2 = _fleet(
            broker, model, group_id="drain", commit_every=4,
            consumer_factory=lambda rid: tk.MemoryConsumer(
                broker, "p", group_id="drain"
            ),
        )
        got2 = [
            (rec.partition, rec.offset)
            for _rid, rec, _t in fleet2.serve(idle_timeout_ms=1000)
        ]
        fleet2.close()
        assert set(got1) | set(got2) == {
            (i % parts, i // parts) for i in range(n)
        }
        # ZERO replayed completions, asserted via the commit log: fleet1's
        # durable watermark (the last log entry per partition before
        # fleet2 started) bounds everything fleet2 served from below.
        with open(log_path) as f:
            entries = [json.loads(line) for line in f]
        run1_entries = entries[: len(fleet1.replicas)]  # one flush/replica
        assert run1_entries, "drain never committed"
        run1_high: dict[int, int] = {}
        for e in run1_entries:
            for tp_s, off in e["offsets"].items():
                p = int(tp_s.split(":")[1])
                run1_high[p] = max(run1_high.get(p, 0), off)
        assert run1_high == {
            p: off for p, off in committed1.items() if off
        } or run1_high == committed1
        for p, off in committed1.items():
            assert all(o >= off for q, o in got2 if q == p), (p, off)
        assert not (set(got1) & set(got2))
        # And the log's final state covers the whole topic.
        final = {
            p: broker.committed("drain", tk.TopicPartition("p", p))
            for p in range(parts)
        }
        assert final == {p: n // parts for p in range(parts)}

    def test_drain_without_signal_is_equivalent(self, model):
        """fleet.drain() (the programmatic path) has the same semantics:
        admitted work finishes and commits; queued work re-delivers."""
        broker = tk.InMemoryBroker()
        _produce(broker, 12, parts=2)
        fleet = _fleet(broker, model, group_id="d2", commit_every=100)
        got = []
        for _rid, rec, _t in fleet.serve(idle_timeout_ms=2000):
            got.append((rec.partition, rec.offset))
            if len(got) == 4:
                fleet.drain()
        assert all(rep.state == "done" for rep in fleet.replicas)
        committed = sum(
            broker.committed("d2", tk.TopicPartition("p", p)) or 0
            for p in range(2)
        )
        assert committed == len(got) >= 4

    def test_finish_drain_retries_survivable_flush_failure(self):
        """The fleet-wide-drain generation race (caught by scenario 24):
        a peer's clean leave bumps the group generation mid-drain and the
        last replica's final flush gets CommitFailedError. finish_drain
        must RETRY — flush_commits keeps the outbox/cadence intact and
        the next attempt re-syncs the group — not exit rc=0 with
        finished completions stranded uncommitted."""
        from torchkafka_tpu.fleet.replica import DRAINING, Replica

        class _Gen:
            def __init__(self):
                self.flush_calls = 0
                self.synced = False

            def flush_commits(self):
                self.flush_calls += 1
                # Two survivable failures (rebalanced-generation commit
                # rejections), then the re-synced attempt lands.
                return self.flush_calls >= 3

            def sync_journal(self):
                self.synced = True

            def has_active(self):
                return False

        class _Consumer:
            closed = False

            def close(self):
                self.closed = True

        gen, consumer = _Gen(), _Consumer()
        rep = Replica(0, gen, consumer, None, None, None)
        rep.state = DRAINING
        rep.finish_drain()
        assert gen.flush_calls == 3  # retried past both failures
        assert gen.synced and consumer.closed
        assert rep.state == "done"
